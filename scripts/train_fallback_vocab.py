"""Train the committed fallback CLIP-format BPE vocab.

The runtime has zero network egress, so OpenAI's CLIP BPE vocab
(bpe_simple_vocab_16e6) cannot be fetched. This script trains a
byte-level BPE with CLIP's exact structure (GPT-2 byte alphabet,
``</w>`` end-of-word suffix, CLIP pre-tokenization regex) on English
prose available on the build host, then emits the canonical CLIP file
pair — ``vocab.json`` + ``merges.txt`` — where the vocab is derived
from the merge list exactly the way OpenAI's vocab is:

    [256 byte units] + [256 byte units + '</w>'] + [one token per
    merge, in rank order] + ['<|startoftext|>', '<|endoftext|>']

Dropping in the real CLIP files (same format) at
``models/assets/clip_vocab/`` or via ``CDT_CLIP_VOCAB`` swaps in exact
CLIP tokenization with no code change.

Usage: python scripts/train_fallback_vocab.py [--out DIR] [--vocab-size N]
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import tempfile

CLIP_PATTERN = (
    r"(?i)<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|"
    r"[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+"
)

CORPUS_ROOTS = (
    "/opt/venv/lib/python3.12/site-packages",
    "/usr/share/doc",
    "/usr/lib/python3.12",
)
CORPUS_EXTS = (".md", ".rst", ".txt")


def bytes_to_unicode() -> dict[int, str]:
    """GPT-2/CLIP byte→printable-unicode table (order matters: it
    defines vocab ids 0..255)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(2**8):
        if b not in bs:
            bs.append(b)
            cs.append(2**8 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def collect_corpus(max_bytes: int = 64_000_000) -> list[str]:
    files: list[str] = []
    total = 0
    for root in CORPUS_ROOTS:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in ("node_modules",)]
            for name in sorted(filenames):
                upper = name.upper()
                if not name.endswith(CORPUS_EXTS):
                    continue
                if "LICENSE" in upper or "COPYING" in upper:
                    continue
                path = os.path.join(dirpath, name)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                if size < 2000 or size > 4_000_000:
                    continue
                files.append(path)
                total += size
                if total > max_bytes:
                    return files
    return files


def train_merges(corpus_files: list[str], vocab_size: int) -> list[tuple[str, str]]:
    from tokenizers import Regex, Tokenizer, models, normalizers, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(end_of_word_suffix="</w>"))
    tok.normalizer = normalizers.Sequence(
        [normalizers.NFC(), normalizers.Lowercase()]
    )
    tok.pre_tokenizer = pre_tokenizers.Sequence(
        [
            pre_tokenizers.Split(Regex(CLIP_PATTERN), behavior="isolated"),
            pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=False),
        ]
    )
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        min_frequency=2,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        end_of_word_suffix="</w>",
        show_progress=False,
    )

    def read_lines():
        for path in corpus_files:
            try:
                with open(path, encoding="utf-8", errors="ignore") as fh:
                    yield fh.read()
            except OSError:
                continue

    tok.train_from_iterator(read_lines(), trainer)

    with tempfile.TemporaryDirectory() as tmp:
        tok.model.save(tmp)
        with open(os.path.join(tmp, "merges.txt"), encoding="utf-8") as fh:
            lines = [ln for ln in fh.read().splitlines() if ln and not ln.startswith("#")]
    return [tuple(ln.split(" ")) for ln in lines]  # type: ignore[misc]


def build_vocab(merges: list[tuple[str, str]], total_size: int = 49408) -> dict[str, int]:
    byte_units = list(bytes_to_unicode().values())
    tokens = byte_units + [u + "</w>" for u in byte_units]
    tokens += [a + b for a, b in merges]
    # pad so the specials land at CLIP's exact ids (49406/49407) even
    # when the corpus yields fewer merges than CLIP's 48894
    while len(tokens) < total_size - 2:
        tokens.append(f"<|unused{len(tokens)}|>")
    tokens += ["<|startoftext|>", "<|endoftext|>"]
    vocab: dict[str, int] = {}
    for token in tokens:
        if token not in vocab:  # merges can re-derive a byte unit
            vocab[token] = len(vocab)
    assert len(vocab) == total_size, len(vocab)
    return vocab


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--out",
        default=os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "comfyui_distributed_tpu", "models", "assets", "clip_vocab",
        ),
    )
    # 49408 total = 512 byte units + 48894 merges + 2 specials (CLIP's
    # exact layout); the trainer may stop earlier on a small corpus.
    ap.add_argument("--vocab-size", type=int, default=49406)
    args = ap.parse_args()

    corpus = collect_corpus()
    print(f"corpus: {len(corpus)} files")
    merges = train_merges(corpus, args.vocab_size)
    # drop merges whose product collides with a byte unit (id reuse)
    seen: set[str] = set()
    byte_units = set(bytes_to_unicode().values())
    byte_units |= {u + "</w>" for u in byte_units}
    clean: list[tuple[str, str]] = []
    for a, b in merges:
        prod = a + b
        if prod in byte_units or prod in seen:
            continue
        seen.add(prod)
        clean.append((a, b))
    # CLIP's merge table is exactly 49152-256-2 = 48894 entries; the
    # transformers reader hard-caps at that count, so so do we.
    clean = clean[:48894]
    vocab = build_vocab(clean)
    print(f"merges: {len(clean)}, vocab: {len(vocab)}")

    os.makedirs(args.out, exist_ok=True)
    with gzip.open(os.path.join(args.out, "vocab.json.gz"), "wt", encoding="utf-8") as fh:
        json.dump(vocab, fh, ensure_ascii=False)
    with gzip.open(os.path.join(args.out, "merges.txt.gz"), "wt", encoding="utf-8") as fh:
        fh.write("#version: 0.2\n")
        for a, b in clean:
            fh.write(f"{a} {b}\n")
    print(f"wrote {args.out}/vocab.json.gz + merges.txt.gz")


if __name__ == "__main__":
    main()
