#!/usr/bin/env python
"""Generate committed golden outputs (round-3 verdict item 8).

Freezes end-to-end numerics of the three canonical pipelines on tiny
models — txt2img (UNet+CLIP+VAE+sampler), USDU tiled upscale
(plan/extract/diffuse/blend), and t2v (DiT+causal-3D-VAE) — so any
refactor of samplers/VAE/tokenizer/blend that shifts output fails
tests/golden/ loudly. The reference gets this stability implicitly
from ComfyUI's battle-tested torch stack; with no network egress and
no published weights here, pinned tiny-model outputs are the
substitute.

Run ONLY to intentionally re-baseline after a deliberate
numerics-changing fix:  python scripts/gen_goldens.py

`--check` recomputes and compares against the committed npz instead of
rewriting (exit 1 on drift); tests/golden/test_goldens.py runs that in
a subprocess.

Environment pinning (measured, not assumed): XLA CPU numerics depend
on the host-platform DEVICE COUNT — under
--xla_force_host_platform_device_count=8 the tiny VAE encode already
differs by ~8e-4 from the 1-device client (same box, same wheel), and
two diffusion steps amplify that to ~2e-2. Goldens are therefore
generated AND checked under a pinned 1-device CPU client; the test
wrapper strips the inherited 8-device XLA_FLAGS before spawning.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def compute_goldens() -> dict[str, np.ndarray]:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from comfyui_distributed_tpu.models import pipeline as pl
    from comfyui_distributed_tpu.models import video_pipeline as vp
    from comfyui_distributed_tpu.ops import upscale as up

    out: dict[str, np.ndarray] = {}

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    out["txt2img_64"] = np.asarray(
        pl.txt2img(
            bundle, "a golden test image", height=64, width=64,
            steps=2, seed=1234, cfg_scale=7.0,
        )
    )

    img = (
        np.linspace(0, 1, 64 * 64 * 3, dtype=np.float32).reshape(1, 64, 64, 3)
    )
    pos = pl.encode_text(bundle, ["golden upscale"])
    neg = pl.encode_text(bundle, [""])
    out["usdu_64_to_128"] = np.asarray(
        up.run_upscale(
            bundle, img, pos, neg, mesh=None, seed=7, upscale_by=2.0,
            tile=64, padding=16, steps=2, sampler="euler",
            scheduler="karras", cfg=7.0, denoise=0.35,
            # goldens pin the K=1 numerics; an inherited CDT_TILE_BATCH
            # would silently bake batched (allclose-only) outputs in
            tile_batch=1,
        )
    )

    vbundle = vp.load_video_pipeline("tiny-dit", vae_name="tiny-video-vae-3d")
    out["t2v_5f_32"] = np.asarray(
        vp.t2v(
            vbundle, "a golden test clip", frames=5, height=32, width=32,
            steps=2, seed=42,
        )
    )

    # rectified-flow family (Flux class): flow sigmas + interpolation
    # noising + T5-context/CLIP-pooled conditioning end to end
    fbundle = pl.load_pipeline("tiny-flux", seed=0)
    out["flux_txt2img_32"] = np.asarray(
        pl.txt2img(
            fbundle, "a golden flux image", height=32, width=32,
            steps=2, seed=99, cfg_scale=1.0, sampler="euler",
            scheduler="simple",
        )
    )

    # SD3 family: joint blocks + triple CLIP-L/G + T5 conditioning +
    # true CFG on the flow schedule
    sbundle = pl.load_pipeline("tiny-sd3", seed=0)
    out["sd3_txt2img_32"] = np.asarray(
        pl.txt2img(
            sbundle, "a golden sd3 image", height=32, width=32,
            steps=2, seed=77, cfg_scale=4.0, sampler="euler",
            scheduler="simple",
        )
    )
    return out


def main() -> int:
    dest = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "golden", "goldens.npz",
    )
    if "--check" in sys.argv[1:]:
        atol = float(os.environ.get("CDT_GOLDEN_ATOL", 1e-3))
        want = np.load(dest)
        fresh = compute_goldens()
        failed = []
        for name in fresh:
            drift = float(np.abs(fresh[name] - want[name]).max())
            status = "ok" if drift <= atol else "DRIFTED"
            print(f"{name}: max|Δ|={drift:.3e} (atol {atol:g}) {status}")
            if drift > atol:
                failed.append(name)
        if failed:
            print(
                f"DRIFT in {failed}: end-to-end numerics changed. If "
                "intentional, re-baseline with scripts/gen_goldens.py "
                "and say so in the commit message."
            )
            return 1
        return 0

    os.makedirs(os.path.dirname(dest), exist_ok=True)
    goldens = compute_goldens()
    np.savez_compressed(dest, **goldens)
    for name, arr in goldens.items():
        print(f"{name}: {arr.shape} {arr.dtype} "
              f"mean={arr.mean():.6f} std={arr.std():.6f}")
    print(f"wrote {dest} ({os.path.getsize(dest)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
