#!/usr/bin/env python
"""Generate committed golden outputs (round-3 verdict item 8; coverage
extended round 5 per r4 VERDICT item 2).

Freezes end-to-end numerics of every canonical pipeline on tiny
models — txt2img (UNet+CLIP+VAE+sampler), USDU tiled upscale
(plan/extract/diffuse/blend), t2v (DiT+causal-3D-VAE), Flux and SD3
rectified flow, the inpaint/outpaint substrate, the hi-res-fix chain,
Kontext reference-latent editing, v-prediction, and the beta /
kl_optimal schedules — so any refactor of samplers/VAE/tokenizer/
blend that shifts output fails tests/golden/ loudly. The reference gets this stability implicitly
from ComfyUI's battle-tested torch stack; with no network egress and
no published weights here, pinned tiny-model outputs are the
substitute.

Run ONLY to intentionally re-baseline after a deliberate
numerics-changing fix:  python scripts/gen_goldens.py

`--check` recomputes and compares against the committed npz instead of
rewriting (exit 1 on drift); tests/golden/test_goldens.py runs that in
a subprocess.

Environment pinning (measured, not assumed): XLA CPU numerics depend
on the host-platform DEVICE COUNT — under
--xla_force_host_platform_device_count=8 the tiny VAE encode already
differs by ~8e-4 from the 1-device client (same box, same wheel), and
two diffusion steps amplify that to ~2e-2. Goldens are therefore
generated AND checked under a pinned 1-device CPU client; the test
wrapper strips the inherited 8-device XLA_FLAGS before spawning.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def compute_goldens(quick: bool = False) -> dict[str, np.ndarray]:
    """All pinned arrays; `quick` computes only the cheap core subset
    (txt2img + USDU + schedule pins — the `-m integration` tier's
    <10-min budget), skipping the compile-heavy model families."""
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    from comfyui_distributed_tpu.models import pipeline as pl
    from comfyui_distributed_tpu.models import video_pipeline as vp
    from comfyui_distributed_tpu.ops import samplers as smp
    from comfyui_distributed_tpu.ops import upscale as up

    out: dict[str, np.ndarray] = {}

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    out["txt2img_64"] = np.asarray(
        pl.txt2img(
            bundle, "a golden test image", height=64, width=64,
            steps=2, seed=1234, cfg_scale=7.0,
        )
    )

    img = (
        np.linspace(0, 1, 64 * 64 * 3, dtype=np.float32).reshape(1, 64, 64, 3)
    )
    pos = pl.encode_text(bundle, ["golden upscale"])
    neg = pl.encode_text(bundle, [""])
    out["usdu_64_to_128"] = np.asarray(
        up.run_upscale(
            bundle, img, pos, neg, mesh=None, seed=7, upscale_by=2.0,
            tile=64, padding=16, steps=2, sampler="euler",
            scheduler="karras", cfg=7.0, denoise=0.35,
            # goldens pin the K=1 numerics; an inherited CDT_TILE_BATCH
            # would silently bake batched (allclose-only) outputs in
            tile_batch=1,
        )
    )

    # schedule pins (r4 VERDICT item 2): the beta quantile grid (incl.
    # its collision resolution and the scipy-free PPF) and the
    # kl_optimal arctan grid, frozen exactly
    out["sigmas_beta_12"] = np.asarray(smp.get_sigmas("beta", 12))
    out["sigmas_kl_optimal_12"] = np.asarray(
        smp.get_sigmas("kl_optimal", 12)
    )

    if quick:
        return out

    from comfyui_distributed_tpu.graph.nodes_controlnet import ReferenceLatent
    from comfyui_distributed_tpu.graph.nodes_core import (
        EmptyLatentImage,
        ImagePadForOutpaint,
        KSampler,
        LatentUpscaleBy,
        VAEDecode,
        VAEEncode,
        VAEEncodeForInpaint,
    )

    # inpaint chain (r4 substrate): gray-neutralized encode with the
    # un-grown mask, dilated noise_mask, masked KSampler
    rng = np.random.default_rng(31)
    pix = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    imask = np.zeros((32, 32), np.float32)
    imask[10:22, 10:22] = 1.0
    (ilat,) = VAEEncodeForInpaint().encode(
        pix, bundle, jnp.asarray(imask), grow_mask_by=6
    )
    pos_p = pl.encode_text_pooled(bundle, ["golden inpaint"])
    neg_p = pl.encode_text_pooled(bundle, [""])
    (ilat2,) = KSampler().sample(
        bundle, 3, 2, 7.0, "euler", "karras", pos_p, neg_p, ilat,
        denoise=1.0,
    )
    out["inpaint_latent_32"] = np.asarray(ilat2["samples"])

    # outpaint pad: edge-replicated canvas + feathered mask
    (opad, omask) = ImagePadForOutpaint().expand(
        pix, left=0, top=0, right=16, bottom=8, feathering=8
    )
    out["outpaint_pad_32"] = np.asarray(opad)
    out["outpaint_mask_32"] = np.asarray(omask)

    # hi-res-fix chain: base sample -> LatentUpscaleBy 1.5x -> refine
    # pass -> decode (the two-KSampler workflow the latent-upscale
    # nodes exist for; the By-factor node scales the latent grid
    # directly, so the refine pass genuinely runs at higher res even
    # with the tiny VAE's 2x pixel factor)
    (el,) = EmptyLatentImage().generate(64, 64, 1)
    (base,) = KSampler().sample(
        bundle, 9, 2, 7.0, "euler", "karras", pos_p, neg_p, el,
        denoise=1.0,
    )
    (up_lat,) = LatentUpscaleBy().upscale(base, "bilinear", 1.5)
    (refined,) = KSampler().sample(
        bundle, 10, 2, 7.0, "euler", "karras", pos_p, neg_p, up_lat,
        denoise=0.5,
    )
    (hires_img,) = VAEDecode().decode(refined, bundle)
    out["hiresfix_64_to_96"] = np.asarray(hires_img)

    # v-prediction parameterization end to end, on the beta schedule
    # (also freezes beta spacing through a full sampling run)
    vbun = pl.load_pipeline("tiny-unet-v", seed=0)
    out["vpred_txt2img_32"] = np.asarray(
        pl.txt2img(
            vbun, "a golden vpred image", height=32, width=32,
            steps=2, seed=55, cfg_scale=7.0, sampler="euler",
            scheduler="beta",
        )
    )

    vbundle = vp.load_video_pipeline("tiny-dit", vae_name="tiny-video-vae-3d")
    out["t2v_5f_32"] = np.asarray(
        vp.t2v(
            vbundle, "a golden test clip", frames=5, height=32, width=32,
            steps=2, seed=42,
        )
    )

    # rectified-flow family (Flux class): flow sigmas + interpolation
    # noising + T5-context/CLIP-pooled conditioning end to end
    fbundle = pl.load_pipeline("tiny-flux", seed=0)
    out["flux_txt2img_32"] = np.asarray(
        pl.txt2img(
            fbundle, "a golden flux image", height=32, width=32,
            steps=2, seed=99, cfg_scale=1.0, sampler="euler",
            scheduler="simple",
        )
    )

    # Flux-Kontext editing: reference latents joined to the token
    # stream through ReferenceLatent -> KSampler -> decode
    (ref_lat,) = VAEEncode().encode(pix, fbundle)
    kpos = pl.encode_text_pooled(fbundle, ["golden kontext edit"])
    kneg = pl.encode_text_pooled(fbundle, [""])
    (kpos_r,) = ReferenceLatent().append(kpos, ref_lat)
    (kel,) = EmptyLatentImage().generate(32, 32, 1)
    (klat,) = KSampler().sample(
        fbundle, 21, 2, 1.0, "euler", "simple", kpos_r, kneg, kel,
        denoise=1.0,
    )
    (kimg,) = VAEDecode().decode(klat, fbundle)
    out["kontext_txt2img_32"] = np.asarray(kimg)

    # SD3 family: joint blocks + triple CLIP-L/G + T5 conditioning +
    # true CFG on the flow schedule
    sbundle = pl.load_pipeline("tiny-sd3", seed=0)
    out["sd3_txt2img_32"] = np.asarray(
        pl.txt2img(
            sbundle, "a golden sd3 image", height=32, width=32,
            steps=2, seed=77, cfg_scale=4.0, sampler="euler",
            scheduler="simple",
        )
    )

    # SD3.5-medium layout (MMDiT-X): the dual-attention x_block branch
    xbundle = pl.load_pipeline("tiny-sd35m", seed=0)
    out["sd35m_txt2img_32"] = np.asarray(
        pl.txt2img(
            xbundle, "a golden mmditx image", height=32, width=32,
            steps=2, seed=88, cfg_scale=4.0, sampler="euler",
            scheduler="simple",
        )
    )

    # --- round-5 surfaces ------------------------------------------------

    from comfyui_distributed_tpu.graph.nodes_controlnet import (
        ConditioningCombine,
        ConditioningSetArea,
        ConditioningSetTimestepRange,
        ConditioningZeroOut,
    )
    from comfyui_distributed_tpu.graph.nodes_core import (
        CLIPTextEncodeSDXL,
        ImageSharpen,
        InpaintModelConditioning,
    )
    from comfyui_distributed_tpu.graph.nodes_custom_sampling import (
        BasicScheduler,
        CFGGuider,
        DisableNoise,
        KSamplerSelect,
        RandomNoise,
        SamplerCustomAdvanced,
        SplitSigmas,
    )

    # custom-sampling two-stage split: stage-1 leftover-noise output,
    # its x0 prediction (the denoised extra eval), and the stage-2
    # resume — freezes the static-sigma-tuple jit path end to end
    (cel,) = EmptyLatentImage().generate(32, 32, 1)
    (csig,) = BasicScheduler().get_sigmas(bundle, "karras", 4, 1.0)
    high, low = SplitSigmas().split(csig, 2)
    (csamp,) = KSamplerSelect().get_sampler("euler")
    (cnoise,) = RandomNoise().get_noise(5)
    (cguider,) = CFGGuider().get_guider(bundle, pos_p, neg_p, 7.0)
    s1, s1_den = SamplerCustomAdvanced().sample(
        cnoise, cguider, csamp, high, cel
    )
    (cno,) = DisableNoise().get_noise()
    s2, _ = SamplerCustomAdvanced().sample(cno, cguider, csamp, low, s1)
    out["custom_stage1_32"] = np.asarray(s1["samples"])
    out["custom_stage1_denoised_32"] = np.asarray(s1_den["samples"])
    out["custom_stage2_32"] = np.asarray(s2["samples"])

    # regional conditioning: two areas + a timestep-split negative
    # through one KSampler run (composite_eps + window gates)
    pos_b = pl.encode_text_pooled(bundle, ["golden region two"])
    (area_a,) = ConditioningSetArea().set_area(pos_p, 16, 32, 0, 0, 1.0)
    (area_b,) = ConditioningSetArea().set_area(pos_b, 16, 32, 16, 0, 1.2)
    (regional,) = ConditioningCombine().combine(area_a, area_b)
    (zeroed,) = ConditioningZeroOut().zero_out(neg_p)
    (neg_early,) = ConditioningSetTimestepRange().set_range(neg_p, 0.0, 0.5)
    (neg_late,) = ConditioningSetTimestepRange().set_range(zeroed, 0.5, 1.0)
    (neg_split,) = ConditioningCombine().combine(neg_early, neg_late)
    (rlat,) = KSampler().sample(
        bundle, 13, 2, 7.0, "euler", "karras", regional, neg_split, cel,
        denoise=1.0,
    )
    out["regional_latent_32"] = np.asarray(rlat["samples"])

    # SDXL dual-prompt + size conditioning (adm Fourier embeddings)
    abundle = pl.load_pipeline("tiny-unet-adm", seed=0)
    (sdxl_cond,) = CLIPTextEncodeSDXL().encode(
        abundle, 64, 64, 8, 8, 32, 32, "golden castle", "golden stone"
    )
    aneg = pl.encode_text_pooled(abundle, [""])
    (alat,) = KSampler().sample(
        abundle, 17, 2, 7.0, "euler", "karras", sdxl_cond, aneg, cel,
        denoise=1.0,
    )
    out["sdxl_sizecond_latent_32"] = np.asarray(alat["samples"])

    # inpaint-model conditioning: 9-channel UNet + concat channels
    ibundle = pl.load_pipeline("tiny-unet-inpaint", seed=0)
    ipos = pl.encode_text_pooled(ibundle, ["golden fill"])
    ineg = pl.encode_text_pooled(ibundle, [""])
    ip, ineg2, ilat9 = InpaintModelConditioning().encode(
        ipos, ineg, ibundle, pix, jnp.asarray(imask)
    )
    (ilat9s,) = KSampler().sample(
        ibundle, 19, 2, 7.0, "euler", "karras", ip, ineg2, ilat9,
        denoise=1.0,
    )
    out["inpaint_model_latent_32"] = np.asarray(ilat9s["samples"])

    # ModelSamplingFlux resolution shift reshapes the flow grid
    import dataclasses as _dc

    shifted = _dc.replace(fbundle, flow_shift_override=2.5)
    out["flux_shift25_txt2img_32"] = np.asarray(
        pl.txt2img(
            shifted, "a golden shifted flux image", height=32, width=32,
            steps=2, seed=99, cfg_scale=1.0, sampler="euler",
            scheduler="simple",
        )
    )

    # image filter kernels (separable Gaussian + unsharp mask)
    (sharp,) = ImageSharpen().sharpen(pix, 2, 1.0, 0.8)
    out["sharpen_32"] = np.asarray(sharp)

    # round-5 guidance compositions (PAG / SAG / PerpNeg / DualCFG):
    # one guided-model eval each at a fixed (x, sigma) — the full
    # trajectories route through these same guided fns. Zero-init
    # leaves are perturbed deterministically first: with a zero
    # out_conv, eps is identically 0 and every perturbation delta
    # vanishes, making the pin vacuous.
    rng_g = np.random.default_rng(123)

    def _fix(leaf):
        arr = np.asarray(leaf)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng_g.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return leaf

    gb = pl.load_pipeline("tiny-unet", seed=0)
    gb.params = dict(
        gb.params, unet=jax.tree_util.tree_map(_fix, gb.params["unet"])
    )
    gpos = pl.encode_text(gb, ["golden guidance"])
    galt = pl.encode_text(gb, ["golden alternative"])
    gneg = pl.encode_text(gb, [""])
    gx = jnp.asarray(
        np.random.default_rng(77).normal(size=(1, 8, 8, 4)).astype(
            np.float32
        )
    ) * 5.0
    gsig = jnp.full((1,), 5.0)
    pagb = _dc.replace(gb, pag=pl.PAGSpec(scale=2.0))
    out["guided_pag_8"] = np.asarray(
        pl.guided_model(pagb, pagb.params, 4.0)(gx, gsig, (gpos, gneg))
    )
    sagb = _dc.replace(gb, sag=pl.SAGSpec(scale=0.8, blur_sigma=2.0))
    out["guided_sag_8"] = np.asarray(
        pl.guided_model(sagb, sagb.params, 4.0)(gx, gsig, (gpos, gneg))
    )
    perpb = _dc.replace(gb, perp_neg=pl.PerpNegSpec(neg_scale=1.0))
    out["guided_perpneg_8"] = np.asarray(
        pl.guided_model(perpb, perpb.params, 4.0)(
            gx, gsig, ((gpos, galt), gneg)
        )
    )
    dualb = _dc.replace(gb, dual_cfg=pl.DualCFGSpec(cfg_cond2_negative=3.0))
    out["guided_dualcfg_8"] = np.asarray(
        pl.guided_model(dualb, dualb.params, 4.0)(
            gx, gsig, ((gpos, galt), gneg)
        )
    )
    return out


def main() -> int:
    dest = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "golden", "goldens.npz",
    )
    quick = "--quick" in sys.argv[1:]
    if "--check" in sys.argv[1:]:
        atol = float(os.environ.get("CDT_GOLDEN_ATOL", 1e-3))
        want = np.load(dest)
        fresh = compute_goldens(quick=quick)
        failed = []
        if not quick:
            # reverse direction: a committed key no longer computed is
            # a silently-lost pin (quick mode legitimately computes a
            # subset, so only the full check can assert this)
            for name in sorted(set(want.files) - set(fresh)):
                print(f"{name}: STALE committed golden (no longer computed)")
                failed.append(name)
        for name in fresh:
            if name not in want.files:
                print(f"{name}: MISSING from committed goldens")
                failed.append(name)
                continue
            drift = float(np.abs(fresh[name] - want[name]).max())
            status = "ok" if drift <= atol else "DRIFTED"
            print(f"{name}: max|Δ|={drift:.3e} (atol {atol:g}) {status}")
            if drift > atol:
                failed.append(name)
        if failed:
            print(
                f"DRIFT in {failed}: end-to-end numerics changed. If "
                "intentional, re-baseline with scripts/gen_goldens.py "
                "and say so in the commit message."
            )
            return 1
        return 0

    if quick:
        print("--quick is check-only; full generation writes every key")
        return 2
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    goldens = compute_goldens()
    np.savez_compressed(dest, **goldens)
    for name, arr in goldens.items():
        print(f"{name}: {arr.shape} {arr.dtype} "
              f"mean={arr.mean():.6f} std={arr.std():.6f}")
    print(f"wrote {dest} ({os.path.getsize(dest)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
