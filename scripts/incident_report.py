#!/usr/bin/env python3
"""Offline critical-path analysis of an incident bundle (or trace).

Input: an incident debug bundle written by the IncidentManager
(telemetry/incidents.py) — or a bare trace JSONL export — with the
producing process long dead. Output: each job's wall time attributed
across the tile lifecycle's stages

    queue_wait -> grant_rtt -> sample -> encode_submit -> blend

plus `other` (wall time no instrumented stage covered), with the
DOMINANT stage named per job and in aggregate. Attribution is exact by
construction: a priority sweep assigns every instant of the job's wall
window to exactly one category (compute outranks I/O outranks waiting
when spans overlap — pipelined I/O that rides under sampling is
correctly credited to sampling), so the per-stage seconds sum to the
wall time to float precision.

Stdlib only; importable (scripts/perf_report.py reuses
`critical_path` for its --critical-path column; tests call the pieces
directly) and runnable:

    python scripts/incident_report.py incident-....json [--json]
    python scripts/incident_report.py trace.jsonl [--trace TRACE_ID]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

# Stage categories in PRIORITY order (first wins where spans overlap):
# device compute > master-side blend work > worker I/O > the pull RTT
# > admission queue wait. `other` is the uncovered remainder.
STAGE_PRIORITY = (
    "sample",
    "blend",
    "encode_submit",
    "grant_rtt",
    "queue_wait",
)
OTHER = "other"

# span -> category mapping: `attrs.stage` values from the elastic tile
# pipeline's cdt_tile_stage_seconds spans, plus the scheduler's
# admission-wait span and the pull RPC span names.
_STAGE_ATTR_MAP = {
    "sample": "sample",
    "readback": "encode_submit",
    "encode": "encode_submit",
    "submit": "encode_submit",
    "decode": "blend",
    "blend": "blend",
    "pull": "grant_rtt",
}
_NAME_MAP = {
    "sched.wait": "queue_wait",
    "tile.pull": "grant_rtt",
    "rpc.request_image": "grant_rtt",
}


def load_document(path: str) -> tuple[dict[str, Any] | None, list[dict[str, Any]]]:
    """(bundle, spans): bundle is None for a trace JSONL. A bundle is
    ONE JSON document (a dict) carrying bundle markers — a single-line
    JSONL also parses whole, so the markers (not parseability) decide:
    a one-span trace must not read as an empty bundle."""
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    if text.lstrip().startswith("{"):
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            doc = None
        if isinstance(doc, dict) and any(
            key in doc for key in ("schema", "trigger", "flight", "trace")
        ):
            return doc, bundle_spans(doc)
    spans = []
    for line_no, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            spans.append(json.loads(line))
        except json.JSONDecodeError as exc:
            raise SystemExit(f"{path}:{line_no}: bad JSON line: {exc}")
    return None, spans


def load_spans(path: str) -> list[dict[str, Any]]:
    """Spans from a trace JSONL (one span per line) or an incident
    bundle JSON (trace section + flight span_close frames, de-duped)."""
    return load_document(path)[1]


def bundle_spans(bundle: dict[str, Any]) -> list[dict[str, Any]]:
    """Merge the bundle's trace-section spans with the flight ring's
    span_close frames (the ring may hold spans of OTHER jobs the trace
    section doesn't — an incident is rarely about one job alone)."""
    spans: list[dict[str, Any]] = []
    seen: set[tuple] = set()

    def add(span: dict[str, Any]) -> None:
        key = (span.get("trace_id"), span.get("span_id"), span.get("start"))
        if key in seen:
            return
        seen.add(key)
        spans.append(span)

    trace = bundle.get("trace") or {}
    for span in trace.get("spans") or []:
        if isinstance(span, dict):
            add(span)
    flight = bundle.get("flight") or {}
    for frame in flight.get("spans") or []:
        data = frame.get("data") if isinstance(frame, dict) else None
        if isinstance(data, dict) and data.get("trace_id"):
            add(data)
    return spans


def _category(span: dict[str, Any]) -> str | None:
    attrs = span.get("attrs") or {}
    stage = attrs.get("stage")
    if stage in _STAGE_ATTR_MAP:
        return _STAGE_ATTR_MAP[stage]
    return _NAME_MAP.get(span.get("name"))


def _finished_interval(span: dict[str, Any]) -> tuple[float, float] | None:
    start = span.get("start")
    end = span.get("end")
    if end is None and span.get("duration") is not None and start is not None:
        end = float(start) + float(span["duration"])
    if start is None or end is None:
        return None
    start, end = float(start), float(end)
    if end < start:
        return None
    return start, end


def _sweep(
    window: tuple[float, float],
    by_category: dict[str, list[tuple[float, float]]],
) -> dict[str, float]:
    """Assign every instant of `window` to the highest-priority
    category covering it; the returned seconds (including OTHER) sum
    to the window width exactly. Sweep line with per-category active
    counts — O(n log n) in interval count, so bundles at the retention
    bounds (thousands of spans) analyze in milliseconds."""
    t0, t1 = window
    cat_index = {name: i for i, name in enumerate(STAGE_PRIORITY)}
    # boundary -> per-category active-count delta applied AT that time
    delta_at: dict[float, list[int]] = {}

    def deltas(t: float) -> list[int]:
        row = delta_at.get(t)
        if row is None:
            row = [0] * len(STAGE_PRIORITY)
            delta_at[t] = row
        return row

    deltas(t0)
    deltas(t1)
    for name, intervals in by_category.items():
        index = cat_index.get(name)
        if index is None:
            continue
        for start, end in intervals:
            start = min(max(start, t0), t1)
            end = min(max(end, t0), t1)
            if end <= start:
                continue
            deltas(start)[index] += 1
            deltas(end)[index] -= 1
    ordered = sorted(delta_at)
    totals = {name: 0.0 for name in STAGE_PRIORITY}
    totals[OTHER] = 0.0
    active = [0] * len(STAGE_PRIORITY)
    for left, right in zip(ordered, ordered[1:]):
        row = delta_at[left]
        for i, delta in enumerate(row):
            active[i] += delta
        if right <= left:
            continue
        assigned = OTHER
        for i, name in enumerate(STAGE_PRIORITY):
            if active[i] > 0:
                assigned = name
                break
        totals[assigned] += right - left
    return totals


def critical_path(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Per-job (per-trace) wall-time attribution + aggregate. Jobs
    with no finished spans are skipped (nothing to attribute)."""
    by_trace: dict[str, list[dict[str, Any]]] = {}
    for span in spans:
        trace_id = span.get("trace_id")
        if trace_id:
            by_trace.setdefault(str(trace_id), []).append(span)
    jobs: dict[str, Any] = {}
    agg_totals = {name: 0.0 for name in (*STAGE_PRIORITY, OTHER)}
    agg_wall = 0.0
    for trace_id, trace_spans in sorted(by_trace.items()):
        intervals: dict[str, list[tuple[float, float]]] = {}
        t0: float | None = None
        t1: float | None = None
        for span in trace_spans:
            interval = _finished_interval(span)
            if interval is None:
                continue
            t0 = interval[0] if t0 is None else min(t0, interval[0])
            t1 = interval[1] if t1 is None else max(t1, interval[1])
            category = _category(span)
            if category is not None:
                intervals.setdefault(category, []).append(interval)
        if t0 is None or t1 is None or t1 <= t0:
            continue
        totals = _sweep((t0, t1), intervals)
        wall = t1 - t0
        stages = {
            name: {
                "seconds": round(seconds, 6),
                "share": round(seconds / wall, 4),
            }
            for name, seconds in totals.items()
        }
        dominant = max(totals, key=lambda n: totals[n])
        jobs[trace_id] = {
            "wall_s": round(wall, 6),
            "stages": stages,
            "dominant": dominant,
            "dominant_share": stages[dominant]["share"],
        }
        agg_wall += wall
        for name, seconds in totals.items():
            agg_totals[name] += seconds
    aggregate = None
    if agg_wall > 0:
        agg_stages = {
            name: {
                "seconds": round(seconds, 6),
                "share": round(seconds / agg_wall, 4),
            }
            for name, seconds in agg_totals.items()
        }
        dominant = max(agg_totals, key=lambda n: agg_totals[n])
        aggregate = {
            "wall_s": round(agg_wall, 6),
            "stages": agg_stages,
            "dominant": dominant,
            "dominant_share": agg_stages[dominant]["share"],
        }
    return {"jobs": jobs, "aggregate": aggregate}


def render_text(report: dict[str, Any], bundle_meta: dict | None = None) -> str:
    lines: list[str] = []
    if bundle_meta:
        trigger = bundle_meta.get("trigger") or {}
        lines.append(
            f"incident {bundle_meta.get('id', '?')} — trigger "
            f"{trigger.get('kind', '?')}:{trigger.get('key', '')}"
        )
        lines.append("")
    columns = (*STAGE_PRIORITY, OTHER)
    header = f"{'job (trace)':32} {'wall_s':>9} {'dominant':>14}" + "".join(
        f" {name:>14}" for name in columns
    )
    lines.append(header)
    lines.append("-" * len(header))
    for trace_id, job in report["jobs"].items():
        row = (
            f"{trace_id[:32]:32} {job['wall_s']:>9.4f} "
            f"{job['dominant']:>14}"
        )
        for name in columns:
            share = job["stages"][name]["share"]
            row += f" {share * 100:>13.1f}%"
        lines.append(row)
    aggregate = report.get("aggregate")
    if aggregate:
        lines.append("")
        lines.append(
            f"aggregate: wall {aggregate['wall_s']:.4f}s, dominant stage "
            f"{aggregate['dominant']} "
            f"({aggregate['dominant_share'] * 100:.1f}%)"
        )
        for name in columns:
            stage = aggregate["stages"][name]
            lines.append(
                f"  {name:14} {stage['seconds']:>10.4f}s "
                f"({stage['share'] * 100:>5.1f}%)"
            )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "path", help="incident bundle JSON, or trace JSONL (one span/line)"
    )
    parser.add_argument(
        "--trace", default=None, help="only spans of this trace id"
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    bundle_meta = None
    try:
        bundle, spans = load_document(args.path)
        if bundle is not None:
            bundle_meta = {
                "id": bundle.get("id"), "trigger": bundle.get("trigger")
            }
    except OSError as exc:
        print(f"cannot read {args.path}: {exc}", file=sys.stderr)
        return 1
    if args.trace:
        spans = [s for s in spans if s.get("trace_id") == args.trace]
    report = critical_path(spans)
    if not report["jobs"]:
        print("no finished spans to attribute", file=sys.stderr)
        return 2
    if args.json:
        payload = dict(report)
        if bundle_meta:
            payload["bundle"] = bundle_meta
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(render_text(report, bundle_meta))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
