#!/usr/bin/env python3
"""Per-stage latency breakdown from a trace JSONL export.

Input: one span per line, as written by `Tracer.write_jsonl`
(telemetry/tracing.py) — by the chaos harness (`run_chaos_usdu(...,
trace_jsonl=...)`), or by a live server with CDT_TRACE_EXPORT_DIR set.

Output: a per-span-name latency table (count / total / mean / p50 /
p95 / p99 / max) and, for spans carrying a `tile_idx` attribute, the
reconstructed per-tile lifecycle (which stages each tile went through,
in span-clock order, and which tiles are missing stages).

`--compare OLD.jsonl` turns the report into a regression gate: the
per-stage p95 of the new trace is checked against the old one and the
process exits 3 when any shared stage regressed by more than
`--regress-pct` percent (default 25) — the bench/CI hook for "did this
PR make a stage slower".

Stdlib only; importable (tests call `build_report` / `tile_lifecycle`
/ `compare_reports` directly) and runnable:

    python scripts/perf_report.py trace.jsonl [--trace TRACE_ID] [--json]
    python scripts/perf_report.py new.jsonl --compare old.jsonl [--regress-pct 25]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

# A tile's lifecycle is complete when SOMEONE sampled it and the
# master blended it. That is the invariant of every completion path:
# master-computed (pull→sample→blend), worker-computed (worker
# pull→sample[→encode/submit], master decode→blend), requeue recovery
# (the successful attempt closes it), and the deadline fallback (the
# master samples un-pulled tiles directly). Per-tile submit spans are
# optional — the production worker flushes submits in batches without
# a tile_idx, while the chaos harness records them per tile. The ONE
# legitimate path with neither sample nor blend is a cache settle: a
# `tile.cache.hit` span on the master means the content-addressed
# cache served the tile and nobody computed it this run.
REQUIRED_ANY_ROLE = "sample"
REQUIRED_MASTER = "blend"
REQUIRED_CACHED = "cache.hit"

# Cache serving reconstruction: the master opens one `tile.cache.probe`
# span per job (attrs: `hits`) and one `tile.cache.hit` span per tile
# it settles from the cache; `tile.dispatch` spans carry the `real`
# tiles that DID burn device slots. hits / (hits + dispatched real) is
# the offline cache hit rate for the trace.
CACHE_HIT_STAGE = "cache.hit"
CACHE_PROBE_STAGE = "cache.probe"

# Scheduler queue-wait reconstruction: the admission gate opens a
# `sched.wait` span when a request is admitted (api/job_routes.py);
# the wait ends at the execution's FIRST tile pull (master- or
# worker-side). Requests that never reach a tile job (pure fan-out)
# fall back to the grant wait itself (the span's own duration).
SCHED_WAIT_SPAN = "sched.wait"
PULL_SPAN_NAMES = ("tile.pull", "rpc.request_image")

# Pipeline-overlap reconstruction: the elastic tier's staged executor
# (graph/tile_pipeline.py) dispatches the next batch's `sample` while
# the previous batch's readback/encode/submit ride the I/O stage. The
# overlap fraction — how much of the sample-stage wall ran concurrently
# with I/O-stage work — is reconstructed from the span timeline of the
# existing cdt_tile_stage_seconds spans (no new instrumentation).
SAMPLE_STAGE = "sample"
IO_STAGES = ("readback", "encode", "submit")

# Host-tax reconstruction (telemetry/profiling.py offline counterpart):
# `tile.dispatch` spans carry a `device` attr — True when a COMPILED
# program ran (device time), False/absent for the eager-stub tier
# (host time: Python ran the math). Host-bucket stages are the
# gather/encode/ship work between dispatches. The ratio
# host_ns / (host_ns + device_ns) is the host tax; a zero-device trace
# (eager chaos run) honestly reads 1.0, never NaN.
HOST_TAX_STAGES = ("readback", "encode", "decode", "submit")
_NS = 1_000_000_000


def _to_ns(seconds: Any) -> int:
    """PR-15 conservation idiom: one float->int rounding at ingest,
    integer arithmetic after — sums are exact, never float-drifty."""
    return int(round(float(seconds) * _NS))


def load_spans(path: str) -> list[dict[str, Any]]:
    spans = []
    with open(path, "r", encoding="utf-8") as fh:
        for line_no, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                spans.append(json.loads(line))
            except json.JSONDecodeError as exc:
                raise SystemExit(f"{path}:{line_no}: bad JSON line: {exc}")
    return spans


def _percentile(sorted_values: list[float], fraction: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, int(round(fraction * (len(sorted_values) - 1))))
    return sorted_values[idx]


def queue_wait_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Admission→first-pull wait per trace, aggregated.

    For every trace carrying a `sched.wait` span, the queue wait is
    the gap between that span's start (admission) and the start of the
    trace's first tile pull; when the trace recorded no pulls, the
    grant wait (the sched.wait duration) stands in. None when no
    scheduler spans exist (pre-scheduler traces stay comparable)."""
    admits: dict[Any, dict[str, Any]] = {}
    first_pull: dict[Any, float] = {}
    for span in spans:
        trace_id = span.get("trace_id")
        start = span.get("start")
        if start is None:
            continue
        if span.get("name") == SCHED_WAIT_SPAN:
            current = admits.get(trace_id)
            if current is None or start < current["start"]:
                admits[trace_id] = {
                    "start": float(start),
                    "duration": span.get("duration"),
                }
        elif span.get("name") in PULL_SPAN_NAMES:
            prev = first_pull.get(trace_id)
            if prev is None or start < prev:
                first_pull[trace_id] = float(start)
    if not admits:
        return None
    waits: list[float] = []
    for trace_id, admit in admits.items():
        pull = first_pull.get(trace_id)
        if pull is not None and pull >= admit["start"]:
            waits.append(pull - admit["start"])
        elif admit["duration"] is not None:
            waits.append(float(admit["duration"]))
    if not waits:
        return None
    waits.sort()
    return {
        "count": len(waits),
        "mean": sum(waits) / len(waits),
        "p50": _percentile(waits, 0.50),
        "p95": _percentile(waits, 0.95),
        "max": waits[-1],
    }


def _merge_intervals(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    merged: list[tuple[float, float]] = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return merged


def pipeline_overlap_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Fraction of sample-stage wall overlapped by the SAME pipeline's
    I/O-stage work (readback/encode/submit), from span start/duration
    timelines.

    Spans are grouped per (role, worker_id) before intersecting:
    participant A's submit riding concurrently with participant B's
    sample is fleet parallelism, not pipelining — counting it would let
    a fully serial per-worker loop read as overlapped just because the
    fleet is busy. 0.0 = fully serial (the pre-pipeline loop shape:
    every encode and submit sat squarely between device dispatches);
    values toward 1.0 mean each pipeline's I/O stages ride concurrently
    with its own sampling. None when no pipeline has both finished
    sample and I/O spans (nothing to overlap)."""
    sample_by: dict[tuple, list[tuple[float, float]]] = {}
    io_by: dict[tuple, list[tuple[float, float]]] = {}
    for span in spans:
        attrs = span.get("attrs") or {}
        stage = attrs.get("stage")
        start = span.get("start")
        duration = span.get("duration")
        if stage is None or start is None or duration is None:
            continue
        key = (attrs.get("role", "?"), attrs.get("worker_id") or "")
        interval = (float(start), float(start) + float(duration))
        if stage == SAMPLE_STAGE:
            sample_by.setdefault(key, []).append(interval)
        elif stage in IO_STAGES:
            io_by.setdefault(key, []).append(interval)
    sample_wall = 0.0
    overlapped = 0.0
    measured = False
    for key, sample_iv in sample_by.items():
        io_iv = io_by.get(key)
        if not io_iv:
            continue
        measured = True
        io_union = _merge_intervals(io_iv)
        sample_wall += sum(end - start for start, end in sample_iv)
        for s_start, s_end in sample_iv:
            for i_start, i_end in io_union:
                if i_start >= s_end:
                    break
                lo, hi = max(s_start, i_start), min(s_end, i_end)
                if hi > lo:
                    overlapped += hi - lo
    if not measured:
        return None
    return {
        "sample_wall": sample_wall,
        "overlapped": overlapped,
        "fraction": (overlapped / sample_wall) if sample_wall > 0 else 0.0,
    }


def batch_fill_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Cross-job continuous-batching fill ratio from the executor's
    per-dispatch spans (graph/batch_executor.py emits one
    ``tile.dispatch`` span per device dispatch with ``real`` tiles vs
    padded ``bucket`` slots). 1.0 = every device slot ran a real tile;
    lower means slots burned on wraparound padding — the utilization
    the cross-job tier exists to recover. None when no dispatch spans
    are present (the scan tier emits none)."""
    real = 0
    slots = 0
    dispatches = 0
    cross_job_dispatches = 0
    for span in spans:
        attrs = span.get("attrs") or {}
        if attrs.get("stage") != "dispatch":
            continue
        try:
            r = int(attrs.get("real", 0))
            b = int(attrs.get("bucket", 0))
        except (TypeError, ValueError):
            continue
        if b <= 0:
            continue
        dispatches += 1
        real += r
        slots += b
        if int(attrs.get("jobs", 1) or 1) > 1:
            cross_job_dispatches += 1
    if dispatches == 0:
        return None
    return {
        "dispatches": dispatches,
        "cross_job_dispatches": cross_job_dispatches,
        "real_tiles": real,
        "slots": slots,
        "fill": (real / slots) if slots > 0 else 0.0,
    }


def adapter_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Adapter-plane dispatch share from the executor's per-dispatch
    spans (graph/batch_executor.py stamps ``adapter=True`` on batches
    running the segmented per-slot LoRA patch). Reports how many
    dispatches/slots wore adapters and the fill ratio INSIDE those
    batches — personalized batches under-filling while base batches
    stay full is the adapter-thrashing signature (runbook §4p). None
    when the trace has no adapter dispatches (an adapter-less run
    stays comparable — absence is not a 0% share)."""
    dispatches = 0
    adapter_dispatches = 0
    adapter_real = 0
    adapter_slots = 0
    for span in spans:
        attrs = span.get("attrs") or {}
        if attrs.get("stage") != "dispatch":
            continue
        try:
            r = int(attrs.get("real", 0))
            b = int(attrs.get("bucket", 0))
        except (TypeError, ValueError):
            continue
        if b <= 0:
            continue
        dispatches += 1
        if attrs.get("adapter"):
            adapter_dispatches += 1
            adapter_real += r
            adapter_slots += b
    if adapter_dispatches == 0:
        return None
    return {
        "dispatches": dispatches,
        "adapter_dispatches": adapter_dispatches,
        "adapter_real_tiles": adapter_real,
        "adapter_slots": adapter_slots,
        "dispatch_share": adapter_dispatches / dispatches,
        "adapter_fill": (
            (adapter_real / adapter_slots) if adapter_slots > 0 else 0.0
        ),
    }


def cache_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Tile-cache serving rate from the master's probe/hit spans vs
    the dispatch spans: what fraction of this trace's tiles were
    settled straight from the content-addressed cache instead of
    burning a device slot. None when the trace recorded no probe (a
    cache-off run stays comparable — absence is not a 0% hit rate)."""
    probes = 0
    hits = 0
    dispatched = 0
    for span in spans:
        attrs = span.get("attrs") or {}
        stage = attrs.get("stage")
        if stage == CACHE_HIT_STAGE:
            hits += 1
        elif stage == CACHE_PROBE_STAGE:
            probes += 1
        elif stage == "dispatch":
            try:
                dispatched += int(attrs.get("real", 0) or 0)
            except (TypeError, ValueError):
                continue
    if probes == 0 and hits == 0:
        return None
    served = hits + dispatched
    return {
        "probes": probes,
        "hits": hits,
        "dispatched_tiles": dispatched,
        "hit_rate": (hits / served) if served > 0 else 0.0,
    }


def host_tax_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Device/host time split from the span stream alone.

    Device ns: dispatch spans whose `device` attr is truthy (a
    compiled program ran — graph/tile_pipeline.py and
    graph/batch_executor.py stamp the attr from the same
    ``hasattr(step, "lower")`` gate the jit decision uses). Eager
    dispatches (chaos stubs) are host work — Python executed the
    math — so they join the host side; that is what makes a
    zero-device run read host_tax = 1.0 instead of NaN. None when the
    trace has neither dispatches nor host-bucket stages (nothing to
    attribute)."""
    device_ns = 0
    eager_ns = 0
    host_ns = 0
    dispatches = 0
    device_dispatches = 0
    for span in spans:
        attrs = span.get("attrs") or {}
        stage = attrs.get("stage")
        duration = span.get("duration")
        if stage is None or duration is None:
            continue
        try:
            ns = _to_ns(duration)
        except (TypeError, ValueError):
            continue
        if stage == "dispatch":
            dispatches += 1
            if attrs.get("device"):
                device_dispatches += 1
                device_ns += ns
            else:
                eager_ns += ns
        elif stage in HOST_TAX_STAGES:
            host_ns += ns
    if dispatches == 0 and host_ns == 0:
        return None
    total_host = host_ns + eager_ns
    if device_ns <= 0:
        tax = 1.0
    else:
        tax = total_host / (total_host + device_ns)
    return {
        "dispatches": dispatches,
        "device_dispatches": device_dispatches,
        "device_ns": device_ns,
        "eager_ns": eager_ns,
        "host_ns": host_ns,
        "host_tax": tax,
    }


def host_tax_regressions(
    old_ht: dict[str, Any] | None,
    new_ht: dict[str, Any] | None,
    regress_pct: float,
) -> list[dict[str, Any]]:
    """The host-tax gate: the device-resident PRs must show the ratio
    FALLING, so growth beyond `regress_pct` percent relative fails
    --compare — host work crept back between device dispatches. Old
    tax below 1% gates on absolute growth of more than one percentage
    point (the usage_waste_share near-zero-base rule)."""
    if not old_ht or not new_ht:
        return []
    old_tax = old_ht["host_tax"]
    new_tax = new_ht["host_tax"]
    if old_tax < 0.01:
        if new_tax - old_tax <= 0.01:
            return []
        delta_pct = (new_tax - old_tax) * 100.0  # absolute points
    else:
        delta_pct = (new_tax / old_tax - 1.0) * 100.0
        if delta_pct <= regress_pct:
            return []
    return [
        {
            "stage": "host_tax",
            # shares, not seconds — old_p95/new_p95 keep the comparison
            # machinery uniform (the usage_waste_share convention)
            "old_p95": old_tax,
            "new_p95": new_tax,
            "old_share": old_tax,
            "new_share": new_tax,
            "delta_pct": delta_pct,
        }
    ]


def waterfall_report(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Per-tile lifecycle waterfall with EXACT integer-ns conservation.

    Each tile's spans (batched spans credit every tile in their
    ``batch`` attr) become an ordered sequence of stage segments on the
    span clock. Overlap is clipped with a cursor — a segment only
    credits the part past the furthest point already attributed — and
    gaps become explicit ``wait`` segments, so

        sum(stage_ns) + wait_ns == wall_ns   (exactly, per tile)

    where wall_ns is the tile's measured first-span-start to
    last-span-end. That telescoping identity is the acceptance check
    (`conserved` per tile, `all_conserved` for the run) — the PR-13
    analyzer's conservation rule at per-tile granularity."""
    segments: dict[int, list[tuple[int, int, str]]] = {}
    for span in spans:
        attrs = span.get("attrs") or {}
        tile_idx = attrs.get("tile_idx")
        stage = attrs.get("stage")
        start = span.get("start")
        duration = span.get("duration")
        if tile_idx is None or stage is None or start is None:
            continue
        if duration is None:
            continue
        try:
            start_ns = _to_ns(start)
            end_ns = start_ns + _to_ns(duration)
        except (TypeError, ValueError):
            continue
        for idx in attrs.get("batch") or [tile_idx]:
            segments.setdefault(int(idx), []).append(
                (start_ns, end_ns, str(stage))
            )
    tiles: dict[int, dict[str, Any]] = {}
    all_conserved = True
    for tile_idx in sorted(segments):
        segs = sorted(segments[tile_idx])
        first = segs[0][0]
        last = max(end for _start, end, _stage in segs)
        wall_ns = last - first
        stages: dict[str, int] = {}
        timeline: list[dict[str, Any]] = []
        wait_ns = 0
        cursor = first
        for start_ns, end_ns, stage in segs:
            if start_ns > cursor:
                gap = start_ns - cursor
                wait_ns += gap
                timeline.append(
                    {"stage": "wait", "start_ns": cursor, "ns": gap}
                )
                cursor = start_ns
            seg_start = max(cursor, start_ns)
            if end_ns > seg_start:
                credited = end_ns - seg_start
                stages[stage] = stages.get(stage, 0) + credited
                timeline.append(
                    {"stage": stage, "start_ns": seg_start, "ns": credited}
                )
                cursor = end_ns
        attributed = sum(stages.values()) + wait_ns
        conserved = attributed == wall_ns
        all_conserved = all_conserved and conserved
        tiles[tile_idx] = {
            "wall_ns": wall_ns,
            "wait_ns": wait_ns,
            "stages": stages,
            "timeline": timeline,
            "conserved": conserved,
        }
    return {"tiles": tiles, "all_conserved": all_conserved}


def render_waterfall(waterfall: dict[str, Any]) -> str:
    tiles = waterfall["tiles"]
    lines = [
        f"waterfall ({len(tiles)} tile(s), conservation "
        f"{'OK' if waterfall['all_conserved'] else 'VIOLATED'}):"
    ]
    for tile_idx, tile in tiles.items():
        flow = " -> ".join(
            f"{seg['stage']}({seg['ns'] / _NS:.4f}s)"
            for seg in tile["timeline"]
        )
        verdict = "" if tile["conserved"] else "  [NOT CONSERVED]"
        lines.append(
            f"  tile {tile_idx:>3}: wall {tile['wall_ns'] / _NS:.4f}s = "
            f"{flow}{verdict}"
        )
    return "\n".join(lines)


def usage_stats(spans: list[dict[str, Any]]) -> dict[str, Any] | None:
    """Chip-second attribution from the per-dispatch spans both
    execution tiers emit (``tile.dispatch`` with ``real``/``bucket``
    slot counts plus ``slot_jobs``/``slot_tenants`` breakdowns —
    graph/batch_executor.py and graph/tile_pipeline.py): each span's
    wall splits evenly across its bucket slots exactly like the live
    usage meter, so per-tenant/per-job shares and the waste share
    (padding + recompute slots) are reconstructable offline from a
    trace alone. ``recompute`` slots stay inside their job's slot count
    (the job caused the re-run) but count toward waste. None when no
    dispatch spans are present."""
    per_job: dict[str, float] = {}
    per_tenant: dict[str, float] = {}
    total = 0.0
    waste = 0.0
    dispatches = 0
    for span in spans:
        attrs = span.get("attrs") or {}
        if attrs.get("stage") != "dispatch":
            continue
        duration = span.get("duration")
        if duration is None:
            continue
        try:
            bucket = int(attrs.get("bucket", 0))
            real = int(attrs.get("real", 0) or 0)
            recompute = int(attrs.get("recompute", 0) or 0)
        except (TypeError, ValueError):
            continue
        if bucket <= 0:
            continue
        dispatches += 1
        share = float(duration) / bucket
        total += float(duration)
        waste += share * (max(0, bucket - real) + max(0, recompute))
        for job, n in (attrs.get("slot_jobs") or {}).items():
            try:
                per_job[str(job)] = per_job.get(str(job), 0.0) + share * int(n)
            except (TypeError, ValueError):
                continue
        for tenant, n in (attrs.get("slot_tenants") or {}).items():
            try:
                per_tenant[str(tenant)] = (
                    per_tenant.get(str(tenant), 0.0) + share * int(n)
                )
            except (TypeError, ValueError):
                continue
    if dispatches == 0 or total <= 0:
        return None
    return {
        "dispatches": dispatches,
        "total_s": total,
        "waste_s": waste,
        "waste_share": waste / total,
        "tenants": {
            t: {"chip_s": s, "share": s / total}
            for t, s in sorted(per_tenant.items())
        },
        "jobs": {
            j: {"chip_s": s, "share": s / total}
            for j, s in sorted(per_job.items())
        },
    }


def usage_regressions(
    old_usage: dict[str, Any] | None,
    new_usage: dict[str, Any] | None,
    regress_pct: float,
) -> list[dict[str, Any]]:
    """The --usage gate: waste share (padding + recompute fraction of
    dispatch chip time) growing by more than `regress_pct` percent
    relative fails --compare — device slots went back to burning
    wraparound padding or redundant recompute. Old waste below 1% is
    gated on absolute growth of more than one percentage point instead
    (relative growth on a near-zero base is noise — 0.99% -> 1.01%
    must pass, 0% -> 3% must fail)."""
    if not old_usage or not new_usage:
        return []
    old_share = old_usage["waste_share"]
    new_share = new_usage["waste_share"]
    if old_share < 0.01:
        if new_share - old_share <= 0.01:
            return []
        delta_pct = (new_share - old_share) * 100.0  # absolute points
    else:
        delta_pct = (new_share / old_share - 1.0) * 100.0
        if delta_pct <= regress_pct:
            return []
    return [
        {
            "stage": "usage_waste_share",
            # shares, not seconds — old_p95/new_p95 keep the comparison
            # machinery uniform (the critical_path convention)
            "old_p95": old_share,
            "new_p95": new_share,
            "old_share": old_share,
            "new_share": new_share,
            "delta_pct": delta_pct,
        }
    ]


def render_usage(usage: dict[str, Any]) -> str:
    lines = [
        "usage (chip-second attribution across "
        f"{usage['dispatches']} dispatch(es)): "
        f"{usage['total_s']:.4f}s total, waste share "
        f"{usage['waste_share'] * 100:.1f}%"
    ]
    for tenant, stats in usage["tenants"].items():
        lines.append(
            f"  tenant {tenant:24} {stats['chip_s']:>10.4f}s "
            f"({stats['share'] * 100:5.1f}%)"
        )
    for job, stats in usage["jobs"].items():
        lines.append(
            f"  job    {job:24} {stats['chip_s']:>10.4f}s "
            f"({stats['share'] * 100:5.1f}%)"
        )
    return "\n".join(lines)


def build_report(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Aggregate span durations per name → latency stats."""
    by_name: dict[str, list[float]] = {}
    unfinished = 0
    for span in spans:
        duration = span.get("duration")
        if duration is None:
            unfinished += 1
            continue
        by_name.setdefault(span["name"], []).append(float(duration))
    stages = {}
    for name, durations in sorted(by_name.items()):
        durations.sort()
        stages[name] = {
            "count": len(durations),
            "total": sum(durations),
            "mean": sum(durations) / len(durations),
            "p50": _percentile(durations, 0.50),
            "p95": _percentile(durations, 0.95),
            "p99": _percentile(durations, 0.99),
            "max": durations[-1],
        }
    return {
        "span_count": len(spans),
        "unfinished_spans": unfinished,
        "stages": stages,
        "queue_wait": queue_wait_stats(spans),
        "pipeline_overlap": pipeline_overlap_stats(spans),
        "batch_fill": batch_fill_stats(spans),
        "adapter": adapter_stats(spans),
        "cache": cache_stats(spans),
        "host_tax": host_tax_stats(spans),
    }


def tile_lifecycle(spans: list[dict[str, Any]]) -> dict[int, list[dict[str, Any]]]:
    """Group tile-stage spans by tile index, ordered by span start."""
    tiles: dict[int, list[dict[str, Any]]] = {}
    for span in spans:
        attrs = span.get("attrs") or {}
        tile_idx = attrs.get("tile_idx")
        stage = attrs.get("stage")
        if tile_idx is None or stage is None:
            continue
        # batched stages (pipelined grants) record one span covering
        # several tiles via the `batch` attr — credit each of them, or
        # the lifecycle of tiles 2..k in a batch would read incomplete
        for idx in attrs.get("batch") or [tile_idx]:
            tiles.setdefault(int(idx), []).append(
                {
                    "stage": stage,
                    "role": attrs.get("role", "?"),
                    "worker_id": attrs.get("worker_id"),
                    "start": span.get("start"),
                    "duration": span.get("duration"),
                    "status": span.get("status"),
                }
            )
    for stages in tiles.values():
        stages.sort(key=lambda s: (s["start"] is None, s["start"]))
    return dict(sorted(tiles.items()))


def incomplete_tiles(tiles: dict[int, list[dict[str, Any]]]) -> dict[int, str]:
    """Tiles whose recorded stages never completed: no participant
    sampled them, or the master never blended them (requeued tiles
    legitimately show extra abandoned attempts — one successful
    attempt closes the lifecycle)."""
    problems: dict[int, str] = {}
    for tile_idx, stages in tiles.items():
        seen: dict[str, set[str]] = {}
        for stage in stages:
            seen.setdefault(stage["role"], set()).add(stage["stage"])
        sampled = any(REQUIRED_ANY_ROLE in st for st in seen.values())
        blended = REQUIRED_MASTER in seen.get("master", set())
        cached = REQUIRED_CACHED in seen.get("master", set())
        if not (cached or (sampled and blended)):
            problems[tile_idx] = (
                "stages seen: "
                + "; ".join(
                    f"{role}={sorted(st)}" for role, st in sorted(seen.items())
                )
            )
    return problems


def compare_reports(
    old_report: dict[str, Any],
    new_report: dict[str, Any],
    regress_pct: float,
) -> list[dict[str, Any]]:
    """Per-stage p95 regressions of `new_report` vs `old_report`:
    stages present in BOTH whose new p95 exceeds the old by more than
    `regress_pct` percent. Stages that only exist on one side are
    skipped (new instrumentation is not a regression)."""
    regressions = []
    for name, new_stats in new_report["stages"].items():
        old_stats = old_report["stages"].get(name)
        if old_stats is None or old_stats["p95"] <= 0:
            continue
        delta_pct = (new_stats["p95"] / old_stats["p95"] - 1.0) * 100.0
        if delta_pct > regress_pct:
            regressions.append(
                {
                    "stage": name,
                    "old_p95": old_stats["p95"],
                    "new_p95": new_stats["p95"],
                    "delta_pct": delta_pct,
                }
            )
    # queue wait (admission→first pull) rides the same gate as a
    # pseudo-stage: a scheduler change that silently doubles time-to-
    # first-tile is exactly the regression this report exists to catch.
    old_wait = old_report.get("queue_wait")
    new_wait = new_report.get("queue_wait")
    if old_wait and new_wait and old_wait["p95"] > 0:
        delta_pct = (new_wait["p95"] / old_wait["p95"] - 1.0) * 100.0
        if delta_pct > regress_pct:
            regressions.append(
                {
                    "stage": "queue_wait",
                    "old_p95": old_wait["p95"],
                    "new_p95": new_wait["p95"],
                    "delta_pct": delta_pct,
                }
            )
    # pipeline overlap gates INVERTED: a DROP in the sample/IO overlap
    # fraction means the elastic pipeline lost concurrency (I/O time
    # moved back between device dispatches). delta_pct is the relative
    # drop so the same threshold applies.
    old_ov = old_report.get("pipeline_overlap")
    new_ov = new_report.get("pipeline_overlap")
    if old_ov and new_ov and old_ov["fraction"] > 0:
        drop_pct = (1.0 - new_ov["fraction"] / old_ov["fraction"]) * 100.0
        if drop_pct > regress_pct:
            regressions.append(
                {
                    "stage": "pipeline_overlap",
                    "old_p95": old_ov["fraction"],
                    "new_p95": new_ov["fraction"],
                    "delta_pct": drop_pct,
                }
            )
    # batch fill gates inverted too: a DROP in the cross-job fill
    # ratio means device slots went back to running wraparound padding
    # instead of other jobs' real tiles.
    old_bf = old_report.get("batch_fill")
    new_bf = new_report.get("batch_fill")
    if old_bf and new_bf and old_bf["fill"] > 0:
        drop_pct = (1.0 - new_bf["fill"] / old_bf["fill"]) * 100.0
        if drop_pct > regress_pct:
            regressions.append(
                {
                    "stage": "batch_fill",
                    "old_p95": old_bf["fill"],
                    "new_p95": new_bf["fill"],
                    "delta_pct": drop_pct,
                }
            )
    # adapter fill gates inverted like batch fill, but scoped to the
    # personalized batches: a DROP means adapter-wearing tiles stopped
    # sharing programs/batches (a signature or rank-bucket change that
    # splinters the segmented tier shows up exactly here).
    old_ad = old_report.get("adapter")
    new_ad = new_report.get("adapter")
    if old_ad and new_ad and old_ad["adapter_fill"] > 0:
        drop_pct = (
            1.0 - new_ad["adapter_fill"] / old_ad["adapter_fill"]
        ) * 100.0
        if drop_pct > regress_pct:
            regressions.append(
                {
                    "stage": "adapter_fill",
                    "old_p95": old_ad["adapter_fill"],
                    "new_p95": new_ad["adapter_fill"],
                    "delta_pct": drop_pct,
                }
            )
    # cache hit rate gates inverted too: a DROP means tiles the old
    # trace settled near-free from the content-addressed cache went
    # back to burning device slots (a key-schema change that silently
    # misses everything is exactly this regression).
    old_cache = old_report.get("cache")
    new_cache = new_report.get("cache")
    if old_cache and new_cache and old_cache["hit_rate"] > 0:
        drop_pct = (1.0 - new_cache["hit_rate"] / old_cache["hit_rate"]) * 100.0
        if drop_pct > regress_pct:
            regressions.append(
                {
                    "stage": "cache_hit_rate",
                    "old_p95": old_cache["hit_rate"],
                    "new_p95": new_cache["hit_rate"],
                    "delta_pct": drop_pct,
                }
            )
    # host tax gates on GROWTH: the device-resident PRs must show the
    # host share of every (host + device) nanosecond falling.
    regressions.extend(
        host_tax_regressions(
            old_report.get("host_tax"), new_report.get("host_tax"),
            regress_pct,
        )
    )
    return regressions


def render_comparison(
    regressions: list[dict[str, Any]], regress_pct: float
) -> str:
    if not regressions:
        return f"p95 comparison: no stage regressed more than {regress_pct:g}%"
    lines = [f"p95 REGRESSIONS (> {regress_pct:g}%):"]
    for item in regressions:
        if item["stage"] == "pipeline_overlap":
            lines.append(
                f"  {item['stage']:28} overlap {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (-{item['delta_pct']:.1f}%)"
            )
            continue
        if item["stage"] == "batch_fill":
            lines.append(
                f"  {item['stage']:28} fill {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (-{item['delta_pct']:.1f}%)"
            )
            continue
        if item["stage"] == "adapter_fill":
            lines.append(
                f"  {item['stage']:28} fill {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (-{item['delta_pct']:.1f}%)"
            )
            continue
        if item["stage"] == "cache_hit_rate":
            lines.append(
                f"  {item['stage']:28} hit rate {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (-{item['delta_pct']:.1f}%)"
            )
            continue
        if item["stage"] == "host_tax":
            # host SHARE of (host + device) time, unitless
            lines.append(
                f"  {item['stage']:28} tax {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (+{item['delta_pct']:.1f}%)"
            )
            continue
        if item["stage"] == "usage_waste_share":
            # waste SHARES (unitless fractions of dispatch chip time)
            lines.append(
                f"  {item['stage']:28} share {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (+{item['delta_pct']:.1f}%)"
            )
            continue
        if item["stage"].startswith("critical_path:"):
            # wall-time SHARES (unitless fractions), not p95 seconds
            lines.append(
                f"  {item['stage']:28} share {item['old_p95']:.3f} -> "
                f"{item['new_p95']:.3f} (+{item['delta_pct']:.1f}%)"
            )
            continue
        lines.append(
            f"  {item['stage']:28} {item['old_p95']:.4f}s -> "
            f"{item['new_p95']:.4f}s (+{item['delta_pct']:.1f}%)"
        )
    return "\n".join(lines)


def critical_path_report(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Wall-time stage attribution per job, reusing the offline
    incident analyzer (scripts/incident_report.py) — the same code
    path that reads a debug bundle with the process dead."""
    import incident_report

    return incident_report.critical_path(spans)


def critical_path_regressions(
    old_cp: dict[str, Any] | None,
    new_cp: dict[str, Any] | None,
    regress_pct: float,
) -> list[dict[str, Any]]:
    """Aggregate stage-share regressions: a stage whose share of total
    wall time grew by more than `regress_pct` percent (relative) —
    e.g. grant RTT creeping from 10% to 15% of wall — flagged under
    the same gate as the p95 stages. Stages below a 5% old share are
    skipped (noise on tiny denominators is not a regression)."""
    old_agg = (old_cp or {}).get("aggregate")
    new_agg = (new_cp or {}).get("aggregate")
    if not old_agg or not new_agg:
        return []
    regressions = []
    for name, new_stage in new_agg["stages"].items():
        old_stage = old_agg["stages"].get(name)
        if not old_stage or old_stage["share"] < 0.05:
            continue
        delta_pct = (new_stage["share"] / old_stage["share"] - 1.0) * 100.0
        if delta_pct > regress_pct:
            regressions.append(
                {
                    "stage": f"critical_path:{name}",
                    # shares, not seconds — old_p95/new_p95 keep the
                    # comparison machinery uniform, old_share/new_share
                    # carry the honest unit for JSON consumers, and
                    # render_comparison has a dedicated share branch
                    "old_p95": old_stage["share"],
                    "new_p95": new_stage["share"],
                    "old_share": old_stage["share"],
                    "new_share": new_stage["share"],
                    "delta_pct": delta_pct,
                }
            )
    return regressions


def render_critical_path(cp: dict[str, Any]) -> str:
    lines = ["critical path (dominant-stage share per job):"]
    for trace_id, job in cp["jobs"].items():
        lines.append(
            f"  {trace_id[:40]:40} wall {job['wall_s']:.4f}s  "
            f"dominant {job['dominant']} "
            f"({job['dominant_share'] * 100:.1f}%)"
        )
    aggregate = cp.get("aggregate")
    if aggregate:
        lines.append(
            f"  aggregate: dominant {aggregate['dominant']} "
            f"({aggregate['dominant_share'] * 100:.1f}% of "
            f"{aggregate['wall_s']:.4f}s)"
        )
    return "\n".join(lines)


def parse_slo_budgets(specs: list[str]) -> dict[str, float]:
    """``stage=seconds`` pairs (stage = a span name, or `queue_wait`)."""
    budgets: dict[str, float] = {}
    for spec in specs:
        stage, sep, value = spec.partition("=")
        if not sep:
            raise ValueError(f"--slo expects stage=seconds, got {spec!r}")
        budgets[stage.strip()] = float(value)
    return budgets


def slo_violations(
    report: dict[str, Any], budgets: dict[str, float]
) -> list[dict[str, Any]]:
    """Offline counterpart of the live burn-rate engine
    (docs/observability.md §SLO): check each budgeted stage's p95 in
    this trace against its target. A stage the trace never recorded is
    reported as `missing` (a lifecycle that skipped the instrumented
    path entirely should not pass silently)."""
    out: list[dict[str, Any]] = []
    for stage, budget in sorted(budgets.items()):
        stats = (
            report.get("queue_wait")
            if stage == "queue_wait"
            else report["stages"].get(stage)
        )
        if not stats:
            out.append({"stage": stage, "budget": budget, "missing": True})
        elif stats["p95"] > budget:
            out.append(
                {
                    "stage": stage,
                    "budget": budget,
                    "p95": stats["p95"],
                    "missing": False,
                }
            )
    return out


def render_slo(violations: list[dict[str, Any]]) -> str:
    if not violations:
        return "SLO check: every budgeted stage p95 within target"
    lines = ["SLO VIOLATIONS:"]
    for item in violations:
        if item["missing"]:
            lines.append(
                f"  {item['stage']:28} no samples in trace "
                f"(budget {item['budget']:g}s)"
            )
        else:
            lines.append(
                f"  {item['stage']:28} p95 {item['p95']:.4f}s > "
                f"budget {item['budget']:g}s"
            )
    return "\n".join(lines)


def render_text(report: dict[str, Any], tiles, problems) -> str:
    lines = []
    lines.append(
        f"spans: {report['span_count']} "
        f"(unfinished: {report['unfinished_spans']})"
    )
    lines.append("")
    header = (
        f"{'span':28} {'count':>6} {'total_s':>10} {'mean_s':>10} "
        f"{'p50_s':>10} {'p95_s':>10} {'p99_s':>10} {'max_s':>10}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for name, stats in report["stages"].items():
        lines.append(
            f"{name:28} {stats['count']:>6} {stats['total']:>10.4f} "
            f"{stats['mean']:>10.4f} {stats['p50']:>10.4f} "
            f"{stats['p95']:>10.4f} {stats['p99']:>10.4f} "
            f"{stats['max']:>10.4f}"
        )
    wait = report.get("queue_wait")
    if wait:
        lines.append("")
        lines.append(
            "queue wait (admission -> first pull): "
            f"count={wait['count']} mean={wait['mean']:.4f}s "
            f"p50={wait['p50']:.4f}s p95={wait['p95']:.4f}s "
            f"max={wait['max']:.4f}s"
        )
    overlap = report.get("pipeline_overlap")
    if overlap:
        lines.append("")
        lines.append(
            "pipeline overlap (sample wall concurrent with encode/"
            f"submit): {overlap['overlapped']:.4f}s of "
            f"{overlap['sample_wall']:.4f}s "
            f"(fraction {overlap['fraction']:.3f})"
        )
    fill = report.get("batch_fill")
    if fill:
        lines.append("")
        lines.append(
            "batch fill (real tiles / device slots across "
            f"{fill['dispatches']} dispatch(es), "
            f"{fill['cross_job_dispatches']} cross-job): "
            f"{fill['real_tiles']}/{fill['slots']} "
            f"(fill {fill['fill']:.3f})"
        )
    adapter = report.get("adapter")
    if adapter:
        lines.append("")
        lines.append(
            "adapter plane "
            f"({adapter['adapter_dispatches']}/{adapter['dispatches']} "
            f"dispatch(es) personalized, share "
            f"{adapter['dispatch_share']:.3f}): "
            f"{adapter['adapter_real_tiles']}/{adapter['adapter_slots']} "
            f"slots real (fill {adapter['adapter_fill']:.3f})"
        )
    cache = report.get("cache")
    if cache:
        lines.append("")
        lines.append(
            f"tile cache ({cache['probes']} probe(s)): "
            f"{cache['hits']} settled from cache vs "
            f"{cache['dispatched_tiles']} dispatched "
            f"(hit rate {cache['hit_rate']:.3f})"
        )
    host_tax = report.get("host_tax")
    if host_tax:
        lines.append("")
        lines.append(
            f"host tax ({host_tax['dispatches']} dispatch(es), "
            f"{host_tax['device_dispatches']} on device): "
            f"device {host_tax['device_ns'] / _NS:.4f}s, host "
            f"{(host_tax['host_ns'] + host_tax['eager_ns']) / _NS:.4f}s "
            f"(tax {host_tax['host_tax']:.3f})"
        )
    if tiles:
        lines.append("")
        lines.append(f"tile lifecycles: {len(tiles)} tile(s)")
        for tile_idx, stages in tiles.items():
            flow = " -> ".join(
                f"{s['stage']}[{s['role']}"
                + (f":{s['worker_id']}" if s.get("worker_id") else "")
                + "]"
                for s in stages
            )
            lines.append(f"  tile {tile_idx:>3}: {flow}")
        if problems:
            lines.append("")
            lines.append(f"INCOMPLETE tiles ({len(problems)}):")
            for tile_idx, detail in problems.items():
                lines.append(f"  tile {tile_idx}: {detail}")
        else:
            lines.append("  all tile lifecycles complete")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("path", help="trace JSONL file (one span per line)")
    parser.add_argument(
        "--trace", default=None, help="only spans of this trace id"
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--compare",
        default=None,
        metavar="OLD.jsonl",
        help="baseline trace JSONL; exit 3 on per-stage p95 regression",
    )
    parser.add_argument(
        "--regress-pct",
        type=float,
        default=25.0,
        help="p95 regression threshold in percent for --compare (default 25)",
    )
    parser.add_argument(
        "--critical-path",
        action="store_true",
        help="attribute each job's wall time across queue-wait/grant-"
        "RTT/sample/encode-submit/blend (scripts/incident_report.py "
        "analyzer) and name the dominant stage; with --compare, "
        "aggregate stage-share regressions join the exit-3 gate",
    )
    parser.add_argument(
        "--usage",
        action="store_true",
        help="chip-second attribution from tile.dispatch spans: "
        "per-tenant chip-second shares, per-job shares, and the waste "
        "share (padding + recompute slots); with --compare, waste-share "
        "growth beyond --regress-pct joins the exit-3 gate",
    )
    parser.add_argument(
        "--waterfall",
        action="store_true",
        help="per-tile lifecycle waterfall: ordered stage segments + "
        "explicit waits on the span clock, with EXACT integer-ns "
        "conservation (stage sums + waits == tile wall); exit 5 when "
        "any tile's attribution fails to conserve",
    )
    parser.add_argument(
        "--slo",
        action="append",
        default=[],
        metavar="STAGE=SECONDS",
        help="p95 budget per stage (repeatable; stage may be `queue_wait`); "
        "exit 4 on violation — the offline counterpart of the live "
        "burn-rate SLO engine. A --compare regression takes exit-code "
        "precedence (3); both verdicts are always printed/serialized",
    )
    args = parser.parse_args(argv)
    try:
        slo_budgets = parse_slo_budgets(args.slo)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 1

    try:
        spans = load_spans(args.path)
    except OSError as exc:
        print(f"cannot read {args.path}: {exc}", file=sys.stderr)
        return 1
    if args.trace:
        spans = [s for s in spans if s.get("trace_id") == args.trace]
    if not spans:
        print("no spans found", file=sys.stderr)
        return 1
    report = build_report(spans)
    tiles = tile_lifecycle(spans)
    problems = incomplete_tiles(tiles)

    critical = critical_path_report(spans) if args.critical_path else None
    usage = usage_stats(spans) if args.usage else None
    waterfall = waterfall_report(spans) if args.waterfall else None

    regressions = None
    if args.compare:
        try:
            old_spans = load_spans(args.compare)
        except OSError as exc:
            print(f"cannot read {args.compare}: {exc}", file=sys.stderr)
            return 1
        regressions = compare_reports(
            build_report(old_spans), report, args.regress_pct
        )
        if critical is not None:
            regressions.extend(
                critical_path_regressions(
                    critical_path_report(old_spans), critical,
                    args.regress_pct,
                )
            )
        if args.usage:
            regressions.extend(
                usage_regressions(
                    usage_stats(old_spans), usage, args.regress_pct
                )
            )

    violations = slo_violations(report, slo_budgets) if slo_budgets else None

    if args.json:
        payload = {
            "report": report,
            "tiles": {str(k): v for k, v in tiles.items()},
            "incomplete": {str(k): v for k, v in problems.items()},
        }
        if critical is not None:
            payload["critical_path"] = critical
        if usage is not None:
            payload["usage"] = usage
        if waterfall is not None:
            payload["waterfall"] = {
                "all_conserved": waterfall["all_conserved"],
                "tiles": {
                    str(k): v for k, v in waterfall["tiles"].items()
                },
            }
        if regressions is not None:
            payload["regressions"] = regressions
        if violations is not None:
            payload["slo_violations"] = violations
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(render_text(report, tiles, problems))
        if critical is not None:
            print()
            print(render_critical_path(critical))
        if usage is not None:
            print()
            print(render_usage(usage))
        if waterfall is not None:
            print()
            print(render_waterfall(waterfall))
        if regressions is not None:
            print()
            print(render_comparison(regressions, args.regress_pct))
        if violations is not None:
            print()
            print(render_slo(violations))
    if regressions:
        return 3
    if violations:
        return 4
    if waterfall is not None and not waterfall["all_conserved"]:
        return 5
    return 2 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
