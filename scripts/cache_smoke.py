#!/usr/bin/env python3
"""Tile-result-cache smoke: the CI `cache-smoke` job's driver.

One cold->warm pass per tier of the content-addressed tile cache
(docs/caching.md) asserting its load-bearing properties:

1. **near-free warm serving** — the warm re-run of an identical
   elastic request probes once, hits every tile, settles them all at
   grant time, and dispatches ZERO tiles to workers (the
   accepted-submission ledger shows every tile on the master);
2. **bit-identity, always** — the cold run, the warm run, and every
   degraded run below produce a canvas bit-identical to the
   cache-free reference. A cache may change WHO computes a tile,
   never WHAT lands on the canvas;
3. **disk tier survives restarts** — a fresh cache instance on the
   same directory (empty RAM) serves every tile from disk;
4. **corruption degrades to recompute** — flipping one byte of a
   disk entry's body makes its CRC check fail: the entry is counted
   corrupt, unlinked, recomputed, re-put — and the canvas is still
   bit-identical (a corrupt read is a miss, never a wrong canvas);
5. **cached chip-time is metered, not hidden** — the warm run's
   usage rollup shows the `cached` bucket carrying the settled tiles
   at ~zero chip-time.

Writes the combined stats JSON (uploaded as a CI artifact) to the
path given as argv[1] (default: cache-smoke.json). Exit 0 = every
assertion held. Runs on CPU with the stubbed diffusion core.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check(condition: bool, label: str, detail=None) -> None:
    if not condition:
        raise SystemExit(f"cache-smoke FAILED: {label}: {detail!r}")
    print(f"  ok: {label}")


def _assert_dispatch_free(result, n: int, label: str) -> None:
    workers = {
        k: v for k, v in result.tiles_by_worker.items() if k != "master"
    }
    check(
        all(v == 0 for v in workers.values())
        and result.tiles_by_worker["master"] == n,
        f"{label}: zero worker dispatches ({n} tiles settled on master)",
        result.tiles_by_worker,
    )


def ram_tier(baseline: np.ndarray) -> dict:
    from comfyui_distributed_tpu.cache.store import TileResultCache
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    print("RAM tier: cold populate -> warm serve")
    cache = TileResultCache(ram_mb=128)
    cold = run_chaos_usdu(seed=11, cache=cache)
    check(
        np.array_equal(baseline, cold.output),
        "cold canvas bit-identical to cache-free reference",
    )
    n = cold.cache["puts"]
    check(n > 0 and cold.cache["hits"] == 0, "cold run populated the cache",
          cold.cache)

    warm = run_chaos_usdu(seed=11, cache=cache)
    check(
        np.array_equal(baseline, warm.output),
        "warm canvas bit-identical to cache-free reference",
    )
    hits = warm.cache["hits"] - cold.cache["hits"]
    check(hits == n, "warm run: 100% probe hits", warm.cache)
    check(
        warm.cache["settled"] - cold.cache["settled"] == n,
        "warm run: every tile settled from cache at grant time",
        warm.cache,
    )
    _assert_dispatch_free(warm, n, "warm run")
    totals = warm.usage["totals"]
    check(totals["conserved"], "warm usage rollup still conserves exactly",
          totals)
    check(
        totals["cached_tiles"] - cold.usage["totals"]["cached_tiles"] == n,
        "every warm tile charged to the `cached` bucket", totals,
    )
    print(f"  info: cached bucket: {totals['cached_tiles']} tiles, "
          f"{totals['cached_ns']} ns")
    return {"tiles": n, "cold": cold.cache, "warm": warm.cache}


def disk_tier(baseline: np.ndarray) -> dict:
    from comfyui_distributed_tpu.cache.store import TileResultCache
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    print("disk tier: restart -> corrupt entry -> recompute")
    with tempfile.TemporaryDirectory(prefix="cdt-cache-smoke-") as tmp:
        disk = os.path.join(tmp, "tile-cache")

        def fresh():
            return TileResultCache(ram_mb=64, disk_dir=disk, disk_mb=64)

        cold = run_chaos_usdu(seed=11, cache=fresh())
        check(np.array_equal(baseline, cold.output),
              "disk cold canvas bit-identical")
        n = cold.cache["puts"]

        warm = run_chaos_usdu(seed=11, cache=fresh())
        check(np.array_equal(baseline, warm.output),
              "disk warm canvas bit-identical after 'restart'")
        check(
            warm.cache["hits_disk"] == n and warm.cache["hits_ram"] == 0,
            "restart: every tile served from the disk tier", warm.cache,
        )
        _assert_dispatch_free(warm, n, "disk warm run")

        victims = []
        for root, _dirs, files in os.walk(disk):
            victims += [os.path.join(root, f) for f in files
                        if f.endswith(".tile")]
        victim = sorted(victims)[0]
        blob = bytearray(open(victim, "rb").read())
        blob[-1] ^= 0xFF
        with open(victim, "wb") as fh:
            fh.write(bytes(blob))

        hurt = run_chaos_usdu(seed=11, cache=fresh())
        check(np.array_equal(baseline, hurt.output),
              "corrupt entry: canvas STILL bit-identical")
        check(hurt.cache["corrupt"] == 1,
              "corrupt entry detected by CRC and dropped", hurt.cache)
        check(
            hurt.cache["settled"] == n - 1 and hurt.cache["puts"] == 1,
            "corrupt tile recomputed and written back", hurt.cache,
        )
        return {"tiles": n, "warm": warm.cache, "corrupt": hurt.cache}


def main() -> int:
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    out_path = sys.argv[1] if len(sys.argv) > 1 else "cache-smoke.json"
    print("reference: cache-free chaos run")
    baseline = run_chaos_usdu(seed=11).output
    report = {
        "ram_tier": ram_tier(baseline),
        "disk_tier": disk_tier(baseline),
    }
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(f"cache-smoke OK; stats written to {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
