#!/usr/bin/env python3
"""Region soak: rotating SIGKILLs of shard masters and lease peers.

Two phases (CI job `region-soak` runs this and uploads the JSON report
as an artifact):

1. **quorum failover cycles** — `--cycles` in-process kill-the-active
   scenarios (resilience/chaos.run_chaos_failover) arbitrated by ONE
   shared set of quorum lease peers and ONE shared journal directory,
   so each promoted master is the active the NEXT cycle kills and the
   lease epoch must climb strictly across the whole ladder. The kill
   rotation covers both faces of the control plane: the shard master
   (after a pull, after a partial submit) and the lease peers
   themselves (a peer crashing mid-acquire before/after applying the
   proposal; a peer dead for an entire cycle — the SIGKILL'd-register
   case, survivable because any minority of dead peers still leaves an
   electing majority). Every cycle must (a) fire its crash, (b) elect
   exactly one new master through the surviving majority, (c) produce
   a canvas bit-identical to the uninterrupted baseline, and (d) prove
   fencing: the zombie's journal append raises, stale-epoch RPCs are
   rejected, and the zombie journals zero records.

2. **region cycles** — `--region-cycles` two-shard region runs
   (resilience/chaos.run_chaos_region): shard0's master dies mid-job
   and fails over through the quorum lease while shard1's job — open
   across the whole outage — completes with zero tile loss on its own
   epoch, the consistent-hash placement map never moves, and the
   autoscaler's decision ledger spans the outage with measured
   chip-second cost/benefit.

    python scripts/region_soak.py [--out region_soak.json]
        [--cycles 6] [--region-cycles 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEED = 11
N_PEERS = 3

# Rotating kill points: (name, crash plan, peer_crash mode, index of a
# peer held dead for the whole cycle). The master plans are the same
# guaranteed-to-fire store-RPC faults failover_soak uses; the peer
# faults exercise the quorum medium itself.
KILL_POINTS = [
    ("master_after_pull", "crash@store:pull:master#2", None, None),
    ("master_after_partial_submit",
     "latency(1.0)@store:pull:w1#1;latency(1.0)@store:pull:w2#1;"
     "crash@store:submit:master#1", None, None),
    ("peer_crash_mid_acquire_write_lost",
     "crash@store:pull:master#2", "before", None),
    ("peer_crash_mid_acquire_ack_lost",
     "crash@store:pull:master#2", "after", None),
    ("lease_peer_down_all_cycle", "crash@store:pull:master#2", None, 0),
]


def run_quorum_cycles(cycles: int) -> dict:
    import numpy as np

    from comfyui_distributed_tpu.durability import MemoryLeasePeer
    from comfyui_distributed_tpu.resilience.chaos import (
        run_chaos_failover,
        run_chaos_usdu,
    )

    baseline = run_chaos_usdu(seed=SEED).output
    # ONE peer set for the whole ladder: the registers carry the epoch
    # across cycles, exactly as region peers would across failovers.
    peers = [MemoryLeasePeer(f"soak-peer{i}") for i in range(N_PEERS)]
    results = []
    last_epoch = 0
    with tempfile.TemporaryDirectory(prefix="cdt-region-soak-") as journal_dir:
        for cycle in range(cycles):
            name, plan, peer_crash, dead_peer = (
                KILL_POINTS[cycle % len(KILL_POINTS)]
            )
            # rotate WHICH peer dies so every register gets its turn
            if dead_peer is not None:
                dead_peer = cycle % N_PEERS
                peers[dead_peer].crashed = True
            started = time.perf_counter()
            entry = {
                "cycle": cycle,
                "kill_point": name,
                "peer_crash": peer_crash,
                "dead_peer": dead_peer,
            }
            try:
                result = run_chaos_failover(
                    seed=SEED,
                    crash_plan=plan,
                    journal_dir=journal_dir,
                    quorum_peers=peers,
                    peer_crash=peer_crash,
                    job_id=f"soak-region-{cycle}",
                )
                identical = bool(np.array_equal(baseline, result.output))
                epoch_climbed = (
                    result.epochs[1] > result.epochs[0] > last_epoch
                )
                entry.update(
                    {
                        "crash_fired": "crash" in result.fired_kinds(),
                        "epochs": list(result.epochs),
                        "epoch_climbed": epoch_climbed,
                        "bit_identical": identical,
                        "zombie_fenced": result.zombie_fenced,
                        "stale_pull_rejected": result.stale_pull_rejected,
                        "stale_submit_rejected": result.stale_submit_rejected,
                        "zombie_journaled_records":
                            result.zombie_journaled_records,
                        "jobs_recovered": result.report["jobs_recovered"],
                        "seconds": round(time.perf_counter() - started, 2),
                    }
                )
                entry["ok"] = (
                    entry["crash_fired"]
                    and epoch_climbed
                    and identical
                    and result.zombie_fenced
                    and result.stale_pull_rejected
                    and result.stale_submit_rejected
                    and result.zombie_journaled_records == 0
                )
                last_epoch = result.epochs[1]
            except Exception as exc:  # noqa: BLE001 - reported per cycle
                entry.update(
                    {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
                )
            finally:
                if dead_peer is not None:
                    peers[dead_peer].crashed = False
            results.append(entry)
            status = "ok" if entry["ok"] else "FAIL"
            print(
                f"cycle {cycle} [{name}]: {status} "
                f"(epochs {entry.get('epochs')})"
            )
    return {
        "ok": all(r["ok"] for r in results),
        "cycles": cycles,
        "final_epoch": last_epoch,
        "peer_epochs": [
            getattr(p.read(), "epoch", None) for p in peers
        ],
        "results": results,
    }


def run_region_cycles(cycles: int) -> dict:
    import numpy as np

    from comfyui_distributed_tpu.resilience.chaos import (
        run_chaos_region,
        run_chaos_usdu,
    )

    baseline = run_chaos_usdu(seed=SEED).output
    peer_modes = [None, "before", "after"]
    results = []
    for cycle in range(cycles):
        peer_crash = peer_modes[cycle % len(peer_modes)]
        started = time.perf_counter()
        entry = {"cycle": cycle, "peer_crash": peer_crash}
        try:
            with tempfile.TemporaryDirectory(
                prefix="cdt-region-soak-shards-"
            ) as root:
                result = run_chaos_region(
                    seed=SEED,
                    journal_root=root,
                    peer_crash=peer_crash,
                )
            ups = [
                d for d in result.autoscale_decisions
                if d["action"] == "scale_up"
            ]
            entry.update(
                {
                    "shard0_bit_identical": bool(
                        np.array_equal(baseline, result.shard0.output)
                    ),
                    "shard0_epochs": list(result.shard0.epochs),
                    "shard0_zombie_fenced": result.shard0.zombie_fenced,
                    "shard1_tiles_completed": result.shard1_tiles_completed,
                    "shard1_epoch": result.shard1_epoch,
                    "placement_drift": result.placement_drift,
                    "autoscale_decisions": len(result.autoscale_decisions),
                    "scale_up_measured": bool(
                        ups and ups[0].get("measured")
                    ),
                    "seconds": round(time.perf_counter() - started, 2),
                }
            )
            entry["ok"] = (
                entry["shard0_bit_identical"]
                and result.shard0.zombie_fenced
                and result.shard0.zombie_journaled_records == 0
                and result.shard1_tiles_completed == 4
                and result.shard1_epoch == 1
                and result.placement_drift == 0
                and entry["scale_up_measured"]
            )
        except Exception as exc:  # noqa: BLE001 - reported per cycle
            entry.update(
                {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            )
        results.append(entry)
        status = "ok" if entry["ok"] else "FAIL"
        print(
            f"region cycle {cycle} [peer_crash={peer_crash}]: {status} "
            f"(drift {entry.get('placement_drift')}, "
            f"shard1 {entry.get('shard1_tiles_completed')}/4 tiles)"
        )
    return {
        "ok": all(r["ok"] for r in results),
        "cycles": cycles,
        "results": results,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="region_soak.json")
    parser.add_argument("--cycles", type=int, default=6)
    parser.add_argument(
        "--region-cycles", type=int, default=2,
        help="two-shard region runs (0 skips the phase)",
    )
    args = parser.parse_args(argv)

    quorum = run_quorum_cycles(args.cycles)
    region = (
        {"ok": True, "skipped": True}
        if args.region_cycles <= 0
        else run_region_cycles(args.region_cycles)
    )
    report = {
        "ok": quorum["ok"] and region["ok"],
        "quorum_cycles": quorum,
        "region_cycles": region,
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    passed = sum(1 for r in quorum["results"] if r.get("ok"))
    print(
        f"quorum cycles: {passed}/{quorum['cycles']} elected "
        f"bit-identical with fencing (final epoch "
        f"{quorum['final_epoch']}) -> {'OK' if quorum['ok'] else 'FAIL'}"
    )
    if not region.get("skipped"):
        rpassed = sum(1 for r in region["results"] if r.get("ok"))
        print(
            f"region cycles: {rpassed}/{region['cycles']} zero "
            f"cross-shard loss, zero placement drift -> "
            f"{'OK' if region['ok'] else 'FAIL'}"
        )
    print(f"report written to {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
