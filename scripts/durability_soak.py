#!/usr/bin/env python3
"""Durability soak: N kill-the-master crash/restart cycles + the
journaling overhead A/B.

Two phases (CI job `durability-soak` runs this and uploads the JSON
recovery report as an artifact):

1. **crash cycles** — `--cycles` in-process SIGKILL-the-master
   scenarios (resilience/chaos.run_chaos_master_crash), rotating
   through distinct kill points (after a pull, after a partial
   submit), each against a fresh journal directory. Every cycle must
   (a) actually fire its crash, (b) recover, (c) produce a canvas
   bit-identical to the uninterrupted baseline, and (d) replay
   idempotently.

2. **overhead** — the CPU tile-pipeline A/B: the standard chaos USDU
   run with and without the write-ahead seam attached
   (CDT_JOURNAL_FSYNC=0, the page-cache mode), median of `--reps`
   runs each. The journaled median must stay within `--max-overhead`
   (default 5%) of plain.

    python scripts/durability_soak.py [--out durability_soak.json]
        [--cycles 6] [--reps 3] [--max-overhead 0.05]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEED = 11

# Deterministic kill points (see tests/test_chaos_usdu.py for why each
# plan is guaranteed to fire): worker pulls delayed → the master always
# reaches its submit RPCs; pull #2 always happens on any master run.
CRASH_PLANS = [
    ("after_pull",
     "latency(1.5)@store:pull:w1#1;latency(1.5)@store:pull:w2#1;"
     "crash@store:submit:master#1"),
    ("after_partial_submit",
     "latency(1.5)@store:pull:w1#1;latency(1.5)@store:pull:w2#1;"
     "crash@store:submit:master#2"),
    ("mid_drain",
     "latency(0.3)@store:pull:master#1;crash@store:pull:master#2"),
]


def run_crash_cycles(cycles: int) -> dict:
    import numpy as np

    from comfyui_distributed_tpu.durability.recovery import (
        verify_idempotent_replay,
    )
    from comfyui_distributed_tpu.resilience.chaos import (
        run_chaos_master_crash,
        run_chaos_usdu,
    )

    baseline = run_chaos_usdu(seed=SEED).output
    results = []
    ok = True
    for cycle in range(cycles):
        name, plan = CRASH_PLANS[cycle % len(CRASH_PLANS)]
        journal_dir = tempfile.mkdtemp(prefix=f"cdt-soak-{cycle}-")
        try:
            started = time.monotonic()
            result = run_chaos_master_crash(
                seed=SEED, crash_plan=plan, journal_dir=journal_dir
            )
            elapsed = time.monotonic() - started
            identical = bool(np.array_equal(baseline, result.output))
            idempotent = verify_idempotent_replay(journal_dir)
            crashed = "crash" in result.fired_kinds()
            cycle_ok = identical and idempotent and crashed
            ok = ok and cycle_ok
            results.append(
                {
                    "cycle": cycle,
                    "scenario": name,
                    "ok": cycle_ok,
                    "crash_fired": crashed,
                    "bit_identical": identical,
                    "idempotent_replay": idempotent,
                    "elapsed_seconds": round(elapsed, 3),
                    "recovery": result.report,
                }
            )
        except Exception as exc:  # noqa: BLE001 - a cycle failure is the report
            ok = False
            results.append(
                {"cycle": cycle, "scenario": name, "ok": False,
                 "error": f"{type(exc).__name__}: {exc}"}
            )
        finally:
            shutil.rmtree(journal_dir, ignore_errors=True)
    return {"ok": ok, "cycles": cycles, "results": results}


def run_overhead(reps: int, max_overhead: float) -> dict:
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    os.environ["CDT_JOURNAL_FSYNC"] = "0"

    def timed(journal: bool) -> float:
        journal_dir = tempfile.mkdtemp(prefix="cdt-soak-ab-") if journal else None
        try:
            started = time.monotonic()
            run_chaos_usdu(
                seed=SEED, image_hw=(128, 128), journal_dir=journal_dir
            )
            return time.monotonic() - started
        finally:
            if journal_dir:
                shutil.rmtree(journal_dir, ignore_errors=True)
    # warm the jit/vmap caches once so neither arm pays first-compile
    timed(False)
    # Interleave the arms and compare MINIMA: the chaos run's wall time
    # is thread-scheduling noisy (±40% observed), and the minimum is
    # the standard noise-robust estimator for an A/B on a shared box —
    # any real journaling cost shifts the floor, scheduler noise only
    # inflates individual samples upward.
    plain: list[float] = []
    journaled: list[float] = []
    for _ in range(reps):
        plain.append(timed(False))
        journaled.append(timed(True))
    plain_min = min(plain)
    journaled_min = min(journaled)
    overhead = (journaled_min - plain_min) / plain_min if plain_min > 0 else 0.0
    return {
        "ok": overhead <= max_overhead,
        "fsync": 0,
        "plain_seconds": [round(t, 4) for t in plain],
        "journaled_seconds": [round(t, 4) for t in journaled],
        "plain_min_seconds": round(plain_min, 4),
        "journaled_min_seconds": round(journaled_min, 4),
        "overhead_fraction": round(overhead, 4),
        "max_overhead": max_overhead,
        "reps": reps,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="durability_soak.json")
    parser.add_argument("--cycles", type=int, default=6)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--max-overhead", type=float, default=0.05)
    parser.add_argument(
        "--skip-overhead", action="store_true",
        help="crash cycles only (fast CI smoke)",
    )
    args = parser.parse_args(argv)

    crash = run_crash_cycles(args.cycles)
    overhead = (
        {"ok": True, "skipped": True}
        if args.skip_overhead
        else run_overhead(args.reps, args.max_overhead)
    )
    report = {
        "ok": crash["ok"] and overhead["ok"],
        "crash_cycles": crash,
        "overhead": overhead,
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    passed = sum(1 for r in crash["results"] if r.get("ok"))
    print(
        f"crash cycles: {passed}/{crash['cycles']} recovered bit-identical "
        f"-> {'OK' if crash['ok'] else 'FAIL'}"
    )
    if not args.skip_overhead:
        print(
            f"journaling overhead (fsync=0): "
            f"{overhead['overhead_fraction'] * 100:.1f}% "
            f"(budget {overhead['max_overhead'] * 100:.0f}%) "
            f"-> {'OK' if overhead['ok'] else 'FAIL'}"
        )
    print(f"report written to {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
