#!/usr/bin/env python3
"""Profiling smoke: the CI `profile-smoke` job's driver.

One tiny elastic chaos run with the transfer ledger on, asserting the
device-time attribution plane's load-bearing properties
(docs/observability.md §Profiling):

1. **ledger populated** — the chaos run's dispatches/readbacks land in
   the process ledger (tiles, host buckets, dispatch counts nonzero);
2. **honest eager tax** — a zero-device run (CPU eager stubs) reports
   host_tax exactly 1.0, never NaN;
3. **compiled split** — a jitted GrantSampler dispatch credits
   device_ns, drops the tax below 1.0, and the integer-ns totals obey
   host_total_ns == sum(buckets);
4. **waterfall conservation, exact** — `perf_report --waterfall` on the
   exported trace attributes every tile's wall time to stages + wait
   with zero remainder (`all_conserved`; exit 5 would mean an
   attribution bug);
5. **capture round-trip** — /distributed/profile's ProfilerCapture
   start/stop works on CPU (jax.profiler), retains the capture on
   disk, and is single-flight;
6. **the compare gate fires** — a fabricated trace whose host tax grew
   past --regress-pct makes `perf_report --compare` exit 3.

Writes a combined JSON report (uploaded as a CI artifact) to the path
given as argv[1] (default: profile-smoke.json). Exit 0 = every
assertion held. Runs on CPU; the CI job forces 4 host devices.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PERF_REPORT = os.path.join(REPO, "scripts", "perf_report.py")


def check(condition: bool, label: str, detail=None) -> None:
    if not condition:
        raise SystemExit(f"profile-smoke FAILED: {label}: {detail!r}")
    print(f"  ok: {label}")


def eager_run(trace_path: str) -> dict:
    """Chaos USDU (eager stubs): ledger populated, host_tax == 1.0."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu
    from comfyui_distributed_tpu.telemetry.profiling import (
        _reset_transfer_ledger_for_tests,
        get_transfer_ledger,
    )

    print("scan tier: eager chaos run, ledger on")
    _reset_transfer_ledger_for_tests()
    run_chaos_usdu(seed=13, tile_batch=2, image_hw=(64, 96),
                   trace_jsonl=trace_path)
    totals = get_transfer_ledger().totals()
    check(totals["tiles"] > 0, "ledger counted tiles", totals)
    check(
        totals["eager_dispatches"] > 0 and totals["device_dispatches"] == 0,
        "eager stubs dispatch on the host side", totals,
    )
    check(totals["host_total_ns"] > 0, "host buckets populated", totals)
    check(
        totals["host_tax"] == 1.0,
        "zero-device run reads host_tax exactly 1.0", totals["host_tax"],
    )
    check(
        totals["host_total_ns"] == sum(totals["host_ns"].values()),
        "integer-ns bucket sum identity", totals,
    )
    return totals


def compiled_split() -> dict:
    """A jitted GrantSampler dispatch must credit device time."""
    from comfyui_distributed_tpu.graph.tile_pipeline import GrantSampler
    from comfyui_distributed_tpu.telemetry.profiling import (
        _reset_transfer_ledger_for_tests,
        get_transfer_ledger,
    )
    import jax
    import jax.numpy as jnp

    print("scan tier: jitted dispatch (device split)")
    _reset_transfer_ledger_for_tests()

    @jax.jit
    def step(params, tile, key, pos, neg, yx):
        return tile * 2.0

    sampler = GrantSampler(
        step, None, jnp.ones((3, 4, 4, 3), jnp.float32),
        jax.random.key(0), jnp.zeros((3, 2), jnp.int32), None, None,
        k_max=4, job_id="profile-jit", tenant="tenant-a",
    )
    check(sampler._device, "jit gate detected the compiled step")
    out = sampler.sample([0, 1, 2])
    sampler.collect(out)
    totals = get_transfer_ledger().totals()
    check(totals["device_ns"] > 0, "device dispatch credited device_ns",
          totals)
    check(totals["host_tax"] < 1.0, "compiled run drops the tax below 1.0",
          totals["host_tax"])
    check(
        totals["transfer"]["d2h"]["bytes"] > 0,
        "readback recorded d2h bytes", totals["transfer"],
    )
    return totals


def waterfall_gate(trace_path: str) -> dict:
    """perf_report --waterfall: exact per-tile conservation, not exit 5."""
    print("perf_report: waterfall conservation")
    proc = subprocess.run(
        [sys.executable, PERF_REPORT, trace_path, "--waterfall", "--json"],
        capture_output=True, text=True,
    )
    check(proc.returncode != 5, "waterfall conservation held (exit != 5)",
          proc.stdout[-2000:])
    check(proc.returncode in (0, 2), "perf_report ran clean",
          (proc.returncode, proc.stderr[-2000:]))
    payload = json.loads(proc.stdout)
    waterfall = payload.get("waterfall")
    check(bool(waterfall), "waterfall present in --json payload")
    check(waterfall["all_conserved"], "every tile's stages sum to wall",
          waterfall)
    check(len(waterfall["tiles"]) > 0, "waterfall reconstructed tiles",
          len(waterfall["tiles"]))
    host_tax = payload["report"].get("host_tax")
    check(
        host_tax is not None and host_tax["host_tax"] == 1.0,
        "offline host_tax agrees with the eager ledger (1.0)", host_tax,
    )
    return {"tiles": len(waterfall["tiles"]),
            "all_conserved": waterfall["all_conserved"],
            "host_tax": host_tax}


def capture_roundtrip(profile_dir: str) -> dict:
    """jax.profiler start/stop round-trip on CPU + single-flight."""
    from comfyui_distributed_tpu.telemetry.profiling import ProfilerCapture

    print("profiler capture: start/stop round-trip")
    capture = ProfilerCapture(profile_dir, max_seconds=30.0)
    started = capture.start(duration_s=10.0, tag="smoke")
    check(started.get("started") is True, "capture started", started)
    busy = capture.start(duration_s=10.0)
    check(busy.get("started") is False and busy.get("reason") == "busy",
          "second start answers busy (single-flight)", busy)
    stopped = capture.stop()
    check(stopped.get("stopped") is True, "capture stopped", stopped)
    again = capture.stop()
    check(again.get("stopped") is False, "stop is idempotent", again)
    entries = capture.captures()
    check(len(entries) == 1, "one capture retained", entries)
    check(os.path.isdir(os.path.join(profile_dir, entries[0]["id"])),
          "capture directory exists on disk", entries)
    counters = dict(capture.counters)
    check(counters["started"] == 1 and counters["stopped"] == 1
          and counters["busy"] == 1, "lifecycle counters exact", counters)
    return {"captures": entries, "counters": counters}


def _write_spans(path: str, host_s: float) -> None:
    """A minimal trace: one compiled dispatch + one readback of
    `host_s` — the host tax is host_s / (host_s + 1.0)."""
    spans = [
        {"name": "tile.dispatch", "start": 1.0, "duration": 1.0,
         "attrs": {"stage": "dispatch", "role": "master", "tile_idx": 0,
                   "real": 1, "bucket": 1, "device": True}},
        {"name": "tile.readback", "start": 2.0, "duration": host_s,
         "attrs": {"stage": "readback", "role": "master", "tile_idx": 0}},
    ]
    with open(path, "w", encoding="utf-8") as fh:
        for span in spans:
            fh.write(json.dumps(span) + "\n")


def compare_gate(workdir: str) -> dict:
    """A regressed host tax must fail --compare with exit 3."""
    print("perf_report: host-tax compare gate")
    old_path = os.path.join(workdir, "old.jsonl")
    new_path = os.path.join(workdir, "new.jsonl")
    _write_spans(old_path, host_s=0.10)   # tax ~0.091
    _write_spans(new_path, host_s=0.50)   # tax ~0.333 (+266%)
    proc = subprocess.run(
        [sys.executable, PERF_REPORT, new_path, "--compare", old_path],
        capture_output=True, text=True,
    )
    check(proc.returncode == 3, "regressed host tax exits 3",
          (proc.returncode, proc.stdout[-2000:]))
    check("host_tax" in proc.stdout, "regression names host_tax",
          proc.stdout[-2000:])
    ok = subprocess.run(
        [sys.executable, PERF_REPORT, old_path, "--compare", old_path],
        capture_output=True, text=True,
    )
    check(ok.returncode != 3, "identical traces pass the gate",
          (ok.returncode, ok.stdout[-2000:]))
    return {"regress_rc": proc.returncode, "self_rc": ok.returncode}


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "profile-smoke.json"
    trace_path = os.environ.get(
        "PROFILE_SMOKE_TRACE", os.path.join(tempfile.gettempdir(),
                                            "profile-smoke-trace.jsonl")
    )
    report: dict = {}
    report["eager_ledger"] = eager_run(trace_path)
    report["compiled_ledger"] = compiled_split()
    report["waterfall"] = waterfall_gate(trace_path)
    with tempfile.TemporaryDirectory(prefix="cdt-profile-smoke-") as tmp:
        report["capture"] = capture_roundtrip(os.path.join(tmp, "traces"))
        report["compare_gate"] = compare_gate(tmp)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
    print(f"profile-smoke OK; report written to {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
