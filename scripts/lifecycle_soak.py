#!/usr/bin/env python3
"""Lifecycle soak: concurrent-job cancels, a poison tile, and an
overload burst — the CI job for the request-lifecycle armor (ISSUE 10).

Phases (CI job `lifecycle-soak` runs this and uploads the JSON report
as an artifact):

1. **cancel cycles** — `--cycles` cancel-mid-job chaos runs
   (resilience/chaos.run_chaos_cancel) with the write-ahead journal
   attached and a live standby replica teed in. Every cycle must (a)
   settle the master with a terminal JobCancelled, (b) balance the
   refund accounting — zero leaked in-flight assignments the instant
   the cancel returns, (c) round-trip the journal (terminal drained
   state at cancel time, replica parity, idempotent replay), and (d)
   report the cancel→refund latency (the reclaim-speed number bench
   stamps as `lifecycle.cancel_latency_ms`).

2. **poison tile** — one injected payload that crashes three
   consecutive workers (each crash opening that worker's breaker at
   the harshest failure_threshold=1 setting). The tile must be
   quarantined after CDT_TILE_MAX_ATTEMPTS, the job must complete
   DEGRADED with every unaffected tile bit-identical to a clean run,
   and the pardon must leave no worker quarantined for the poison.

3. **overload burst** — a synthetic flood drives queue-wait p95 far
   over threshold on a fake clock: the brownout controller must shed
   the low-priority lanes (429s recorded in cdt_shed_total) while the
   premium lane keeps admitting with zero-wait grants.

4. **bystander invariance** — an undisturbed chaos run before and
   after the whole soak must produce bit-identical canvases: the
   armor may change WHO finishes and WHEN jobs die, never WHAT
   surviving jobs render.

    python scripts/lifecycle_soak.py [--out lifecycle_soak.json]
        [--cycles 4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEED = 11


def run_cancel_cycles(cycles: int) -> dict:
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_cancel

    results = []
    for cycle in range(cycles):
        started = time.perf_counter()
        entry: dict = {"cycle": cycle}
        try:
            with tempfile.TemporaryDirectory(
                prefix="cdt-lifecycle-soak-"
            ) as journal_dir:
                r = run_chaos_cancel(
                    seed=SEED,
                    journal_dir=journal_dir,
                    job_id=f"soak-cancel-{cycle}",
                    cancel_after=1 + (cycle % 3),
                )
            refunded = (
                r.accounting.get("pending_refunded", 0)
                + r.accounting.get("in_flight_refunded", 0)
            )
            entry.update(
                {
                    "raised": r.raised,
                    "refunded": refunded,
                    "completed_before_cancel": r.completed_before_cancel,
                    "leaked_in_flight": r.stats_after.get("in_flight", -1),
                    "leaked_pending": r.stats_after.get("queue_depth", -1),
                    "terminal_state": bool(
                        r.state_after_cancel.get("cancelled")
                        and r.state_after_cancel.get("pending") == []
                        and r.state_after_cancel.get("assigned") == {}
                    ),
                    "replica_saw_cancel": r.replica_saw_cancel,
                    "idempotent_replay": r.idempotent_replay,
                    "cancel_latency_ms": round(r.cancel_latency_ms, 3),
                    "seconds": round(time.perf_counter() - started, 2),
                }
            )
            entry["ok"] = (
                r.raised == "JobCancelled"
                and refunded > 0
                and entry["leaked_in_flight"] == 0
                and entry["leaked_pending"] == 0
                and entry["terminal_state"]
                and r.replica_saw_cancel
                and r.idempotent_replay
            )
        except Exception as exc:  # noqa: BLE001 - reported per cycle
            entry.update({"ok": False, "error": f"{type(exc).__name__}: {exc}"})
        results.append(entry)
    latencies = [
        e["cancel_latency_ms"] for e in results if "cancel_latency_ms" in e
    ]
    return {
        "cycles": results,
        "ok": all(e["ok"] for e in results),
        "cancel_latency_ms_mean": (
            round(sum(latencies) / len(latencies), 3) if latencies else None
        ),
    }


def run_poison_phase() -> dict:
    import numpy as np

    from comfyui_distributed_tpu.resilience.chaos import (
        run_chaos_poison,
        run_chaos_usdu,
    )

    entry: dict = {}
    try:
        with tempfile.TemporaryDirectory(
            prefix="cdt-lifecycle-poison-"
        ) as journal_dir:
            r = run_chaos_poison(seed=SEED, journal_dir=journal_dir)
        baseline = run_chaos_usdu(
            seed=SEED, image_hw=(96, 96), tile=48, padding=16,
            job_id="soak-poison-baseline",
        )
        y, x, th, tw = r.poison_rect
        mask = np.ones(r.output.shape, bool)
        mask[:, y : y + th, x : x + tw, :] = False
        unaffected_identical = bool(
            np.array_equal(r.output[mask], baseline.output[mask])
        )
        entry.update(
            {
                "crashed_workers": r.crashed_workers,
                "attempts_on_poison": r.attempts.get(r.poison_tile),
                "quarantined": r.quarantined,
                "pardons": r.pardons,
                "workers_healthy_after": all(
                    s["state"] == "healthy" for s in r.health_after.values()
                ),
                "unaffected_tiles_bit_identical": unaffected_identical,
            }
        )
        entry["ok"] = (
            len(r.crashed_workers) == 3
            and r.poison_tile in r.quarantined
            and entry["workers_healthy_after"]
            and unaffected_identical
        )
    except Exception as exc:  # noqa: BLE001 - reported
        entry.update({"ok": False, "error": f"{type(exc).__name__}: {exc}"})
    return entry


def run_overload_burst() -> dict:
    from comfyui_distributed_tpu.scheduler import (
        BrownoutController,
        SchedulerControl,
        SchedulerOverloaded,
    )
    from comfyui_distributed_tpu.scheduler.queue import AdmissionQueue

    clock_now = [0.0]
    clock = lambda: clock_now[0]  # noqa: E731
    queue = AdmissionQueue(
        lanes=[("interactive", 64), ("batch", 64), ("background", 64)],
        max_active=2,
        clock=clock,
    )
    brownout = BrownoutController(
        queue.lane_order, wait_p95_threshold=1.0,
        journal_p95_threshold=0.25, cooldown=0.5, clock=clock,
    )
    control = SchedulerControl(queue=queue, brownout=brownout, clock=clock)

    class Payload:
        def __init__(self, lane):
            self.lane = lane
            self.tenant = "soak"
            self.trace_id = None
            self.deadline_s = None
            self.extra = {}

    # the burst: flood queue waits far past threshold, then step time
    # (the overload keeps feeding samples each step — premium grants
    # never stop — so the starvation decay stays out of the picture)
    for _ in range(64):
        brownout.note_queue_wait(30.0)
    shed = {"background": 0, "batch": 0}
    admitted_premium = 0
    premium_waits = []
    for step in range(8):
        clock_now[0] = (step + 1) * 1.0
        for _ in range(4):
            brownout.note_queue_wait(30.0)
        for lane in ("background", "batch"):
            try:
                control.submit_payload(Payload(lane))
            except SchedulerOverloaded:
                shed[lane] += 1
        ticket = control.submit_payload(Payload("interactive"))
        admitted_premium += 1
        if ticket.queue_wait_seconds is not None:
            premium_waits.append(ticket.queue_wait_seconds)
        queue.release(ticket) if ticket.state == "granted" else None
    entry = {
        "shed": shed,
        "shed_counts": dict(brownout.shed_counts),
        "level": brownout.level,
        "admitted_premium": admitted_premium,
        "premium_wait_max": max(premium_waits) if premium_waits else None,
    }
    entry["ok"] = (
        shed["background"] > 0
        and brownout.level >= 1
        and admitted_premium == 8
        and (not premium_waits or max(premium_waits) <= 1.0)
    )
    return entry


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="lifecycle_soak.json")
    parser.add_argument("--cycles", type=int, default=4)
    args = parser.parse_args()

    import numpy as np

    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    started = time.perf_counter()
    bystander_before = run_chaos_usdu(seed=7, job_id="soak-bystander-before")

    report = {
        "cancel": run_cancel_cycles(args.cycles),
        "poison": run_poison_phase(),
        "overload": run_overload_burst(),
    }

    bystander_after = run_chaos_usdu(seed=7, job_id="soak-bystander-after")
    report["bystander_bit_identical"] = bool(
        np.array_equal(bystander_before.output, bystander_after.output)
    )
    report["seconds"] = round(time.perf_counter() - started, 1)
    report["ok"] = (
        report["cancel"]["ok"]
        and report["poison"]["ok"]
        and report["overload"]["ok"]
        and report["bystander_bit_identical"]
    )

    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, indent=2, sort_keys=True))
    if not report["ok"]:
        print("lifecycle soak FAILED", file=sys.stderr)
        return 1
    print(
        f"lifecycle soak OK: {args.cycles} cancel cycle(s), poison "
        f"quarantine, overload burst in {report['seconds']}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
