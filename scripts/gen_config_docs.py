#!/usr/bin/env python3
"""Generate docs/configuration.md from the env-knob registry.

The registry (``comfyui_distributed_tpu/utils/knob_registry.py``) is
the single source of truth; this script renders it. cdt-lint CDT005
statically enforces that every knob read in code is declared there and
that the generated doc is in sync, so a new knob lands as: read it in
code -> add a Knob(...) entry -> run this script -> commit both.

Usage:
    python scripts/gen_config_docs.py            # rewrite docs/configuration.md
    python scripts/gen_config_docs.py --check    # exit 1 if the doc is stale
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from comfyui_distributed_tpu.utils.knob_registry import KNOBS, by_subsystem  # noqa: E402

DOC_PATH = os.path.join(_REPO_ROOT, "docs", "configuration.md")

_SUBSYSTEM_TITLES = {
    "roles": "Roles & process identity",
    "liveness": "Heartbeat & liveness",
    "payloads": "Payloads & batching",
    "orchestration": "Orchestration & retries",
    "resilience": "Resilience & fault injection",
    "lifecycle": "Request lifecycle (deadlines, cancel, poison, brownout)",
    "watchdog": "Watchdog",
    "ha": "High availability (failover, push grants)",
    "region": "Region control plane (quorum lease, shards, autoscaler)",
    "incidents": "Incident plane",
    "scheduler": "Scheduler control plane",
    "durability": "Durable control plane",
    "pipeline": "Tile pipeline & compile cache",
    "telemetry": "Telemetry",
    "cache": "Tile result cache",
    "jobs": "Job store",
    "workers": "Worker lifecycle",
    "network": "Network & config",
    "tunnel": "Tunnel",
    "models": "Models",
    "ops": "Ops / kernels",
    "parallel": "Multihost parallelism",
    "graph-io": "Graph I/O directories",
    "native": "Native extension",
    "tools": "Tools & scripts",
}


def render() -> str:
    lines = [
        "# Configuration knobs",
        "",
        "<!-- GENERATED FILE - do not edit by hand. -->",
        "<!-- Source: comfyui_distributed_tpu/utils/knob_registry.py -->",
        "<!-- Regenerate: python scripts/gen_config_docs.py -->",
        "",
        f"Every `CDT_*` environment variable the codebase reads — {len(KNOBS)} knobs.",
        "Each can be set before launching the master or a worker; none require a",
        "code change. Static analysis (cdt-lint `CDT005`, see",
        "[static-analysis.md](static-analysis.md)) fails CI when a knob is read in",
        "code but missing here, so this table is complete by construction.",
        "",
    ]
    for subsystem, knobs in by_subsystem().items():
        lines.append(f"## {_SUBSYSTEM_TITLES.get(subsystem, subsystem)}")
        lines.append("")
        lines.append("| Knob | Default | Effect |")
        lines.append("|---|---|---|")
        for knob in knobs:
            lines.append(f"| `{knob.name}` | `{knob.default}` | {knob.effect} |")
        lines.append("")
    lines.append("See also: [operator-runbook.md](operator-runbook.md) for triage")
    lines.append("recipes that tune these, and [observability.md](observability.md)")
    lines.append("for the metric and event surface they influence.")
    lines.append("")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--check", action="store_true", help="verify the doc is current")
    args = parser.parse_args(argv)

    content = render()
    if args.check:
        try:
            with open(DOC_PATH, "r", encoding="utf-8") as fh:
                current = fh.read()
        except OSError:
            current = ""
        if current != content:
            print(
                "docs/configuration.md is stale; run `python scripts/gen_config_docs.py`",
                file=sys.stderr,
            )
            return 1
        print("docs/configuration.md is current")
        return 0

    with open(DOC_PATH, "w", encoding="utf-8") as fh:
        fh.write(content)
    print(f"wrote {os.path.relpath(DOC_PATH, _REPO_ROOT)} ({len(KNOBS)} knobs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
