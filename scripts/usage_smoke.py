#!/usr/bin/env python3
"""Usage-metering smoke: the CI `usage-smoke` job's driver.

One mixed-tenant chaos pass over BOTH execution tiers asserting the
attribution plane's load-bearing properties (docs/observability.md
§Usage metering):

1. **conservation, exact** — per run, attributed tenant chip-time +
   dispatch-family waste + overhead equals the measured dispatch
   chip-time to the nanosecond (`totals.conserved`), on the cross-job
   executor AND the scan-tier GrantSampler;
2. **nonzero padding on a ragged grid** — a fleet whose tile count
   doesn't fill the pow2 buckets must show chip-time in the `padding`
   waste bucket (silently attributing padded slots to tenants would be
   billing fiction);
3. **recompute waste is charged** — a preemption that loses its
   checkpoints re-runs steps, and those slots land in
   `preempt_recompute`, not on the tenant;
4. **metering never touches numerics** — every metered canvas is
   bit-identical to its solo (single-job) run;
5. **per-tenant attribution is real** — both tenants of the mixed run
   show nonzero chip-seconds, and the shares sum to ~the attributed
   fraction.

Writes the combined usage rollup JSON (uploaded as a CI artifact) to
the path given as argv[1] (default: usage-rollup.json). Exit 0 =
every assertion held. Runs on CPU; the CI job forces 4 host devices
so bucket rounding and the mesh-width chips factor are exercised.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


FLEET = [
    {
        "job_id": f"usage-xjob-{i}",
        "seed": 31 + i,
        "tenant": "tenant-a" if i % 2 == 0 else "tenant-b",
        "lane": "batch",
        "image_hw": (32, 96),  # 3 tiles each; 5 jobs = 15: ragged vs pow2
    }
    for i in range(5)
]

BATCH_SPEC = {
    "job_id": "usage-batch", "seed": 7, "tenant": "tenant-a",
    "lane": "batch", "image_hw": (32, 160),  # 5 tiles
}
PREMIUM = {
    "job_id": "usage-prem", "seed": 99, "tenant": "tenant-b",
    "image_hw": (32, 64), "after_dispatches": 2,
}


def check(condition: bool, label: str, detail=None) -> None:
    if not condition:
        raise SystemExit(f"usage-smoke FAILED: {label}: {detail!r}")
    print(f"  ok: {label}")


def xjob_mixed() -> dict:
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_xjob

    print("xjob tier: mixed-tenant ragged fleet")
    mixed = run_chaos_xjob(seed=31, jobs=FLEET)
    totals = mixed.usage["totals"]
    rollup = mixed.usage["rollup"]
    check(totals["conserved"], "conservation (xjob, exact ns identity)",
          totals)
    check(totals["dispatch_chip_ns"] > 0, "nonzero measured chip time",
          totals)
    check(
        totals["waste_ns"].get("padding", 0) > 0,
        "nonzero padding bucket on the ragged grid", totals["waste_ns"],
    )
    tenants = rollup["tenants"]
    check(
        tenants.get("tenant-a", {}).get("chip_s", 0) > 0
        and tenants.get("tenant-b", {}).get("chip_s", 0) > 0,
        "both tenants attributed nonzero chip-seconds", tenants,
    )
    check(not mixed.leaks or all(
        v["pending"] == 0 and v["assigned"] == 0
        for v in mixed.leaks.values()
    ), "zero capacity leaks", mixed.leaks)
    for spec in FLEET:
        solo = run_chaos_xjob(seed=0, jobs=[dict(spec)])
        jid = spec["job_id"]
        check(
            np.array_equal(solo.canvases[jid], mixed.canvases[jid]),
            f"canvas bit-identical to solo ({jid})",
        )
    return mixed.usage


def xjob_preempt_recompute() -> dict:
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_xjob

    print("xjob tier: preemption with dropped checkpoints (recompute)")
    r = run_chaos_xjob(
        seed=7, jobs=[dict(BATCH_SPEC)], steps=5, premium=PREMIUM,
        drop_checkpoints=True,
    )
    totals = r.usage["totals"]
    check(totals["conserved"], "conservation (xjob + recompute)", totals)
    check(r.resumes_recompute > 0, "recompute resumes fired",
          r.resumes_recompute)
    check(
        totals["waste_ns"].get("preempt_recompute", 0) > 0,
        "recompute steps charged to waste{preempt_recompute}",
        totals["waste_ns"],
    )
    solo = run_chaos_xjob(seed=0, jobs=[dict(BATCH_SPEC)], steps=5)
    check(
        np.array_equal(
            solo.canvases["usage-batch"], r.canvases["usage-batch"]
        ),
        "preempted+recomputed canvas bit-identical to solo",
    )
    return r.usage


def scan_tier() -> dict:
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    print("scan tier: elastic USDU run (batched, ragged grid)")
    r = run_chaos_usdu(seed=13, tile_batch=4, image_hw=(64, 96))
    baseline = run_chaos_usdu(seed=13, tile_batch=4, image_hw=(64, 96))
    totals = r.usage["totals"]
    check(totals["conserved"], "conservation (scan tier)", totals)
    check(totals["dispatch_chip_ns"] > 0, "nonzero scan-tier chip time",
          totals)
    check(
        np.array_equal(r.output, baseline.output),
        "scan canvas bit-identical across metered runs",
    )
    # the bucket-padding path, directly: 3 tiles through a K=4 sampler
    # pad to the 4-bucket, and the meter charges exactly one slot of
    # padding per dispatch
    from comfyui_distributed_tpu.graph.tile_pipeline import GrantSampler
    from comfyui_distributed_tpu.telemetry.usage import UsageMeter
    import jax
    import jax.numpy as jnp

    def stub(params, tile, key, pos, neg, yx):
        return tile * 2.0

    meter = UsageMeter()
    sampler = GrantSampler(
        stub, None, jnp.ones((3, 4, 4, 3), jnp.float32),
        jax.random.key(0), jnp.zeros((3, 2), jnp.int32), None, None,
        k_max=4, job_id="scan-pad", tenant="tenant-a", usage_meter=meter,
    )
    sampler.sample([0, 1, 2])
    totals_direct = meter.totals()
    check(totals_direct["conserved"], "conservation (direct GrantSampler)",
          totals_direct)
    check(
        totals_direct["waste_ns"].get("padding", 0) > 0,
        "scan-tier ragged dispatch charges the padding bucket",
        totals_direct["waste_ns"],
    )
    return r.usage


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "usage-rollup.json"
    report = {
        "xjob_mixed": xjob_mixed(),
        "xjob_preempt_recompute": xjob_preempt_recompute(),
        "scan": scan_tier(),
    }
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(f"usage-smoke OK; rollup written to {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
