#!/usr/bin/env python
"""Generate checkpoint key+shape manifests for the published model
families this framework loads (SD1.5, SDXL-base, SD2.1, Wan2.1,
UMT5-XXL, Flux).

These manifests pin sd_checkpoint.py's key schedules against *reality*
— the state-dict layout of the published checkpoints — instead of
against themselves (a schedule bug reproduces identically through
synthesize_state_dict round-trips; it cannot reproduce here).

The enumeration below is written from the TORCH side: it follows the
module construction order and parameter shapes of the original
implementations (CompVis `ldm/modules/diffusionmodules/openaimodel.py`
UNetModel, `ldm/models/autoencoder.py` AutoencoderKL, HuggingFace
`CLIPTextModel`, OpenCLIP's text transformer as packed by SGM, Wan2.1's
`WanModel`/`WanVAE`, HF `UMT5EncoderModel`) — independent of the flax
module trees and of the schedule code under test.  Strategic keys are
additionally hand-pinned in tests/models/test_checkpoint_manifests.py
against shapes published in checkpoint inspectors.

Manifests contain exactly the keys the loader consumes.  Real files
carry extra non-parameter buffers (`position_ids`, `logit_scale`,
`model_ema.*`, `alphas_cumprod`, ...) which every SD loader ignores;
they are intentionally absent.

Usage:
  python scripts/gen_reference_manifests.py
      rewrites tests/models/manifests/*.json (output is committed)
  python scripts/gen_reference_manifests.py --from-file ckpt.safetensors \
      [--family sd15|sdxl|...]
      reads the ACTUAL key+shape table of a real checkpoint file
      (safetensors header — no tensor data is loaded — or a torch
      .ckpt/.pt) and diffs it against the committed manifest, so the
      first operator machine with a real checkpoint validates these
      hand-derived layouts for free. Exit 0 = manifest confirmed
      (extra non-parameter buffers in the file are ignored, as every
      SD loader ignores them); exit 1 = divergence (missing keys or
      shape mismatches), printed per key.
"""

from __future__ import annotations

import json
import math
import os
import struct
import sys

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "models", "manifests",
)

Manifest = dict[str, list[int]]


# --- primitive emitters (torch layouts) -----------------------------------

def _norm(m: Manifest, key: str, ch: int) -> None:
    m[f"{key}.weight"] = [ch]
    m[f"{key}.bias"] = [ch]


def _conv(m: Manifest, key: str, o: int, i: int, k: int) -> None:
    m[f"{key}.weight"] = [o, i, k, k]
    m[f"{key}.bias"] = [o]


def _linear(m: Manifest, key: str, o: int, i: int, bias: bool = True) -> None:
    m[f"{key}.weight"] = [o, i]
    if bias:
        m[f"{key}.bias"] = [o]


# --- SD UNet (openai-guided-diffusion layout) ------------------------------

def _unet_resblock(m: Manifest, key: str, i: int, o: int, ted: int) -> None:
    _norm(m, f"{key}.in_layers.0", i)
    _conv(m, f"{key}.in_layers.2", o, i, 3)
    _linear(m, f"{key}.emb_layers.1", o, ted)
    _norm(m, f"{key}.out_layers.0", o)
    _conv(m, f"{key}.out_layers.3", o, o, 3)
    if i != o:
        _conv(m, f"{key}.skip_connection", o, i, 1)


def _unet_transformer(
    m: Manifest, key: str, ch: int, depth: int, ctx: int, use_linear: bool
) -> None:
    _norm(m, f"{key}.norm", ch)
    if use_linear:  # SDXL (SGM) packs proj_in/out as nn.Linear
        _linear(m, f"{key}.proj_in", ch, ch)
    else:  # SD1.x: 1x1 convs
        _conv(m, f"{key}.proj_in", ch, ch, 1)
    inner = 4 * ch
    for d in range(depth):
        tb = f"{key}.transformer_blocks.{d}"
        _norm(m, f"{tb}.norm1", ch)
        _linear(m, f"{tb}.attn1.to_q", ch, ch, bias=False)
        _linear(m, f"{tb}.attn1.to_k", ch, ch, bias=False)
        _linear(m, f"{tb}.attn1.to_v", ch, ch, bias=False)
        _linear(m, f"{tb}.attn1.to_out.0", ch, ch)
        _norm(m, f"{tb}.norm2", ch)
        _linear(m, f"{tb}.attn2.to_q", ch, ch, bias=False)
        _linear(m, f"{tb}.attn2.to_k", ch, ctx, bias=False)
        _linear(m, f"{tb}.attn2.to_v", ch, ctx, bias=False)
        _linear(m, f"{tb}.attn2.to_out.0", ch, ch)
        _norm(m, f"{tb}.norm3", ch)
        _linear(m, f"{tb}.ff.net.0.proj", inner * 2, ch)  # GEGLU
        _linear(m, f"{tb}.ff.net.2", ch, inner)
    if use_linear:
        _linear(m, f"{key}.proj_out", ch, ch)
    else:
        _conv(m, f"{key}.proj_out", ch, ch, 1)


def unet_manifest(
    model_ch: int,
    mult: tuple[int, ...],
    nres: int,
    tdepth: tuple[int, ...],
    ctx: int,
    adm: int,
    use_linear: bool,
    in_ch: int = 4,
    out_ch: int = 4,
) -> Manifest:
    m: Manifest = {}
    p = "model.diffusion_model"
    ted = model_ch * 4
    _linear(m, f"{p}.time_embed.0", ted, model_ch)
    _linear(m, f"{p}.time_embed.2", ted, ted)
    if adm:
        _linear(m, f"{p}.label_emb.0.0", ted, adm)
        _linear(m, f"{p}.label_emb.0.2", ted, ted)
    _conv(m, f"{p}.input_blocks.0.0", model_ch, in_ch, 3)

    # down path: nres resblocks (+transformer) per level, stride-2
    # conv between levels
    n = 1
    ch = model_ch
    skips = [model_ch]
    for level, mu in enumerate(mult):
        o = model_ch * mu
        for _ in range(nres):
            _unet_resblock(m, f"{p}.input_blocks.{n}.0", ch, o, ted)
            if tdepth[level] > 0:
                _unet_transformer(
                    m, f"{p}.input_blocks.{n}.1", o, tdepth[level], ctx,
                    use_linear,
                )
            ch = o
            skips.append(ch)
            n += 1
        if level != len(mult) - 1:
            _conv(m, f"{p}.input_blocks.{n}.0.op", o, o, 3)
            skips.append(o)
            n += 1

    # middle: res / transformer / res at the top width (SD1.x keeps a
    # depth-1 transformer here even though its level list ends in 0)
    top = model_ch * mult[-1]
    mid_depth = max(tdepth[-1], 1)
    _unet_resblock(m, f"{p}.middle_block.0", top, top, ted)
    _unet_transformer(m, f"{p}.middle_block.1", top, mid_depth, ctx, use_linear)
    _unet_resblock(m, f"{p}.middle_block.2", top, top, ted)

    # up path: nres+1 resblocks per level consuming skip concats,
    # nearest-upsample conv between levels
    n = 0
    ch = top
    for level, mu in reversed(list(enumerate(mult))):
        o = model_ch * mu
        for i in range(nres + 1):
            concat = ch + skips.pop()
            _unet_resblock(m, f"{p}.output_blocks.{n}.0", concat, o, ted)
            has_attn = tdepth[level] > 0
            if has_attn:
                _unet_transformer(
                    m, f"{p}.output_blocks.{n}.1", o, tdepth[level], ctx,
                    use_linear,
                )
            if level != 0 and i == nres:
                idx = 2 if has_attn else 1
                _conv(m, f"{p}.output_blocks.{n}.{idx}.conv", o, o, 3)
            ch = o
            n += 1

    _norm(m, f"{p}.out.0", model_ch)
    _conv(m, f"{p}.out.2", out_ch, model_ch, 3)
    return m


# --- SD AutoencoderKL (kl-f8) ---------------------------------------------

def _vae_resblock(m: Manifest, key: str, i: int, o: int) -> None:
    _norm(m, f"{key}.norm1", i)
    _conv(m, f"{key}.conv1", o, i, 3)
    _norm(m, f"{key}.norm2", o)
    _conv(m, f"{key}.conv2", o, o, 3)
    if i != o:
        _conv(m, f"{key}.nin_shortcut", o, i, 1)


def _vae_mid(m: Manifest, key: str, ch: int) -> None:
    _vae_resblock(m, f"{key}.block_1", ch, ch)
    _norm(m, f"{key}.attn_1.norm", ch)
    for leaf in ("q", "k", "v", "proj_out"):
        _conv(m, f"{key}.attn_1.{leaf}", ch, ch, 1)
    _vae_resblock(m, f"{key}.block_2", ch, ch)


def vae_manifest(
    base: int = 128,
    mult: tuple[int, ...] = (1, 2, 4, 4),
    nres: int = 2,
    z: int = 4,
    img_ch: int = 3,
    quant_convs: bool = True,
) -> Manifest:
    m: Manifest = {}
    p = "first_stage_model"
    _conv(m, f"{p}.encoder.conv_in", base, img_ch, 3)
    ch = base
    for level, mu in enumerate(mult):
        o = base * mu
        for i in range(nres):
            _vae_resblock(m, f"{p}.encoder.down.{level}.block.{i}", ch, o)
            ch = o
        if level != len(mult) - 1:
            _conv(m, f"{p}.encoder.down.{level}.downsample.conv", o, o, 3)
    top = base * mult[-1]
    _vae_mid(m, f"{p}.encoder.mid", top)
    _norm(m, f"{p}.encoder.norm_out", top)
    _conv(m, f"{p}.encoder.conv_out", 2 * z, top, 3)
    if quant_convs:
        _conv(m, f"{p}.quant_conv", 2 * z, 2 * z, 1)
        _conv(m, f"{p}.post_quant_conv", z, z, 1)

    _conv(m, f"{p}.decoder.conv_in", top, z, 3)
    _vae_mid(m, f"{p}.decoder.mid", top)
    ch = top
    for level, mu in reversed(list(enumerate(mult))):
        o = base * mu
        for i in range(nres + 1):
            _vae_resblock(m, f"{p}.decoder.up.{level}.block.{i}", ch, o)
            ch = o
        if level != 0:
            _conv(m, f"{p}.decoder.up.{level}.upsample.conv", o, o, 3)
    _norm(m, f"{p}.decoder.norm_out", base)
    _conv(m, f"{p}.decoder.conv_out", img_ch, base, 3)
    return m


# --- CLIP text encoders ----------------------------------------------------

def clip_text_manifest(
    prefix: str,
    width: int = 768,
    layers: int = 12,
    vocab: int = 49408,
    positions: int = 77,
) -> Manifest:
    """HF CLIPTextModel layout (SD1.x `cond_stage_model.transformer.
    text_model`, SDXL `conditioner.embedders.0.transformer.text_model`)."""
    m: Manifest = {}
    m[f"{prefix}.embeddings.token_embedding.weight"] = [vocab, width]
    m[f"{prefix}.embeddings.position_embedding.weight"] = [positions, width]
    for i in range(layers):
        sd = f"{prefix}.encoder.layers.{i}"
        _norm(m, f"{sd}.layer_norm1", width)
        for leaf in ("q_proj", "k_proj", "v_proj", "out_proj"):
            _linear(m, f"{sd}.self_attn.{leaf}", width, width)
        _norm(m, f"{sd}.layer_norm2", width)
        _linear(m, f"{sd}.mlp.fc1", 4 * width, width)
        _linear(m, f"{sd}.mlp.fc2", width, 4 * width)
    _norm(m, f"{prefix}.final_layer_norm", width)
    return m


def open_clip_text_manifest(
    prefix: str = "conditioner.embedders.1.model",
    width: int = 1280,
    layers: int = 32,
    vocab: int = 49408,
    positions: int = 77,
) -> Manifest:
    """OpenCLIP text transformer as packed in SGM/SDXL single-file
    checkpoints (bigG half): bare positional/text_projection params and
    fused attn in_proj."""
    m: Manifest = {}
    m[f"{prefix}.token_embedding.weight"] = [vocab, width]
    m[f"{prefix}.positional_embedding"] = [positions, width]
    for i in range(layers):
        sd = f"{prefix}.transformer.resblocks.{i}"
        _norm(m, f"{sd}.ln_1", width)
        m[f"{sd}.attn.in_proj_weight"] = [3 * width, width]
        m[f"{sd}.attn.in_proj_bias"] = [3 * width]
        _linear(m, f"{sd}.attn.out_proj", width, width)
        _norm(m, f"{sd}.ln_2", width)
        _linear(m, f"{sd}.mlp.c_fc", 4 * width, width)
        _linear(m, f"{sd}.mlp.c_proj", width, 4 * width)
    _norm(m, f"{prefix}.ln_final", width)
    m[f"{prefix}.text_projection"] = [width, width]
    return m


# --- Wan2.1 DiT ------------------------------------------------------------

def wan_dit_manifest(
    dim: int,
    ffn: int,
    depth: int,
    in_ch: int = 16,
    out_ch: int = 16,
    patch: tuple[int, int, int] = (1, 2, 2),
    text_dim: int = 4096,
    freq_dim: int = 256,
    i2v: bool = False,
    img_dim: int = 1280,
) -> Manifest:
    m: Manifest = {}
    pf, ph, pw = patch
    m["patch_embedding.weight"] = [dim, in_ch, pf, ph, pw]
    m["patch_embedding.bias"] = [dim]
    _linear(m, "text_embedding.0", dim, text_dim)
    _linear(m, "text_embedding.2", dim, dim)
    _linear(m, "time_embedding.0", dim, freq_dim)
    _linear(m, "time_embedding.2", dim, dim)
    _linear(m, "time_projection.1", 6 * dim, dim)
    for i in range(depth):
        sd = f"blocks.{i}"
        for attn in ("self_attn", "cross_attn"):
            for leaf in ("q", "k", "v", "o"):
                _linear(m, f"{sd}.{attn}.{leaf}", dim, dim)
            m[f"{sd}.{attn}.norm_q.weight"] = [dim]
            m[f"{sd}.{attn}.norm_k.weight"] = [dim]
        if i2v:
            _linear(m, f"{sd}.cross_attn.k_img", dim, dim)
            _linear(m, f"{sd}.cross_attn.v_img", dim, dim)
            m[f"{sd}.cross_attn.norm_k_img.weight"] = [dim]
        _norm(m, f"{sd}.norm3", dim)
        _linear(m, f"{sd}.ffn.0", ffn, dim)
        _linear(m, f"{sd}.ffn.2", dim, ffn)
        m[f"{sd}.modulation"] = [1, 6, dim]
    if i2v:
        # MLPProj: LayerNorm(in), Linear(in, in), GELU, Linear(in, out),
        # LayerNorm(out)
        _norm(m, "img_emb.proj.0", img_dim)
        _linear(m, "img_emb.proj.1", img_dim, img_dim)
        _linear(m, "img_emb.proj.3", dim, img_dim)
        _norm(m, "img_emb.proj.4", dim)
    _linear(m, "head.head", out_ch * pf * ph * pw, dim)
    m["head.modulation"] = [1, 2, dim]
    return m


# --- Wan2.1 causal video VAE ----------------------------------------------

def _wan_conv3(m: Manifest, key: str, o: int, i: int, kt: int, ks: int) -> None:
    m[f"{key}.weight"] = [o, i, kt, ks, ks]
    m[f"{key}.bias"] = [o]


def _wan_resblock(m: Manifest, key: str, i: int, o: int) -> None:
    m[f"{key}.residual.0.gamma"] = [i, 1, 1, 1]
    _wan_conv3(m, f"{key}.residual.2", o, i, 3, 3)
    m[f"{key}.residual.3.gamma"] = [o, 1, 1, 1]
    _wan_conv3(m, f"{key}.residual.6", o, o, 3, 3)
    if i != o:
        _wan_conv3(m, f"{key}.shortcut", o, i, 1, 1)


def _wan_attn(m: Manifest, key: str, ch: int) -> None:
    m[f"{key}.norm.gamma"] = [ch, 1, 1]
    _conv(m, f"{key}.to_qkv", 3 * ch, ch, 1)
    _conv(m, f"{key}.proj", ch, ch, 1)


def wan_vae_manifest(
    base: int = 96,
    mult: tuple[int, ...] = (1, 2, 4, 4),
    nres: int = 2,
    z: int = 16,
    temporal_down: tuple[bool, ...] = (False, True, True),
) -> Manifest:
    m: Manifest = {}
    dims = [base * u for u in (1,) + tuple(mult)]
    _wan_conv3(m, "encoder.conv1", dims[0], 3, 3, 3)
    idx = 0
    ch = dims[0]
    for level in range(len(mult)):
        o = dims[level + 1]
        for _ in range(nres):
            _wan_resblock(m, f"encoder.downsamples.{idx}", ch, o)
            ch = o
            idx += 1
        if level != len(mult) - 1:
            _conv(m, f"encoder.downsamples.{idx}.resample.1", o, o, 3)
            if temporal_down[level]:
                _wan_conv3(m, f"encoder.downsamples.{idx}.time_conv", o, o, 3, 1)
            idx += 1
    top = dims[-1]
    _wan_resblock(m, "encoder.middle.0", top, top)
    _wan_attn(m, "encoder.middle.1", top)
    _wan_resblock(m, "encoder.middle.2", top, top)
    m["encoder.head.0.gamma"] = [top, 1, 1, 1]
    _wan_conv3(m, "encoder.head.2", 2 * z, top, 3, 3)
    _wan_conv3(m, "conv1", 2 * z, 2 * z, 1, 1)
    _wan_conv3(m, "conv2", z, z, 1, 1)

    rev = tuple(reversed(mult))
    ddims = [base * u for u in (rev[0],) + rev]
    temporal_up = tuple(reversed(temporal_down))
    _wan_conv3(m, "decoder.conv1", ddims[0], z, 3, 3)
    top = ddims[0]
    _wan_resblock(m, "decoder.middle.0", top, top)
    _wan_attn(m, "decoder.middle.1", top)
    _wan_resblock(m, "decoder.middle.2", top, top)
    idx = 0
    ch = ddims[0]
    for level in range(len(mult)):
        o = ddims[level + 1]
        for _ in range(nres + 1):
            _wan_resblock(m, f"decoder.upsamples.{idx}", ch, o)
            ch = o
            idx += 1
        if level != len(mult) - 1:
            # upsample Resample halves channels in its spatial conv
            _conv(m, f"decoder.upsamples.{idx}.resample.1", o // 2, o, 3)
            if temporal_up[level]:
                _wan_conv3(m, f"decoder.upsamples.{idx}.time_conv", 2 * o, o, 3, 1)
            idx += 1
            ch = o // 2
    m["decoder.head.0.gamma"] = [ddims[-1], 1, 1, 1]
    _wan_conv3(m, "decoder.head.2", 3, ddims[-1], 3, 3)
    return m


# --- UMT5 encoder ----------------------------------------------------------

def umt5_encoder_manifest(
    d_model: int = 4096,
    d_ff: int = 10240,
    layers: int = 24,
    heads: int = 64,
    d_kv: int = 64,
    vocab: int = 256384,
    buckets: int = 32,
    per_layer_bias: bool = True,
) -> Manifest:
    m: Manifest = {}
    inner = heads * d_kv
    m["shared.weight"] = [vocab, d_model]
    for i in range(layers):
        sd = f"encoder.block.{i}"
        m[f"{sd}.layer.0.layer_norm.weight"] = [d_model]
        for leaf in ("q", "k", "v"):
            m[f"{sd}.layer.0.SelfAttention.{leaf}.weight"] = [inner, d_model]
        m[f"{sd}.layer.0.SelfAttention.o.weight"] = [d_model, inner]
        # UMT5: per-layer relative position bias; vanilla T5 v1.1 (the
        # Flux text encoder) has it on block 0 only
        if per_layer_bias or i == 0:
            m[f"{sd}.layer.0.SelfAttention.relative_attention_bias.weight"] = [
                buckets, heads,
            ]
        m[f"{sd}.layer.1.layer_norm.weight"] = [d_model]
        m[f"{sd}.layer.1.DenseReluDense.wi_0.weight"] = [d_ff, d_model]
        m[f"{sd}.layer.1.DenseReluDense.wi_1.weight"] = [d_ff, d_model]
        m[f"{sd}.layer.1.DenseReluDense.wo.weight"] = [d_model, d_ff]
    m["encoder.final_layer_norm.weight"] = [d_model]
    return m


# --- Flux image MMDiT (black-forest-labs flux layout) ----------------------

def flux_dit_manifest(
    hidden: int = 3072,
    double: int = 19,
    single: int = 38,
    heads: int = 24,
    ctx: int = 4096,
    vec: int = 768,
    mlp_ratio: float = 4.0,
    in_dim: int = 64,        # 16 latent channels x 2x2 patch
    time_dim: int = 256,
    guidance: bool = True,
) -> Manifest:
    """flux1-dev/schnell.safetensors transformer keys, following the
    original module construction (flux/model.py Flux + modules/layers):
    MLPEmbedders, 19 DoubleStreamBlocks, 38 SingleStreamBlocks,
    LastLayer. Per-head RMS q/k norms are stored as `.scale` (not
    `.weight`)."""
    m: Manifest = {}
    mlp = int(hidden * mlp_ratio)
    hd = hidden // heads
    _linear(m, "img_in", hidden, in_dim)
    _linear(m, "txt_in", hidden, ctx)
    _linear(m, "time_in.in_layer", hidden, time_dim)
    _linear(m, "time_in.out_layer", hidden, hidden)
    _linear(m, "vector_in.in_layer", hidden, vec)
    _linear(m, "vector_in.out_layer", hidden, hidden)
    if guidance:
        _linear(m, "guidance_in.in_layer", hidden, time_dim)
        _linear(m, "guidance_in.out_layer", hidden, hidden)
    for i in range(double):
        sd = f"double_blocks.{i}"
        for s in ("img", "txt"):
            _linear(m, f"{sd}.{s}_mod.lin", 6 * hidden, hidden)
            _linear(m, f"{sd}.{s}_attn.qkv", 3 * hidden, hidden)
            m[f"{sd}.{s}_attn.norm.query_norm.scale"] = [hd]
            m[f"{sd}.{s}_attn.norm.key_norm.scale"] = [hd]
            _linear(m, f"{sd}.{s}_attn.proj", hidden, hidden)
            _linear(m, f"{sd}.{s}_mlp.0", mlp, hidden)
            _linear(m, f"{sd}.{s}_mlp.2", hidden, mlp)
    for i in range(single):
        sd = f"single_blocks.{i}"
        _linear(m, f"{sd}.modulation.lin", 3 * hidden, hidden)
        _linear(m, f"{sd}.linear1", 3 * hidden + mlp, hidden)
        _linear(m, f"{sd}.linear2", hidden, hidden + mlp)
        m[f"{sd}.norm.query_norm.scale"] = [hd]
        m[f"{sd}.norm.key_norm.scale"] = [hd]
    _linear(m, "final_layer.adaLN_modulation.1", 2 * hidden, hidden)
    _linear(m, "final_layer.linear", in_dim, hidden)
    return m


def sd3_dit_manifest(
    depth: int = 24,
    hidden: int | None = None,
    heads: int | None = None,
    qk_norm: bool = False,
    ctx: int = 4096,
    pooled: int = 2048,
    pos_max: int = 192,
    in_ch: int = 16,
    p: int = 2,
    time_dim: int = 256,
    dual_attn_blocks: int = 0,
) -> Manifest:
    """SD3/SD3.5 MMDiT under model.diffusion_model.* (the single-file
    layout), following the original mmdit.py construction: conv
    patchify, learned pos table, joint_blocks with a pre_only final
    context side, SD3.5's per-head ln_q/ln_k when qk_norm, and
    SD3.5-medium's MMDiT-X attn2 branch (9-way x adaLN) in the first
    dual_attn_blocks x_blocks."""
    hidden = hidden if hidden is not None else 64 * depth
    heads = heads if heads is not None else depth
    hd = hidden // heads
    mlp = 4 * hidden
    pfx = "model.diffusion_model."
    m: Manifest = {}
    m[f"{pfx}x_embedder.proj.weight"] = [hidden, in_ch, p, p]
    m[f"{pfx}x_embedder.proj.bias"] = [hidden]
    m[f"{pfx}pos_embed"] = [1, pos_max * pos_max, hidden]
    _linear(m, f"{pfx}context_embedder", hidden, ctx)
    _linear(m, f"{pfx}t_embedder.mlp.0", hidden, time_dim)
    _linear(m, f"{pfx}t_embedder.mlp.2", hidden, hidden)
    _linear(m, f"{pfx}y_embedder.mlp.0", hidden, pooled)
    _linear(m, f"{pfx}y_embedder.mlp.2", hidden, hidden)
    for i in range(depth):
        sd = f"{pfx}joint_blocks.{i}"
        pre = i == depth - 1
        dual = i < dual_attn_blocks
        for tb in ("context_block", "x_block"):
            _linear(m, f"{sd}.{tb}.attn.qkv", 3 * hidden, hidden)
            if qk_norm:
                m[f"{sd}.{tb}.attn.ln_q.weight"] = [hd]
                m[f"{sd}.{tb}.attn.ln_k.weight"] = [hd]
            if pre and tb == "context_block":
                n_mod = 2
            elif dual and tb == "x_block":
                n_mod = 9
            else:
                n_mod = 6
            _linear(m, f"{sd}.{tb}.adaLN_modulation.1", n_mod * hidden, hidden)
            if pre and tb == "context_block":
                continue
            _linear(m, f"{sd}.{tb}.attn.proj", hidden, hidden)
            _linear(m, f"{sd}.{tb}.mlp.fc1", mlp, hidden)
            _linear(m, f"{sd}.{tb}.mlp.fc2", hidden, mlp)
            if dual and tb == "x_block":
                _linear(m, f"{sd}.x_block.attn2.qkv", 3 * hidden, hidden)
                _linear(m, f"{sd}.x_block.attn2.proj", hidden, hidden)
                if qk_norm:
                    m[f"{sd}.x_block.attn2.ln_q.weight"] = [hd]
                    m[f"{sd}.x_block.attn2.ln_k.weight"] = [hd]
    _linear(m, f"{pfx}final_layer.adaLN_modulation.1", 2 * hidden, hidden)
    _linear(m, f"{pfx}final_layer.linear", p * p * in_ch, hidden)
    return m


def flux_ae_manifest() -> Manifest:
    """ae.safetensors: SD AutoencoderKL architecture with 16-channel
    latents, BARE encoder./decoder. keys, and no 1x1 quant convs."""
    nested = vae_manifest(z=16, quant_convs=False)
    return {k.split(".", 1)[1]: v for k, v in nested.items()}


# --- assembly --------------------------------------------------------------

def build_all() -> dict[str, Manifest]:
    sd15: Manifest = {}
    sd15.update(
        unet_manifest(
            320, (1, 2, 4, 4), 2, (1, 1, 1, 0), 768, adm=0, use_linear=False
        )
    )
    sd15.update(vae_manifest())
    sd15.update(clip_text_manifest("cond_stage_model.transformer.text_model"))

    sdxl: Manifest = {}
    sdxl.update(
        unet_manifest(
            320, (1, 2, 4), 2, (0, 2, 10), 2048, adm=2816, use_linear=True
        )
    )
    sdxl.update(vae_manifest())
    sdxl.update(
        clip_text_manifest("conditioner.embedders.0.transformer.text_model")
    )
    sdxl.update(open_clip_text_manifest())

    # SD2.1 (768-v and base share the layout): SD1.x UNet topology with
    # context 1024 + linear transformer projections, SD VAE, OpenCLIP
    # ViT-H text tower under cond_stage_model.model.*
    sd21: Manifest = {}
    sd21.update(
        unet_manifest(
            320, (1, 2, 4, 4), 2, (1, 1, 1, 0), 1024, adm=0, use_linear=True
        )
    )
    sd21.update(vae_manifest())
    sd21.update(
        open_clip_text_manifest(
            prefix="cond_stage_model.model", width=1024, layers=24
        )
    )

    return {
        "sd15": sd15,
        "sdxl": sdxl,
        "sd21": sd21,
        "wan21_1_3b_dit": wan_dit_manifest(1536, 8960, 30),
        "wan21_14b_dit": wan_dit_manifest(5120, 13824, 40),
        "wan21_14b_i2v_dit": wan_dit_manifest(
            5120, 13824, 40, in_ch=36, i2v=True
        ),
        "wan21_vae": wan_vae_manifest(),
        "umt5_xxl_encoder": umt5_encoder_manifest(),
        "flux1_dev": flux_dit_manifest(guidance=True),
        "flux1_schnell": flux_dit_manifest(guidance=False),
        "flux_ae": flux_ae_manifest(),
        "t5_xxl_encoder": umt5_encoder_manifest(
            vocab=32128, per_layer_bias=False
        ),
        "sd3_medium_dit": sd3_dit_manifest(depth=24, qk_norm=False),
        "sd35_large_dit": sd3_dit_manifest(
            depth=38, hidden=2432, heads=38, qk_norm=True
        ),
        "sd35_medium_dit": sd3_dit_manifest(
            depth=24, qk_norm=True, pos_max=384, dual_attn_blocks=13
        ),
        "sd3_vae": vae_manifest(z=16, quant_convs=False),
    }


# --- --from-file: validate a manifest against a real checkpoint -----------

def read_safetensors_shapes(path: str) -> Manifest:
    """Key -> shape from a .safetensors file by reading ONLY the JSON
    header (8-byte LE header length + header), never the tensor data —
    a 14B checkpoint validates in milliseconds."""
    with open(path, "rb") as fh:
        (header_len,) = struct.unpack("<Q", fh.read(8))
        header = json.loads(fh.read(header_len))
    return {
        key: list(entry["shape"])
        for key, entry in header.items()
        if key != "__metadata__"
    }


def read_torch_shapes(path: str) -> Manifest:
    """Key -> shape from a torch .ckpt/.pt (loads tensors — needs the
    checkpoint to fit in RAM; prefer safetensors when available)."""
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    return {
        key: list(value.shape)
        for key, value in state.items()
        if hasattr(value, "shape")
    }


def diff_manifest(actual: Manifest, manifest: Manifest) -> dict[str, list]:
    """Compare a real file's key+shape table against a committed
    manifest. Extra keys in the file are expected (non-parameter
    buffers like position_ids / model_ema.* / alphas_cumprod that all
    SD loaders skip) and reported informationally only."""
    missing = sorted(k for k in manifest if k not in actual)
    extra = sorted(k for k in actual if k not in manifest)
    mismatched = sorted(
        f"{k}: manifest {manifest[k]} != file {actual[k]}"
        for k in manifest
        if k in actual and list(actual[k]) != list(manifest[k])
    )
    return {"missing": missing, "extra": extra, "mismatched": mismatched}


def _detect_family(actual: Manifest, manifests: dict[str, Manifest]) -> str:
    """Pick the committed manifest sharing the most keys with the file."""
    return max(
        manifests, key=lambda name: len(manifests[name].keys() & actual.keys())
    )


def validate_from_file(path: str, family: str | None = None) -> int:
    actual = (
        read_safetensors_shapes(path)
        if path.endswith(".safetensors")
        else read_torch_shapes(path)
    )
    manifests = {}
    for name in os.listdir(OUT_DIR):
        if name.endswith(".json"):
            with open(os.path.join(OUT_DIR, name)) as fh:
                manifests[name[:-5]] = json.load(fh)
    if not manifests:
        manifests = build_all()
    if family is None:
        family = _detect_family(actual, manifests)
        print(f"auto-detected family: {family}")
    if family not in manifests:
        print(f"unknown family {family!r}; have {sorted(manifests)}")
        return 2
    diff = diff_manifest(actual, manifests[family])
    print(
        f"{os.path.basename(path)} vs {family}: "
        f"{len(actual)} file keys, {len(manifests[family])} manifest keys"
    )
    for kind in ("missing", "mismatched"):
        for item in diff[kind]:
            print(f"{kind}: {item}")
    print(f"extra (ignored by loaders): {len(diff['extra'])} keys")
    if diff["missing"] or diff["mismatched"]:
        print("DIVERGED: the committed manifest does not match this file")
        return 1
    print("OK: manifest confirmed against the real checkpoint")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--from-file",
        metavar="CKPT",
        help="validate the committed manifests against a real "
        ".safetensors/.ckpt file instead of regenerating",
    )
    parser.add_argument(
        "--family",
        help="manifest to diff against (default: auto-detect by key overlap)",
    )
    args = parser.parse_args(argv)
    if args.from_file:
        return validate_from_file(args.from_file, args.family)

    os.makedirs(OUT_DIR, exist_ok=True)
    for name, manifest in build_all().items():
        path = os.path.join(OUT_DIR, f"{name}.json")
        with open(path, "w") as fh:
            json.dump(manifest, fh, indent=0, sort_keys=True)
            fh.write("\n")
        total = sum(math.prod(shape) for shape in manifest.values())
        print(f"{name}: {len(manifest)} tensors, {total / 1e6:.1f}M params")
    return 0


if __name__ == "__main__":
    sys.exit(main())
