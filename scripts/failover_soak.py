#!/usr/bin/env python3
"""Failover soak: N kill-promote-kill-back cycles + the push-vs-poll
grant dispatch smoke.

Two phases (CI job `failover-soak` runs this and uploads the JSON
report as an artifact):

1. **failover cycles** — `--cycles` in-process kill-the-active-master
   scenarios (resilience/chaos.run_chaos_failover), rotating through
   distinct kill points (after a pull, after a partial submit, inside
   the snapshot cadence) and alternating push-mode grants on and off.
   All cycles share ONE journal directory, so each promoted master is
   the active the NEXT cycle kills — the lease epoch must climb
   strictly across the whole ladder (the kill-promote-kill-back
   property). Every cycle must (a) actually fire its crash, (b)
   promote the standby without a process restart, (c) produce a canvas
   bit-identical to the uninterrupted baseline, and (d) prove fencing:
   the zombie's journal append raises, the promoted store rejects
   stale-epoch RPCs, and neither journals a single record.

2. **grant A/B smoke** — bench's push-vs-poll grant dispatch
   measurement over the real HTTP surface (wave-released grants): push
   mode must land a lower mean grant RTT and fewer idle poll requests
   than pull mode.

    python scripts/failover_soak.py [--out failover_soak.json]
        [--cycles 6] [--skip-grant-ab]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

SEED = 11

# Rotating kill points. The master always performs at least two pulls
# (its empty_pulls<2 drain loop) and the store-side fault fires at the
# RPC boundary regardless of queue state, so every plan is guaranteed
# to fire on every run. snapshot_every=1 on the third plan lands the
# crash inside the snapshot cadence (a snapshot precedes every append).
KILL_POINTS = [
    ("after_pull", "crash@store:pull:master#2", 4),
    ("after_partial_submit",
     "latency(1.0)@store:pull:w1#1;latency(1.0)@store:pull:w2#1;"
     "crash@store:submit:master#1", 4),
    ("during_snapshot", "crash@store:pull:master#3", 1),
]


def run_failover_cycles(cycles: int) -> dict:
    import numpy as np

    from comfyui_distributed_tpu.resilience.chaos import (
        run_chaos_failover,
        run_chaos_usdu,
    )

    baseline = run_chaos_usdu(seed=SEED).output
    results = []
    last_epoch = 0
    with tempfile.TemporaryDirectory(prefix="cdt-failover-soak-") as journal_dir:
        for cycle in range(cycles):
            name, plan, snapshot_every = KILL_POINTS[cycle % len(KILL_POINTS)]
            push = cycle % 2 == 1
            started = time.perf_counter()
            entry = {
                "cycle": cycle,
                "kill_point": name,
                "push_grants": push,
            }
            try:
                result = run_chaos_failover(
                    seed=SEED,
                    crash_plan=plan,
                    journal_dir=journal_dir,
                    snapshot_every=snapshot_every,
                    push_grants=push,
                    job_id=f"soak-failover-{cycle}",
                )
                identical = bool(np.array_equal(baseline, result.output))
                epoch_climbed = result.epochs[1] > max(
                    result.epochs[0], last_epoch
                )
                entry.update(
                    {
                        "crash_fired": "crash" in result.fired_kinds(),
                        "epochs": list(result.epochs),
                        "epoch_climbed": epoch_climbed,
                        "bit_identical": identical,
                        "zombie_fenced": result.zombie_fenced,
                        "stale_pull_rejected": result.stale_pull_rejected,
                        "stale_submit_rejected": result.stale_submit_rejected,
                        "zombie_journaled_records":
                            result.zombie_journaled_records,
                        "tasks_requeued": result.report["tasks_requeued"],
                        "tasks_restored": result.report["tasks_restored"],
                        "repointed_workers": result.repointed_workers,
                        "seconds": round(time.perf_counter() - started, 2),
                    }
                )
                entry["ok"] = (
                    entry["crash_fired"]
                    and epoch_climbed
                    and identical
                    and result.zombie_fenced
                    and result.stale_pull_rejected
                    and result.stale_submit_rejected
                    and result.zombie_journaled_records == 0
                )
                last_epoch = result.epochs[1]
            except Exception as exc:  # noqa: BLE001 - reported per cycle
                entry.update({"ok": False, "error": f"{type(exc).__name__}: {exc}"})
            results.append(entry)
            status = "ok" if entry["ok"] else "FAIL"
            print(
                f"cycle {cycle} [{name}, push={push}]: {status} "
                f"(epochs {entry.get('epochs')})"
            )
    return {
        "ok": all(r["ok"] for r in results),
        "cycles": cycles,
        "final_epoch": last_epoch,
        "results": results,
    }


def run_grant_ab() -> dict:
    import bench

    ab = bench._measure_grant_ab()
    if ab is None:
        return {"ok": False, "error": "grant A/B did not produce a result"}
    ok = (
        ab["push"]["grant_rtt_ms_mean"] < ab["pull"]["grant_rtt_ms_mean"]
        and ab["push"]["idle_polls"] <= ab["pull"]["idle_polls"]
    )
    return {"ok": ok, **ab}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="failover_soak.json")
    parser.add_argument("--cycles", type=int, default=6)
    parser.add_argument(
        "--skip-grant-ab", action="store_true",
        help="failover cycles only (fast smoke)",
    )
    args = parser.parse_args(argv)

    cycles = run_failover_cycles(args.cycles)
    grant_ab = (
        {"ok": True, "skipped": True}
        if args.skip_grant_ab
        else run_grant_ab()
    )
    report = {
        "ok": cycles["ok"] and grant_ab["ok"],
        "failover_cycles": cycles,
        "grant_ab": grant_ab,
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    passed = sum(1 for r in cycles["results"] if r.get("ok"))
    print(
        f"failover cycles: {passed}/{cycles['cycles']} promoted "
        f"bit-identical with fencing (final epoch "
        f"{cycles['final_epoch']}) -> {'OK' if cycles['ok'] else 'FAIL'}"
    )
    if not args.skip_grant_ab:
        if grant_ab["ok"]:
            print(
                f"grant A/B: push {grant_ab['push']['grant_rtt_ms_mean']}ms "
                f"vs pull {grant_ab['pull']['grant_rtt_ms_mean']}ms mean RTT "
                f"({grant_ab['rtt_speedup']}x), idle polls "
                f"{grant_ab['push']['idle_polls']} vs "
                f"{grant_ab['pull']['idle_polls']} -> OK"
            )
        else:
            print(f"grant A/B FAILED: {grant_ab}")
    print(f"report written to {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
