#!/usr/bin/env python3
"""Adapter-plane smoke: the CI `adapter-smoke` job's driver.

One mixed-tenant pass through the adapter plane (docs/personalization.md)
asserting its load-bearing properties:

1. **slot isolation, bit-exact** — three jobs wearing three DIFFERENT
   LoRA adapters plus one adapter-less job share the cross-job
   executor's batches, and every job's tiles are bit-identical to
   sampling that job alone;
2. **one program per rank bucket** — all three adapter jobs carry the
   SAME extended signature (content is a traced operand, not a compile
   key), the adapter-less job keeps the unmodified base signature, so
   the whole fleet compiles exactly two device programs;
3. **adapter-less jobs are untouched** — the base job's batched canvas
   equals a run on a fleet with no adapter anywhere (the plane adds
   zero risk to jobs that don't opt in);
4. **tier parity** — the elastic tier's whole-grant `patch_params`
   application produces the same samples as the xjob tier's segmented
   per-slot patch for the same adapter + strength;
5. **conservation holds under personalization** — the run's usage
   meter attributes every dispatch nanosecond (attributed + waste +
   overhead == measured, `totals.conserved`) and each adapter plan
   shows up in the rollup's adapters section;
6. **operand cache behaves** — first resolution decodes (3 misses),
   a strength sweep re-resolves every plan from the LRU (operands are
   strength-independent), and the `cdt_adapter_*` instruments are
   live in the metrics registry after the run.

Writes the stats JSON (uploaded as a CI artifact) to the path given
as argv[1] (default: adapter-smoke.json). Exit 0 = every assertion
held. Runs on CPU; forcing multiple host devices is fine but not
required — the executor batches on one device.
"""

from __future__ import annotations

import json
import os
import sys
import types

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_ADAPTERS = 3
N_TILES = 2
STEPS = 4
DIM = 3
RANK = 2


def check(condition: bool, label: str, detail=None) -> None:
    if not condition:
        raise SystemExit(f"adapter-smoke FAILED: {label}: {detail!r}")
    print(f"  ok: {label}")


def build_fixtures():
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.adapters.registry import AdapterCatalog

    target_map = {"lora_unet_dense": ("unet/dense/kernel", (DIM, DIM))}
    params = {
        "unet": {"dense": {"kernel": jnp.eye(DIM, dtype=jnp.float32) * 0.9}}
    }
    catalog = AdapterCatalog()
    for i in range(N_ADAPTERS):
        rng = np.random.default_rng(2000 + i)
        catalog.register_memory(
            f"smoke-style-{i}",
            {
                "lora_unet_dense.lora_down.weight": (
                    0.1 * rng.normal(size=(RANK, DIM))
                ).astype(np.float32),
                "lora_unet_dense.lora_up.weight": (
                    0.1 * rng.normal(size=(DIM, RANK))
                ).astype(np.float32),
                "lora_unet_dense.alpha": np.float32(RANK),
            },
        )

    def step(p, x, key, pos, neg, yx, i):
        w = p["unet"]["dense"]["kernel"]
        ki = jax.random.fold_in(key, i)
        return (
            jnp.einsum("hwc,cd->hwd", x, w)
            + 0.01 * jax.random.normal(ki, x.shape)
            + 0.001 * pos
        )

    proc = types.SimpleNamespace(
        init=lambda p, tile, key: tile + 0.0,
        step=jax.jit(step),
        finish=lambda p, x: jnp.clip(x, -10.0, 10.0),
        n_steps=STEPS,
        signature=("adapter-smoke-stub",),
    )
    return target_map, params, catalog, proc


class _Master:
    def __init__(self, n_tiles):
        self.pending = list(range(n_tiles))

    def pull(self):
        if not self.pending:
            return None
        grant, self.pending = self.pending, []
        return {"tile_idxs": grant, "checkpoints": {}}

    def release(self, idxs, cks):
        self.pending = sorted(set(self.pending) | set(idxs))


def make_job(job_id, seed, tenant, *, proc, params, adapter):
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.graph.batch_executor import XJobHandle
    from comfyui_distributed_tpu.parallel.seeds import fold_job_key

    master = _Master(N_TILES)
    rng = np.random.default_rng(seed)
    outs: dict[int, np.ndarray] = {}
    handle = XJobHandle(
        job_id=job_id,
        proc=proc,
        params=params,
        extracted=jnp.asarray(rng.random((N_TILES, 4, 4, DIM)), jnp.float32),
        positions=jnp.zeros((N_TILES, 2), jnp.int32),
        pos=jnp.float32(seed),
        neg=jnp.float32(0),
        base_key=fold_job_key(jax.random.key(seed), job_id),
        pull=master.pull,
        emit=lambda idx, arr: outs.__setitem__(int(idx), np.asarray(arr)),
        flush=lambda final: None,
        release=master.release,
        tenant=tenant,
        adapter=adapter,
    )
    return handle, outs


def solo(job_id, seed, *, proc, params, adapter):
    from comfyui_distributed_tpu.graph.batch_executor import CrossJobExecutor

    ex = CrossJobExecutor(k_max=8)
    handle, outs = make_job(
        job_id, seed, "tenant-a", proc=proc, params=params, adapter=adapter
    )
    ex.register(handle)
    ex.run()
    return outs


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "adapter-smoke.json"

    from comfyui_distributed_tpu.adapters import AdapterSpec
    from comfyui_distributed_tpu.adapters.cache import (
        AdapterOperandCache,
        operands_for_plan,
    )
    from comfyui_distributed_tpu.adapters.segmented import patch_params
    from comfyui_distributed_tpu.graph.batch_executor import CrossJobExecutor
    from comfyui_distributed_tpu.telemetry.metrics import get_metrics_registry
    from comfyui_distributed_tpu.telemetry.usage import UsageMeter

    target_map, params, catalog, proc = build_fixtures()
    op_cache = AdapterOperandCache()

    def ops_for(i, strength):
        (resolved,) = catalog.resolve(
            [AdapterSpec(f"smoke-style-{i}", strength)]
        )
        return operands_for_plan(
            [resolved], target_map, catalog=catalog, cache=op_cache
        )

    print("xjob tier: 3 distinct adapters + 1 base job, one batch pool")
    meter = UsageMeter()
    ex = CrossJobExecutor(k_max=8, usage_meter=meter)
    fleet = {}
    sigs = set()
    for i in range(N_ADAPTERS):
        handle, outs = make_job(
            f"smoke-adapter-{i}",
            300 + i,
            "tenant-a" if i % 2 == 0 else "tenant-b",
            proc=proc,
            params=params,
            adapter=ops_for(i, 1.0),
        )
        ex.register(handle)
        meter.note_job_adapter(
            handle.job_id, catalog.content_hash(f"smoke-style-{i}")
        )
        fleet[handle.job_id] = (handle, outs)
        sigs.add(handle.sig)
    base_handle, base_outs = make_job(
        "smoke-base", 900, "tenant-b", proc=proc, params=params, adapter=None
    )
    ex.register(base_handle)
    fleet[base_handle.job_id] = (base_handle, base_outs)
    sigs.add(base_handle.sig)
    stats = ex.run()

    check(len(sigs) == 2, "two device programs for the whole fleet",
          sorted(sigs))
    check(
        stats["tiles"] == (N_ADAPTERS + 1) * N_TILES,
        "every tile finished",
        stats,
    )
    first_misses = op_cache.stats()["misses"]
    check(first_misses == N_ADAPTERS, "one operand decode per adapter",
          op_cache.stats())

    rollup = meter.rollup()
    totals = rollup["totals"]
    check(totals["conserved"], "conservation (exact ns identity)", totals)
    check(totals["chip_s"] > 0, "nonzero measured chip time", totals)
    check(
        len(rollup["adapters"]) == N_ADAPTERS
        and all(a["tiles"] == N_TILES for a in rollup["adapters"].values()),
        "every adapter plan attributed in the rollup",
        rollup["adapters"],
    )

    for i in range(N_ADAPTERS):
        jid = f"smoke-adapter-{i}"
        ref = solo(jid, 300 + i, proc=proc, params=params,
                   adapter=ops_for(i, 1.0))
        for t in range(N_TILES):
            if not np.array_equal(ref[t], fleet[jid][1][t]):
                raise SystemExit(
                    f"adapter-smoke FAILED: slot isolation broken: {jid} "
                    f"tile {t} diverges from its solo run"
                )
    print("  ok: slot isolation bit-exact (each worn job == its solo run)")

    base_ref = solo("smoke-base", 900, proc=proc, params=params, adapter=None)
    for t in range(N_TILES):
        if not np.array_equal(base_ref[t], base_outs[t]):
            raise SystemExit(
                "adapter-smoke FAILED: adapter-less job perturbed by "
                f"sharing the pool (tile {t})"
            )
    print("  ok: adapter-less job bit-identical to a plane-free run")

    print("elastic tier: whole-grant patch_params parity")
    ops0 = ops_for(0, 0.8)
    patched = patch_params(params, ops0._replace(scale=1.0), scale=0.8)
    merged = solo("smoke-adapter-0", 300, proc=proc, params=patched,
                  adapter=None)
    segmented = solo("smoke-adapter-0", 300, proc=proc, params=params,
                     adapter=ops_for(0, 0.8))
    for t in range(N_TILES):
        np.testing.assert_allclose(
            merged[t], segmented[t], rtol=1e-5, atol=1e-6,
            err_msg=f"tier parity diverged on tile {t}",
        )
    print("  ok: merged (elastic) == segmented (xjob) samples")

    print("operand cache: strength sweep must serve from the LRU")
    before = op_cache.stats()
    for i in range(N_ADAPTERS):
        ops_for(i, 0.25)  # new strength, same content → hit
    after = op_cache.stats()
    check(after["misses"] == before["misses"],
          "strength sweep decodes nothing", after)
    check(after["hits"] >= before["hits"] + N_ADAPTERS,
          "strength sweep hits per adapter", after)

    rendered = get_metrics_registry().render()
    for metric in (
        "cdt_adapter_cache_lookups_total",
        "cdt_adapter_cache_bytes",
        "cdt_adapter_slots_total",
    ):
        check(metric in rendered, f"{metric} live in the registry")

    report = {
        "fleet": {
            "adapters": N_ADAPTERS,
            "tiles_per_job": N_TILES,
            "steps": STEPS,
            "tenants": 2,
            "device_programs": len(sigs),
        },
        "executor": {
            "dispatches": stats["dispatches"],
            "tiles": stats["tiles"],
            "fill_ratio": round(stats["fill_ratio"], 4),
            "slots_real": stats["slots_real"],
            "slots_padded": stats["slots_padded"],
        },
        "operand_cache": after,
        "usage": {
            "conserved": totals["conserved"],
            "chip_s": totals["chip_s"],
            "adapters": rollup["adapters"],
            "tenants": {
                t: {"chip_s": s["chip_s"], "tiles": s["tiles"]}
                for t, s in rollup["tenants"].items()
            },
        },
        "bit_identical": True,
        "tier_parity": True,
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(f"adapter smoke OK -> {out_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
