#!/usr/bin/env bash
# Web (JS) test suite. The suite is plain ES modules with its own tiny
# harness (web/tests/harness.js) because this image ships no JS
# runtime; on machines with node it runs headlessly, elsewhere open
# comfyui_distributed_tpu/web/tests/runner.html in any browser.
set -euo pipefail
cd "$(dirname "$0")/.."
if command -v node >/dev/null 2>&1; then
  exec node comfyui_distributed_tpu/web/tests/run-node.mjs
fi
echo "skip: no JS runtime (node) on this machine."
echo "open comfyui_distributed_tpu/web/tests/runner.html in a browser to run the suite."
exit 0
