#!/usr/bin/env python
"""Fetch and install OpenAI's real CLIP BPE vocabulary.

The committed fallback under models/assets/clip_vocab/ reproduces
CLIP's exact id *layout* (512 byte units, 48894 merge slots, BOS 49406,
EOS 49407) but its merge table was trained on build-host prose: real SD
checkpoints need OpenAI's published merges to receive the token ids
they were trained with.  The build environment for this repo has no
network egress, so the real table cannot be committed from here; this
script is the operator's one-command path to exact-CLIP tokenization.

Sources (either works; both carry the identical table):
  - openai/CLIP's `bpe_simple_vocab_16e6.txt.gz` (GitHub), converted to
    the standard vocab.json + merges.txt pair with CLIP's own
    construction rule, or
  - HuggingFace `openai/clip-vit-base-patch32` `vocab.json`/`merges.txt`
    (already in the target format).

A local copy can be installed with --from-bpe/--from-vocab-dir for
air-gapped hosts.

The installed pair is verified SEMANTICALLY before being accepted:
canonical prompts must produce the published CLIP token ids (e.g.
`tokenize("hello world!")` → [49406, 3306, 1002, 256, 49407] in the
official CLIP notebook).  This is a stronger guarantee than a file
hash — any file that passes is, behaviorally, the CLIP vocabulary.
The known sha256 of the official txt.gz is additionally checked when
fetching from GitHub (skip with --no-verify-hash if OpenAI re-uploads).

Usage:
    python scripts/fetch_clip_vocab.py              # fetch + install
    python scripts/fetch_clip_vocab.py --from-bpe /path/bpe_simple_vocab_16e6.txt.gz
    python scripts/fetch_clip_vocab.py --from-vocab-dir /path/with/vocab.json+merges.txt
"""

from __future__ import annotations

import argparse
import gzip
import hashlib
import json
import os
import shutil
import sys
import tempfile
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from comfyui_distributed_tpu.models.clip_bpe import (  # noqa: E402
    _MAX_MERGES,
    ClipBPE,
    bytes_to_unicode,
)

ASSET_DIR = os.path.join(
    REPO, "comfyui_distributed_tpu", "models", "assets", "clip_vocab"
)

BPE_URL = "https://github.com/openai/CLIP/raw/main/clip/bpe_simple_vocab_16e6.txt.gz"
# sha256 of the official file as distributed by openai/CLIP
BPE_SHA256 = "924691ac288e54409236115652ad4aa250f48203de50a9e4722a6ecd48d6804a"
HF_BASE = "https://huggingface.co/openai/clip-vit-base-patch32/resolve/main"

# Published CLIP token ids (official CLIP notebook / transformers docs);
# the gate a candidate vocab must pass before installation.
CANONICAL_IDS = {
    "hello world!": [49406, 3306, 1002, 256, 49407],
    "a photo of a cat": [49406, 320, 1125, 539, 320, 2368, 49407],
    "a photo of a dog": [49406, 320, 1125, 539, 320, 1929, 49407],
}


def convert_bpe_txt(raw: bytes) -> tuple[dict[str, int], list[str]]:
    """openai/CLIP `bpe_simple_vocab_16e6.txt.gz` bytes → (vocab dict,
    merge lines).  Reproduces the construction in CLIP's
    SimpleTokenizer.__init__: 256 byte units, their `</w>` variants,
    one token per merge (capped at 48894), then the two specials."""
    text = gzip.decompress(raw).decode("utf-8")
    lines = text.split("\n")
    merge_lines = [ln for ln in lines[1 : _MAX_MERGES + 1] if ln.strip()]
    units = list(bytes_to_unicode().values())
    tokens = units + [u + "</w>" for u in units]
    for ln in merge_lines:
        tokens.append("".join(ln.split()))
    tokens += ["<|startoftext|>", "<|endoftext|>"]
    vocab = {tok: i for i, tok in enumerate(tokens)}
    if len(vocab) != len(tokens):
        raise ValueError("merge table produced duplicate tokens")
    return vocab, merge_lines


def write_pair(vocab: dict[str, int], merges: list[str], out_dir: str) -> None:
    """Write the standard (gzipped) vocab.json + merges.txt pair."""
    os.makedirs(out_dir, exist_ok=True)
    with gzip.open(
        os.path.join(out_dir, "vocab.json.gz"), "wt", encoding="utf-8"
    ) as fh:
        json.dump(vocab, fh, ensure_ascii=False)
    with gzip.open(
        os.path.join(out_dir, "merges.txt.gz"), "wt", encoding="utf-8"
    ) as fh:
        fh.write("#version: 0.2\n")
        fh.write("\n".join(merges))
        fh.write("\n")


def validate(vocab_dir: str) -> list[str]:
    """Return a list of validation failures (empty = behaviorally CLIP)."""
    bpe = ClipBPE(vocab_dir)
    problems = []
    if len(bpe.encoder) != 49408:
        problems.append(f"vocab size {len(bpe.encoder)} != 49408")
    if bpe.bos_id != 49406 or bpe.eos_id != 49407:
        problems.append(f"specials at {bpe.bos_id}/{bpe.eos_id}, want 49406/49407")
    for prompt, want in CANONICAL_IDS.items():
        got = [bpe.bos_id] + bpe.encode_text(prompt) + [bpe.eos_id]
        if got != want:
            problems.append(f"{prompt!r}: got {got}, want {want}")
    return problems


def _fetch(url: str) -> bytes:
    print(f"fetching {url} ...")
    with urllib.request.urlopen(url, timeout=120) as resp:
        return resp.read()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--from-bpe", help="local bpe_simple_vocab_16e6.txt.gz")
    ap.add_argument(
        "--from-vocab-dir", help="local dir with vocab.json[.gz] + merges.txt[.gz]"
    )
    ap.add_argument("--source", choices=("github", "hf"), default="github")
    ap.add_argument("--no-verify-hash", action="store_true")
    ap.add_argument("--dest", default=ASSET_DIR)
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as tmp:
        if args.from_vocab_dir:
            for name in ("vocab.json", "merges.txt"):
                src = args.from_vocab_dir
                for cand in (f"{name}.gz", name):
                    p = os.path.join(src, cand)
                    if os.path.exists(p):
                        shutil.copy(p, os.path.join(tmp, cand))
                        break
                else:
                    print(f"error: {src} lacks {name}[.gz]", file=sys.stderr)
                    return 1
        elif args.from_bpe or args.source == "github":
            raw = (
                open(args.from_bpe, "rb").read()
                if args.from_bpe
                else _fetch(BPE_URL)
            )
            digest = hashlib.sha256(raw).hexdigest()
            if digest != BPE_SHA256:
                msg = f"sha256 {digest} != pinned {BPE_SHA256}"
                if args.no_verify_hash or args.from_bpe:
                    print(f"warning: {msg} (continuing; semantic check gates)")
                else:
                    print(f"error: {msg} (--no-verify-hash to override; the "
                          "semantic id check below still gates installation)",
                          file=sys.stderr)
                    return 1
            vocab, merges = convert_bpe_txt(raw)
            write_pair(vocab, merges, tmp)
        else:  # hf
            for name in ("vocab.json", "merges.txt"):
                data = _fetch(f"{HF_BASE}/{name}")
                with open(os.path.join(tmp, name), "wb") as fh:
                    fh.write(data)

        problems = validate(tmp)
        if problems:
            print("candidate vocab FAILED canonical-id validation:",
                  file=sys.stderr)
            for p in problems:
                print(f"  - {p}", file=sys.stderr)
            return 1

        os.makedirs(args.dest, exist_ok=True)
        # clear every stale variant first: _open_maybe_gz prefers .gz,
        # so a leftover stand-in .gz would shadow newly installed
        # plain files (and vice versa)
        for name in ("vocab.json", "merges.txt"):
            for cand in (name, f"{name}.gz"):
                p = os.path.join(args.dest, cand)
                if os.path.exists(p):
                    os.remove(p)
        for name in os.listdir(tmp):
            shutil.copy(os.path.join(tmp, name), os.path.join(args.dest, name))
    print(f"installed exact CLIP vocab into {args.dest}")
    print("(restart any running servers; get_bpe() caches per-directory)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
