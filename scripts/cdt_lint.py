#!/usr/bin/env python3
"""cdt-lint CLI — project-specific static analysis gate.

Usage:
    python scripts/cdt_lint.py [PATHS...] [options]

Options:
    --format text|json   output format (default text; json is the CI artifact)
    --baseline PATH      baseline file (default tools/cdtlint/baseline.json)
    --no-baseline        ignore the baseline entirely (audit mode)
    --update-baseline    rewrite the baseline from the current scan.
                         Policy: shrink-only — refuses to *grow* the
                         baseline unless --force is also given, and every
                         new entry lands with a TODO justification that
                         must be edited before commit.
    --force              allow --update-baseline to add entries
    --select CODES       comma-separated checker codes to run (e.g. CDT001,CDT004)
    --list-checkers      print the checker catalogue and exit
    --verbose            also print baselined and suppressed findings

Exit codes:
    0  clean (no unbaselined findings, no stale baseline entries)
    1  findings present / stale baseline entries / parse errors
    2  usage or internal error

Suppressions: `# cdt: noqa[CDT00X]` on the offending line (bare
`# cdt: noqa` suppresses every checker on that line). See
docs/static-analysis.md for the policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools.cdtlint import all_checkers  # noqa: E402
from tools.cdtlint.baseline import DEFAULT_BASELINE_PATH, Baseline  # noqa: E402
from tools.cdtlint.runner import (  # noqa: E402
    DEFAULT_SCAN_PATHS,
    compute_fingerprints,
    render_text,
    run_lint,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdt_lint", description="project-specific static analysis gate"
    )
    parser.add_argument("paths", nargs="*", help=f"scan roots (default: {DEFAULT_SCAN_PATHS})")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", default=os.path.join(_REPO_ROOT, DEFAULT_BASELINE_PATH))
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument("--update-baseline", action="store_true")
    parser.add_argument("--force", action="store_true")
    parser.add_argument("--select", default=None)
    parser.add_argument("--list-checkers", action="store_true")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    if args.list_checkers:
        for info in all_checkers().values():
            print(f"{info.code}  {info.name:<24} [{info.scope}]  {info.description}")
        return 0

    try:
        baseline = (
            Baseline(path=args.baseline)
            if args.no_baseline
            else Baseline.load(args.baseline)
        )
    except (ValueError, json.JSONDecodeError) as exc:
        print(f"cdt-lint: bad baseline: {exc}", file=sys.stderr)
        return 2

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",") if c.strip()}
        unknown = select - set(all_checkers())
        if unknown:
            print(f"cdt-lint: unknown checker code(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    result = run_lint(
        _REPO_ROOT,
        paths=args.paths or None,
        baseline=baseline,
        select=select,
    )

    if args.update_baseline:
        new_entries = compute_fingerprints(
            _REPO_ROOT, result.findings, already_baselined=result.baselined
        )
        kept = {
            fp: entry for fp, entry in baseline.entries.items() if fp not in result.stale_baseline
        }
        if new_entries and not args.force:
            print(
                f"cdt-lint: refusing to add {len(new_entries)} new baseline entr(y/ies) "
                "without --force (baseline policy is shrink-only); fix the findings instead",
                file=sys.stderr,
            )
            return 2
        baseline.entries = {**kept, **new_entries}
        baseline.save()
        print(
            f"cdt-lint: baseline rewritten: {len(baseline.entries)} entr(y/ies) "
            f"({len(new_entries)} added, {len(result.stale_baseline)} stale removed)"
        )
        return 0

    if args.format == "json":
        print(json.dumps(result.as_json(), indent=2))
    else:
        print(render_text(result, verbose=args.verbose))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
