#!/usr/bin/env python3
"""Scheduler soak: two synthetic tenants + skewed worker speeds.

The tier-1-adjacent smoke for the scheduler control plane
(comfyui_distributed_tpu/scheduler/). Two phases:

1. **fairness** — two synthetic tenants with 3:1 weights flood one
   admission lane with single-tile requests; the grant sequence under
   deficit-round-robin must hand out tile work 3:1 (±tolerance), and
   the queue-wait EWMA/back-pressure counters land in the report.

2. **placement** — an in-process chaos USDU run (resilience/chaos.py)
   with a 10x straggler injected via the FaultInjector's latency
   faults, once under uniform pull and once under cost-aware weighted
   placement. The straggler must receive no MORE tiles weighted than
   uniform, the placement snapshot must show its depressed speed
   ratio, and both canvases must be bit-identical to the fault-free
   baseline (placement changes WHO, never WHAT).

Writes a JSON fairness report (CI uploads it as an artifact) and exits
non-zero when either property fails:

    python scripts/scheduler_soak.py [--out scheduler_soak.json]
        [--requests 200] [--tolerance 0.10]
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

TENANT_WEIGHTS = {"tenant-a": 3.0, "tenant-b": 1.0}

STRAGGLER_PLAN = (
    "seed=11;latency(0.2)@store:pull:master#1-8;"
    "latency(0.35)@chaos:w1:pulled#*;latency(0.035)@chaos:w2:pulled#*"
)
PLACEMENT_OVERRIDES = dict(
    base_batch=1, max_batch=4, tail_tiles=8, min_samples=1, trim_ratio=0.5
)


def run_fairness(requests: int, tolerance: float) -> dict:
    """Grant `requests` single-tile requests across two backlogged
    tenants; the realized split must match the 3:1 weights."""
    from comfyui_distributed_tpu.scheduler import AdmissionQueue

    async def scenario() -> dict:
        queue = AdmissionQueue(
            lanes=[("interactive", max(4 * requests, 64))],
            max_active=1,
            tenant_weights=dict(TENANT_WEIGHTS),
        )
        tickets = []
        for _ in range(requests):
            for tenant in TENANT_WEIGHTS:
                tickets.append(queue.submit(tenant, "interactive", cost=1.0))
        grant_order: list[str] = []
        waits: list[float] = []
        for _ in range(requests):
            granted = [t for t in tickets if t.state == "granted"]
            assert len(granted) == 1, "exactly one active grant expected"
            ticket = granted[0]
            grant_order.append(ticket.tenant)
            waits.append(ticket.queue_wait_seconds or 0.0)
            queue.release(ticket)
        counts = collections.Counter(grant_order)
        return {"counts": dict(counts), "snapshot": queue.snapshot()}

    result = asyncio.run(scenario())
    counts = result["counts"]
    total = sum(counts.values())
    share_a = counts.get("tenant-a", 0) / total if total else 0.0
    target = TENANT_WEIGHTS["tenant-a"] / sum(TENANT_WEIGHTS.values())
    ok = abs(share_a - target) <= tolerance
    return {
        "ok": ok,
        "requests_granted": total,
        "counts": counts,
        "tenant_a_share": round(share_a, 4),
        "target_share": round(target, 4),
        "tolerance": tolerance,
        "totals": result["snapshot"]["totals"],
    }


def run_placement() -> dict:
    """Chaos USDU with a 10x straggler: weighted placement must not
    hand the straggler more tiles than uniform pull, and the canvas
    stays bit-identical to the fault-free baseline."""
    import numpy as np

    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    baseline = run_chaos_usdu(seed=11, image_hw=(128, 128))
    weighted = run_chaos_usdu(
        seed=11,
        image_hw=(128, 128),
        fault_plan=STRAGGLER_PLAN,
        placement=dict(PLACEMENT_OVERRIDES),
        worker_timeout=10.0,
    )
    uniform = run_chaos_usdu(
        seed=11,
        image_hw=(128, 128),
        fault_plan=STRAGGLER_PLAN,
        worker_timeout=10.0,
    )
    identical = bool(
        np.array_equal(baseline.output, weighted.output)
        and np.array_equal(baseline.output, uniform.output)
    )
    w1_weighted = weighted.tiles_by_worker.get("w1", 0)
    w1_uniform = uniform.tiles_by_worker.get("w1", 0)
    straggler_ratio = (
        weighted.placement.get("workers", {}).get("w1", {}).get("speed_ratio")
    )
    ok = (
        identical
        and w1_weighted <= w1_uniform
        and (straggler_ratio is None or straggler_ratio < 1.0)
    )
    return {
        "ok": ok,
        "bit_identical": identical,
        "tiles_weighted": weighted.tiles_by_worker,
        "tiles_uniform": uniform.tiles_by_worker,
        "straggler_speed_ratio": straggler_ratio,
        "placement_snapshot": weighted.placement,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="scheduler_soak.json")
    parser.add_argument("--requests", type=int, default=200)
    parser.add_argument("--tolerance", type=float, default=0.10)
    args = parser.parse_args(argv)

    fairness = run_fairness(args.requests, args.tolerance)
    placement = run_placement()
    report = {
        "ok": fairness["ok"] and placement["ok"],
        "fairness": fairness,
        "placement": placement,
    }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps({k: report[k] for k in ("ok",)}, indent=2))
    print(
        f"fairness: tenant-a share {fairness['tenant_a_share']} "
        f"(target {fairness['target_share']} ± {fairness['tolerance']}) "
        f"-> {'OK' if fairness['ok'] else 'FAIL'}"
    )
    print(
        f"placement: straggler tiles weighted={placement['tiles_weighted'].get('w1')} "
        f"uniform={placement['tiles_uniform'].get('w1')} "
        f"bit_identical={placement['bit_identical']} "
        f"-> {'OK' if placement['ok'] else 'FAIL'}"
    )
    print(f"report written to {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
