#!/usr/bin/env python
"""Chaos smoke: run the in-process USDU loop under a handful of seeded
fault plans and verify every run is bit-identical to the fault-free
baseline.

CPU-only and hermetic (JAX_PLATFORMS=cpu is forced); a few seconds per
scenario. Exit code 0 = all scenarios recovered bit-identically.

Usage:
    python scripts/chaos_smoke.py            # default seeds 11,23,47
    python scripts/chaos_smoke.py --seeds 1 2 3
"""

from __future__ import annotations

import argparse
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOW_MASTER = "latency(0.15)@store:pull:master#1-3"

# (name, plan template) — {seed} is substituted per run
SCENARIOS = [
    ("crash-after-pull w1", "seed={seed};" + SLOW_MASTER + ";crash@chaos:w1:pulled#1"),
    (
        "double crash",
        "seed={seed};" + SLOW_MASTER
        + ";crash@chaos:w1:pulled#1;crash@chaos:w2:pulled#1",
    ),
    (
        "dropped heartbeats w1",
        "seed={seed};" + SLOW_MASTER
        + ";drop@store:heartbeat:w1#*;latency(0.8)@chaos:w1:submit#1",
    ),
    ("latency spikes", "seed={seed};latency(0.2)@chaos:w2:pull#1-2"),
    ("pull connect_error w2", "seed={seed};" + SLOW_MASTER + ";connect_error@chaos:w2:pull#2"),
]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--seeds", type=int, nargs="+", default=[11, 23, 47],
        help="image/noise seeds to sweep (default: 11 23 47)",
    )
    args = parser.parse_args()

    import numpy as np

    from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

    failures = 0
    for seed in args.seeds:
        t0 = time.monotonic()
        baseline = run_chaos_usdu(seed=seed)
        print(
            f"seed {seed}: baseline {baseline.output.shape} "
            f"in {time.monotonic() - t0:.1f}s"
        )
        for name, template in SCENARIOS:
            plan = template.format(seed=seed)
            t0 = time.monotonic()
            result = run_chaos_usdu(seed=seed, fault_plan=plan)
            identical = np.array_equal(baseline.output, result.output)
            fired = ",".join(sorted(result.fired_kinds())) or "-"
            status = "OK " if identical else "FAIL"
            print(
                f"  [{status}] {name:<24} fired={fired:<28} "
                f"crashed={result.crashed_workers or '-'} "
                f"({time.monotonic() - t0:.1f}s)"
            )
            if not identical:
                failures += 1
                diff = np.abs(baseline.output - result.output)
                print(
                    f"         max|diff|={diff.max():.3e} "
                    f"at {np.unravel_index(diff.argmax(), diff.shape)}"
                )
    if failures:
        print(f"\n{failures} scenario(s) diverged from the fault-free baseline")
        return 1
    print("\nall chaos scenarios recovered bit-identically")
    return 0


if __name__ == "__main__":
    sys.exit(main())
