"""CLI entry point: run a master or worker server.

    python -m comfyui_distributed_tpu --port 8188            # master
    python -m comfyui_distributed_tpu --port 8189 --worker   # worker

The same process serves both roles (role decided per-prompt by hidden
inputs, reference distributed.py pattern); --worker only suppresses
master-side startup behavior (auto-launch, signal-driven worker
cleanup) and enables the master-pid watchdog.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="comfyui_distributed_tpu")
    parser.add_argument("--port", type=int, default=8188)
    parser.add_argument(
        "--host", type=str, default=None,
        help="bind address (default 127.0.0.1, or CDT_HOST; pass "
             "0.0.0.0 to accept LAN/remote masters and workers — the "
             "/distributed/* surface has no auth, so binding wide is "
             "an explicit opt-in)",
    )
    parser.add_argument("--worker", action="store_true")
    parser.add_argument(
        "--standby", type=str, default=None, metavar="URLS",
        help="run as a warm-standby master tailing the given active "
             "master URL(s) (comma-separated; or CDT_STANDBY_OF). "
             "Requires CDT_JOURNAL_DIR — the lease file there is the "
             "takeover arbitration medium. The standby serves 503 on "
             "work RPCs until the active's lease expires, then "
             "promotes itself in place (docs/durability.md §failover)",
    )
    parser.add_argument("--config", type=str, default=None)
    parser.add_argument(
        "--platform", type=str, default=None,
        help="force a jax platform (e.g. cpu for smoke tests)",
    )
    args = parser.parse_args(argv)

    if args.worker:
        os.environ.setdefault("CDT_IS_WORKER", "1")
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    # persistent XLA compilation cache: every process after the first
    # skips its first compiles (master and workers share the dir)
    from .workers.startup import configure_compile_cache

    configure_compile_cache()

    # join the pod's shared JAX runtime when configured (no-op otherwise)
    from .parallel.multihost import maybe_init_multihost

    maybe_init_multihost()

    from .api.server import DistributedServer
    from .workers.monitor import start_master_watchdog
    from .workers.startup import (
        auto_populate_workers,
        delayed_auto_launch,
        register_signals,
        register_worker_drain,
    )

    server = DistributedServer(
        port=args.port, is_worker=args.worker, config_path=args.config,
        host=args.host, standby_of=args.standby,
    )

    async def run():
        await server.start()
        register_signals(asyncio.get_running_loop(), args.config)
        if not server.is_worker:
            auto_populate_workers(args.config)
            delayed_auto_launch(args.config)
        else:
            start_master_watchdog()
            # SIGTERM/SIGINT on a worker drains gracefully: finish the
            # in-flight batch, flush encoded tiles, hand the remainder
            # back via return_tiles, then deregister and stop
            register_worker_drain(asyncio.get_running_loop(), server)
        # run until the loop is stopped by a signal handler
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except (KeyboardInterrupt, RuntimeError):
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
