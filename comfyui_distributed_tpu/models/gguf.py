"""GGUF checkpoint reader (quantized single-file models).

The reference loads GGUF-quantized UNets through ComfyUI's GGUF
loader ecosystem; this is the native equivalent: parse the GGUF v2/v3
container and dequantize the common block formats to float32 numpy,
yielding the same state-dict shape `sd_checkpoint.py` maps into flax
trees. Tensor names in diffusion GGUF files are the original state-
dict names, so the existing key schedules apply unchanged.

Supported tensor types: F32, F16, Q8_0, Q4_0, Q4_1, Q5_0, Q5_1.
K-quants (Q*_K) raise with a clear message rather than misread.

A writer for the same subset (`write_gguf`) exists so round-trip tests
don't need binary fixtures; it is also handy for exporting.
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

GGUF_MAGIC = b"GGUF"

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32 = 0, 1, 2, 3, 4, 5
_T_F32, _T_BOOL, _T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = (
    6, 7, 8, 9, 10, 11, 12
)

# tensor (ggml) types
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0 = 8

_BLOCK = 32  # elements per quant block for the supported formats

_TYPE_NAMES = {
    GGML_F32: "F32", GGML_F16: "F16", GGML_Q4_0: "Q4_0",
    GGML_Q4_1: "Q4_1", GGML_Q5_0: "Q5_0", GGML_Q5_1: "Q5_1",
    GGML_Q8_0: "Q8_0",
}

_BLOCK_BYTES = {
    GGML_Q4_0: 2 + 16,
    GGML_Q4_1: 2 + 2 + 16,
    GGML_Q5_0: 2 + 4 + 16,
    GGML_Q5_1: 2 + 2 + 4 + 16,
    GGML_Q8_0: 2 + 32,
}


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise ValueError("truncated GGUF file")
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]

    def string(self) -> str:
        return self.take(self.u64()).decode("utf-8")

    def value(self, vtype: int) -> Any:
        fmt = {
            _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
            _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
            _T_I64: "<q", _T_F64: "<d",
        }.get(vtype)
        if fmt is not None:
            return struct.unpack(fmt, self.take(struct.calcsize(fmt)))[0]
        if vtype == _T_BOOL:
            return bool(self.take(1)[0])
        if vtype == _T_STRING:
            return self.string()
        if vtype == _T_ARRAY:
            etype = self.u32()
            count = self.u64()
            return [self.value(etype) for _ in range(count)]
        raise ValueError(f"unknown GGUF metadata type {vtype}")


def _dequant(raw: np.ndarray, gtype: int, n_elements: int) -> np.ndarray:
    """raw uint8 block data → float32 [n_elements]."""
    if gtype == GGML_Q8_0:
        blocks = raw.reshape(-1, 2 + 32)
        d = blocks[:, :2].copy().view(np.float16).astype(np.float32)
        q = blocks[:, 2:].copy().view(np.int8).astype(np.float32)
        out = (d * q).reshape(-1)
    elif gtype in (GGML_Q4_0, GGML_Q4_1):
        has_m = gtype == GGML_Q4_1
        bb = _BLOCK_BYTES[gtype]
        blocks = raw.reshape(-1, bb)
        d = blocks[:, :2].copy().view(np.float16).astype(np.float32)
        off = 2
        if has_m:
            m = blocks[:, 2:4].copy().view(np.float16).astype(np.float32)
            off = 4
        qs = blocks[:, off:]
        lo = (qs & 0x0F).astype(np.float32)
        hi = (qs >> 4).astype(np.float32)
        q = np.concatenate([lo, hi], axis=1)  # [B, 32]
        if has_m:
            out = (d * q + m).reshape(-1)
        else:
            out = (d * (q - 8.0)).reshape(-1)
    elif gtype in (GGML_Q5_0, GGML_Q5_1):
        has_m = gtype == GGML_Q5_1
        bb = _BLOCK_BYTES[gtype]
        blocks = raw.reshape(-1, bb)
        d = blocks[:, :2].copy().view(np.float16).astype(np.float32)
        off = 2
        if has_m:
            m = blocks[:, 2:4].copy().view(np.float16).astype(np.float32)
            off = 4
        qh = blocks[:, off : off + 4].copy().view(np.uint32)[:, 0]
        qs = blocks[:, off + 4 :]
        lo = (qs & 0x0F).astype(np.uint8)
        hi = (qs >> 4).astype(np.uint8)
        bit = np.arange(16, dtype=np.uint32)
        lo_h = ((qh[:, None] >> bit) & 1).astype(np.uint8) << 4
        hi_h = ((qh[:, None] >> (bit + 16)) & 1).astype(np.uint8) << 4
        q = np.concatenate([lo | lo_h, hi | hi_h], axis=1).astype(np.float32)
        if has_m:
            out = (d * q + m).reshape(-1)
        else:
            out = (d * (q - 16.0)).reshape(-1)
    else:  # pragma: no cover
        raise ValueError(f"unsupported ggml type {gtype}")
    return out[:n_elements]


def read_gguf(path: str) -> dict[str, np.ndarray]:
    """Read a GGUF file → {tensor_name: float32/float16 ndarray}."""
    with open(path, "rb") as fh:
        data = fh.read()
    r = _Reader(data)
    if r.take(4) != GGUF_MAGIC:
        raise ValueError(f"{path}: not a GGUF file")
    version = r.u32()
    if version not in (2, 3):
        raise ValueError(f"{path}: unsupported GGUF version {version}")
    tensor_count = r.u64()
    kv_count = r.u64()

    metadata: dict[str, Any] = {}
    for _ in range(kv_count):
        key = r.string()
        vtype = r.u32()
        metadata[key] = r.value(vtype)
    alignment = int(metadata.get("general.alignment", 32))

    infos = []
    for _ in range(tensor_count):
        name = r.string()
        n_dims = r.u32()
        # ggml dims: ne[0] is innermost/contiguous → numpy shape reversed
        dims = [r.u64() for _ in range(n_dims)]
        gtype = r.u32()
        offset = r.u64()
        infos.append((name, dims, gtype, offset))

    base = (r.pos + alignment - 1) // alignment * alignment
    out: dict[str, np.ndarray] = {}
    for name, dims, gtype, offset in infos:
        n = int(np.prod(dims)) if dims else 1
        shape = tuple(reversed(dims))
        start = base + offset
        if gtype == GGML_F32:
            arr = np.frombuffer(data, np.float32, count=n, offset=start).copy()
        elif gtype == GGML_F16:
            arr = np.frombuffer(data, np.float16, count=n, offset=start)
            arr = arr.astype(np.float32)
        elif gtype in _BLOCK_BYTES:
            n_blocks = -(-n // _BLOCK)
            nbytes = n_blocks * _BLOCK_BYTES[gtype]
            raw = np.frombuffer(data, np.uint8, count=nbytes, offset=start)
            arr = _dequant(raw, gtype, n)
        else:
            raise ValueError(
                f"{path}: tensor {name!r} uses unsupported ggml type "
                f"{gtype} (supported: {sorted(_TYPE_NAMES.values())})"
            )
        out[name] = arr.reshape(shape)
    return out


# --- quantized cheap lane (budget tenants) --------------------------------
#
# The adapter plane's budget story: tenants named in CDT_BUDGET_TENANTS
# are routed to CDT_CHEAP_LANE at the queue route (api/job_routes.py),
# and the checkpoints registered here are the quantized variants that
# lane is expected to serve — smaller HBM footprint, cheaper per-tile,
# same key schedules as the full-precision files (GGUF tensor names are
# the original state-dict names).

_QUANTIZED_CHECKPOINTS: dict[str, str] = {}


def register_quantized_checkpoint(name: str, path: str) -> None:
    """Register a GGUF-quantized checkpoint under a model name so the
    cheap lane's loaders (and the `quantized_lane_info` surface) can
    find it. Re-registering a name overwrites (latest wins)."""
    _QUANTIZED_CHECKPOINTS[str(name)] = str(path)


def quantized_checkpoint_path(name: str) -> str | None:
    return _QUANTIZED_CHECKPOINTS.get(str(name))


def quantized_lane_info() -> dict[str, Any]:
    """The budget-routing surface: which lane budget tenants land on,
    which tenants are routed, and which quantized checkpoints are
    registered to serve them. Consumed by the queue route's lane
    resolution (api/job_routes.py) and by docs/observability — pure
    read, never raises."""
    from ..utils.constants import budget_tenants, cheap_lane

    return {
        "lane": cheap_lane(),
        "tenants": list(budget_tenants()),
        "checkpoints": dict(sorted(_QUANTIZED_CHECKPOINTS.items())),
    }


def _reset_quantized_registry_for_tests() -> None:
    _QUANTIZED_CHECKPOINTS.clear()


# --- writer (tests / export) ---------------------------------------------

def _quantize(arr: np.ndarray, gtype: int) -> bytes:
    flat = arr.astype(np.float32).reshape(-1)
    pad = (-len(flat)) % _BLOCK
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    blocks = flat.reshape(-1, _BLOCK)
    out = bytearray()
    for block in blocks:
        if gtype == GGML_Q8_0:
            d = float(np.abs(block).max()) / 127.0 or 1e-12
            q = np.clip(np.round(block / d), -127, 127).astype(np.int8)
            out += np.float16(d).tobytes() + q.tobytes()
        elif gtype == GGML_Q4_0:
            amax_idx = int(np.abs(block).argmax())
            d = float(block[amax_idx]) / -8.0 or 1e-12
            q = np.clip(np.round(block / d) + 8, 0, 15).astype(np.uint8)
            packed = (q[:16] | (q[16:] << 4)).astype(np.uint8)
            out += np.float16(d).tobytes() + packed.tobytes()
        elif gtype == GGML_Q5_0:
            amax_idx = int(np.abs(block).argmax())
            d = float(block[amax_idx]) / -16.0 or 1e-12
            q = np.clip(np.round(block / d) + 16, 0, 31).astype(np.uint8)
            qh = 0
            for i in range(16):
                qh |= int(q[i] >> 4) << i
                qh |= int(q[i + 16] >> 4) << (i + 16)
            packed = ((q[:16] & 0xF) | ((q[16:] & 0xF) << 4)).astype(np.uint8)
            out += (
                np.float16(d).tobytes()
                + struct.pack("<I", qh)
                + packed.tobytes()
            )
        else:  # pragma: no cover
            raise ValueError(f"writer does not support ggml type {gtype}")
    return bytes(out)


def write_gguf(
    path: str,
    tensors: dict[str, tuple[np.ndarray, int]],
    metadata: dict[str, Any] | None = None,
    alignment: int = 32,
) -> None:
    """Write {name: (array, ggml_type)} to a GGUF v3 file."""
    def enc_string(s: str) -> bytes:
        raw = s.encode("utf-8")
        return struct.pack("<Q", len(raw)) + raw

    meta = {"general.alignment": alignment, **(metadata or {})}
    head = bytearray()
    head += GGUF_MAGIC
    head += struct.pack("<I", 3)
    head += struct.pack("<Q", len(tensors))
    head += struct.pack("<Q", len(meta))
    for key, value in meta.items():
        head += enc_string(key)
        if isinstance(value, bool):
            head += struct.pack("<I", _T_BOOL) + struct.pack("<B", value)
        elif isinstance(value, int):
            head += struct.pack("<I", _T_U32) + struct.pack("<I", value)
        elif isinstance(value, float):
            head += struct.pack("<I", _T_F32) + struct.pack("<f", value)
        else:
            head += struct.pack("<I", _T_STRING) + enc_string(str(value))

    blobs = []
    offset = 0
    for name, (arr, gtype) in tensors.items():
        if gtype == GGML_F32:
            blob = arr.astype(np.float32).tobytes()
        elif gtype == GGML_F16:
            blob = arr.astype(np.float16).tobytes()
        else:
            blob = _quantize(arr, gtype)
        head += enc_string(name)
        dims = list(reversed(arr.shape))  # numpy → ggml dim order
        head += struct.pack("<I", len(dims))
        for dim in dims:
            head += struct.pack("<Q", dim)
        head += struct.pack("<I", gtype)
        head += struct.pack("<Q", offset)
        padded = (len(blob) + alignment - 1) // alignment * alignment
        blobs.append(blob + b"\x00" * (padded - len(blob)))
        offset += padded

    base_pad = (-len(head)) % alignment
    with open(path, "wb") as fh:
        fh.write(bytes(head) + b"\x00" * base_pad)
        for blob in blobs:
            fh.write(blob)
