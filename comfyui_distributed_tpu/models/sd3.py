"""SD3-class image MMDiT (Stable Diffusion 3 / 3.5), flax.linen.

The second rectified-flow image family the reference serves through
ComfyUI's model zoo. Architecturally a sibling of Flux
(models/mmdit.py) with the original MMDiT design choices, kept
checkpoint-faithful to the published `model.diffusion_model.*` layout:

- 2x2 patchify via a stride-2 conv (`x_embedder.proj`) instead of a
  token linear; a LEARNED position table (`pos_embed`,
  [1, max*max, hidden]) center-cropped to the latent grid instead of
  rope;
- N "joint blocks", each an (x_block, context_block) pair with
  separate adaLN modulation/attention/MLP params and one joint
  attention over [context; x]; the FINAL block's context side is
  `pre_only` (qkv + 2-way adaLN, no proj/MLP) and its context output
  is discarded;
- optional per-head RMS Q/K norm (`attn.ln_q/ln_k` — the SD3.5
  addition; SD3-medium ships without);
- conditioning: CLIP-L + CLIP-G penultimate states concatenated on
  features, zero-padded to the T5 width, then sequence-concatenated
  with T5-XXL states; the modulation vector is timestep MLP + pooled
  (CLIP-L ++ CLIP-G) MLP.

Rectified flow exactly as the Flux family: velocity == eps under the
sampler contract, flow sigma schedule + interpolation noising selected
by `parameterization == "flow"` (models/pipeline.py, ops/samplers.py).

Flax submodule names mirror the original state-dict keys
(joint_blocks_N/x_attn_qkv ↔ joint_blocks.N.x_block.attn.qkv, ...) so
sd_checkpoint.sd3_schedule stays a straight rename.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import timestep_embedding
from ..ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class SD3Config:
    in_channels: int = 16
    patch_size: int = 2
    depth: int = 24                # joint blocks; hidden = 64 * depth
    hidden_dim: int | None = None  # default 64 * depth (the SD3 rule)
    heads: int | None = None       # default depth (head_dim 64)
    context_dim: int = 4096        # T5 width == padded CLIP width
    pooled_dim: int = 2048         # CLIP-L (768) ++ CLIP-G (1280)
    mlp_ratio: float = 4.0
    freq_dim: int = 256
    pos_embed_max: int = 192       # learned table is [max*max, hidden]
    qk_norm: bool = False          # SD3.5: per-head RMS ln_q/ln_k
    # SD3.5-medium (MMDiT-X): the first N x_blocks carry a SECOND,
    # image-only self-attention branch (`x_block.attn2.*`) and a 9-way
    # adaLN (the published x_block_self_attn_layers list is the
    # contiguous range 0..12, so an int prefix count captures it)
    dual_attn_blocks: int = 0
    parameterization: str = "flow"
    flow_shift: float = 3.0        # the published SD3 sampling shift
    dtype: str = "bfloat16"
    remat: bool = False

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def width(self) -> int:
        return self.hidden_dim if self.hidden_dim is not None else 64 * self.depth

    @property
    def n_heads(self) -> int:
        return self.heads if self.heads is not None else self.depth

    @property
    def mlp_width(self) -> int:
        return int(self.width * self.mlp_ratio)

    @property
    def adm_in_channels(self) -> int:
        """Hooks the pooled-text plumbing in pipeline._make_model_fn."""
        return self.pooled_dim


def _modulation(vec: jax.Array, n: int, width: int, name: str) -> list[jax.Array]:
    """silu(vec) → Dense(n*width) → n [B, 1, width] chunks (maps
    <name>.adaLN_modulation.1)."""
    out = nn.Dense(n * width, dtype=jnp.float32, name=f"{name}_mod_lin")(
        nn.silu(vec.astype(jnp.float32))
    )
    return [out[:, None, i * width:(i + 1) * width] for i in range(n)]


class _JointBlock(nn.Module):
    """One SD3 joint block: context/x streams with separate params and
    one joint attention, text tokens first. `pre_only` marks the final
    block's context side (qkv + 2-way adaLN only, output discarded)."""

    heads: int
    mlp_width: int
    dtype: jnp.dtype
    qk_norm: bool
    pre_only: bool
    dual_attn: bool = False  # MMDiT-X x-side self-attention branch

    @nn.compact
    def __call__(
        self,
        ctx: jax.Array,     # [B, Nc, H]
        x: jax.Array,       # [B, Nx, H]
        vec: jax.Array,     # [B, H]
    ) -> tuple[jax.Array | None, jax.Array]:
        dim = x.shape[-1]
        hd = dim // self.heads
        b, nx, _ = x.shape
        nc = ctx.shape[1]

        def qkv(h_in, n, name):
            proj = nn.Dense(3 * dim, dtype=self.dtype, name=f"{name}_attn_qkv")(
                h_in
            )
            q, k, v = jnp.split(proj, 3, axis=-1)
            q = q.reshape(b, n, self.heads, hd)
            k = k.reshape(b, n, self.heads, hd)
            v = v.reshape(b, n, self.heads, hd)
            if self.qk_norm:
                q = nn.RMSNorm(
                    epsilon=1e-6, dtype=jnp.float32, name=f"{name}_attn_ln_q"
                )(q).astype(self.dtype)
                k = nn.RMSNorm(
                    epsilon=1e-6, dtype=jnp.float32, name=f"{name}_attn_ln_k"
                )(k).astype(self.dtype)
            return q, k, v

        def pre(h_in, sh, sc, name):
            h = nn.LayerNorm(
                use_bias=False, use_scale=False, dtype=jnp.float32,
                name=f"{name}_norm1",
            )(h_in.astype(jnp.float32))
            return ((h * (1 + sc) + sh)).astype(self.dtype)

        if self.pre_only:
            c_sh1, c_sc1 = _modulation(vec, 2, dim, "ctx")
        else:
            c_sh1, c_sc1, c_g1, c_sh2, c_sc2, c_g2 = _modulation(
                vec, 6, dim, "ctx"
            )
        if self.dual_attn:
            # MMDiT-X chunk order: (msa, mlp, msa2) shift/scale/gate
            (
                x_sh1, x_sc1, x_g1, x_sh2, x_sc2, x_g2,
                x2_sh, x2_sc, x2_g,
            ) = _modulation(vec, 9, dim, "x")
        else:
            x_sh1, x_sc1, x_g1, x_sh2, x_sc2, x_g2 = _modulation(
                vec, 6, dim, "x"
            )

        cq, ck, cv = qkv(pre(ctx, c_sh1, c_sc1, "ctx"), nc, "ctx")
        xq, xk, xv = qkv(pre(x, x_sh1, x_sc1, "x"), nx, "x")

        q = jnp.concatenate([cq, xq], axis=1)
        k = jnp.concatenate([ck, xk], axis=1)
        v = jnp.concatenate([cv, xv], axis=1)
        attn = dot_product_attention(q, k, v).reshape(b, nc + nx, dim)
        c_attn, x_attn = attn[:, :nc], attn[:, nc:]

        x2_attn = None
        if self.dual_attn:
            # image-only self-attention on the same pre-norm input,
            # separately modulated (x_block.attn2.* in the checkpoint)
            q2, k2, v2 = qkv(pre(x, x2_sh, x2_sc, "x2"), nx, "x2")
            x2_attn = dot_product_attention(q2, k2, v2).reshape(b, nx, dim)

        def post(h_in, a, g1, sh2, sc2, g2, name, a2=None, g2a=None):
            h_in = (
                h_in.astype(jnp.float32)
                + nn.Dense(dim, dtype=self.dtype, name=f"{name}_attn_proj")(
                    a
                ).astype(jnp.float32) * g1
            )
            if a2 is not None:
                # MMDiT-X: the second attention's residual lands
                # between the joint-attn residual and the MLP
                h_in = h_in + nn.Dense(
                    dim, dtype=self.dtype, name=f"{name}2_attn_proj"
                )(a2).astype(jnp.float32) * g2a
            h = nn.LayerNorm(
                use_bias=False, use_scale=False, dtype=jnp.float32,
                name=f"{name}_norm2",
            )(h_in)
            h = (h * (1 + sc2) + sh2).astype(self.dtype)
            h = nn.Dense(self.mlp_width, dtype=self.dtype, name=f"{name}_mlp_fc1")(h)
            h = nn.gelu(h, approximate=True)
            y = nn.Dense(dim, dtype=self.dtype, name=f"{name}_mlp_fc2")(h)
            return (h_in + y.astype(jnp.float32) * g2).astype(self.dtype)

        x = post(
            x, x_attn, x_g1, x_sh2, x_sc2, x_g2, "x",
            a2=x2_attn, g2a=(x2_g if self.dual_attn else None),
        )
        if self.pre_only:
            return None, x
        ctx = post(ctx, c_attn, c_g1, c_sh2, c_sc2, c_g2, "ctx")
        return ctx, x


class SD3MMDiT(nn.Module):
    config: SD3Config

    @nn.compact
    def __call__(
        self,
        x: jax.Array,           # [B, h, w, C] noisy latents (NHWC)
        timesteps: jax.Array,   # [B] flow time in [0, 1]
        context: jax.Array,     # [B, T, context_dim]
        y: jax.Array | None = None,        # [B, pooled_dim]
        control: jax.Array | None = None,  # rejected (no SD3 ControlNet path)
        guidance: jax.Array | None = None,  # accepted, unused (CFG family)
        ref_latents: list | None = None,   # rejected (Kontext is Flux-only)
        skip_layers: tuple = (),           # SLG: joint blocks to bypass
    ) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        del guidance  # SD3 is CFG-guided; no distilled-guidance embedding
        if control is not None:
            raise ValueError(
                "SD3-class MMDiT has no ControlNet input path"
            )
        if ref_latents:
            raise ValueError(
                "reference latents are a Flux-Kontext capability; "
                "SD3-class MMDiT has no reference token path"
            )
        b, hh, ww, c = x.shape
        p = cfg.patch_size
        assert hh % p == 0 and ww % p == 0, "patch misalign"
        gh, gw = hh // p, ww // p
        nx = gh * gw
        dim = cfg.width

        # stride-p conv patchify as a dense over (c, ph, pw)-flattened
        # patches — matches the x_embedder.proj conv kernel transform
        tokens = x.reshape(b, gh, p, gw, p, c)
        tokens = tokens.transpose(0, 1, 3, 5, 2, 4).reshape(b, nx, c * p * p)
        img = nn.Dense(dim, dtype=dt, name="x_embedder_proj")(
            tokens.astype(dt)
        )

        # learned position table, center-cropped to the latent grid
        # (the SD3 cropped_pos_embed rule)
        m = cfg.pos_embed_max
        assert gh <= m and gw <= m, "latent grid exceeds pos_embed_max"
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=dim**-0.5),
            (1, m * m, dim),
            jnp.float32,
        )
        top = (m - gh) // 2
        left = (m - gw) // 2
        pos2d = pos.reshape(m, m, dim)[top:top + gh, left:left + gw]
        img = img + pos2d.reshape(1, nx, dim).astype(dt)

        ctx = nn.Dense(dim, dtype=dt, name="context_embedder")(
            context.astype(dt)
        )

        vec = nn.Dense(dim, dtype=jnp.float32, name="t_embedder_mlp_0")(
            timestep_embedding(
                timesteps.astype(jnp.float32) * 1000.0, cfg.freq_dim
            )
        )
        vec = nn.Dense(dim, dtype=jnp.float32, name="t_embedder_mlp_2")(
            nn.silu(vec)
        )
        if y is None:
            y = jnp.zeros((b, cfg.pooled_dim), jnp.float32)
        yv = nn.Dense(dim, dtype=jnp.float32, name="y_embedder_mlp_0")(
            y.astype(jnp.float32)
        )
        vec = vec + nn.Dense(dim, dtype=jnp.float32, name="y_embedder_mlp_2")(
            nn.silu(yv)
        )

        block_cls = (
            nn.remat(_JointBlock, static_argnums=()) if cfg.remat else _JointBlock
        )
        for i in range(cfg.depth):
            if i in skip_layers:
                # skip-layer guidance: the whole joint block is
                # bypassed (static python control flow — skip sets are
                # compile-time constants, one program per set)
                continue
            pre_only = i == cfg.depth - 1
            ctx_out, img = block_cls(
                cfg.n_heads, cfg.mlp_width, dt, cfg.qk_norm, pre_only,
                i < cfg.dual_attn_blocks,
                name=f"joint_blocks_{i}",
            )(ctx, img, vec)
            if not pre_only:
                ctx = ctx_out

        sh, sc = _modulation(vec, 2, dim, "final_layer_adaLN")
        # reuse the Flux chunk order (shift, scale): x*(1+scale)+shift
        h = nn.LayerNorm(
            use_bias=False, use_scale=False, dtype=jnp.float32
        )(img.astype(jnp.float32))
        h = h * (1 + sc) + sh
        out = nn.Dense(c * p * p, dtype=jnp.float32, name="final_layer_linear")(h)
        # unpatchify in DiT order (p, p, c) — unlike Flux's (c, ph, pw),
        # SD3's final_layer.linear emits 'nhw(pqc)' columns; mixing the
        # orders would permute every 2x2 patch of real checkpoints
        out = out.reshape(b, gh, gw, p, p, c)
        out = out.transpose(0, 1, 3, 2, 4, 5).reshape(b, hh, ww, c)
        return out
