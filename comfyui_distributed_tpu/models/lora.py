"""LoRA loading + application onto flax param trees.

The reference applies LoRAs through ComfyUI's LoraLoader; here the
standard kohya-format safetensors layout —

    lora_unet_<sd_path_with_underscores>.lora_down.weight  [r, I]
    lora_unet_<...>.lora_up.weight                         [O, r]
    lora_unet_<...>.alpha                                  scalar
    lora_te_* (SD1.x) / lora_te1_* + lora_te2_* (SDXL)     (text enc)

— is mapped onto the same flax paths the checkpoint schedules use.
UNet kohya names are derived FROM the schedule (sd key with
dots→underscores) so there is exactly one naming source of truth;
text-encoder names are generated in the HF layout kohya uses for BOTH
SDXL encoders (its te2 keys say `text_model_encoder_layers_…` even
though the checkpoint stores that encoder in the OpenCLIP layout).

Application: W' = W + strength * (alpha / rank) * (up @ down), merged
into the kernel ([I, O] layout: delta = down.T @ up.T). Merging keeps
the sampling path identical (no runtime adapter branches) — the
ComfyUI model-patch semantics. Only targeted leaves are pulled to host
and replaced; every other leaf stays device-resident.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .sd_checkpoint import (
    _LINEAR,
    _LINEAR_NOBIAS,
    _PROJ,
    flux_schedule,
    unet_schedule,
)


def _kohya_unet_name(sd_key: str) -> str | None:
    """sd schedule key → kohya LoRA module name (None if not a LoRA
    target family)."""
    if sd_key.startswith("model.diffusion_model."):
        stem = sd_key[len("model.diffusion_model."):]
        return "lora_unet_" + stem.replace(".", "_")
    return None


# kohya module suffix → flax Dense name inside a text-encoder block
_TE_MODULES = (
    ("self_attn_q_proj", "q"),
    ("self_attn_k_proj", "k"),
    ("self_attn_v_proj", "v"),
    ("self_attn_out_proj", "proj"),
    ("mlp_fc1", "fc1"),
    ("mlp_fc2", "fc2"),
)


def _te_targets(cfg, kohya_prefix: str, part: str) -> dict[str, tuple[str, str]]:
    """Kohya names for one CLIP text transformer. Generated directly
    (not from the checkpoint schedule) because kohya's naming is fixed
    to the HF layout regardless of checkpoint prefix or on-disk layout
    — this also makes SDXL's `conditioner.embedders.*` prefixes a
    non-issue."""
    targets: dict[str, tuple[str, str]] = {}
    for i in range(cfg.layers):
        for suffix, dense in _TE_MODULES:
            name = f"{kohya_prefix}_text_model_encoder_layers_{i}_{suffix}"
            targets[name] = (part, f"params/block_{i}/{dense}/kernel")
    return targets


def lora_target_map(
    unet_cfg, te_cfg=None, te2_cfg=None
) -> dict[str, tuple[str, str]]:
    """{kohya_module_name: (part, flax_kernel_path)} for every linear/
    projection weight a LoRA can target.

    Raises ValueError for unsupported backbone configs (video DiT) —
    LoRA merging is implemented for the UNet and MMDiT (Flux) families.
    """
    from .mmdit import MMDiTConfig
    from .unet import UNetConfig

    if isinstance(unet_cfg, MMDiTConfig):
        # Flux kohya layout: bare transformer keys, underscored
        # (lora_unet_double_blocks_0_img_attn_qkv). Text-encoder LoRAs
        # target the CLIP tower as lora_te1_* — T5 is not a LoRA
        # target in the kohya flux trainers, so te_cfg (the T5 config)
        # is ignored and te2_cfg (CLIP, part 'te2') takes lora_te1.
        targets: dict[str, tuple[str, str]] = {}
        for sd, fx, kind in flux_schedule(unet_cfg):
            if kind not in (_LINEAR, _LINEAR_NOBIAS, _PROJ):
                continue
            targets["lora_unet_" + sd.replace(".", "_")] = (
                "unet", f"params/{fx}/kernel",
            )
        if te2_cfg is not None:
            targets.update(_te_targets(te2_cfg, "lora_te1", "te2"))
        return targets
    if not isinstance(unet_cfg, UNetConfig):
        raise ValueError(
            "LoRA merging is only supported for UNet- and MMDiT-family "
            f"models (got config {type(unet_cfg).__name__})"
        )
    targets: dict[str, tuple[str, str]] = {}
    for sd, fx, kind in unet_schedule(unet_cfg):
        if kind not in (_LINEAR, _LINEAR_NOBIAS, _PROJ):
            continue
        name = _kohya_unet_name(sd)
        if name is None:
            continue
        targets[name] = ("unet", f"params/{fx}/kernel")
    if te_cfg is not None:
        # SD1.x tools emit lora_te_*, SDXL tools lora_te1_* for the
        # CLIP-L half; accept both for the primary encoder.
        targets.update(_te_targets(te_cfg, "lora_te", "te"))
        targets.update(_te_targets(te_cfg, "lora_te1", "te"))
    if te2_cfg is not None:
        targets.update(_te_targets(te2_cfg, "lora_te2", "te2"))
    return targets


def read_lora(path: str) -> dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    return load_file(path)


def parse_lora(state_dict: dict[str, np.ndarray]) -> dict[str, dict]:
    """Group flat LoRA keys → {module: {down, up, alpha}}."""
    modules: dict[str, dict] = {}
    for key, value in state_dict.items():
        if key.endswith(".lora_down.weight"):
            modules.setdefault(key[: -len(".lora_down.weight")], {})["down"] = value
        elif key.endswith(".lora_up.weight"):
            modules.setdefault(key[: -len(".lora_up.weight")], {})["up"] = value
        elif key.endswith(".alpha"):
            modules.setdefault(key[: -len(".alpha")], {})["alpha"] = float(value)
    return modules


def _flatten_leaves(tree: Any, out: dict[str, Any], path: str = "") -> None:
    """Flatten to {path: leaf} keeping leaves as-is (device arrays stay
    on device — no tree-wide host copy)."""
    if isinstance(tree, dict):
        for key, value in tree.items():
            _flatten_leaves(value, out, f"{path}/{key}" if path else str(key))
    else:
        out[path] = tree


def apply_lora(
    params_by_part: dict[str, Any],
    lora_sd: dict[str, np.ndarray],
    unet_cfg,
    te_cfg=None,
    te2_cfg=None,
    strength: float = 1.0,
    te_strength: float | None = None,
) -> tuple[dict[str, Any], list[str]]:
    """Merge a LoRA into {'unet': tree, 'te': tree[, 'te2': tree]}.

    Returns (new trees, unmatched module names). Unmatched modules are
    reported, not fatal — partial LoRAs (unet-only, te-only) are
    normal. Parts whose trees are untouched are returned as the same
    object; patched parts are rebuilt with only the targeted kernels
    replaced (device-put back), so a few-layer LoRA neither copies nor
    re-uploads the full weight set.
    """
    import jax.numpy as jnp

    from .io import unflatten_params

    te_strength = strength if te_strength is None else te_strength
    targets = lora_target_map(unet_cfg, te_cfg, te2_cfg)
    modules = parse_lora(lora_sd)

    flats: dict[str, dict[str, Any]] = {}
    for part, tree in params_by_part.items():
        flat: dict[str, Any] = {}
        _flatten_leaves(tree, flat)
        flats[part] = flat
    touched: set[str] = set()
    unmatched: list[str] = []
    for name, payload in modules.items():
        target = targets.get(name)
        if target is None or "down" not in payload or "up" not in payload:
            unmatched.append(name)
            continue
        part, path = target
        flat = flats.get(part)
        if flat is None or path not in flat:
            unmatched.append(name)
            continue
        down = np.asarray(payload["down"], np.float32)
        up = np.asarray(payload["up"], np.float32)
        if down.ndim == 4:  # conv1x1-style LoRA on projection layers
            down = down[:, :, 0, 0]
            up = up[:, :, 0, 0]
        rank = down.shape[0]
        alpha = float(payload.get("alpha", rank))
        s = strength if part == "unet" else te_strength
        delta = (alpha / rank) * (down.T @ up.T)  # [I, O] kernel layout
        kernel = np.asarray(flat[path], np.float32)  # single-leaf fetch
        if delta.shape != kernel.shape:
            unmatched.append(name)
            continue
        dtype = flat[path].dtype
        flat[path] = jnp.asarray(kernel + s * delta, dtype=dtype)
        touched.add(part)
    return (
        {
            part: unflatten_params(flat) if part in touched
            else params_by_part[part]
            for part, flat in flats.items()
        },
        unmatched,
    )
