"""LoRA loading + application onto flax param trees.

The reference applies LoRAs through ComfyUI's LoraLoader; here the
standard kohya-format safetensors layout —

    lora_unet_<sd_path_with_underscores>.lora_down.weight  [r, I]
    lora_unet_<...>.lora_up.weight                         [O, r]
    lora_unet_<...>.alpha                                  scalar
    lora_te_text_model_<...> / lora_te1_* / lora_te2_*     (text enc)

— is mapped onto the same flax paths the checkpoint schedules use.
The kohya name of a target is derived FROM the schedule (sd key with
dots→underscores), so there is exactly one naming source of truth and
no ambiguity when parsing underscored names back.

Application: W' = W + strength * (alpha / rank) * (up @ down), merged
into the kernel ([I, O] layout: delta = down.T @ up.T). Merging keeps
the sampling path identical (no runtime adapter branches) — the
ComfyUI model-patch semantics.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .sd_checkpoint import (
    _LINEAR,
    _LINEAR_NOBIAS,
    _PROJ,
    text_encoder_schedule,
    unet_schedule,
)


def _kohya_name(sd_key: str) -> str | None:
    """sd schedule key → kohya LoRA module name (None if not a LoRA
    target family)."""
    if sd_key.startswith("model.diffusion_model."):
        stem = sd_key[len("model.diffusion_model."):]
        return "lora_unet_" + stem.replace(".", "_")
    if sd_key.startswith("cond_stage_model.transformer."):
        stem = sd_key[len("cond_stage_model.transformer."):]
        return "lora_te_" + stem.replace(".", "_")
    return None


def lora_target_map(unet_cfg, te_cfg=None) -> dict[str, tuple[str, str]]:
    """{kohya_module_name: (part, flax_kernel_path)} for every linear/
    projection weight a LoRA can target."""
    targets: dict[str, tuple[str, str]] = {}
    schedules = [("unet", unet_schedule(unet_cfg))]
    if te_cfg is not None:
        schedules.append(("te", text_encoder_schedule(te_cfg)))
    for part, entries in schedules:
        for sd, fx, kind in entries:
            if kind not in (_LINEAR, _LINEAR_NOBIAS, _PROJ):
                continue
            name = _kohya_name(f"{sd}.weight")
            if name is None:
                continue
            targets[name.removesuffix("_weight")] = (
                part, f"params/{fx}/kernel"
            )
    return targets


def read_lora(path: str) -> dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    return load_file(path)


def parse_lora(state_dict: dict[str, np.ndarray]) -> dict[str, dict]:
    """Group flat LoRA keys → {module: {down, up, alpha}}."""
    modules: dict[str, dict] = {}
    for key, value in state_dict.items():
        if key.endswith(".lora_down.weight"):
            modules.setdefault(key[: -len(".lora_down.weight")], {})["down"] = value
        elif key.endswith(".lora_up.weight"):
            modules.setdefault(key[: -len(".lora_up.weight")], {})["up"] = value
        elif key.endswith(".alpha"):
            modules.setdefault(key[: -len(".alpha")], {})["alpha"] = float(value)
    return modules


def apply_lora(
    params_by_part: dict[str, Any],
    lora_sd: dict[str, np.ndarray],
    unet_cfg,
    te_cfg=None,
    strength: float = 1.0,
    te_strength: float | None = None,
) -> tuple[dict[str, Any], list[str]]:
    """Merge a LoRA into {'unet': tree, 'te': tree} param trees.

    Returns (new trees, unmatched module names). Unmatched modules are
    reported, not fatal — partial LoRAs (unet-only, te-only) are
    normal.
    """
    import jax

    from .io import flatten_params, unflatten_params

    te_strength = strength if te_strength is None else te_strength
    targets = lora_target_map(unet_cfg, te_cfg)
    modules = parse_lora(lora_sd)

    flats = {
        part: flatten_params(jax.device_get(tree))
        for part, tree in params_by_part.items()
    }
    unmatched: list[str] = []
    for name, payload in modules.items():
        target = targets.get(name)
        if target is None or "down" not in payload or "up" not in payload:
            unmatched.append(name)
            continue
        part, path = target
        flat = flats.get(part)
        if flat is None or path not in flat:
            unmatched.append(name)
            continue
        down = np.asarray(payload["down"], np.float32)
        up = np.asarray(payload["up"], np.float32)
        if down.ndim == 4:  # conv1x1-style LoRA on projection layers
            down = down[:, :, 0, 0]
            up = up[:, :, 0, 0]
        rank = down.shape[0]
        alpha = float(payload.get("alpha", rank))
        s = strength if part == "unet" else te_strength
        delta = (alpha / rank) * (down.T @ up.T)  # [I, O] kernel layout
        kernel = np.asarray(flat[path], np.float32)
        if delta.shape != kernel.shape:
            unmatched.append(name)
            continue
        flat[path] = (kernel + s * delta).astype(flat[path].dtype)
    return (
        {part: unflatten_params(flat) for part, flat in flats.items()},
        unmatched,
    )
