"""CLIP ViT image encoder (WAN i2v's image-conditioning model).

The reference's WAN i2v workflow feeds the first frame through
ComfyUI's CLIPVisionLoader/CLIPVisionEncode (reference
workflows/distributed-wan i2v variant); WAN conditions on ViT-H/14
PENULTIMATE hidden states (257 patch+class tokens, width 1280). This
is that tower, HF CLIPVisionModel layout-faithful so real
clip-vision checkpoints map key-by-key
(sd_checkpoint.clip_vision_schedule).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# CLIP preprocessing constants (OpenAI/open_clip convention)
CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_STD = (0.26862954, 0.26130258, 0.27577711)


@dataclasses.dataclass(frozen=True)
class ClipVisionConfig:
    image_size: int = 224
    patch_size: int = 14
    width: int = 1280
    layers: int = 32
    heads: int = 16
    mlp_ratio: float = 4.0
    dtype: str = "bfloat16"
    # WAN consumes the penultimate layer's hidden states (skip the last
    # block, no post LN); False returns the post-LN final hidden states
    penultimate_hidden: bool = True

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def tokens(self) -> int:
        return (self.image_size // self.patch_size) ** 2 + 1


class _ViTBlock(nn.Module):
    heads: int
    mlp_dim: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from ..ops.attention import dot_product_attention

        b, n, width = x.shape
        head_dim = width // self.heads
        h = nn.LayerNorm(dtype=jnp.float32, name="LayerNorm_0")(
            x.astype(jnp.float32)
        ).astype(self.dtype)
        q = nn.Dense(width, dtype=self.dtype, name="q")(h)
        k = nn.Dense(width, dtype=self.dtype, name="k")(h)
        v = nn.Dense(width, dtype=self.dtype, name="v")(h)
        attn = dot_product_attention(
            q.reshape(b, n, self.heads, head_dim),
            k.reshape(b, n, self.heads, head_dim),
            v.reshape(b, n, self.heads, head_dim),
        ).reshape(b, n, width)
        x = x + nn.Dense(width, dtype=self.dtype, name="proj")(attn)
        h = nn.LayerNorm(dtype=jnp.float32, name="LayerNorm_1")(
            x.astype(jnp.float32)
        ).astype(self.dtype)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="fc1")(h)
        h = nn.gelu(h, approximate=False)
        return x + nn.Dense(width, dtype=self.dtype, name="fc2")(h)


class ClipVisionEncoder(nn.Module):
    """[B, H, W, 3] image in [0, 1] → [B, tokens, width] hidden states
    (class token first, HF ordering)."""

    config: ClipVisionConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        b = images.shape[0]
        if images.shape[1] != cfg.image_size or images.shape[2] != cfg.image_size:
            # reference CLIP preprocessing: scale the SHORT side to the
            # target then center-crop — aspect-preserving (a straight
            # resize would anisotropically stretch non-square frames)
            h, w = images.shape[1], images.shape[2]
            scale = cfg.image_size / min(h, w)
            nh, nw = max(cfg.image_size, round(h * scale)), max(
                cfg.image_size, round(w * scale)
            )
            images = jax.image.resize(
                images, (b, nh, nw, images.shape[3]), method="cubic"
            )
            top = (nh - cfg.image_size) // 2
            left = (nw - cfg.image_size) // 2
            images = images[
                :, top : top + cfg.image_size, left : left + cfg.image_size, :
            ]
        mean = jnp.asarray(CLIP_MEAN, images.dtype)
        std = jnp.asarray(CLIP_STD, images.dtype)
        x = (images - mean) / std

        patches = nn.Conv(
            cfg.width,
            (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            use_bias=False,
            dtype=dt,
            name="patch_embedding",
        )(x.astype(dt))
        patches = patches.reshape(b, -1, cfg.width)

        cls = self.param(
            "class_embedding", nn.initializers.normal(0.02), (cfg.width,),
            jnp.float32,
        )
        tokens = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(dt), (b, 1, cfg.width)), patches],
            axis=1,
        )
        pos = self.param(
            "position_embedding",
            nn.initializers.normal(0.02),
            (cfg.tokens, cfg.width),
            jnp.float32,
        )
        tokens = tokens + pos.astype(dt)[None]
        tokens = nn.LayerNorm(dtype=jnp.float32, name="pre_ln")(
            tokens.astype(jnp.float32)
        ).astype(dt)

        mlp_dim = int(cfg.width * cfg.mlp_ratio)
        depth = cfg.layers - 1 if cfg.penultimate_hidden else cfg.layers
        for i in range(depth):
            tokens = _ViTBlock(
                cfg.heads, mlp_dim, dt, name=f"block_{i}"
            )(tokens)
        if cfg.penultimate_hidden:
            # WAN consumes the raw penultimate hidden states — no
            # final block, no post LN
            return tokens
        # run the last block + post LN (standard CLIP pooled path)
        tokens = _ViTBlock(
            cfg.heads, mlp_dim, dt, name=f"block_{cfg.layers - 1}"
        )(tokens)
        return nn.LayerNorm(dtype=jnp.float32, name="post_ln")(
            tokens.astype(jnp.float32)
        ).astype(dt)


@dataclasses.dataclass
class ClipVisionBundle:
    """A standalone CLIP-vision tower (the CLIPVisionLoader node's
    output): `.encode(images)` returns the hidden-state tokens
    [B, T, width] (class token first; penultimate layer for the
    WAN-style configs)."""

    name: str
    module: ClipVisionEncoder
    params: object

    def encode(self, images: jax.Array) -> jax.Array:
        return self.module.apply(self.params, images)


def build_clip_vision(name: str, key):
    """create + init + real-weight merge for a registry CLIP-vision
    tower (weights through CDT_CHECKPOINT_DIR/<name>.{safetensors,
    ckpt}). The ONE shared build path: the standalone CLIPVisionLoader
    and the bundled i2v path (video_pipeline.load_video_pipeline) both
    call this, so loading fixes land in both. Returns
    (module, cfg, params)."""
    from . import sd_checkpoint as sdc
    from .registry import create_model, get_config

    module = create_model(name)
    cfg = get_config(name)
    params = module.init(
        key, jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
    )
    ckpt = sdc.find_checkpoint(name)
    if ckpt:
        from ..utils.logging import log

        log(f"loading CLIP-vision checkpoint {ckpt} for {name}")
        params, _ = sdc.load_clip_vision_weights(
            sdc.read_checkpoint(ckpt), cfg, params
        )
    return module, cfg, params


def load_clip_vision(name: str = "clip-vision-h", seed: int = 0) -> ClipVisionBundle:
    """Standalone tower for the CLIPVisionLoader node."""
    from .pipeline import maybe_cast_params

    module, _cfg, params = build_clip_vision(name, jax.random.key(seed))
    return ClipVisionBundle(
        name=name, module=module, params=maybe_cast_params(params)
    )
