"""UMT5-class text encoder (WAN's conditioning model), flax.linen.

The reference's WAN workflows condition on UMT5-XXL embeddings via
ComfyUI's CLIPLoader (reference workflows/distributed-wan*.json load a
umt5 text-encoder checkpoint). This is the architecture-faithful
encoder half: relative-position-bias attention (per-layer bias, the
UMT5 variant), RMS pre-norms, gated-GELU feed-forward, no biases
anywhere, and T5's unscaled attention logits. Real `encoder.block.N.*`
state dicts map onto this tree via sd_checkpoint.t5_encoder_schedule.

Tokenization: UMT5 uses a SentencePiece vocab, which is a separate
asset. When `CDT_T5_SPM` points at a real spm model (loaded through
transformers' T5 tokenizer), prompts tokenize faithfully; without it
the pipeline falls back to the committed CLIP BPE ids — deterministic
across hosts (what the distributed tier needs) but only meaningful
with random-init weights.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class T5EncoderConfig:
    vocab_size: int = 256384  # umt5 sentencepiece vocab
    d_model: int = 4096
    d_kv: int = 64
    d_ff: int = 10240
    layers: int = 24
    heads: int = 64
    rel_buckets: int = 32
    rel_max_distance: int = 128
    max_length: int = 512
    pad_id: int = 0
    # UMT5 (WAN) gives every layer its own relative-position bias
    # table; classic T5 v1.1 (the Flux text encoder) shares layer 0's
    # table across the stack
    per_layer_rel_bias: bool = True
    dtype: str = "bfloat16"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def relative_position_buckets(
    length: int, num_buckets: int = 32, max_distance: int = 128
) -> np.ndarray:
    """[L, L] int32 bidirectional T5 bucket table (query rows, key
    cols), computed trace-time in numpy — static for a fixed length."""
    ctx = np.arange(length)[:, None]
    mem = np.arange(length)[None, :]
    rel = mem - ctx  # key pos - query pos
    half = num_buckets // 2
    out = np.where(rel > 0, half, 0).astype(np.int64)
    rp = np.abs(rel)
    max_exact = half // 2
    is_small = rp < max_exact
    # log-spaced buckets out to max_distance
    with np.errstate(divide="ignore"):
        large = max_exact + (
            np.log(np.maximum(rp, 1) / max_exact)
            / np.log(max_distance / max_exact)
            * (half - max_exact)
        ).astype(np.int64)
    large = np.minimum(large, half - 1)
    out += np.where(is_small, rp, large)
    return out.astype(np.int32)


class _T5Block(nn.Module):
    config: T5EncoderConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        buckets: jax.Array,
        key_mask: jax.Array,
        shared_bias: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        b, n, _ = x.shape
        inner = cfg.heads * cfg.d_kv

        # --- self-attention (pre-RMS, unscaled logits; per-layer
        # relative position bias is the UMT5 distinction — classic T5
        # passes the stack-shared table in via shared_bias) ---
        h = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name="attn_norm")(
            x.astype(jnp.float32)
        ).astype(dt)
        q = nn.Dense(inner, use_bias=False, dtype=dt, name="q")(h)
        k = nn.Dense(inner, use_bias=False, dtype=dt, name="k")(h)
        v = nn.Dense(inner, use_bias=False, dtype=dt, name="v")(h)
        q = q.reshape(b, n, cfg.heads, cfg.d_kv)
        k = k.reshape(b, n, cfg.heads, cfg.d_kv)
        v = v.reshape(b, n, cfg.heads, cfg.d_kv)
        if shared_bias is not None:
            rel_bias = shared_bias
        else:
            rel_bias = nn.Embed(
                cfg.rel_buckets, cfg.heads, dtype=jnp.float32, name="rel_bias"
            )(buckets)  # [N, N, H]
        scores = jnp.einsum(
            "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
        )  # T5: no 1/sqrt(d) scaling (folded into init)
        scores = scores + rel_bias.transpose(2, 0, 1)[None]
        scores = jnp.where(key_mask[:, None, None, :], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhnm,bmhd->bnhd", probs, v.astype(jnp.float32))
        x = x + nn.Dense(
            cfg.d_model, use_bias=False, dtype=dt, name="o"
        )(attn.reshape(b, n, inner).astype(dt))

        # --- gated-GELU feed-forward ---
        h = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name="ffn_norm")(
            x.astype(jnp.float32)
        ).astype(dt)
        gate = nn.gelu(
            nn.Dense(cfg.d_ff, use_bias=False, dtype=dt, name="wi_0")(h),
            approximate=True,
        )
        up = nn.Dense(cfg.d_ff, use_bias=False, dtype=dt, name="wi_1")(h)
        return x + nn.Dense(
            cfg.d_model, use_bias=False, dtype=dt, name="wo"
        )(gate * up)


class T5Encoder(nn.Module):
    """Returns (hidden [B, N, d_model], pooled [B, d_model]) — pooled is
    the mask-weighted mean, the usual T5 sentence embedding; WAN uses
    the hidden states only."""

    config: T5EncoderConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> tuple[jax.Array, jax.Array]:
        cfg = self.config
        b, n = tokens.shape
        key_mask = tokens != cfg.pad_id
        x = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype,
            name="token_embed",
        )(tokens)
        buckets = jnp.asarray(
            relative_position_buckets(
                n, cfg.rel_buckets, cfg.rel_max_distance
            )
        )
        shared_bias = None
        if not cfg.per_layer_rel_bias:
            shared_bias = nn.Embed(
                cfg.rel_buckets, cfg.heads, dtype=jnp.float32, name="rel_bias"
            )(buckets)
        for i in range(cfg.layers):
            x = _T5Block(cfg, name=f"block_{i}")(
                x, buckets, key_mask, shared_bias
            )
        hidden = nn.RMSNorm(
            epsilon=1e-6, dtype=jnp.float32, name="final_norm"
        )(x.astype(jnp.float32))
        denom = jnp.maximum(key_mask.sum(axis=1, keepdims=True), 1)
        pooled = (hidden * key_mask[:, :, None]).sum(axis=1) / denom
        return hidden, pooled


def t5_vocab_canonical() -> bool:
    """Whether default-constructed T5 tokenizers are sentencepiece-
    backed (CDT_T5_SPM names a loadable asset). Cached per resolved
    path — polled by /distributed/system_info, which must not re-parse
    the spm file per request (mirrors clip_bpe's memoized singleton)."""
    return _t5_canonical_cached(os.environ.get("CDT_T5_SPM") or "")


def _t5_canonical_cached(path: str) -> bool:
    if path not in _T5_CANONICAL_CACHE:
        if not path:
            _T5_CANONICAL_CACHE[path] = False
        else:
            _T5_CANONICAL_CACHE[path] = T5Tokenizer(
                max_length=1, spm_path=path
            ).is_canonical
    return _T5_CANONICAL_CACHE[path]


_T5_CANONICAL_CACHE: dict[str, bool] = {}


class T5Tokenizer:
    """SentencePiece-faithful when a real spm asset is available
    (`CDT_T5_SPM` or `spm_path`); otherwise falls back to the committed
    CLIP BPE (deterministic ids, placeholder semantics — see module
    doc). Output is fixed-length, 0-padded (T5 pad id), with T5's
    closing </s> (id 1) when the spm path is active."""

    EOS = 1
    #: the CLIP-BPE fallback emits ids in [0, 49408) — larger than the
    #: real T5 sentencepiece vocab (32128). XLA gather silently clamps
    #: out-of-range ids, which would corrupt conditioning without a
    #: trace; pass the model's ``vocab_size`` so the fallback can remap
    #: deterministically and warn loudly instead.
    BPE_ID_SPACE = 49408

    def __init__(
        self,
        max_length: int = 512,
        spm_path: Optional[str] = None,
        vocab_size: Optional[int] = None,
    ):
        self.max_length = max_length
        self.vocab_size = vocab_size
        self._warned_overflow = False
        self._spm = None
        path = spm_path or os.environ.get("CDT_T5_SPM")
        if path:
            if not os.path.exists(path):
                # an explicitly configured vocab must not silently
                # degrade to placeholder ids — garbage conditioning
                # with real weights is worse than a loud failure
                raise FileNotFoundError(
                    f"T5 sentencepiece vocab not found: {path!r} "
                    "(CDT_T5_SPM / spm_path)"
                )
            from transformers import T5TokenizerFast

            self._spm = T5TokenizerFast(vocab_file=path)
            spm_vocab = int(self._spm.vocab_size)
            if vocab_size is not None and spm_vocab > vocab_size:
                # a real vocab paired with a smaller embedding table is
                # a misconfiguration, not a degraded fallback — folding
                # real ids would corrupt real weights, so fail loudly
                raise ValueError(
                    f"sentencepiece vocab at {path!r} has {spm_vocab} "
                    f"ids but this encoder's embedding table holds only "
                    f"{vocab_size}; wrong vocab for this model "
                    "(e.g. a umt5 asset on a t5-xxl Flux/SD3 encoder)"
                )

    @property
    def is_canonical(self) -> bool:
        """True when a real sentencepiece vocab backs tokenization
        (mirrors ``ClipBPE.is_canonical`` for system_info surfacing)."""
        return self._spm is not None

    def encode(self, text: str) -> np.ndarray:
        out = np.zeros((self.max_length,), dtype=np.int32)
        if self._spm is not None:
            ids = self._spm.encode(text)
            if len(ids) > self.max_length:
                # keep the terminal </s> under truncation (T5 contract)
                ids = ids[: self.max_length - 1] + [self.EOS]
        else:
            from .clip_bpe import get_bpe

            body = get_bpe(None).encode_text(text)[: self.max_length - 1]
            ids = body + [self.EOS]
            ids = self._fold_into_vocab(ids)
        out[: len(ids)] = ids
        return out

    def _fold_into_vocab(self, ids: list[int]) -> list[int]:
        """Fallback ids that exceed the model's embedding table would be
        silently clamped by XLA gather — remap them deterministically
        into [2, vocab_size) (skipping pad=0 / eos=1 so the key mask and
        the T5 contract stay intact) and warn loudly once."""
        vs = self.vocab_size
        if vs is None or vs >= self.BPE_ID_SPACE:
            return ids
        if not self._warned_overflow and any(i >= vs for i in ids):
            import logging

            logging.getLogger("cdt.t5_encoder").warning(
                "T5 fallback tokenizer (no CDT_T5_SPM) emits CLIP-BPE ids "
                "up to %d but this encoder's vocab_size is %d; "
                "out-of-range ids are being folded into the valid range. "
                "Conditioning is NOT faithful to real checkpoints — point "
                "CDT_T5_SPM at the model's sentencepiece vocab.",
                self.BPE_ID_SPACE - 1,
                vs,
            )
            self._warned_overflow = True
        span = max(vs - 2, 1)
        return [i if i < vs else 2 + (i - 2) % span for i in ids]

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts], axis=0)
