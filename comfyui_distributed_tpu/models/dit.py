"""Video diffusion transformer (WAN class), flax.linen.

The model family behind the reference's WAN t2v/i2v workflows
(reference workflows/distributed-wan*.json), rebuilt as a TPU-native
DiT: 3D patchification of [B, F, H, W, C] video latents, joint
spatio-temporal self-attention (sequence-parallel-ready token layout),
cross-attention to text, AdaLN-zero timestep modulation, rotary
position embeddings. Sized by config: wan-1.3b-class runs seed-parallel
on a v5e-8; wan-14b-class FSDP-shards across a v5p-16 (BASELINE.md).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .layers import timestep_embedding
from ..ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    in_channels: int = 16
    patch_size: tuple[int, int, int] = (1, 2, 2)  # (frames, h, w)
    hidden_dim: int = 1536
    depth: int = 30
    heads: int = 12
    context_dim: int = 4096
    dtype: str = "bfloat16"
    # Context/sequence parallelism: when set, the model is being called
    # inside shard_map with the FRAME axis sharded along this mesh axis;
    # self-attention runs as ring attention over the full sequence and
    # RoPE positions are offset by the shard index.
    seq_axis: str | None = None

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def _rope_freqs(dim: int, length: int, theta: float = 10000.0) -> np.ndarray:
    inv = 1.0 / (theta ** (np.arange(0, dim, 2) / dim))
    t = np.arange(length)
    freqs = np.outer(t, inv)
    return np.stack([np.cos(freqs), np.sin(freqs)], axis=-1)  # [L, dim/2, 2]


def apply_rope(x: jax.Array, freqs: jax.Array) -> jax.Array:
    """x: [B, N, H, D]; freqs: [N, D/2, 2]."""
    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], -1, 2)
    cos = freqs[None, :, None, :, 0]
    sin = freqs[None, :, None, :, 1]
    out = jnp.stack(
        [
            xf[..., 0] * cos - xf[..., 1] * sin,
            xf[..., 0] * sin + xf[..., 1] * cos,
        ],
        axis=-1,
    )
    return out.reshape(x.shape).astype(x.dtype)


class _AdaLNBlock(nn.Module):
    heads: int
    dtype: jnp.dtype
    seq_axis: str | None = None

    @nn.compact
    def __call__(
        self, x: jax.Array, cond: jax.Array, context: jax.Array, freqs: jax.Array
    ) -> jax.Array:
        dim = x.shape[-1]
        head_dim = dim // self.heads
        # 6-way modulation, zero-init so blocks start as identity
        mod = nn.Dense(
            6 * dim, dtype=jnp.float32, kernel_init=nn.initializers.zeros,
            name="ada_mod",
        )(nn.silu(cond.astype(jnp.float32)))
        sh1, sc1, g1, sh2, sc2, g2 = jnp.split(mod[:, None, :], 6, axis=-1)

        h = nn.LayerNorm(use_bias=False, use_scale=False, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        h = (h * (1 + sc1) + sh1).astype(self.dtype)
        b, n, _ = h.shape
        q = nn.Dense(dim, dtype=self.dtype, name="q")(h).reshape(
            b, n, self.heads, head_dim
        )
        k = nn.Dense(dim, dtype=self.dtype, name="k")(h).reshape(
            b, n, self.heads, head_dim
        )
        v = nn.Dense(dim, dtype=self.dtype, name="v")(h).reshape(
            b, n, self.heads, head_dim
        )
        q = apply_rope(q, freqs)
        k = apply_rope(k, freqs)
        if self.seq_axis is not None:
            from ..ops.ring_attention import ring_attention

            attn = ring_attention(q, k, v, self.seq_axis).reshape(b, n, dim)
        else:
            attn = dot_product_attention(q, k, v).reshape(b, n, dim)
        x = x + g1 * nn.Dense(dim, dtype=self.dtype, name="attn_proj")(attn)

        # cross-attention to text (un-modulated, WAN-style)
        h = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32)).astype(self.dtype)
        m = context.shape[1]
        qc = nn.Dense(dim, dtype=self.dtype, name="xq")(h).reshape(
            b, n, self.heads, head_dim
        )
        kc = nn.Dense(dim, dtype=self.dtype, name="xk")(context).reshape(
            b, m, self.heads, head_dim
        )
        vc = nn.Dense(dim, dtype=self.dtype, name="xv")(context).reshape(
            b, m, self.heads, head_dim
        )
        xattn = dot_product_attention(qc, kc, vc).reshape(b, n, dim)
        x = x + nn.Dense(dim, dtype=self.dtype, name="xattn_proj")(xattn)

        h = nn.LayerNorm(use_bias=False, use_scale=False, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        h = (h * (1 + sc2) + sh2).astype(self.dtype)
        h = nn.Dense(dim * 4, dtype=self.dtype, name="mlp_fc1")(h)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(dim, dtype=self.dtype, name="mlp_fc2")(h)
        return x + g2 * h


class VideoDiT(nn.Module):
    config: DiTConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,          # [B, F, H, W, C] noisy video latents
        timesteps: jax.Array,  # [B]
        context: jax.Array,    # [B, T, context_dim]
    ) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        b, f, hh, ww, c = x.shape
        pf, ph, pw = cfg.patch_size
        assert f % pf == 0 and hh % ph == 0 and ww % pw == 0, "patch misalign"
        gf, gh, gw = f // pf, hh // ph, ww // pw
        n = gf * gh * gw

        # 3D patchify → tokens
        tokens = x.reshape(b, gf, pf, gh, ph, gw, pw, c)
        tokens = tokens.transpose(0, 1, 3, 5, 2, 4, 6, 7).reshape(
            b, n, pf * ph * pw * c
        )
        tokens = nn.Dense(cfg.hidden_dim, dtype=dt, name="patch_embed")(
            tokens.astype(dt)
        )

        cond = nn.Dense(cfg.hidden_dim, dtype=jnp.float32, name="t_embed_0")(
            timestep_embedding(timesteps, 256)
        )
        cond = nn.Dense(cfg.hidden_dim, dtype=jnp.float32, name="t_embed_1")(
            nn.silu(cond)
        )

        context = nn.Dense(cfg.hidden_dim, dtype=dt, name="context_proj")(
            context.astype(dt)
        )

        head_dim = cfg.hidden_dim // cfg.heads
        if cfg.seq_axis is not None:
            # sharded sequence: local tokens are a contiguous chunk; the
            # RoPE table covers the GLOBAL sequence and each shard slices
            # its window by ring position
            axis_size = jax.lax.psum(1, cfg.seq_axis)
            global_n = n * axis_size
            full = jnp.asarray(_rope_freqs(head_dim, global_n), dtype=jnp.float32)
            offset = jax.lax.axis_index(cfg.seq_axis) * n
            freqs = jax.lax.dynamic_slice(
                full, (offset, 0, 0), (n, full.shape[1], full.shape[2])
            )
        else:
            freqs = jnp.asarray(_rope_freqs(head_dim, n), dtype=jnp.float32)

        for i in range(cfg.depth):
            tokens = _AdaLNBlock(
                cfg.heads, dt, seq_axis=cfg.seq_axis, name=f"block_{i}"
            )(tokens, cond, context, freqs)

        # final AdaLN + unpatchify, zero-init output
        mod = nn.Dense(
            2 * cfg.hidden_dim, dtype=jnp.float32,
            kernel_init=nn.initializers.zeros, name="final_mod",
        )(nn.silu(cond))
        shift, scale = jnp.split(mod[:, None, :], 2, axis=-1)
        h = nn.LayerNorm(use_bias=False, use_scale=False, dtype=jnp.float32)(
            tokens.astype(jnp.float32)
        )
        h = h * (1 + scale) + shift
        out = nn.Dense(
            pf * ph * pw * cfg.in_channels,
            dtype=jnp.float32,
            kernel_init=nn.initializers.zeros,
            name="final_proj",
        )(h)
        out = out.reshape(b, gf, gh, gw, pf, ph, pw, cfg.in_channels)
        out = out.transpose(0, 1, 4, 2, 5, 3, 6, 7).reshape(b, f, hh, ww, cfg.in_channels)
        return out
