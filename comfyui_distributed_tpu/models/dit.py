"""Video diffusion transformer (WAN class), flax.linen.

The model family behind the reference's WAN t2v/i2v workflows
(reference workflows/distributed-wan*.json), rebuilt as a TPU-native
DiT that is *checkpoint-faithful* to the original WAN 2.x layout:
3D patchification of [B, F, H, W, C] video latents, joint
spatio-temporal self-attention with 3D rotary embeddings (frequency
budget split across frame/height/width like WAN's rope_params),
RMS-normed Q/K, cross-attention to text, learned per-block AdaLN
modulation added to a shared 6-way timestep projection, and a
modulated output head. Real `blocks.N.*` WAN state dicts map onto this
tree via `sd_checkpoint.wan_schedule`.

Sized by config: wan-1.3b-class runs seed-parallel on a v5e-8;
wan-14b-class FSDP-shards across a v5p-16 (BASELINE.md).

Sequence parallelism: with `seq_axis` set the model is being called
inside shard_map with the FRAME axis sharded along that mesh axis;
self-attention runs as ring attention over the full sequence and the
rope grid uses each shard's global frame offset. The parameter tree is
identical either way — the same params serve sharded and unsharded
calls.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .layers import timestep_embedding
from ..ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    in_channels: int = 16
    out_channels: int | None = None  # defaults to in_channels
    patch_size: tuple[int, int, int] = (1, 2, 2)  # (frames, h, w)
    hidden_dim: int = 1536
    ffn_dim: int | None = None  # defaults to 4*hidden_dim; WAN uses ~5.8x
    depth: int = 30
    heads: int = 12
    context_dim: int = 4096
    freq_dim: int = 256  # sinusoidal timestep embedding width (WAN: 256)
    # WAN i2v: image cross-attention branch (k_img/v_img) over CLIP
    # ViT-H penultimate tokens projected through img_emb; the latent
    # input carries [noise 16 | mask 4 | conditioning latent 16] = 36
    # channels (set in_channels accordingly in i2v configs)
    i2v: bool = False
    img_dim: int = 1280  # CLIP ViT-H width
    dtype: str = "bfloat16"
    # Context/sequence parallelism: when set, the model is being called
    # inside shard_map with the FRAME axis sharded along this mesh axis;
    # self-attention runs as ring attention over the full sequence and
    # rope positions are offset by the shard index.
    seq_axis: str | None = None

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def ffn_width(self) -> int:
        return self.ffn_dim if self.ffn_dim is not None else 4 * self.hidden_dim

    @property
    def out_width(self) -> int:
        return self.out_channels if self.out_channels is not None else self.in_channels


def _axis_freqs(dim: int, length: int, theta: float = 10000.0) -> np.ndarray:
    """[length, dim/2, 2] cos/sin table for one rope axis."""
    inv = 1.0 / (theta ** (np.arange(0, dim, 2) / dim))
    t = np.arange(length)
    freqs = np.outer(t, inv)
    return np.stack([np.cos(freqs), np.sin(freqs)], axis=-1)


def rope_split(head_dim: int) -> tuple[int, int, int]:
    """Frequency-pair budget per (frame, h, w) axis — WAN's rope_params
    split: of the d/2 complex pairs, h and w each get (d/2)//3 and the
    frame axis gets the remainder."""
    pairs = head_dim // 2
    kh = kw = pairs // 3
    kt = pairs - 2 * kh
    return kt, kh, kw


def rope_freqs_3d(head_dim: int, grid: tuple[int, int, int]) -> np.ndarray:
    """[N, head_dim/2, 2] rope table for a (gf, gh, gw) token grid in
    row-major (f, h, w) order. (Sharded frame axes build the table over
    the global frame count and slice their window by ring position —
    VideoDiT.__call__.)"""
    gf, gh, gw = grid
    kt, kh, kw = rope_split(head_dim)
    tf = _axis_freqs(2 * kt, gf)
    th = _axis_freqs(2 * kh, gh)
    tw = _axis_freqs(2 * kw, gw)
    parts = [
        np.broadcast_to(tf[:, None, None], (gf, gh, gw, kt, 2)),
        np.broadcast_to(th[None, :, None], (gf, gh, gw, kh, 2)),
        np.broadcast_to(tw[None, None, :], (gf, gh, gw, kw, 2)),
    ]
    return np.concatenate(parts, axis=3).reshape(gf * gh * gw, head_dim // 2, 2)


def apply_rope(x: jax.Array, freqs: jax.Array) -> jax.Array:
    """x: [B, N, H, D]; freqs: [N, D/2, 2] (adjacent-pair rotation)."""
    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], -1, 2)
    cos = freqs[None, :, None, :, 0]
    sin = freqs[None, :, None, :, 1]
    out = jnp.stack(
        [
            xf[..., 0] * cos - xf[..., 1] * sin,
            xf[..., 0] * sin + xf[..., 1] * cos,
        ],
        axis=-1,
    )
    return out.reshape(x.shape).astype(x.dtype)


class _WanBlock(nn.Module):
    """One WAN transformer block.

    Submodule names mirror the original state-dict keys (self_attn_q ↔
    blocks.N.self_attn.q, ...) so the key schedule in
    sd_checkpoint.wan_schedule stays a straight rename."""

    heads: int
    ffn_width: int
    dtype: jnp.dtype
    seq_axis: str | None = None
    i2v: bool = False

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        e6: jax.Array,
        context: jax.Array,
        freqs: jax.Array,
        context_img: jax.Array | None = None,
    ) -> jax.Array:
        dim = x.shape[-1]
        head_dim = dim // self.heads
        b, n, _ = x.shape

        # learned per-block modulation added to the shared 6-way
        # timestep projection (WAN blocks.N.modulation)
        modulation = self.param(
            "modulation",
            nn.initializers.normal(stddev=dim**-0.5),
            (1, 6, dim),
            jnp.float32,
        )
        e = modulation + e6.astype(jnp.float32)  # [B, 6, dim]
        sh1, sc1, g1, sh2, sc2, g2 = [e[:, i][:, None, :] for i in range(6)]

        # --- self-attention (modulated, rope, rms q/k norm) ---
        h = nn.LayerNorm(
            use_bias=False, use_scale=False, dtype=jnp.float32, name="norm1"
        )(x.astype(jnp.float32))
        h = (h * (1 + sc1) + sh1).astype(self.dtype)
        q = nn.Dense(dim, dtype=self.dtype, name="self_attn_q")(h)
        k = nn.Dense(dim, dtype=self.dtype, name="self_attn_k")(h)
        v = nn.Dense(dim, dtype=self.dtype, name="self_attn_v")(h)
        q = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name="self_attn_norm_q")(q)
        k = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name="self_attn_norm_k")(k)
        q = apply_rope(q.astype(self.dtype).reshape(b, n, self.heads, head_dim), freqs)
        k = apply_rope(k.astype(self.dtype).reshape(b, n, self.heads, head_dim), freqs)
        v = v.reshape(b, n, self.heads, head_dim)
        if self.seq_axis is not None:
            from ..ops.ring_attention import ring_attention

            attn = ring_attention(q, k, v, self.seq_axis).reshape(b, n, dim)
        else:
            attn = dot_product_attention(q, k, v).reshape(b, n, dim)
        y = nn.Dense(dim, dtype=self.dtype, name="self_attn_o")(attn)
        x = (x.astype(jnp.float32) + y.astype(jnp.float32) * g1).astype(x.dtype)

        # --- cross-attention to text (un-modulated, affine-normed) ---
        h = nn.LayerNorm(dtype=jnp.float32, name="norm3")(
            x.astype(jnp.float32)
        ).astype(self.dtype)
        m = context.shape[1]
        qc = nn.Dense(dim, dtype=self.dtype, name="cross_attn_q")(h)
        kc = nn.Dense(dim, dtype=self.dtype, name="cross_attn_k")(context)
        vc = nn.Dense(dim, dtype=self.dtype, name="cross_attn_v")(context)
        qc = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name="cross_attn_norm_q")(qc)
        kc = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name="cross_attn_norm_k")(kc)
        qc = qc.astype(self.dtype).reshape(b, n, self.heads, head_dim)
        kc = kc.astype(self.dtype).reshape(b, m, self.heads, head_dim)
        vc = vc.reshape(b, m, self.heads, head_dim)
        xattn = dot_product_attention(qc, kc, vc).reshape(b, n, dim)
        if self.i2v and context_img is not None:
            # WAN i2v: a second K/V pair over image tokens, summed with
            # the text attention before the output projection
            mi = context_img.shape[1]
            ki = nn.Dense(dim, dtype=self.dtype, name="cross_attn_k_img")(
                context_img
            )
            vi = nn.Dense(dim, dtype=self.dtype, name="cross_attn_v_img")(
                context_img
            )
            ki = nn.RMSNorm(
                epsilon=1e-6, dtype=jnp.float32, name="cross_attn_norm_k_img"
            )(ki)
            ki = ki.astype(self.dtype).reshape(b, mi, self.heads, head_dim)
            vi = vi.reshape(b, mi, self.heads, head_dim)
            xattn = xattn + dot_product_attention(qc, ki, vi).reshape(b, n, dim)
        x = x + nn.Dense(dim, dtype=self.dtype, name="cross_attn_o")(xattn)

        # --- feed-forward (modulated) ---
        h = nn.LayerNorm(
            use_bias=False, use_scale=False, dtype=jnp.float32, name="norm2"
        )(x.astype(jnp.float32))
        h = (h * (1 + sc2) + sh2).astype(self.dtype)
        h = nn.Dense(self.ffn_width, dtype=self.dtype, name="ffn_0")(h)
        h = nn.gelu(h, approximate=True)
        y = nn.Dense(dim, dtype=self.dtype, name="ffn_2")(h)
        return (x.astype(jnp.float32) + y.astype(jnp.float32) * g2).astype(x.dtype)


class VideoDiT(nn.Module):
    config: DiTConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,          # [B, F, H, W, C] noisy video latents
        timesteps: jax.Array,  # [B]
        context: jax.Array,    # [B, T, context_dim]
        image_embeds: jax.Array | None = None,  # i2v: [B, 257, img_dim]
    ) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        b, f, hh, ww, c = x.shape
        pf, ph, pw = cfg.patch_size
        assert f % pf == 0 and hh % ph == 0 and ww % pw == 0, "patch misalign"
        gf, gh, gw = f // pf, hh // ph, ww // pw
        n = gf * gh * gw

        # 3D patchify → tokens; flatten order (pf, ph, pw, c) matches the
        # conv3d kernel transform in sd_checkpoint (patch_embedding)
        tokens = x.reshape(b, gf, pf, gh, ph, gw, pw, c)
        tokens = tokens.transpose(0, 1, 3, 5, 2, 4, 6, 7).reshape(
            b, n, pf * ph * pw * c
        )
        tokens = nn.Dense(cfg.hidden_dim, dtype=dt, name="patch_embed")(
            tokens.astype(dt)
        )

        # timestep MLP (WAN time_embedding) + shared 6-way projection
        # (WAN time_projection); blocks add their learned modulation
        e_t = nn.Dense(cfg.hidden_dim, dtype=jnp.float32, name="time_embed_0")(
            timestep_embedding(timesteps, cfg.freq_dim)
        )
        e_t = nn.Dense(cfg.hidden_dim, dtype=jnp.float32, name="time_embed_2")(
            nn.silu(e_t)
        )
        e6 = nn.Dense(6 * cfg.hidden_dim, dtype=jnp.float32, name="time_proj")(
            nn.silu(e_t)
        ).reshape(b, 6, cfg.hidden_dim)

        # text MLP (WAN text_embedding)
        context = nn.Dense(cfg.hidden_dim, dtype=dt, name="text_embed_0")(
            context.astype(dt)
        )
        context = nn.Dense(cfg.hidden_dim, dtype=dt, name="text_embed_2")(
            nn.gelu(context, approximate=True)
        )

        # i2v image tokens: CLIP penultimate states → hidden (WAN
        # img_emb MLPProj: LN, Linear, GELU, Linear, LN)
        context_img = None
        if cfg.i2v and image_embeds is not None:
            h_img = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="img_emb_norm_in")(
                image_embeds.astype(jnp.float32)
            ).astype(dt)
            h_img = nn.Dense(cfg.img_dim, dtype=dt, name="img_emb_fc1")(h_img)
            h_img = nn.gelu(h_img, approximate=False)
            h_img = nn.Dense(cfg.hidden_dim, dtype=dt, name="img_emb_fc2")(h_img)
            context_img = nn.LayerNorm(
                epsilon=1e-5, dtype=jnp.float32, name="img_emb_norm_out"
            )(h_img.astype(jnp.float32)).astype(dt)

        head_dim = cfg.hidden_dim // cfg.heads
        if cfg.seq_axis is not None:
            # sharded frame axis: local tokens are a contiguous frame
            # window; the rope table covers the GLOBAL frame count and
            # each shard takes its window by ring position
            axis_size = jax.lax.psum(1, cfg.seq_axis)
            shard = jax.lax.axis_index(cfg.seq_axis)
            full = jnp.asarray(
                rope_freqs_3d(head_dim, (gf * axis_size, gh, gw)), jnp.float32
            ).reshape(gf * axis_size, gh * gw, head_dim // 2, 2)
            freqs = jax.lax.dynamic_slice_in_dim(full, shard * gf, gf, axis=0)
            freqs = freqs.reshape(n, head_dim // 2, 2)
        else:
            freqs = jnp.asarray(rope_freqs_3d(head_dim, (gf, gh, gw)), jnp.float32)

        for i in range(cfg.depth):
            tokens = _WanBlock(
                cfg.heads, cfg.ffn_width, dt, seq_axis=cfg.seq_axis,
                i2v=cfg.i2v, name=f"block_{i}",
            )(tokens, e6, context, freqs, context_img)

        # modulated output head (WAN head: norm → Linear, with a learned
        # 2-way modulation added to the raw timestep embedding)
        head_mod = self.param(
            "head_modulation",
            nn.initializers.normal(stddev=cfg.hidden_dim**-0.5),
            (1, 2, cfg.hidden_dim),
            jnp.float32,
        )
        e2 = head_mod + e_t[:, None, :]
        shift, scale = e2[:, 0][:, None, :], e2[:, 1][:, None, :]
        h = nn.LayerNorm(use_bias=False, use_scale=False, dtype=jnp.float32)(
            tokens.astype(jnp.float32)
        )
        h = h * (1 + scale) + shift
        out = nn.Dense(
            pf * ph * pw * cfg.out_width, dtype=jnp.float32, name="head"
        )(h)
        out = out.reshape(b, gf, gh, gw, pf, ph, pw, cfg.out_width)
        out = out.transpose(0, 1, 4, 2, 5, 3, 6, 7).reshape(
            b, f, hh, ww, cfg.out_width
        )
        return out
