"""Video generation pipeline (WAN-class t2v / i2v).

The model family behind the reference's WAN workflows (reference
workflows/distributed-wan*.json), end to end: text → video frames.
Latents are [B, F, h, w, C]; the image VAE decodes frames via vmap
over the frame axis (temporal-compression VAEs slot in behind the
same decode_frames interface).

Distribution:
- seed-parallel: one video per mesh participant (t2v_parallel), the
  reference's Image-Batch-Divider fan-out collapsed into SPMD;
- context-parallel: frames sharded + ring attention for videos whose
  sequence exceeds one chip (parallel/sequence.py) — beyond-reference.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import samplers as smp
from ..parallel.mesh import DATA_AXIS, data_axis_size, shard_map_compat
from ..parallel.seeds import participant_keys
from .pipeline import _Static, maybe_cast_params
from .registry import create_model, get_config, model_family
from .t5_encoder import T5Tokenizer
from .text_encoder import Tokenizer


@dataclasses.dataclass
class VideoPipelineBundle:
    model_name: str
    dit: Any
    vae: Any
    text_encoder: Any
    params: dict[str, Any]
    tokenizer: Tokenizer
    latent_channels: int
    latent_scale: int
    flow_shift: float = 3.0
    # i2v: CLIP vision tower for image conditioning (WAN i2v layout)
    clip_vision: Any = None
    # 1 for per-frame 2D VAEs; the WAN causal VAE compresses 4x with
    # the 4n+1 pixel-frame contract
    temporal_scale: int = 1

    def latent_frames(self, frames: int) -> int:
        if self.temporal_scale == 1:
            return frames
        if (frames - 1) % self.temporal_scale != 0:
            raise ValueError(
                f"frame count {frames} must be {self.temporal_scale}n+1 "
                "for this VAE (WAN causal contract)"
            )
        return (frames - 1) // self.temporal_scale + 1


def load_video_pipeline(
    model_name: str = "tiny-dit",
    vae_name: str | None = None,
    te_name: str | None = None,
    seed: int = 0,
    checkpoint: str | None = None,
) -> VideoPipelineBundle:
    """Build a video pipeline; load real DiT weights when a checkpoint
    resolves (explicit `checkpoint` arg, then
    `CDT_CHECKPOINT_DIR/<model_name>.{safetensors,ckpt,gguf}`). WAN 2.x
    DiT state dicts — original `blocks.N.*` layout or ComfyUI-repacked
    `model.diffusion_model.*` — map key-by-key into the VideoDiT tree
    (sd_checkpoint.wan_schedule). A T5-family encoder (te_name=
    "umt5-xxl") and a video-VAE family VAE (vae_name="wan-vae")
    likewise load their own checkpoint files when they resolve by
    name — the full real-weight WAN stack is DiT + umt5-xxl +
    wan-vae (+ clip-vision-h for i2v)."""
    from . import sd_checkpoint as sdc

    tiny = model_name.startswith("tiny")
    # non-tiny video models default to the causal WAN VAE (the real
    # stack); tiny tests keep the cheap per-frame 2D VAE. The text
    # encoder defaults to CLIP-L for init cost — pass te_name=
    # "umt5-xxl" for the full real-weight WAN stack (a random-init
    # UMT5-XXL is ~6B params, pointless without its checkpoint).
    vae_name = vae_name or ("tiny-vae-video" if tiny else "wan-vae")
    te_name = te_name or ("tiny-te" if tiny else "clip-l")

    dit = create_model(model_name)
    vae = create_model(vae_name)
    te = create_model(te_name)
    dit_cfg = get_config(model_name)
    te_cfg = get_config(te_name)
    vae_cfg = get_config(vae_name)

    root = jax.random.key(seed)
    k_dit, k_vae, k_te = jax.random.split(root, 3)
    lat = jnp.zeros((1, 4, 8, 8, dit_cfg.in_channels))
    ctx = jnp.zeros((1, te_cfg.max_length, dit_cfg.context_dim))
    i2v = getattr(dit_cfg, "i2v", False)
    clip_vision = None
    cv_params = None
    if i2v:
        from .clip_vision import build_clip_vision

        cv_name = "tiny-clip-vision" if tiny else "clip-vision-h"
        clip_vision, cv_cfg, cv_params = build_clip_vision(
            cv_name, jax.random.fold_in(k_te, 7)
        )
        embeds = jnp.zeros((1, cv_cfg.tokens, dit_cfg.img_dim))
        dit_params = dit.init(k_dit, lat, jnp.zeros((1,)), ctx, embeds)
    else:
        dit_params = dit.init(k_dit, lat, jnp.zeros((1,)), ctx)
    video_vae = model_family(vae_name) == "video_vae"
    if video_vae:
        tds = vae_cfg.temporal_downscale
        vae_params = vae.init(k_vae, jnp.zeros((1, tds + 1, 32, 32, 3)))
        vae_ckpt = sdc.find_checkpoint(vae_name)
        if vae_ckpt:
            from ..utils.logging import log

            log(f"loading WAN VAE checkpoint {vae_ckpt} for {vae_name}")
            vae_params, _ = sdc.load_wan_vae_weights(
                sdc.read_checkpoint(vae_ckpt), vae_cfg, vae_params
            )
    else:
        vae_params = vae.init(k_vae, jnp.zeros((1, 32, 32, 3)))
    te_params = te.init(k_te, jnp.zeros((1, te_cfg.max_length), jnp.int32))

    ckpt_path = checkpoint or sdc.find_checkpoint(model_name)
    if ckpt_path:
        from ..utils.logging import log

        log(f"loading WAN checkpoint {ckpt_path} for {model_name}")
        state_dict = sdc.read_checkpoint(ckpt_path)
        dit_params, _problems = sdc.load_wan_weights(
            state_dict, dit_cfg, dit_params
        )

    # T5-family encoder: its own checkpoint file (the reference loads
    # umt5 separately through CLIPLoader) resolves by encoder name
    if model_family(te_name) == "t5_encoder":
        te_ckpt = sdc.find_checkpoint(te_name)
        if te_ckpt:
            from ..utils.logging import log

            log(f"loading T5 encoder checkpoint {te_ckpt} for {te_name}")
            te_params, _ = sdc.load_t5_weights(
                sdc.read_checkpoint(te_ckpt), te_cfg, te_params
            )
        tokenizer = T5Tokenizer(
            max_length=te_cfg.max_length, vocab_size=te_cfg.vocab_size
        )
    else:
        tokenizer = Tokenizer(
            max_length=te_cfg.max_length,
            pad_id=getattr(te_cfg, "pad_token_id", None),
        )

    params = {"unet": dit_params, "vae": vae_params, "te": te_params}
    if cv_params is not None:
        params["clip_vision"] = cv_params
    return VideoPipelineBundle(
        model_name=model_name,
        dit=dit,
        vae=vae,
        text_encoder=te,
        params=maybe_cast_params(params),
        tokenizer=tokenizer,
        latent_channels=vae_cfg.latent_channels,
        latent_scale=vae_cfg.downscale,
        clip_vision=clip_vision,
        temporal_scale=(
            vae_cfg.temporal_downscale if video_vae else 1
        ),
    )


def encode_video_text(bundle: VideoPipelineBundle, texts: list[str]) -> jax.Array:
    tokens = jnp.asarray(bundle.tokenizer.encode_batch(texts))
    hidden, _ = bundle.text_encoder.apply(bundle.params["te"], tokens)
    ctx_dim = get_config(bundle.model_name).context_dim
    if hidden.shape[-1] < ctx_dim:
        hidden = jnp.pad(hidden, ((0, 0), (0, 0), (0, ctx_dim - hidden.shape[-1])))
    elif hidden.shape[-1] > ctx_dim:
        hidden = hidden[..., :ctx_dim]
    return hidden


def decode_frames(bundle: VideoPipelineBundle, latents: jax.Array) -> jax.Array:
    """[B, F_lat, h, w, C] latents → [B, F, H, W, 3] frames. Per-frame
    2D VAEs decode frame-wise (F == F_lat); the causal 3D VAE expands
    time 4x (F = 4(F_lat - 1) + 1)."""
    if bundle.temporal_scale != 1:
        return bundle.vae.apply(bundle.params["vae"], latents, method="decode")
    b, f = latents.shape[:2]
    flat = latents.reshape((b * f,) + latents.shape[2:])
    frames = bundle.vae.apply(bundle.params["vae"], flat, method="decode")
    return frames.reshape((b, f) + frames.shape[1:])


def _video_model_fn(bundle: VideoPipelineBundle, params):
    def model_fn(x, t_batch, context):
        return bundle.dit.apply(params["unet"], x, t_batch, context).astype(x.dtype)

    return model_fn


def t2v_flops(
    bundle: "VideoPipelineBundle",
    frames: int = 17,
    height: int = 256,
    width: int = 256,
    steps: int = 20,
    cfg_scale: float = 5.0,
    batch: int = 1,
) -> float | None:
    """XLA-estimated FLOPs of ONE t2v program (batch clips) — the
    video MFU numerator, composed scan-free (N guided DiT evals +
    frame decode; XLA cost analysis counts a lax.scan body once, see
    ops/upscale._jitted_for_flops). Text encoding excluded."""
    import logging

    from ..ops.costs import xla_flops as _xla_flops

    try:
        timesteps = smp.get_flow_timesteps(steps, bundle.flow_shift)
        n_pairs = int(timesteps.shape[0]) - 1
        lh, lw = height // bundle.latent_scale, width // bundle.latent_scale
        lf = bundle.latent_frames(frames)
        z = jnp.zeros((batch, lf, lh, lw, bundle.latent_channels))
        pos = encode_video_text(bundle, ["flops"] * batch)
        neg = encode_video_text(bundle, [""] * batch)
        params = bundle.params

        def eval_fn(params, z, pos, neg):
            model = smp.cfg_flow_model(
                _video_model_fn(bundle, params), cfg_scale
            )
            t = jnp.broadcast_to(timesteps[0] * 1000.0, (z.shape[0],))
            return model(z, t, (pos, neg))

        def dec_fn(params, zz):
            # decode_frames with params as a TRACED argument — a
            # closure over bundle.params would bake the VAE weights
            # into the lowered HLO as constants
            if bundle.temporal_scale != 1:
                return bundle.vae.apply(params["vae"], zz, method="decode")
            b, f = zz.shape[:2]
            flat = zz.reshape((b * f,) + zz.shape[2:])
            return bundle.vae.apply(params["vae"], flat, method="decode")

        ev = _xla_flops(eval_fn, params, z, pos, neg)
        dec = _xla_flops(dec_fn, params, z)
        if ev is None or dec is None:
            return None
        return n_pairs * ev + dec
    except Exception:
        logging.getLogger("cdt.video_pipeline").warning(
            "t2v FLOPs estimate failed", exc_info=True
        )
        return None


@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "frames", "height", "width", "steps", "cfg_scale",
        "batch",
    ),
)
def _t2v_jit(
    bundle_static, params, pos, neg, key,
    frames: int, height: int, width: int, steps: int, cfg_scale: float,
    batch: int,
):
    bundle = bundle_static.value
    lh, lw = height // bundle.latent_scale, width // bundle.latent_scale
    timesteps = smp.get_flow_timesteps(steps, bundle.flow_shift)
    lf = bundle.latent_frames(frames)
    x = jax.random.normal(
        key, (batch, lf, lh, lw, bundle.latent_channels)
    )
    model = smp.cfg_flow_model(_video_model_fn(bundle, params), cfg_scale)
    latents = smp.sample_flow(model, x, timesteps, (pos, neg))
    return decode_frames(bundle, latents)


def t2v(
    bundle: VideoPipelineBundle,
    prompt: str,
    negative_prompt: str = "",
    frames: int = 17,
    height: int = 256,
    width: int = 256,
    steps: int = 20,
    cfg_scale: float = 5.0,
    seed: int = 0,
    batch: int = 1,
) -> jax.Array:
    """Text→video; returns [batch, frames, H, W, 3] in [0,1]."""
    pos = encode_video_text(bundle, [prompt] * batch)
    neg = encode_video_text(bundle, [negative_prompt] * batch)
    return _t2v_jit(
        _Static(bundle), bundle.params, pos, neg, jax.random.key(seed),
        frames, height, width, steps, float(cfg_scale), batch,
    )


@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "mesh_static", "frames", "height", "width", "steps",
        "cfg_scale",
    ),
)
def _t2v_parallel_jit(
    bundle_static, mesh_static, params, keys, pos, neg,
    frames: int, height: int, width: int, steps: int, cfg_scale: float,
):
    bundle = bundle_static.value
    mesh = mesh_static.value
    lh, lw = height // bundle.latent_scale, width // bundle.latent_scale
    timesteps = smp.get_flow_timesteps(steps, bundle.flow_shift)

    def per_chip(keys_shard, params, pos, neg):
        key = keys_shard[0]
        lf = bundle.latent_frames(frames)
        x = jax.random.normal(key, (1, lf, lh, lw, bundle.latent_channels))
        model = smp.cfg_flow_model(_video_model_fn(bundle, params), cfg_scale)
        latents = smp.sample_flow(model, x, timesteps, (pos, neg))
        return decode_frames(bundle, latents)

    return shard_map_compat(
        per_chip,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(), P(), P()),
        out_specs=P(DATA_AXIS),
        check=False,
    )(keys, params, pos, neg)


def t2v_parallel(
    bundle: VideoPipelineBundle,
    mesh,
    prompt: str,
    negative_prompt: str = "",
    frames: int = 17,
    height: int = 256,
    width: int = 256,
    steps: int = 20,
    cfg_scale: float = 5.0,
    seed: int = 0,
) -> jax.Array:
    """One video per mesh participant from independent folded seeds;
    returns [n_participants, frames, H, W, 3] participant-major."""
    n = data_axis_size(mesh)
    keys = participant_keys(jax.random.key(seed), n)
    keys = jax.device_put(keys, NamedSharding(mesh, P(DATA_AXIS)))
    pos = encode_video_text(bundle, [prompt])
    neg = encode_video_text(bundle, [negative_prompt])
    params = jax.device_put(bundle.params, NamedSharding(mesh, P()))
    return _t2v_parallel_jit(
        _Static(bundle), _Static(mesh), params, keys,
        jax.device_put(pos, NamedSharding(mesh, P())),
        jax.device_put(neg, NamedSharding(mesh, P())),
        frames, height, width, steps, float(cfg_scale),
    )


# --- image-to-video -------------------------------------------------------

def encode_frames(bundle: VideoPipelineBundle, frames: jax.Array) -> jax.Array:
    """[B, F, H, W, 3] → [B, F_lat, h, w, C] VAE encode (per-frame for
    2D VAEs; 4x temporal compression for the causal 3D VAE)."""
    if bundle.temporal_scale != 1:
        return bundle.vae.apply(bundle.params["vae"], frames, method="encode")
    b, f = frames.shape[:2]
    flat = frames.reshape((b * f,) + frames.shape[2:])
    z = bundle.vae.apply(bundle.params["vae"], flat, method="encode")
    return z.reshape((b, f) + z.shape[1:])


@partial(
    jax.jit,
    static_argnames=("bundle_static", "frames", "steps", "cfg_scale"),
)
def _i2v_jit(
    bundle_static, params, ref_latent, pos, neg, key,
    frames: int, steps: int, cfg_scale: float,
):
    bundle = bundle_static.value
    b = ref_latent.shape[0]
    lh, lw, c = ref_latent.shape[2], ref_latent.shape[3], ref_latent.shape[4]
    timesteps = smp.get_flow_timesteps(steps, bundle.flow_shift)
    noise_key, _ = jax.random.split(key)
    lf = bundle.latent_frames(frames)
    noise = jax.random.normal(noise_key, (b, lf, lh, lw, c))
    # known region = latent frame 0 carries the reference latent
    known = jnp.concatenate(
        [ref_latent, jnp.zeros((b, lf - 1, lh, lw, c))], axis=1
    )
    mask = jnp.zeros((1, lf, 1, 1, 1)).at[:, 0].set(1.0)
    model = smp.cfg_flow_model(_video_model_fn(bundle, params), cfg_scale)
    latents = smp.sample_flow_masked(
        model, noise, timesteps, (pos, neg), known, mask, noise
    )
    return decode_frames(bundle, latents)


@partial(
    jax.jit,
    static_argnames=("bundle_static", "frames", "steps", "cfg_scale"),
)
def _i2v_native_jit(
    bundle_static, params, y, image_embeds, pos, neg, key,
    frames: int, steps: int, cfg_scale: float,
):
    """WAN-i2v-layout sampling: the model input is
    [noise 16 | mask 4 | conditioning latent 16] per frame, with image
    cross-attention over CLIP tokens (models/dit.py i2v branch).

    `y` is the VAE encoding of the full padded PIXEL clip (reference
    first frame + mid-gray blanks), matching the reference WAN i2v
    conditioning — NOT zero latents, which are off the VAE manifold."""
    bundle = bundle_static.value
    b, lf, lh, lw, c = y.shape
    timesteps = smp.get_flow_timesteps(steps, bundle.flow_shift)
    noise = jax.random.normal(key, (b, lf, lh, lw, c))
    # conditioning channels: 4-channel latent-frame mask (1 = given) +
    # the padded-clip encoding, fixed across steps
    mask = jnp.zeros((b, lf, lh, lw, 4)).at[:, 0].set(1.0)
    cond_channels = jnp.concatenate([mask, y], axis=-1)

    def model_fn(x, t_batch, context):
        # the CFG wrapper doubles the batch (pos|neg); the image
        # conditioning is identical for both halves
        reps = x.shape[0] // cond_channels.shape[0]
        cc = jnp.tile(cond_channels, (reps, 1, 1, 1, 1))
        emb = jnp.tile(image_embeds, (reps, 1, 1))
        inp = jnp.concatenate([x, cc], axis=-1)
        return bundle.dit.apply(
            params["unet"], inp, t_batch, context, emb
        ).astype(x.dtype)

    model = smp.cfg_flow_model(model_fn, cfg_scale)
    latents = smp.sample_flow(model, noise, timesteps, (pos, neg))
    return decode_frames(bundle, latents)


def encode_image_embeds(bundle: VideoPipelineBundle, image: jax.Array) -> jax.Array:
    """[B, H, W, 3] → CLIP penultimate tokens [B, T, width] (i2v only)."""
    return bundle.clip_vision.apply(bundle.params["clip_vision"], image)


def i2v(
    bundle: VideoPipelineBundle,
    image: jax.Array,            # [B, H, W, 3] first frame
    prompt: str,
    negative_prompt: str = "",
    frames: int = 17,
    steps: int = 20,
    cfg_scale: float = 5.0,
    seed: int = 0,
) -> jax.Array:
    """Image-to-video; returns [B, frames, H, W, 3] (the WAN i2v
    workflow role, reference workflows/distributed-wan i2v variant).

    i2v-layout models (cfg.i2v) run the native WAN conditioning:
    channel-concat mask + reference latent, plus CLIP-token image
    cross-attention. Other video models fall back to clamping frame 0
    to the reference latent along the flow path (masked flow)."""
    b = int(image.shape[0])
    pos = encode_video_text(bundle, [prompt] * b)
    neg = encode_video_text(bundle, [negative_prompt] * b)
    cfg = get_config(bundle.model_name)
    if getattr(cfg, "i2v", False):
        embeds = encode_image_embeds(bundle, image)
        # conditioning latent = encoding of the padded PIXEL clip
        # (reference frame + mid-gray blanks), the reference WAN i2v
        # construction
        blanks = jnp.full(
            (image.shape[0], frames - 1) + image.shape[1:], 0.5, image.dtype
        )
        y = encode_frames(
            bundle, jnp.concatenate([image[:, None], blanks], axis=1)
        )
        return _i2v_native_jit(
            _Static(bundle), bundle.params, y, embeds, pos, neg,
            jax.random.key(seed), frames, steps, float(cfg_scale),
        )
    ref = encode_frames(bundle, image[:, None])  # [B, 1, h, w, C]
    return _i2v_jit(
        _Static(bundle), bundle.params, ref, pos, neg,
        jax.random.key(seed), frames, steps, float(cfg_scale),
    )
