"""CLIP-class causal text transformer + deterministic tokenizer.

Fills the role of ComfyUI's CLIPTextEncode that the reference's
workflows assume (reference workflows/*.json CLIPTextEncode nodes).
The transformer is architecture-faithful (token+position embeddings,
pre-LN causal blocks, final LN; pooled output = EOS token state).

Tokenizer: real CLIP byte-level BPE (models/clip_bpe.py) over the
committed vocab assets — deterministic across hosts, the property the
distributed tier needs so master and workers agree on conditioning
for identical prompts. OpenAI's exact CLIP vocab drops in via
`CDT_CLIP_VOCAB` or `Tokenizer(vocab_path=...)`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TextEncoderConfig:
    vocab_size: int = 49408
    max_length: int = 77
    width: int = 768
    layers: int = 12
    heads: int = 12
    dtype: str = "bfloat16"
    # "quick_gelu" = OpenAI CLIP-L (SD1.x); "gelu" = OpenCLIP bigG (SDXL)
    activation: str = "quick_gelu"
    # SDXL encoders expose the PENULTIMATE block's hidden states (no
    # final LN) as the context; pooled always comes from the full stack
    penultimate_hidden: bool = False
    # SD2 (OpenCLIP-H) applies the model's final LayerNorm to the
    # penultimate hidden before it becomes cross-attention context
    # (ComfyUI SD2ClipHModel layer_norm_hidden_state=True); SDXL's
    # encoders do not. Ignored unless penultimate_hidden.
    final_ln_on_hidden: bool = False
    # Token id used to pad after EOS. None = pad with EOS (OpenAI
    # CLIP-L convention, SD1.x/SDXL clip-l); OpenCLIP towers (SDXL
    # bigG, SD2 ViT-H) pad with 0 (open_clip.tokenize).
    pad_token_id: Optional[int] = None
    # OpenCLIP text_projection: pooled = eos_state @ W [width, proj_dim]
    proj_dim: Optional[int] = None

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


class Tokenizer:
    """CLIP BPE tokenizer with BOS/EOS, fixed-length padded output.

    Layout: `<bos> tokens[:max-2] <eos>` then padding. The pad token
    is per-encoder: the CLIP-L convention (default, pad_id=None) pads
    with the EOS id; OpenCLIP towers (SDXL bigG, SD2 ViT-H) pad with
    0, matching open_clip.tokenize. Ids are identical on every host
    that shares the committed vocab assets.
    """

    # CLIP id layout (the committed vocab reproduces it exactly; a
    # custom vocab may move them — instances use the vocab's own ids).
    BOS = 49406
    EOS = 49407

    def __init__(
        self,
        max_length: int = 77,
        vocab_path: Optional[str] = None,
        pad_id: Optional[int] = None,
    ):
        from .clip_bpe import get_bpe

        self.max_length = max_length
        self.bpe = get_bpe(vocab_path)
        self.bos_id = self.bpe.bos_id
        self.eos_id = self.bpe.eos_id
        # None = CLIP-L convention (pad with EOS); OpenCLIP pads with 0
        self.pad_id = self.eos_id if pad_id is None else pad_id

    def encode(self, text: str) -> np.ndarray:
        body = self.bpe.encode_text(text)[: self.max_length - 2]
        ids = [self.bos_id] + body + [self.eos_id]
        out = np.full((self.max_length,), self.pad_id, dtype=np.int32)
        out[: len(ids)] = ids
        return out

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts], axis=0)

    def decode(self, ids) -> str:
        return self.bpe.decode(list(map(int, ids)))


class _CausalBlock(nn.Module):
    heads: int
    dtype: jnp.dtype
    activation: str = "quick_gelu"

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        width = x.shape[-1]
        head_dim = width // self.heads
        # eps=1e-5 matches torch/CLIP-L (flax default is 1e-6)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype)
        b, n, _ = h.shape
        q = nn.Dense(width, dtype=self.dtype, name="q")(h)
        k = nn.Dense(width, dtype=self.dtype, name="k")(h)
        v = nn.Dense(width, dtype=self.dtype, name="v")(h)
        q = q.reshape(b, n, self.heads, head_dim)
        k = k.reshape(b, n, self.heads, head_dim)
        v = v.reshape(b, n, self.heads, head_dim)
        # causal mask via explicit bias: flash path not needed at T=77
        scores = jnp.einsum(
            "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / np.sqrt(head_dim)
        scores = jnp.where(mask[None, None, :, :], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhnm,bmhd->bnhd", probs, v).reshape(b, n, width)
        x = x + nn.Dense(width, dtype=self.dtype, name="proj")(out)

        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype)
        h = nn.Dense(width * 4, dtype=self.dtype, name="fc1")(h)
        if self.activation == "quick_gelu":
            # OpenAI CLIP — required for real CLIP-L weights to
            # reproduce reference activations
            h = h * jax.nn.sigmoid(1.702 * h)
        else:  # OpenCLIP (SDXL bigG) uses exact gelu
            h = nn.gelu(h, approximate=False)
        h = nn.Dense(width, dtype=self.dtype, name="fc2")(h)
        return x + h


class TextEncoder(nn.Module):
    config: TextEncoderConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        eos_id: int | None = None,
        skip_last: int | None = None,
    ) -> tuple[jax.Array, jax.Array]:
        """[B, T] int tokens → (hidden [B, T, width], pooled [B, width]).

        `eos_id` selects the pooled position (first EOS occurrence);
        defaults to the CLIP layout id — pass the active tokenizer's
        eos_id when a custom vocab moves it.

        `skip_last` (clip-skip, the CLIPSetLastLayer knob) overrides
        how many final blocks are excluded from the HIDDEN output:
        None = the model's configured default (1 when
        penultimate_hidden, else 0), 0 = full stack. The pooled vector
        always comes from the full stack + final LN + projection
        (ComfyUI semantics). For natively-full-stack models the final
        LN is applied to the intermediate state
        (layer_norm_hidden_state=True, the SD1 clip model); configured
        penultimate models keep their final_ln_on_hidden setting.
        """
        cfg = self.config
        dt = cfg.compute_dtype
        b, t = tokens.shape
        default_skip = 1 if cfg.penultimate_hidden else 0
        skip = default_skip if skip_last is None else max(int(skip_last), 0)
        force_post_ln = False
        if skip >= cfg.layers:
            # reference semantics (SDClipModel.clip_layer): a skip
            # deeper than this tower falls back to layer='last', whose
            # output is POST final_layer_norm regardless of
            # layer_norm_hidden_state (unlike an explicit skip 0,
            # which is the pre-LN intermediate for no-LN towers) —
            # dual-tower bundles have different depths and a value
            # valid for the deeper tower must not reject the shallower
            skip = 0
            force_post_ln = True
        tok_emb = nn.Embed(cfg.vocab_size, cfg.width, name="token_embedding")(tokens)
        pos_emb = self.param(
            "position_embedding",
            nn.initializers.normal(0.01),
            (cfg.max_length, cfg.width),
        )
        x = (tok_emb + pos_emb[None, :t, :]).astype(dt)
        causal = jnp.tril(jnp.ones((t, t), dtype=bool))
        intermediate = None
        for i in range(cfg.layers):
            if skip and i == cfg.layers - skip:
                intermediate = x
            x = _CausalBlock(
                cfg.heads, dt, cfg.activation, name=f"block_{i}"
            )(x, causal)
        final_ln = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="final_ln")
        pre_ln = x.astype(jnp.float32)
        x = final_ln(pre_ln)
        # pooled = state at first EOS position per sequence (from the
        # FULL stack + final LN, even when hidden is intermediate)
        if eos_id is None:
            eos_id = Tokenizer.EOS
        eos_pos = jnp.argmax((tokens == eos_id).astype(jnp.int32), axis=1)
        pooled = x[jnp.arange(b), eos_pos]
        if cfg.proj_dim is not None:
            proj = self.param(
                "text_projection",
                nn.initializers.normal(cfg.width ** -0.5),
                (cfg.width, cfg.proj_dim),
            )
            pooled = pooled @ proj.astype(pooled.dtype)
        apply_ln = cfg.final_ln_on_hidden if cfg.penultimate_hidden else True
        if skip:
            hidden = intermediate.astype(jnp.float32)
            if apply_ln:
                # the model's final LN (shared params) is applied to
                # the intermediate state used as context (SD1/SD2
                # semantics; SDXL's encoders set final_ln_on_hidden
                # False and keep the raw state)
                hidden = final_ln(hidden)
        else:
            # skip=0 honors the same LN setting: a no-LN tower (SDXL
            # bigG/L) forced to the last layer returns the PRE-LN
            # state — ComfyUI's layer_norm_hidden_state=False at
            # intermediate_output = num_layers - 1. The too-deep
            # fallback is the exception (post-LN 'last', above).
            hidden = x if (apply_ln or force_post_ln) else pre_ln
        return hidden, pooled
