"""CLIP-class causal text transformer + deterministic tokenizer.

Fills the role of ComfyUI's CLIPTextEncode that the reference's
workflows assume (reference workflows/*.json CLIPTextEncode nodes).
The transformer is architecture-faithful (token+position embeddings,
pre-LN causal blocks, final LN; pooled output = EOS token state).

Tokenizer: the runtime has no network egress to fetch BPE vocab
files, so the default tokenizer is a deterministic byte-level scheme
(stable across hosts — the property the distributed tier needs so
master and workers agree on conditioning for identical prompts). A
real BPE vocab can be dropped in via `Tokenizer(vocab_path=...)`.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np



@dataclasses.dataclass(frozen=True)
class TextEncoderConfig:
    vocab_size: int = 49408
    max_length: int = 77
    width: int = 768
    layers: int = 12
    heads: int = 12
    dtype: str = "bfloat16"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


class Tokenizer:
    """Byte-level tokenizer with BOS/EOS, fixed-length padded output."""

    BOS = 49406
    EOS = 49407

    def __init__(self, max_length: int = 77, vocab_path: Optional[str] = None):
        self.max_length = max_length
        self.vocab_path = vocab_path  # reserved for real BPE vocab

    def encode(self, text: str) -> np.ndarray:
        # Bytes offset by 1 (0 = pad); words salted with a stable hash so
        # different words with shared prefixes diverge like BPE merges do.
        ids: list[int] = [self.BOS]
        for word in text.strip().lower().split():
            digest = hashlib.sha256(word.encode("utf-8")).digest()
            word_id = 256 + int.from_bytes(digest[:4], "big") % 49000
            ids.append(word_id)
            if len(ids) >= self.max_length - 1:
                break
        ids.append(self.EOS)
        ids = ids[: self.max_length]
        out = np.full((self.max_length,), 0, dtype=np.int32)
        out[: len(ids)] = ids
        # pad positions carry EOS id like CLIP's padding convention
        out[len(ids):] = self.EOS
        return out

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts], axis=0)


class _CausalBlock(nn.Module):
    heads: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        width = x.shape[-1]
        head_dim = width // self.heads
        h = nn.LayerNorm(dtype=jnp.float32)(x).astype(self.dtype)
        b, n, _ = h.shape
        q = nn.Dense(width, dtype=self.dtype, name="q")(h)
        k = nn.Dense(width, dtype=self.dtype, name="k")(h)
        v = nn.Dense(width, dtype=self.dtype, name="v")(h)
        q = q.reshape(b, n, self.heads, head_dim)
        k = k.reshape(b, n, self.heads, head_dim)
        v = v.reshape(b, n, self.heads, head_dim)
        # causal mask via explicit bias: flash path not needed at T=77
        scores = jnp.einsum(
            "bnhd,bmhd->bhnm", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / np.sqrt(head_dim)
        scores = jnp.where(mask[None, None, :, :], scores, -1e9)
        probs = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhnm,bmhd->bnhd", probs, v).reshape(b, n, width)
        x = x + nn.Dense(width, dtype=self.dtype, name="proj")(out)

        h = nn.LayerNorm(dtype=jnp.float32)(x).astype(self.dtype)
        h = nn.Dense(width * 4, dtype=self.dtype, name="fc1")(h)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(width, dtype=self.dtype, name="fc2")(h)
        return x + h


class TextEncoder(nn.Module):
    config: TextEncoderConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> tuple[jax.Array, jax.Array]:
        """[B, T] int tokens → (hidden [B, T, width], pooled [B, width])."""
        cfg = self.config
        dt = cfg.compute_dtype
        b, t = tokens.shape
        tok_emb = nn.Embed(cfg.vocab_size, cfg.width, name="token_embedding")(tokens)
        pos_emb = self.param(
            "position_embedding",
            nn.initializers.normal(0.01),
            (cfg.max_length, cfg.width),
        )
        x = (tok_emb + pos_emb[None, :t, :]).astype(dt)
        causal = jnp.tril(jnp.ones((t, t), dtype=bool))
        for i in range(cfg.layers):
            x = _CausalBlock(cfg.heads, dt, name=f"block_{i}")(x, causal)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x.astype(jnp.float32))
        # pooled = state at first EOS position per sequence
        eos_pos = jnp.argmax((tokens == Tokenizer.EOS).astype(jnp.int32), axis=1)
        pooled = x[jnp.arange(b), eos_pos]
        return x, pooled
