"""Diffusion pipelines: model bundle + jitted txt2img / img2img steps.

The glue the reference gets from ComfyUI's executor + common_ksampler
(checkpoint → CLIP encode → KSampler → VAE decode), re-assembled as
pure functions over a parameter bundle so the whole generation is one
jit-compiled XLA program per static shape. The graph executor (graph/)
calls these; the distributed layers shard their inputs.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops import samplers as smp
from .registry import create_model, get_config
from .text_encoder import Tokenizer


def maybe_cast_params(tree):
    """CDT_PARAMS_DTYPE=bfloat16 stores floating-point weights in bf16
    (halves HBM — the big lever for real checkpoints on 16G chips; the
    models already COMPUTE in bf16, so only the storage precision
    changes). Unset keeps float32: CPU golden numerics are pinned at
    f32 weights. Applied by every model/VAE/TE/ControlNet/upscaler
    loader at bundle-build time.

    Takes OWNERSHIP of the tree: each source buffer is freed as soon
    as its cast completes, so the transient peak stays at the f32
    footprint instead of f32+bf16 — the difference between fitting
    and OOMing an SDXL load on a 16G chip. Callers must not reuse the
    input tree afterwards (every loader discards it immediately)."""
    want = os.environ.get("CDT_PARAMS_DTYPE", "")
    if not want:
        return tree
    dt = jnp.dtype(want)

    def cast(x):
        if (
            hasattr(x, "dtype")
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.dtype != dt
        ):
            y = x.astype(dt)
            if isinstance(x, jax.Array):
                try:
                    y.block_until_ready()
                    x.delete()
                except Exception:
                    pass
            return y
        return x

    return jax.tree_util.tree_map(cast, tree)


@dataclasses.dataclass
class PipelineBundle:
    """A checkpoint: diffusion backbone + VAE + text encoder(s) + params."""

    model_name: str
    unet: Any
    vae: Any
    text_encoder: Any
    params: dict[str, Any]          # {"unet", "vae", "te"[, "te2"]}
    tokenizer: Tokenizer
    latent_channels: int = 4
    latent_scale: int = 8           # spatial down factor of the VAE
    # SDXL-class second encoder (context concat + pooled source)
    text_encoder_2: Any = None
    # second encoder's tokenizer: OpenCLIP towers pad with 0, CLIP-L
    # with EOS, so the dual path tokenizes per encoder (None = share)
    tokenizer_2: Tokenizer | None = None
    # SD3-class third encoder (T5; CLIP-L/G are te/te2)
    text_encoder_3: Any = None
    tokenizer_3: Any = None
    # registry names the encoders were built from (LoRA mapping needs
    # the real configs, not a guess from model_name)
    te_name: str | None = None
    te2_name: str | None = None
    te3_name: str | None = None
    # skip-layer guidance (SD3.5): set by the SkipLayerGuidanceSD3
    # node via dataclasses.replace — a new bundle instance, so the
    # jitted samplers recompile for the patched model exactly once
    slg: "SLGSpec | None" = None
    # clip-skip (CLIPSetLastLayer): how many final CLIP blocks to
    # exclude from the hidden/context output; None = each tower's
    # configured default. Applies to CLIP towers only (T5 unaffected)
    clip_skip: int | None = None
    # ModelSampling* node overrides (ComfyUI patches the model's
    # sampling object; here a replaced bundle recompiles the jitted
    # samplers exactly once). None = the registry config's values.
    flow_shift_override: float | None = None
    parameterization_override: str | None = None
    # RescaleCFG patch: std-rescale multiplier of the guided x0
    # prediction (None = plain CFG)
    cfg_rescale: float | None = None
    # DualCFGGuider: when set, sampling positives must be the 2-tuple
    # (cond1, cond2) and guided_model dispatches smp.dual_cfg_model
    # (the outer cfg knob is cfg_conds). None = single-cond CFG.
    dual_cfg: "DualCFGSpec | None" = None
    # PerturbedAttentionGuidance patch (UNet family only; the node
    # guards the family). None = no PAG pass.
    pag: "PAGSpec | None" = None
    # SelfAttentionGuidance patch (UNet family only). None = no SAG.
    sag: "SAGSpec | None" = None
    # PerpNegGuider composition. None = plain CFG.
    perp_neg: "PerpNegSpec | None" = None


@dataclasses.dataclass
class VAEBundle:
    """A standalone VAE (the VAELoader node's output): satisfies the
    attribute protocol the VAE-consuming nodes use (`.vae`,
    `.params["vae"]`, `.latent_channels`, `.latent_scale`) so it can
    replace a checkpoint's bundled VAE anywhere one is accepted."""

    vae: Any
    params: dict[str, Any]
    latent_channels: int
    latent_scale: int


def load_vae(
    vae_name: str = "vae-sd",
    checkpoint: str | None = None,
    seed: int = 0,
) -> VAEBundle:
    """Build a standalone VAE; load real weights when a checkpoint
    resolves (explicit arg or CDT_CHECKPOINT_DIR/<vae_name>.*).
    Standalone VAE files ship bare `encoder./decoder.` keys (e.g.
    vae-ft-mse, Flux ae.safetensors); full checkpoints carry
    `first_stage_model.*` — both layouts map."""
    from . import sd_checkpoint as sdc
    from .registry import model_family

    if model_family(vae_name) != "vae":
        raise ValueError(
            f"{vae_name!r} is not an image-VAE config "
            f"(family {model_family(vae_name)!r}); use a vae-* registry "
            "name"
        )
    cfg = get_config(vae_name)
    vae = create_model(vae_name)
    params = vae.init(jax.random.key(seed), jnp.zeros((1, 32, 32, 3)))
    ckpt = checkpoint or sdc.find_checkpoint(vae_name)
    if ckpt:
        from ..utils.logging import log

        log(f"loading VAE checkpoint {ckpt} for {vae_name}")
        params, _problems = sdc.load_vae_weights(
            sdc.read_checkpoint(ckpt), cfg, params
        )
    return VAEBundle(
        vae=vae,
        params=maybe_cast_params({"vae": params}),
        latent_channels=cfg.latent_channels,
        latent_scale=cfg.downscale,
    )


@dataclasses.dataclass(frozen=True)
class PAGSpec:
    """Perturbed-attention guidance (PerturbedAttentionGuidance node):
    the guided result gains scale * (cond - cond_with_identity_attn),
    where the perturbed pass runs the middle-block self-attention as
    identity (models/unet.py pag flag)."""

    scale: float = 3.0


@dataclasses.dataclass(frozen=True)
class SAGSpec:
    """Self-attention guidance (SelfAttentionGuidance node, Hong et
    al. 2023): blur the uncond x0 estimate where the middle-block
    self-attention concentrates, re-noise, and guide away from that
    degraded prediction."""

    scale: float = 0.5
    blur_sigma: float = 2.0


@dataclasses.dataclass(frozen=True)
class PerpNegSpec:
    """PerpNegGuider parameters: only the component of the negative
    orthogonal to the positive pushes away (smp.perp_neg_model).
    Sampling positives must be the 2-tuple (positive, negative) and
    the sampler's negative slot carries the EMPTY conditioning."""

    neg_scale: float = 1.0


@dataclasses.dataclass(frozen=True)
class DualCFGSpec:
    """DualCFGGuider parameters riding on the bundle (the outer
    cfg_conds travels as the sampler's cfg knob; see
    smp.dual_cfg_model for the regular/nested formulas)."""

    cfg_cond2_negative: float
    nested: bool = False


@dataclasses.dataclass(frozen=True)
class SLGSpec:
    """Skip-layer guidance parameters (reference SkipLayerGuidanceDiT:
    scale * (cond - cond_with_layers_skipped) over a sampling-progress
    window)."""

    layers: tuple
    scale: float = 3.0
    start_percent: float = 0.01
    end_percent: float = 0.15


def load_pipeline(
    model_name: str = "tiny-unet",
    vae_name: str | None = None,
    te_name: str | None = None,
    seed: int = 0,
    checkpoint: str | None = None,
) -> PipelineBundle:
    """Build a pipeline; load real weights when a checkpoint resolves.

    Checkpoint resolution order: explicit `checkpoint` arg, then
    `CDT_CHECKPOINT_DIR/<model_name>.{safetensors,ckpt}` (the dir env
    var may also point directly at a file). Single-file SD layout
    (model.diffusion_model / first_stage_model / cond_stage_model) is
    mapped key-by-key into the flax trees (models/sd_checkpoint.py).
    Without a checkpoint the weights are deterministic random init —
    the distributed machinery upstream is weight-agnostic.
    """
    from .registry import (
        DEFAULT_TEXT_ENCODERS,
        DUAL_TEXT_ENCODERS,
        HIDDEN_POOLED_ENCODERS,
        TRIPLE_TEXT_ENCODERS,
        model_family,
    )

    tiny = model_name.startswith("tiny")
    family = model_family(model_name)
    dual = DUAL_TEXT_ENCODERS.get(model_name)
    hidden_pooled = HIDDEN_POOLED_ENCODERS.get(model_name)
    triple = TRIPLE_TEXT_ENCODERS.get(model_name)
    vae_name = vae_name or _family_vae_name(model_name, family)
    te3_name = None
    if triple:
        # SD3 layout: CLIP-L + CLIP-G + T5
        te_name = te_name or triple[0]
        te2_name = triple[1]
        te3_name = triple[2]
    elif hidden_pooled:
        # Flux layout: hidden states from a T5-class encoder, pooled
        # vector from a CLIP-class encoder
        te_name = te_name or hidden_pooled[0]
        te2_name = hidden_pooled[1]
    elif dual:
        te_name = te_name or dual[0]
        te2_name = dual[1]
    else:
        te_name = te_name or DEFAULT_TEXT_ENCODERS.get(model_name) or (
            "tiny-te" if tiny else "clip-l"
        )
        te2_name = None

    unet = create_model(model_name)
    vae = create_model(vae_name)
    te = create_model(te_name)
    te_cfg = get_config(te_name)
    unet_cfg = get_config(model_name)
    vae_cfg = get_config(vae_name)

    root = jax.random.key(seed)
    k_unet, k_vae, k_te = jax.random.split(root, 3)

    # Init with minimal dummy shapes; flax params are shape-polymorphic
    # across batch/spatial dims for these architectures.
    lat = jnp.zeros((1, 16, 16, vae_cfg.latent_channels))
    ctx = jnp.zeros((1, te_cfg.max_length, unet_cfg.context_dim))
    ts = jnp.zeros((1,))
    if family == "dit":  # video DiT
        lat5 = jnp.zeros((1, 4, 16, 16, unet_cfg.in_channels))
        unet_params = unet.init(k_unet, lat5, ts, ctx)
    elif family in ("mmdit", "sd3"):
        unet_params = unet.init(
            k_unet, lat, ts, ctx, y=jnp.zeros((1, unet_cfg.adm_in_channels))
        )
    else:
        unet_params = unet.init(
            k_unet, _unet_init_latents(unet_cfg, lat.shape[-1]), ts, ctx
        )
    img = jnp.zeros((1, 32, 32, 3))
    vae_params = vae.init(k_vae, img)
    tokens = jnp.zeros((1, te_cfg.max_length), jnp.int32)
    te_params = te.init(k_te, tokens)

    te2 = None
    te2_params = None
    if te2_name:
        te2 = create_model(te2_name)
        te2_cfg = get_config(te2_name)
        tokens2 = jnp.zeros((1, te2_cfg.max_length), jnp.int32)
        te2_params = te2.init(jax.random.fold_in(k_te, 2), tokens2)
    te3 = None
    te3_params = None
    if te3_name:
        te3 = create_model(te3_name)
        te3_cfg = get_config(te3_name)
        tokens3 = jnp.zeros((1, te3_cfg.max_length), jnp.int32)
        te3_params = te3.init(jax.random.fold_in(k_te, 3), tokens3)

    from . import sd_checkpoint as sdc

    ckpt_supplied: set[str] = set()
    ckpt_path = checkpoint or sdc.find_checkpoint(model_name)
    if ckpt_path:
        from ..utils.logging import log

        log(f"loading checkpoint {ckpt_path} for {model_name}")
        state_dict = sdc.read_checkpoint(ckpt_path)
        templates = {"unet": unet_params, "vae": vae_params, "te": te_params}
        if te2_params is not None:
            templates["te2"] = te2_params
        if te3_params is not None:
            templates["te3"] = te3_params
        mapped, _problems = sdc.load_sd_weights(
            state_dict, unet_cfg, vae_cfg, te_cfg, templates,
            te2_cfg=get_config(te2_name) if te2_name else None,
            te3_cfg=get_config(te3_name) if te3_name else None,
            family=family,
        )
        unet_params = mapped["unet"]
        vae_params = mapped["vae"]
        te_params = mapped["te"]
        te2_params = mapped.get("te2", te2_params)
        te3_params = mapped.get("te3", te3_params)
        # which encoder parts the FILE actually carried — a fine-tuned
        # checkpoint's own encoders must not be clobbered by a
        # same-named standalone file below. Detection mirrors each
        # family loader's own part sniffing: for mmdit (Flux) te is
        # the T5 and te2 the CLIP (load_flux_weights); the SD/SDXL/SD3
        # layouts use their published key prefixes.
        if family == "mmdit":
            if any("layer.0.SelfAttention.q.weight" in k for k in state_dict):
                ckpt_supplied.add("te")
            if any("text_model.encoder.layers.0" in k for k in state_dict):
                ckpt_supplied.add("te2")
        else:
            _te_markers = {
                "te": (
                    "cond_stage_model.", "conditioner.embedders.0.",
                    "text_encoders.clip_l.",
                ),
                "te2": (
                    "conditioner.embedders.1.", "text_encoders.clip_g.",
                ),
                "te3": ("text_encoders.t5xxl.",),
            }
            for part, markers in _te_markers.items():
                if any(k.startswith(markers) for k in state_dict):
                    ckpt_supplied.add(part)

    # Separate-file text encoders (the real Flux/SD3 distribution
    # format: t5xxl_fp16.safetensors / clip_l.safetensors / ... — what
    # ComfyUI's CLIPLoader family consumes): a file resolving under the
    # ENCODER's registry name fills encoders the main checkpoint did
    # NOT supply (checkpoint-bundled fine-tuned encoders win).
    def _load_te_file(name, params_, part):
        if not name or params_ is None or part in ckpt_supplied:
            return params_
        return _load_te_checkpoint(name, params_)

    te_params = _load_te_file(te_name, te_params, "te")
    te2_params = _load_te_file(te2_name, te2_params, "te2")
    te3_params = _load_te_file(te3_name, te3_params, "te3")

    from .t5_encoder import T5Tokenizer

    if family == "mmdit":
        tokenizer = T5Tokenizer(
            max_length=te_cfg.max_length, vocab_size=te_cfg.vocab_size
        )
    else:
        tokenizer = Tokenizer(
            max_length=te_cfg.max_length, pad_id=te_cfg.pad_token_id
        )

    params = {"unet": unet_params, "vae": vae_params, "te": te_params}
    if te2_params is not None:
        params["te2"] = te2_params
    if te3_params is not None:
        params["te3"] = te3_params
    params = maybe_cast_params(params)
    return PipelineBundle(
        model_name=model_name,
        unet=unet,
        vae=vae,
        text_encoder=te,
        params=params,
        tokenizer=tokenizer,
        latent_channels=vae_cfg.latent_channels,
        latent_scale=vae_cfg.downscale,
        text_encoder_2=te2,
        tokenizer_2=(
            Tokenizer(
                max_length=te2_cfg.max_length, pad_id=te2_cfg.pad_token_id
            )
            if te2_name
            else None
        ),
        text_encoder_3=te3,
        tokenizer_3=(
            T5Tokenizer(
                max_length=te3_cfg.max_length, vocab_size=te3_cfg.vocab_size
            )
            if te3_name
            else None
        ),
        te_name=te_name,
        te2_name=te2_name,
        te3_name=te3_name,
    )


def _unet_init_latents(unet_cfg, latent_channels: int):
    """Dummy latents for UNet-family init, honoring in_channels-widened
    inpaint configs (9 = 4 + mask + masked-image latents). Shared by
    load_pipeline and load_unet."""
    in_ch = getattr(unet_cfg, "in_channels", latent_channels)
    return jnp.zeros((1, 16, 16, in_ch))


def _load_te_checkpoint(name: str, params_):
    """Fill a text-encoder param tree from a separate-file checkpoint
    resolving under the encoder's registry name (no-op when none
    does). Shared by load_pipeline and load_clip."""
    from . import sd_checkpoint as sdc
    from .registry import model_family

    ckpt_ = sdc.find_checkpoint(name)
    if not ckpt_:
        return params_
    from ..utils.logging import log

    log(f"loading text-encoder checkpoint {ckpt_} for {name}")
    sd_dict = sdc.read_checkpoint(ckpt_)
    if model_family(name) == "t5_encoder":
        out, _problems = sdc.load_t5_weights(sd_dict, get_config(name), params_)
    else:
        out, _problems = sdc.load_clip_te_weights(
            sd_dict, get_config(name), params_
        )
    return out


def _family_vae_name(model_name: str, family: str) -> str:
    """The default VAE registry name for a diffusion family (the
    latent-geometry source shared by load_pipeline and load_unet)."""
    tiny = model_name.startswith("tiny")
    if family == "mmdit":
        return "tiny-vae-flux" if tiny else "vae-flux"
    if family == "sd3":
        return "tiny-vae-sd3" if tiny else "vae-sd3"
    return "tiny-vae" if tiny else "vae-sd"


def load_unet(
    model_name: str,
    seed: int = 0,
    checkpoint: str | None = None,
) -> PipelineBundle:
    """Diffusion-backbone-only bundle (the ComfyUI UNETLoader: real
    Flux/SD3.5 distributions ship the transformer as its own file and
    load text encoders / VAE separately). The bundle carries no VAE or
    text encoders — wire VAELoader / CLIPLoader outputs alongside it;
    latent geometry comes from the family's default VAE config.
    Checkpoint resolution mirrors load_pipeline
    (CDT_CHECKPOINT_DIR/<model_name>.*); both bare diffusion-file keys
    and model.diffusion_model.-nested layouts map
    (sd_checkpoint.load_diffusion_weights)."""
    from . import sd_checkpoint as sdc
    from .registry import model_family

    family = model_family(model_name)
    if family not in ("unet", "mmdit", "sd3"):
        raise ValueError(
            f"{model_name!r} (family {family!r}) is not an image diffusion "
            "backbone; UNETLoader loads unet/mmdit/sd3 models"
        )
    unet = create_model(model_name)
    unet_cfg = get_config(model_name)
    vae_cfg = get_config(_family_vae_name(model_name, family))

    lat = jnp.zeros((1, 16, 16, vae_cfg.latent_channels))
    ctx = jnp.zeros((1, 8, unet_cfg.context_dim))
    ts = jnp.zeros((1,))
    k_unet = jax.random.key(seed)
    if family in ("mmdit", "sd3"):
        unet_params = unet.init(
            k_unet, lat, ts, ctx, y=jnp.zeros((1, unet_cfg.adm_in_channels))
        )
    else:
        unet_params = unet.init(
            k_unet, _unet_init_latents(unet_cfg, lat.shape[-1]), ts, ctx
        )

    ckpt_path = checkpoint or sdc.find_checkpoint(model_name)
    if ckpt_path:
        from ..utils.logging import log

        log(f"loading diffusion-model checkpoint {ckpt_path} for {model_name}")
        unet_params, _problems = sdc.load_diffusion_weights(
            sdc.read_checkpoint(ckpt_path), unet_cfg, unet_params, family
        )
    return PipelineBundle(
        model_name=model_name,
        unet=unet,
        vae=None,
        text_encoder=None,
        params=maybe_cast_params({"unet": unet_params}),
        tokenizer=None,
        latent_channels=vae_cfg.latent_channels,
        latent_scale=vae_cfg.downscale,
    )


def _order_clip_towers(names: list[str]) -> list[str]:
    """(CLIP-L, CLIP-G) ordering for the sdxl/sd3 layouts, sniffed by
    tower width (G is the wider 1280-d tower) — the reference stack
    identifies towers from the weights, so ported workflows pass the
    two names in either order. Equal widths keep the given order."""
    if len(names) == 2:
        w0 = getattr(get_config(names[0]), "width", 0)
        w1 = getattr(get_config(names[1]), "width", 0)
        if w0 > w1:
            return [names[1], names[0]]
    return list(names)


# CLIP-loader layouts → the representative diffusion family whose
# conditioning composition _encode_raw applies (the bundle's own
# encoders do the work; the name only picks the branch).
_CLIP_LAYOUT_FAMILIES = {
    "sd": None,      # default branch: single tower / SDXL-style concat
    "sdxl": None,
    "flux": ("tiny-flux", "flux-dev"),
    "sd3": ("tiny-sd3", "sd3-medium"),
}


def load_clip(
    te_names: list[str],
    layout: str = "sd",
    seed: int = 0,
) -> PipelineBundle:
    """Text-encoder-only bundle (the ComfyUI CLIPLoader /
    DualCLIPLoader / TripleCLIPLoader family): encoders resolve by
    registry name, real weights load from separate-file checkpoints
    when they resolve (CDT_CHECKPOINT_DIR/<te_name>.*), and `layout`
    picks the conditioning composition:

      sd    — one CLIP tower (hidden + pooled)
      sdxl  — CLIP-L + CLIP-G: feature concat, pooled from G
      flux  — T5 hidden states + CLIP pooled (encoder order is
              sniffed by family, so either argument order works)
      sd3   — CLIP-L + CLIP-G [+ T5]: the SD3 composition; without a
              T5 the CLIP sequence zero-pads to the backbone width
              (the reference stack's low-memory SD3 mode)
    """
    from .registry import model_family
    from .t5_encoder import T5Tokenizer

    names = [str(n) for n in te_names]
    expected = {"sd": 1, "sdxl": 2, "flux": 2, "sd3": (2, 3)}
    if layout not in expected:
        raise ValueError(
            f"unknown CLIP layout {layout!r}; use {sorted(expected)}"
        )
    want = expected[layout]
    ok = len(names) in want if isinstance(want, tuple) else len(names) == want
    if not ok:
        raise ValueError(
            f"layout {layout!r} takes {want} encoder name(s), got {names}"
        )

    t5s = [n for n in names if model_family(n) == "t5_encoder"]
    clips = [n for n in names if model_family(n) != "t5_encoder"]
    if layout == "flux":
        if len(t5s) != 1 or len(clips) != 1:
            raise ValueError(
                f"flux layout needs one T5-family and one CLIP-family "
                f"encoder, got {names}"
            )
        ordered = [t5s[0], clips[0]]          # te = T5, te2 = CLIP
    elif layout == "sd3":
        if len(clips) != 2 or len(t5s) > 1:
            raise ValueError(
                f"sd3 layout needs two CLIP-family encoders and at most "
                f"one T5, got {names}"
            )
        ordered = _order_clip_towers(clips) + t5s  # te = L, te2 = G [, T5]
    else:
        if t5s:
            raise ValueError(
                f"layout {layout!r} takes CLIP-family encoders only, "
                f"got {names}"
            )
        ordered = (
            _order_clip_towers(names) if layout == "sdxl" else names
        )

    rep_family = _CLIP_LAYOUT_FAMILIES[layout]
    if rep_family is None:
        bundle_name = ordered[0]
    else:
        tiny = all(n.startswith("tiny") for n in ordered)
        bundle_name = rep_family[0] if tiny else rep_family[1]

    encoders, tokenizers, params = [], [], {}
    root = jax.random.key(seed)
    for i, name in enumerate(ordered):
        cfg = get_config(name)
        enc = create_model(name)
        tokens = jnp.zeros((1, cfg.max_length), jnp.int32)
        p = enc.init(jax.random.fold_in(root, i), tokens)
        p = _load_te_checkpoint(name, p)
        encoders.append(enc)
        if model_family(name) == "t5_encoder":
            tokenizers.append(
                T5Tokenizer(max_length=cfg.max_length, vocab_size=cfg.vocab_size)
            )
        else:
            tokenizers.append(
                Tokenizer(max_length=cfg.max_length, pad_id=cfg.pad_token_id)
            )
        params["te" if i == 0 else f"te{i + 1}"] = p

    def slot(seq, i):
        return seq[i] if len(seq) > i else None

    return PipelineBundle(
        model_name=bundle_name,
        unet=None,
        vae=None,
        text_encoder=encoders[0],
        params=maybe_cast_params(params),
        tokenizer=tokenizers[0],
        text_encoder_2=slot(encoders, 1),
        tokenizer_2=slot(tokenizers, 1),
        text_encoder_3=slot(encoders, 2),
        tokenizer_3=slot(tokenizers, 2),
        te_name=ordered[0],
        te2_name=slot(ordered, 1),
        te3_name=slot(ordered, 2),
    )


# --- conditioning --------------------------------------------------------

def _encode_raw(bundle: PipelineBundle, texts: list[str]):
    """Prompts → (hidden [B, T, D], pooled [B, P]).

    Dual-encoder bundles (SDXL layout): context is the channel concat
    of both encoders' hidden states and pooled comes from the second
    (projected) encoder — the real SDXL conditioning, replacing the
    round-1 zero-pad hack. Single-encoder bundles pad/truncate to the
    backbone's context_dim only when they genuinely mismatch.
    """
    from .registry import model_family

    if model_family(bundle.model_name) == "sd3":
        # SD3 layout: CLIP-L/G penultimate states concatenated on
        # features, zero-padded to the T5 width, sequence-concatenated
        # with T5 states; pooled = CLIP-L pooled ++ CLIP-G pooled.
        # A missing T5 (DualCLIPLoader type=sd3 — the reference
        # stack's low-memory SD3 mode) keeps the CLIP-only sequence,
        # padded to the backbone's context width.
        if bundle.text_encoder_2 is None:
            raise ValueError(
                f"{bundle.model_name}: sd3 bundles need at least the two "
                "CLIP encoders (CLIP-L, CLIP-G)"
            )
        tokens = jnp.asarray(bundle.tokenizer.encode_batch(texts))
        h_l, p_l = bundle.text_encoder.apply(
            bundle.params["te"], tokens, eos_id=bundle.tokenizer.eos_id,
            skip_last=bundle.clip_skip,
        )
        tok2 = bundle.tokenizer_2
        tokens2 = jnp.asarray(tok2.encode_batch(texts))
        h_g, p_g = bundle.text_encoder_2.apply(
            bundle.params["te2"], tokens2, eos_id=tok2.eos_id,
            skip_last=bundle.clip_skip,
        )
        clip_ctx = jnp.concatenate(
            [h_l.astype(jnp.float32), h_g.astype(jnp.float32)], axis=-1
        )
        if bundle.text_encoder_3 is not None:
            tokens3 = jnp.asarray(bundle.tokenizer_3.encode_batch(texts))
            h_t5, _ = bundle.text_encoder_3.apply(
                bundle.params["te3"], tokens3
            )
            width = h_t5.shape[-1]
        else:
            h_t5 = None
            width = getattr(
                get_config(bundle.model_name), "context_dim",
                clip_ctx.shape[-1],
            )
        if clip_ctx.shape[-1] < width:
            clip_ctx = jnp.pad(
                clip_ctx, ((0, 0), (0, 0), (0, width - clip_ctx.shape[-1]))
            )
        hidden = (
            jnp.concatenate([clip_ctx, h_t5.astype(jnp.float32)], axis=1)
            if h_t5 is not None
            else clip_ctx
        )
        pooled = jnp.concatenate(
            [p_l.astype(jnp.float32), p_g.astype(jnp.float32)], axis=-1
        )
        return hidden, pooled

    if model_family(bundle.model_name) == "mmdit":
        return _encode_flux_parts(bundle, texts, texts)

    tokens = jnp.asarray(bundle.tokenizer.encode_batch(texts))
    hidden, pooled = bundle.text_encoder.apply(
        bundle.params["te"], tokens, eos_id=bundle.tokenizer.eos_id,
        skip_last=bundle.clip_skip,
    )
    if bundle.text_encoder_2 is not None:
        tok2 = bundle.tokenizer_2 or bundle.tokenizer
        tokens2 = jnp.asarray(tok2.encode_batch(texts))
        hidden2, pooled2 = bundle.text_encoder_2.apply(
            bundle.params["te2"], tokens2, eos_id=tok2.eos_id,
            skip_last=bundle.clip_skip,
        )
        hidden = jnp.concatenate(
            [hidden.astype(jnp.float32), hidden2.astype(jnp.float32)], axis=-1
        )
        pooled = pooled2
    ctx_dim = getattr(get_config(bundle.model_name), "context_dim", hidden.shape[-1])
    if hidden.shape[-1] < ctx_dim:
        hidden = jnp.pad(hidden, ((0, 0), (0, 0), (0, ctx_dim - hidden.shape[-1])))
    elif hidden.shape[-1] > ctx_dim:
        hidden = hidden[..., :ctx_dim]
    return hidden, pooled


def encode_text(bundle: PipelineBundle, texts: list[str]) -> jax.Array:
    """Prompts → [B, T, context_dim] context."""
    hidden, _pooled = _encode_raw(bundle, texts)
    return hidden


def encode_text_pooled(bundle: PipelineBundle, texts: list[str]):
    """Prompts → Conditioning with pooled vector (SDXL-class adm
    conditioning: pooled text is part of the UNet's label embedding)."""
    from ..ops.conditioning import Conditioning

    hidden, pooled = _encode_raw(bundle, texts)
    return Conditioning(context=hidden, pooled=pooled)


def _encode_flux_parts(
    bundle: PipelineBundle, texts_t5: list[str], texts_clip: list[str]
):
    """Flux layout (mmdit): T5 hidden states are the context; the
    pooled vector comes from the CLIP encoder — no concat, no padding.
    Both encoders (and their distinct tokenizers) are mandatory for
    this family; a T5 tokenizer feeding the CLIP tower would be
    silently wrong, so no fallback exists. Shared by _encode_raw
    (same text to both towers) and CLIPTextEncodeFlux (per-tower
    prompts)."""
    if bundle.text_encoder_2 is None or bundle.tokenizer_2 is None:
        raise ValueError(
            f"{bundle.model_name}: mmdit bundles need text_encoder_2/"
            "tokenizer_2 (CLIP pooled source)"
        )
    tokens = jnp.asarray(bundle.tokenizer.encode_batch(texts_t5))
    hidden, _ = bundle.text_encoder.apply(bundle.params["te"], tokens)
    tok2 = bundle.tokenizer_2
    tokens2 = jnp.asarray(tok2.encode_batch(texts_clip))
    _, pooled = bundle.text_encoder_2.apply(
        bundle.params["te2"], tokens2, eos_id=tok2.eos_id,
        skip_last=bundle.clip_skip,
    )
    return hidden, pooled


def encode_text_pooled_flux(
    bundle: PipelineBundle,
    texts_t5: list[str],
    texts_clip: list[str],
    guidance: float | None = None,
):
    """Per-tower Flux encoding (CLIPTextEncodeFlux parity): t5xxl text
    feeds the T5 context, clip_l text the CLIP pooled vector, and the
    distilled guidance rides on the conditioning (same slot the
    FluxGuidance node writes). With identical prompts and
    guidance=None this reduces exactly to encode_text_pooled on an
    mmdit bundle."""
    from ..ops.conditioning import Conditioning
    from .registry import model_family

    if model_family(bundle.model_name) != "mmdit":
        raise ValueError(
            f"{bundle.model_name}: CLIPTextEncodeFlux needs a Flux-layout "
            "(mmdit) bundle"
        )
    hidden, pooled = _encode_flux_parts(bundle, texts_t5, texts_clip)
    return Conditioning(
        context=hidden, pooled=pooled,
        guidance=None if guidance is None else float(guidance),
    )


def encode_text_pooled_sdxl(
    bundle: PipelineBundle,
    texts_g: list[str],
    texts_l: list[str],
    size_cond: tuple | None = None,
):
    """Per-tower SDXL encoding (CLIPTextEncodeSDXL parity): text_l
    feeds the CLIP-L tower, text_g the CLIP-G tower; context is the
    feature concat, pooled comes from the projected G tower, and
    size_cond carries the six adm size ints. With identical prompts
    this reduces exactly to encode_text_pooled on a dual bundle."""
    from ..ops.conditioning import Conditioning

    if bundle.text_encoder_2 is None:
        raise ValueError(
            f"{bundle.model_name}: CLIPTextEncodeSDXL needs a dual-tower "
            "(SDXL-layout) CLIP bundle"
        )
    tokens = jnp.asarray(bundle.tokenizer.encode_batch(texts_l))
    h_l, _p_l = bundle.text_encoder.apply(
        bundle.params["te"], tokens, eos_id=bundle.tokenizer.eos_id,
        skip_last=bundle.clip_skip,
    )
    tok2 = bundle.tokenizer_2 or bundle.tokenizer
    tokens2 = jnp.asarray(tok2.encode_batch(texts_g))
    h_g, p_g = bundle.text_encoder_2.apply(
        bundle.params["te2"], tokens2, eos_id=tok2.eos_id,
        skip_last=bundle.clip_skip,
    )
    hidden = jnp.concatenate(
        [h_l.astype(jnp.float32), h_g.astype(jnp.float32)], axis=-1
    )
    ctx_dim = getattr(
        get_config(bundle.model_name), "context_dim", hidden.shape[-1]
    )
    if hidden.shape[-1] < ctx_dim:
        hidden = jnp.pad(
            hidden, ((0, 0), (0, 0), (0, ctx_dim - hidden.shape[-1]))
        )
    elif hidden.shape[-1] > ctx_dim:
        hidden = hidden[..., :ctx_dim]
    return Conditioning(context=hidden, pooled=p_g, size_cond=size_cond)


# --- model fn (VP eps / v / rectified-flow parameterisations) ------------

def model_schedule_info(bundle: PipelineBundle) -> tuple[str, float]:
    """(parameterization, flow_shift) of the bundle's backbone — the
    knobs that pick the sigma schedule and img2img noising rule
    (ops/samplers.get_model_sigmas / noise_latents). Flow-matching
    families (Flux class) carry parameterization == "flow". The
    ModelSampling* nodes override either knob per bundle."""
    cfg = get_config(bundle.model_name)
    param = bundle.parameterization_override or getattr(
        cfg, "parameterization", "eps"
    )
    shift = bundle.flow_shift_override
    if shift is None:
        shift = getattr(cfg, "flow_shift", 3.0)
    return (param, float(shift))


def _make_model_fn(
    bundle: PipelineBundle, params, skip_layers: tuple = (),
    pag: bool = False, sag_capture: bool = False,
):
    """sag_capture=True changes the RETURN CONTRACT: model_fn yields
    (eps, attn_probs, (mid_h, mid_w)) — the SAG capture pass. Only
    smp.sag_cfg_model consumes that form."""
    from ..ops.conditioning import Conditioning

    def model_fn(x, sigma_batch, cond):
        is_flow = model_schedule_info(bundle)[0] == "flow"
        context = cond.context if isinstance(cond, Conditioning) else cond
        if (
            context.shape[0] != x.shape[0]
            and x.shape[0] % context.shape[0] == 0
        ):
            # conditioning broadcast across a larger latent batch
            # (ComfyUI semantics — e.g. a participant-major batch from
            # a mesh pass refined with one prompt). jnp.repeat keeps
            # the CFG concat layout aligned: [pos;neg] doubling of x
            # pairs with [pos*k;neg*k]
            context = jnp.repeat(
                context, x.shape[0] // context.shape[0], axis=0
            )
        control = None
        if (
            isinstance(cond, Conditioning)
            and cond.control_hint is not None
            and cond.control_module is not None
        ):
            if is_flow:
                raise ValueError(
                    "ControlNet conditioning is not supported for "
                    "Flux-class models (Flux ControlNets are a separate "
                    "architecture)"
                )
            feats = cond.control_module.apply(cond.control_params, cond.control_hint)
            lh, lw = x.shape[1], x.shape[2]
            if feats.shape[1] != lh or feats.shape[2] != lw:
                feats = jax.image.resize(
                    feats, (feats.shape[0], lh, lw, feats.shape[3]), method="linear"
                )
            if feats.shape[0] == 1 and x.shape[0] > 1:
                feats = jnp.broadcast_to(feats, (x.shape[0],) + feats.shape[1:])
            control = feats * cond.control_strength
            if cond.control_range is not None:
                # ControlNetApplyAdvanced scheduling window: arithmetic
                # gate on the per-step scalar sigma keeps the
                # trajectory one XLA program
                p2s = percent_converter(bundle)
                sig_hi = p2s(float(cond.control_range[0]))
                sig_lo = p2s(float(cond.control_range[1]))
                s0 = sigma_batch[0]
                gate = ((s0 <= sig_hi) & (s0 > sig_lo)).astype(control.dtype)
                control = control * gate
        if (
            is_flow
            and isinstance(cond, Conditioning)
            and cond.concat_latent is not None
        ):
            raise ValueError(
                "concat-channel inpaint conditioning "
                "(InpaintModelConditioning) applies to SD-class inpaint "
                "UNets; flow-family models have no c_concat input"
            )
        if (
            not is_flow
            and isinstance(cond, Conditioning)
            and cond.reference_latents
        ):
            # loud like the SD3 module's own rejection — a silent drop
            # reads as the feature working
            raise ValueError(
                "reference latents are a Flux-Kontext capability; this "
                "model family has no reference token path"
            )
        y = None
        adm = getattr(get_config(bundle.model_name), "adm_in_channels", 0)
        if adm and isinstance(cond, Conditioning) and cond.pooled is not None:
            pooled = cond.pooled
            size_dims = adm - pooled.shape[-1]
            if size_dims == 6 * 256:
                # real SDXL adm layout: pooled text + six 256-d Fourier
                # size embeddings (orig_h, orig_w, crop_t, crop_l,
                # target_h, target_w) — the CLIPTextEncodeSDXL node
                # overrides them via cond.size_cond; the default is
                # crops 0 with sizes from the latent
                from .layers import timestep_embedding

                if cond.size_cond is not None:
                    vals = jnp.asarray(
                        [float(v) for v in cond.size_cond], jnp.float32
                    )
                else:
                    h_px = x.shape[1] * bundle.latent_scale
                    w_px = x.shape[2] * bundle.latent_scale
                    vals = jnp.asarray(
                        [h_px, w_px, 0.0, 0.0, h_px, w_px], jnp.float32
                    )
                size_emb = timestep_embedding(vals, 256).reshape(1, -1)
                pooled = jnp.concatenate(
                    [
                        pooled.astype(jnp.float32),
                        jnp.broadcast_to(
                            size_emb, (pooled.shape[0], size_emb.shape[-1])
                        ),
                    ],
                    axis=-1,
                )
            elif pooled.shape[-1] < adm:
                pooled = jnp.pad(pooled, ((0, 0), (0, adm - pooled.shape[-1])))
            elif pooled.shape[-1] > adm:
                pooled = pooled[..., :adm]
            if (
                pooled.shape[0] != x.shape[0]
                and x.shape[0] % pooled.shape[0] == 0
            ):
                # repeat, not pooled[:1]-broadcast: under the CFG
                # concat the second half is the NEGATIVE pooled vector
                pooled = jnp.repeat(
                    pooled, x.shape[0] // pooled.shape[0], axis=0
                )
            y = pooled
        if is_flow:
            # rectified flow (Flux class): t IS sigma, no input scaling,
            # and the velocity prediction equals eps under the sampler
            # contract denoised = x - sigma*eps. The distilled guidance
            # scale comes from the conditioning (FluxGuidance node);
            # None falls back to the config default inside the model.
            g = None
            if isinstance(cond, Conditioning) and cond.guidance is not None:
                g = jnp.full((x.shape[0],), float(cond.guidance), jnp.float32)
            kwargs = {}
            if isinstance(cond, Conditioning) and cond.reference_latents:
                # Flux-Kontext editing: reference latents join the
                # image token stream (models/mmdit.py); SD3-class
                # models reject them explicitly
                kwargs["ref_latents"] = [
                    r.astype(x.dtype) for r in cond.reference_latents
                ]
            if skip_layers:
                # skip-layer guidance pass (SD3-class only; the node
                # guards the family)
                kwargs["skip_layers"] = tuple(skip_layers)
            out = bundle.unet.apply(
                params["unet"], x, sigma_batch, context, y=y, guidance=g,
                **kwargs,
            )
            return out.astype(x.dtype)
        c_in = (1.0 / jnp.sqrt(sigma_batch**2 + 1.0)).reshape(
            (-1,) + (1,) * (x.ndim - 1)
        )
        t = smp.sigma_to_timestep(sigma_batch)
        x_in = x * c_in
        if isinstance(cond, Conditioning) and cond.concat_latent is not None:
            # inpaint-model channels join AFTER the VP input scaling
            # (reference c_concat convention: only the noisy latents
            # are scaled). The backbone must be an in_channels-widened
            # config (sd15-inpaint class) — a 4-channel model fails its
            # input conv shape check loudly.
            extra = cond.concat_latent.astype(x_in.dtype)
            if extra.shape[0] != x_in.shape[0]:
                extra = jnp.repeat(
                    extra, x_in.shape[0] // extra.shape[0], axis=0
                )
            if extra.shape[1:3] != x_in.shape[1:3]:
                extra = jax.image.resize(
                    extra,
                    (extra.shape[0],) + x_in.shape[1:3] + (extra.shape[3],),
                    method="linear",
                )
            x_in = jnp.concatenate([x_in, extra], axis=-1)
        unet_kwargs = {"pag": True} if pag else {}
        probs = None
        if sag_capture:
            out, mut = bundle.unet.apply(
                params["unet"], x_in, t, context, y=y, control=control,
                sag_capture=True, mutable=["intermediates"],
                **unet_kwargs,
            )
            probs = jax.tree_util.tree_leaves(mut)[0]
        else:
            out = bundle.unet.apply(
                params["unet"], x_in, t, context, y=y, control=control,
                **unet_kwargs,
            )
        if model_schedule_info(bundle)[0] == "v":
            # SD2.x-768-class velocity prediction. With the VP scalings
            # (c_skip = 1/(sigma^2+1), c_out = -sigma/sqrt(sigma^2+1)):
            #   denoised = x/(sigma^2+1) - v*sigma/sqrt(sigma^2+1)
            # Converted exactly to the sampler's eps contract
            # (denoised = x - sigma*eps):
            #   eps = x*sigma/(sigma^2+1) + v/sqrt(sigma^2+1)
            sig = sigma_batch.reshape((-1,) + (1,) * (x.ndim - 1))
            out = x * (sig / (sig**2 + 1.0)) + out / jnp.sqrt(sig**2 + 1.0)
        if sag_capture:
            levels = len(get_config(bundle.model_name).channel_mult)
            # per-level ceil-div: Downsample is a stride-2 pad-1 conv,
            # so each level yields ceil(H/2) — a single floor division
            # disagrees whenever an intermediate dim is odd
            mid_h, mid_w = x.shape[1], x.shape[2]
            for _ in range(levels - 1):
                mid_h = (mid_h + 1) // 2
                mid_w = (mid_w + 1) // 2
            return out.astype(x.dtype), probs, (mid_h, mid_w)
        return out.astype(x.dtype)

    return model_fn


def percent_converter(bundle: PipelineBundle):
    """The bundle-aware sampling-progress-percent → sigma converter
    (timestep-window gates of multi-entry conditioning and scheduled
    ControlNet hints)."""
    param, shift = model_schedule_info(bundle)

    def p2s(percent: float) -> float:
        return smp.percent_to_sigma(percent, param, shift)

    return p2s


def reject_existing_guidance_patches(bundle, node_name: str) -> None:
    """Patch-time exclusivity shared by the guidance patch nodes (SLG,
    RescaleCFG, DualCFGGuider, PAG): their compositions are mutually
    ambiguous, so the SECOND patch node fails at graph-build time
    naming both nodes (guided_model re-checks at sample time as the
    backstop for hand-built bundles)."""
    existing = [
        name
        for name, active in (
            ("SkipLayerGuidanceSD3", getattr(bundle, "slg", None) is not None),
            (
                "RescaleCFG",
                getattr(bundle, "cfg_rescale", None) is not None,
            ),
            (
                "DualCFGGuider",
                getattr(bundle, "dual_cfg", None) is not None,
            ),
            (
                "PerturbedAttentionGuidance",
                getattr(bundle, "pag", None) is not None,
            ),
            (
                "SelfAttentionGuidance",
                getattr(bundle, "sag", None) is not None,
            ),
            (
                "PerpNegGuider",
                getattr(bundle, "perp_neg", None) is not None,
            ),
        )
        if active
    ]
    if existing:
        raise ValueError(
            f"{node_name} cannot combine with {existing[0]} on the "
            "same model"
        )


def guided_model(bundle: PipelineBundle, params, cfg_scale: float):
    """The guidance composition every sampling path shares: CFG (with
    multi-entry conditioning composition), plus skip-layer guidance
    when the bundle carries an SLGSpec (set by the
    SkipLayerGuidanceSD3 node)."""
    slg = getattr(bundle, "slg", None)
    dual = getattr(bundle, "dual_cfg", None)
    pag = getattr(bundle, "pag", None)
    sag = getattr(bundle, "sag", None)
    perp = getattr(bundle, "perp_neg", None)
    patches = [
        name
        for name, active in (
            ("DualCFGGuider", dual is not None),
            ("SkipLayerGuidance", slg is not None),
            ("RescaleCFG", bundle.cfg_rescale is not None),
            ("PerturbedAttentionGuidance", pag is not None),
            ("SelfAttentionGuidance", sag is not None),
            ("PerpNegGuider", perp is not None),
        )
        if active
    ]
    if len(patches) > 1:
        raise ValueError(
            f"guidance patches cannot combine on one model: {patches}"
        )
    base_fn = _make_model_fn(bundle, params)
    p2s = percent_converter(bundle)
    if dual is not None:
        return smp.dual_cfg_model(
            base_fn, cfg_scale, float(dual.cfg_cond2_negative),
            p2s=p2s, nested=bool(dual.nested),
        )
    if pag is not None:
        return smp.pag_cfg_model(
            base_fn,
            _make_model_fn(bundle, params, pag=True),
            cfg_scale,
            float(pag.scale),
            p2s=p2s,
        )
    if perp is not None:
        return smp.perp_neg_model(
            base_fn, cfg_scale, float(perp.neg_scale), p2s=p2s
        )
    if sag is not None:
        return smp.sag_cfg_model(
            base_fn,
            _make_model_fn(bundle, params, sag_capture=True),
            cfg_scale,
            float(sag.scale),
            float(sag.blur_sigma),
            p2s=p2s,
        )
    if bundle.cfg_rescale is not None:
        return smp.rescale_cfg_model(
            base_fn, cfg_scale, float(bundle.cfg_rescale), p2s=p2s
        )
    if not slg:
        return smp.cfg_model(base_fn, cfg_scale, p2s=p2s)
    return smp.slg_cfg_model(
        base_fn,
        _make_model_fn(bundle, params, skip_layers=slg.layers),
        cfg_scale,
        slg.scale,
        p2s(slg.start_percent),
        p2s(slg.end_percent),
        p2s=p2s,
    )


# --- generation ----------------------------------------------------------

@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "height", "width", "steps", "sampler", "scheduler",
        "batch", "cfg_scale",
    ),
)
def _txt2img_jit(
    bundle_static,  # hashable closure carrier (see txt2img)
    params,
    context_pos,
    context_neg,
    key,
    height: int,
    width: int,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg_scale: float,
    batch: int,
):
    bundle = bundle_static.value
    lh, lw = height // bundle.latent_scale, width // bundle.latent_scale
    param, shift = model_schedule_info(bundle)
    sigmas = smp.get_model_sigmas(param, scheduler, steps, flow_shift=shift)
    key, noise_key, anc_key = jax.random.split(key, 3)
    x = jax.random.normal(
        noise_key, (batch, lh, lw, bundle.latent_channels)
    ) * sigmas[0]
    model = guided_model(bundle, params, cfg_scale)
    latents = smp.sample(
        model, x, sigmas, (context_pos, context_neg), sampler, anc_key,
        flow=(param == "flow"),
    )
    return bundle.vae.apply(params["vae"], latents, method="decode")


def txt2img_flops(
    bundle: PipelineBundle,
    height: int = 512,
    width: int = 512,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg_scale: float = 7.0,
    batch: int = 1,
) -> float | None:
    """XLA-estimated FLOPs of ONE txt2img program (batch images) — the
    txt2img MFU numerator. Composed scan-free (N guided model evals +
    VAE decode; XLA cost analysis counts a lax.scan body once, see
    ops/upscale._jitted_for_flops). Text encoding is excluded (a
    one-time, sub-percent cost). Returns None when the backend exposes
    no cost analysis."""
    import logging

    from ..ops.costs import xla_flops as _xla_flops

    try:
        param, shift = model_schedule_info(bundle)
        sigmas = smp.get_model_sigmas(param, scheduler, steps, flow_shift=shift)
        evals = smp.model_evals_per_scan(sampler, int(sigmas.shape[0]) - 1)
        lh, lw = height // bundle.latent_scale, width // bundle.latent_scale
        z = jnp.zeros((batch, lh, lw, bundle.latent_channels))
        pos = encode_text_pooled(bundle, ["flops"] * batch)
        neg = encode_text_pooled(bundle, [""] * batch)
        params = bundle.params

        def eval_fn(params, z, pos, neg):
            model = guided_model(bundle, params, cfg_scale)
            return model(
                z, jnp.broadcast_to(sigmas[0], (z.shape[0],)), (pos, neg)
            )

        def dec_fn(params, z):
            return bundle.vae.apply(params["vae"], z, method="decode")

        ev = _xla_flops(eval_fn, params, z, pos, neg)
        dec = _xla_flops(dec_fn, params, z)
        if ev is None or dec is None:
            return None
        return evals * ev + dec
    except Exception:
        logging.getLogger("cdt.pipeline").warning(
            "txt2img FLOPs estimate failed", exc_info=True
        )
        return None


class _Static:
    """Wrap a python object as a hashable static jit argument."""

    def __init__(self, value):
        self.value = value

    def __hash__(self):
        return id(self.value)

    def __eq__(self, other):
        return isinstance(other, _Static) and other.value is self.value


def txt2img(
    bundle: PipelineBundle,
    prompt: str,
    negative_prompt: str = "",
    height: int = 512,
    width: int = 512,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg_scale: float = 7.0,
    seed: int = 0,
    batch: int = 1,
) -> jax.Array:
    """Full text→image generation; returns [batch, H, W, 3] in [0,1]."""
    # pooled conditioning rides along for SDXL-adm / Flux-vector models
    # (families without pooled conditioning ignore the field)
    pos = encode_text_pooled(bundle, [prompt] * batch)
    neg = encode_text_pooled(bundle, [negative_prompt] * batch)
    key = jax.random.key(seed)
    return _txt2img_jit(
        _Static(bundle),
        bundle.params,
        pos,
        neg,
        key,
        height,
        width,
        steps,
        sampler,
        scheduler,
        float(cfg_scale),
        batch,
    )


def _batch_noise(key, shape, fixed: bool):
    """Initial-noise policy (LatentBatchSeedBehavior): fixed=True
    repeats index 0's noise across the batch (ComfyUI seed_behavior
    'fixed' — every batch element renders the same trajectory);
    False is fresh noise per element ('random', the default)."""
    if not fixed:
        return jax.random.normal(key, shape)
    one = jax.random.normal(key, (1,) + tuple(shape[1:]))
    return jnp.broadcast_to(one, shape)


@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "steps", "sampler", "scheduler", "cfg_scale",
        "denoise", "batch_fixed_noise",
    ),
)
def _img2img_jit(
    bundle_static,
    params,
    latents,
    context_pos,
    context_neg,
    key,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg_scale: float,
    denoise: float,
    noise_mask=None,
    batch_fixed_noise: bool = False,
):
    bundle = bundle_static.value
    param, shift = model_schedule_info(bundle)
    sigmas = smp.get_model_sigmas(
        param, scheduler, steps, denoise=denoise, flow_shift=shift
    )
    noise_key, anc_key = jax.random.split(key)
    noise = _batch_noise(noise_key, latents.shape, batch_fixed_noise)
    x = smp.noise_latents(param, latents, noise, sigmas[0])
    return _masked_sample(
        bundle, params, cfg_scale, param, latents, noise, x, sigmas,
        (context_pos, context_neg), sampler, anc_key, noise_mask,
    )


def advanced_window_sigmas(
    parameterization: str,
    scheduler: str,
    steps: int,
    start_at_step: int,
    end_at_step: int,
    force_full_denoise: bool,
    shift: float,
) -> jnp.ndarray:
    """KSamplerAdvanced's schedule slice (ComfyUI common_ksampler with
    start_step/last_step/force_full_denoise): the full [steps+1] grid
    windowed to [start, end], with the final sigma forced to 0 when the
    caller wants full denoise despite stopping early."""
    full = smp.get_model_sigmas(
        parameterization, scheduler, int(steps), flow_shift=shift
    )
    start = min(max(int(start_at_step), 0), int(steps))
    end = min(max(int(end_at_step), start), int(steps))
    window = full[start:end + 1]
    if force_full_denoise and window.shape[0] > 1:
        window = window.at[-1].set(0.0)
    return window


@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "steps", "sampler", "scheduler", "cfg_scale",
        "start_at_step", "end_at_step", "add_noise", "force_full_denoise",
        "batch_fixed_noise",
    ),
)
def _advanced_jit(
    bundle_static,
    params,
    latents,
    context_pos,
    context_neg,
    key,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg_scale: float,
    start_at_step: int,
    end_at_step: int,
    add_noise: bool,
    force_full_denoise: bool,
    noise_mask=None,
    batch_fixed_noise: bool = False,
):
    bundle = bundle_static.value
    param, shift = model_schedule_info(bundle)
    window = advanced_window_sigmas(
        param, scheduler, steps, start_at_step, end_at_step,
        force_full_denoise, shift,
    )
    noise_key, anc_key = jax.random.split(key)
    # add_noise=False (the refine pass of a two-pass workflow): the
    # trajectory starts from the latents as-is AND the masked-region
    # pin uses ZERO noise — ComfyUI's disable_noise semantics; pinning
    # with a fresh Gaussian the trajectory never saw would corrupt the
    # preserved-region context at every step
    noise = (
        _batch_noise(noise_key, latents.shape, batch_fixed_noise)
        if add_noise
        else jnp.zeros_like(latents)
    )
    x = (
        smp.noise_latents(param, latents, noise, window[0])
        if add_noise
        else latents
    )
    if window.shape[0] < 2:
        # empty step window: nothing to sample — but the mask contract
        # (preserved region survives intact) still holds
        if noise_mask is not None:
            mask = jnp.clip(noise_mask.astype(jnp.float32), 0.0, 1.0)
            return x * mask + latents * (1.0 - mask)
        return x
    return _masked_sample(
        bundle, params, cfg_scale, param, latents, noise, x, window,
        (context_pos, context_neg), sampler, anc_key, noise_mask,
    )


def _masked_sample(
    bundle, params, cfg_scale, param, latents, noise, x, sigmas, cond,
    sampler, anc_key, noise_mask,
):
    """Guidance + optional masked-inpaint wrap + trajectory + mask
    composite — the sampling core shared by _img2img_jit and
    _advanced_jit (one place to maintain the inpaint pin semantics)."""
    model = guided_model(bundle, params, cfg_scale)
    if noise_mask is not None:
        # inpainting (reference-substrate SetLatentNoiseMask /
        # VAEEncodeForInpaint semantics)
        mask = jnp.clip(noise_mask.astype(jnp.float32), 0.0, 1.0)
        model = smp.masked_inpaint_model(model, param, latents, noise, mask)
    out = smp.sample(
        model, x, sigmas, cond, sampler, anc_key, flow=(param == "flow")
    )
    if noise_mask is not None:
        out = out * mask + latents * (1.0 - mask)
    return out


def img2img_latents_advanced(
    bundle: PipelineBundle,
    latents: jax.Array,
    context_pos: jax.Array,
    context_neg: jax.Array,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg_scale: float = 7.0,
    seed: int = 0,
    start_at_step: int = 0,
    end_at_step: int = 10000,
    add_noise: bool = True,
    force_full_denoise: bool = True,
    noise_mask: jax.Array | None = None,
    batch_fixed_noise: bool = False,
) -> jax.Array:
    """KSamplerAdvanced core: sample a [start_at_step, end_at_step]
    window of the full schedule, optionally without adding noise (the
    second pass of a two-pass workflow) and optionally leaving leftover
    noise (force_full_denoise=False)."""
    key = jax.random.key(seed)
    return _advanced_jit(
        _Static(bundle),
        bundle.params,
        latents,
        context_pos,
        context_neg,
        key,
        int(steps),
        sampler,
        scheduler,
        float(cfg_scale),
        int(start_at_step),
        int(end_at_step),
        bool(add_noise),
        bool(force_full_denoise),
        noise_mask=noise_mask,
        batch_fixed_noise=bool(batch_fixed_noise),
    )


@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "sigmas_t", "sampler", "cfg_scale", "add_noise",
        "batch_fixed_noise",
    ),
)
def _custom_sigmas_jit(
    bundle_static,
    params,
    latents,
    context_pos,
    context_neg,
    key,
    sigmas_t: tuple,
    sampler: str,
    cfg_scale: float,
    add_noise: bool,
    noise_mask=None,
    batch_fixed_noise: bool = False,
):
    """Sampling over an EXPLICIT sigma grid (the SamplerCustom /
    SamplerCustomAdvanced substrate: the schedule arrives as a SIGMAS
    value from a scheduler node instead of being derived from
    steps+scheduler here). sigmas_t is a static tuple so multistep
    samplers that precompute numpy coefficients from the grid (lms)
    keep working, exactly as they do when the grid is built inside the
    other jits. Returns (output, denoised_output): when the grid stops
    above sigma 0 (leftover-noise workflows), denoised is the model's
    x0 prediction at the final point — one extra guided eval — else it
    is the output itself (ComfyUI SamplerCustom's two-output contract).
    """
    bundle = bundle_static.value
    param, _shift = model_schedule_info(bundle)
    sigmas = jnp.asarray(sigmas_t, jnp.float32)
    noise_key, anc_key = jax.random.split(key)
    noise = (
        _batch_noise(noise_key, latents.shape, batch_fixed_noise)
        if add_noise
        else jnp.zeros_like(latents)
    )
    x = (
        smp.noise_latents(param, latents, noise, sigmas[0])
        if add_noise
        else latents
    )
    mask = None
    if noise_mask is not None:
        mask = jnp.clip(noise_mask.astype(jnp.float32), 0.0, 1.0)
    if len(sigmas_t) < 2:
        out = x if mask is None else x * mask + latents * (1.0 - mask)
        return out, out
    out = _masked_sample(
        bundle, params, cfg_scale, param, latents, noise, x, sigmas,
        (context_pos, context_neg), sampler, anc_key, noise_mask,
    )
    if float(sigmas_t[-1]) == 0.0:
        return out, out
    model = guided_model(bundle, params, cfg_scale)
    sig = jnp.broadcast_to(sigmas[-1], (out.shape[0],))
    eps = model(out, sig, (context_pos, context_neg))
    denoised = out - sigmas[-1] * eps
    if mask is not None:
        denoised = denoised * mask + latents * (1.0 - mask)
    return out, denoised


def sample_custom_sigmas(
    bundle: PipelineBundle,
    latents: jax.Array,
    context_pos,
    context_neg,
    sigmas,
    sampler: str = "euler",
    cfg_scale: float = 1.0,
    seed: int = 0,
    add_noise: bool = True,
    noise_mask: jax.Array | None = None,
    batch_fixed_noise: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """SamplerCustom/SamplerCustomAdvanced core: run `sampler` over an
    explicit sigma grid. Returns (output, denoised_output)."""
    import numpy as np

    sig_t = tuple(float(s) for s in np.asarray(sigmas, dtype=np.float32))
    key = jax.random.key(int(seed))
    return _custom_sigmas_jit(
        _Static(bundle),
        bundle.params,
        latents,
        context_pos,
        context_neg,
        key,
        sig_t,
        sampler,
        float(cfg_scale),
        bool(add_noise),
        noise_mask=noise_mask,
        batch_fixed_noise=bool(batch_fixed_noise),
    )


@partial(jax.jit, static_argnames=("bundle_static", "cfg_scale", "sigma"))
def _denoised_at_jit(bundle_static, params, x, pos, neg, cfg_scale, sigma):
    bundle = bundle_static.value
    model = guided_model(bundle, params, cfg_scale)
    sig = jnp.broadcast_to(jnp.float32(sigma), (x.shape[0],))
    eps = model(x, sig, (pos, neg))
    return x - sigma * eps


def denoised_prediction(
    bundle: PipelineBundle, x: jax.Array, pos, neg, cfg_scale: float,
    sigma: float,
) -> jax.Array:
    """The model's x0 prediction for latents sitting at `sigma` — one
    guided eval (denoised = x - sigma*eps, the uniform contract across
    eps/v/flow parameterizations). Backs the denoised_output of
    SamplerCustom(-Advanced) when a trajectory stops above sigma 0 and
    the sampling ran somewhere the prediction wasn't computed inline
    (the mesh fan-out path)."""
    return _denoised_at_jit(
        _Static(bundle), bundle.params, x, pos, neg, float(cfg_scale),
        float(sigma),
    )


def img2img_latents(
    bundle: PipelineBundle,
    latents: jax.Array,
    context_pos: jax.Array,
    context_neg: jax.Array,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg_scale: float = 7.0,
    denoise: float = 0.5,
    seed: int = 0,
    noise_mask: jax.Array | None = None,
    batch_fixed_noise: bool = False,
) -> jax.Array:
    """Latent-space img2img (the tile re-diffusion core of USDU):
    noise to sigma[denoise], sample back down. Returns latents.

    `noise_mask` ([B, lh, lw, 1], 1 = regenerate) enables inpainting:
    the unmasked region is pinned to the original latents re-noised to
    each step's sigma and restored exactly afterwards."""
    key = jax.random.key(seed)
    return _img2img_jit(
        _Static(bundle),
        bundle.params,
        latents,
        context_pos,
        context_neg,
        key,
        steps,
        sampler,
        scheduler,
        float(cfg_scale),
        float(denoise),
        noise_mask=noise_mask,
        batch_fixed_noise=bool(batch_fixed_noise),
    )
