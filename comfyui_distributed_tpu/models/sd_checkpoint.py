"""SD-checkpoint key mapping: original LDM/SD state dicts → flax trees.

The capability the reference gets for free from ComfyUI's
CheckpointLoaderSimple (reference upscale/tile_ops.py:168 imports
ComfyUI's loaders): a single-file SD checkpoint — the
`model.diffusion_model.* / first_stage_model.* / cond_stage_model.*`
layout — loads into this framework's UNet/VAE/TextEncoder flax trees.

Design: each architecture has an explicit, enumerable *key schedule* —
a deterministic function config → [(sd_key, flax_path, kind)] — so the
mapping is testable without any checkpoint present (tests invert the
schedule to synthesize a checkpoint and round-trip it). Transforms:

    conv    torch [O,I,kh,kw]  → flax [kh,kw,I,O]
    linear  torch [O,I]        → flax [I,O]
    proj    conv1x1 OR linear  → flax dense [I,O] (detected by ndim —
            SD1.5 spatial-transformer proj_in/out are 1x1 convs,
            SDXL's are linears)
    norm    weight/bias        → scale/bias (direct)
    direct  as-is (embeddings, position tables)
"""

from __future__ import annotations

import os
from typing import Any, Iterable

import numpy as np

# (sd_key_prefix, flax_path_prefix, kind); each entry expands to the
# weight (+bias where the kind carries one) parameter pair.
Entry = tuple[str, str, str]

_NORM = "norm"
_CONV = "conv"
_LINEAR = "linear"
_LINEAR_NOBIAS = "linear_nobias"
_PROJ = "proj"
_DIRECT = "direct"


# --- key schedules --------------------------------------------------------

def _resblock(sd: str, fx: str, has_skip: bool) -> list[Entry]:
    entries = [
        (f"{sd}.in_layers.0", f"{fx}/norm1/GroupNorm_0", _NORM),
        (f"{sd}.in_layers.2", f"{fx}/conv1", _CONV),
        (f"{sd}.emb_layers.1", f"{fx}/emb_proj", _LINEAR),
        (f"{sd}.out_layers.0", f"{fx}/norm2/GroupNorm_0", _NORM),
        (f"{sd}.out_layers.3", f"{fx}/conv2", _CONV),
    ]
    if has_skip:
        entries.append((f"{sd}.skip_connection", f"{fx}/skip", _CONV))
    return entries


def _spatial_transformer(sd: str, fx: str, depth: int) -> list[Entry]:
    entries = [
        (f"{sd}.norm", f"{fx}/norm/GroupNorm_0", _NORM),
        (f"{sd}.proj_in", f"{fx}/proj_in", _PROJ),
    ]
    for i in range(depth):
        tb, fb = f"{sd}.transformer_blocks.{i}", f"{fx}/block_{i}"
        entries += [
            (f"{tb}.norm1", f"{fb}/LayerNorm_0", _NORM),
            (f"{tb}.attn1.to_q", f"{fb}/attn1/to_q", _LINEAR_NOBIAS),
            (f"{tb}.attn1.to_k", f"{fb}/attn1/to_k", _LINEAR_NOBIAS),
            (f"{tb}.attn1.to_v", f"{fb}/attn1/to_v", _LINEAR_NOBIAS),
            (f"{tb}.attn1.to_out.0", f"{fb}/attn1/to_out", _LINEAR),
            (f"{tb}.norm2", f"{fb}/LayerNorm_1", _NORM),
            (f"{tb}.attn2.to_q", f"{fb}/attn2/to_q", _LINEAR_NOBIAS),
            (f"{tb}.attn2.to_k", f"{fb}/attn2/to_k", _LINEAR_NOBIAS),
            (f"{tb}.attn2.to_v", f"{fb}/attn2/to_v", _LINEAR_NOBIAS),
            (f"{tb}.attn2.to_out.0", f"{fb}/attn2/to_out", _LINEAR),
            (f"{tb}.norm3", f"{fb}/LayerNorm_2", _NORM),
            (f"{tb}.ff.net.0.proj", f"{fb}/ff/GEGLU_0/Dense_0", _LINEAR),
            (f"{tb}.ff.net.2", f"{fb}/ff/Dense_0", _LINEAR),
        ]
    entries.append((f"{sd}.proj_out", f"{fx}/proj_out", _PROJ))
    return entries


def unet_schedule(cfg) -> list[Entry]:
    """SD UNet (`model.diffusion_model.*`) → UNet flax tree.

    Reproduces the input/middle/output_blocks numbering of the original
    openai-guided-diffusion layout used by every SD1.x/SDXL checkpoint.
    """
    p = "model.diffusion_model"
    ch = cfg.model_channels
    entries: list[Entry] = [
        (f"{p}.time_embed.0", "time_embed_0", _LINEAR),
        (f"{p}.time_embed.2", "time_embed_2", _LINEAR),
    ]
    if cfg.adm_in_channels:
        entries += [
            (f"{p}.label_emb.0.0", "label_embed_0", _LINEAR),
            (f"{p}.label_emb.0.2", "label_embed_2", _LINEAR),
        ]
    entries.append((f"{p}.input_blocks.0.0", "input_conv", _CONV))

    # down path
    n = 1
    in_ch = ch
    for level, mult in enumerate(cfg.channel_mult):
        out_ch = ch * mult
        for i in range(cfg.num_res_blocks):
            sd = f"{p}.input_blocks.{n}.0"
            entries += _resblock(sd, f"down_{level}_res_{i}", in_ch != out_ch)
            if cfg.transformer_depth[level] > 0:
                entries += _spatial_transformer(
                    f"{p}.input_blocks.{n}.1",
                    f"down_{level}_attn_{i}",
                    cfg.transformer_depth[level],
                )
            in_ch = out_ch
            n += 1
        if level != len(cfg.channel_mult) - 1:
            entries.append((f"{p}.input_blocks.{n}.0.op", f"down_{level}_ds/op", _CONV))
            n += 1

    # middle
    mid_depth = max(cfg.transformer_depth[-1], 1)
    entries += _resblock(f"{p}.middle_block.0", "mid_res_0", False)
    entries += _spatial_transformer(f"{p}.middle_block.1", "mid_attn", mid_depth)
    entries += _resblock(f"{p}.middle_block.2", "mid_res_1", False)

    # up path — skip-concat means every ResBlock has a channel change,
    # hence a skip_connection, except where concat(in)+skip == out
    n = 0
    skip_chs = [ch]
    for level, mult in enumerate(cfg.channel_mult):
        for _ in range(cfg.num_res_blocks):
            skip_chs.append(ch * mult)
        if level != len(cfg.channel_mult) - 1:
            skip_chs.append(ch * mult)
    h_ch = ch * cfg.channel_mult[-1]
    for level, mult in reversed(list(enumerate(cfg.channel_mult))):
        out_ch = ch * mult
        for i in range(cfg.num_res_blocks + 1):
            concat_ch = h_ch + skip_chs.pop()
            sd = f"{p}.output_blocks.{n}.0"
            entries += _resblock(sd, f"up_{level}_res_{i}", concat_ch != out_ch)
            has_attn = cfg.transformer_depth[level] > 0
            if has_attn:
                entries += _spatial_transformer(
                    f"{p}.output_blocks.{n}.1",
                    f"up_{level}_attn_{i}",
                    cfg.transformer_depth[level],
                )
            if level != 0 and i == cfg.num_res_blocks:
                idx = 2 if has_attn else 1
                entries.append(
                    (f"{p}.output_blocks.{n}.{idx}.conv", f"up_{level}_us/conv", _CONV)
                )
            h_ch = out_ch
            n += 1

    entries += [
        (f"{p}.out.0", "out_norm/GroupNorm_0", _NORM),
        (f"{p}.out.2", "out_conv", _CONV),
    ]
    return entries


def _vae_resblock(sd: str, fx: str, has_skip: bool) -> list[Entry]:
    entries = [
        (f"{sd}.norm1", f"{fx}/norm1/GroupNorm_0", _NORM),
        (f"{sd}.conv1", f"{fx}/conv1", _CONV),
        (f"{sd}.norm2", f"{fx}/norm2/GroupNorm_0", _NORM),
        (f"{sd}.conv2", f"{fx}/conv2", _CONV),
    ]
    if has_skip:
        entries.append((f"{sd}.nin_shortcut", f"{fx}/skip", _CONV))
    return entries


def _vae_mid(sd: str, fx: str) -> list[Entry]:
    return (
        _vae_resblock(f"{sd}.block_1", f"{fx}/mid_res_0", False)
        + [
            (f"{sd}.attn_1.norm", f"{fx}/mid_attn/norm/GroupNorm_0", _NORM),
            (f"{sd}.attn_1.q", f"{fx}/mid_attn/q", _PROJ),
            (f"{sd}.attn_1.k", f"{fx}/mid_attn/k", _PROJ),
            (f"{sd}.attn_1.v", f"{fx}/mid_attn/v", _PROJ),
            (f"{sd}.attn_1.proj_out", f"{fx}/mid_attn/proj", _PROJ),
        ]
        + _vae_resblock(f"{sd}.block_2", f"{fx}/mid_res_1", False)
    )


def vae_schedule(cfg, prefix: str = "first_stage_model") -> list[Entry]:
    """SD AutoencoderKL (`first_stage_model.*`) → VAE flax tree.

    `prefix=""` handles standalone AE files (Flux ae.safetensors: bare
    `encoder.*`/`decoder.*` keys); `use_quant_conv=False` configs
    (Flux layout) skip the 1x1 quant convs."""
    p = f"{prefix}." if prefix else ""
    bc = cfg.base_channels
    entries: list[Entry] = [(f"{p}encoder.conv_in", "encoder/conv_in", _CONV)]

    in_ch = bc
    for level, mult in enumerate(cfg.channel_mult):
        out_ch = bc * mult
        for i in range(cfg.num_res_blocks):
            entries += _vae_resblock(
                f"{p}encoder.down.{level}.block.{i}",
                f"encoder/down_{level}_res_{i}",
                in_ch != out_ch,
            )
            in_ch = out_ch
        if level != len(cfg.channel_mult) - 1:
            entries.append(
                (
                    f"{p}encoder.down.{level}.downsample.conv",
                    f"encoder/down_{level}_ds",
                    _CONV,
                )
            )
    entries += _vae_mid(f"{p}encoder.mid", "encoder")
    entries += [
        (f"{p}encoder.norm_out", "encoder/norm_out/GroupNorm_0", _NORM),
        (f"{p}encoder.conv_out", "encoder/conv_out", _CONV),
    ]
    if getattr(cfg, "use_quant_conv", True):
        entries += [
            (f"{p}quant_conv", "quant_conv", _CONV),
            (f"{p}post_quant_conv", "post_quant_conv", _CONV),
        ]
    entries.append((f"{p}decoder.conv_in", "decoder/conv_in", _CONV))
    entries += _vae_mid(f"{p}decoder.mid", "decoder")
    top_ch = bc * cfg.channel_mult[-1]
    in_ch = top_ch
    for level, mult in reversed(list(enumerate(cfg.channel_mult))):
        out_ch = bc * mult
        for i in range(cfg.num_res_blocks + 1):
            entries += _vae_resblock(
                f"{p}decoder.up.{level}.block.{i}",
                f"decoder/up_{level}_res_{i}",
                in_ch != out_ch,
            )
            in_ch = out_ch
        if level != 0:
            entries.append(
                (
                    f"{p}decoder.up.{level}.upsample.conv",
                    f"decoder/up_{level}_us",
                    _CONV,
                )
            )
    entries += [
        (f"{p}decoder.norm_out", "decoder/norm_out/GroupNorm_0", _NORM),
        (f"{p}decoder.conv_out", "decoder/conv_out", _CONV),
    ]
    return entries


def text_encoder_schedule(
    cfg,
    prefix: str = "cond_stage_model.transformer.text_model",
    projection_layout: str = "bare",
) -> list[Entry]:
    """HF-layout CLIP text transformer → TextEncoder flax tree.

    `prefix` is `cond_stage_model.transformer.text_model` in SD1.x
    single-file checkpoints and `conditioner.embedders.0.transformer.
    text_model` for SDXL's CLIP-L half. `projection_layout="linear"`
    reads text_projection as an nn.Linear (.weight, transposed) — the
    HF CLIPTextModelWithProjection packing SD3 files use — instead of
    the bare parameter."""
    p = prefix
    entries: list[Entry] = [
        (f"{p}.embeddings.token_embedding", "token_embedding", "embedding"),
        (f"{p}.embeddings.position_embedding", "position_embedding", "position"),
    ]
    for i in range(cfg.layers):
        sd, fx = f"{p}.encoder.layers.{i}", f"block_{i}"
        entries += [
            (f"{sd}.layer_norm1", f"{fx}/LayerNorm_0", _NORM),
            (f"{sd}.self_attn.q_proj", f"{fx}/q", _LINEAR),
            (f"{sd}.self_attn.k_proj", f"{fx}/k", _LINEAR),
            (f"{sd}.self_attn.v_proj", f"{fx}/v", _LINEAR),
            (f"{sd}.self_attn.out_proj", f"{fx}/proj", _LINEAR),
            (f"{sd}.layer_norm2", f"{fx}/LayerNorm_1", _NORM),
            (f"{sd}.mlp.fc1", f"{fx}/fc1", _LINEAR),
            (f"{sd}.mlp.fc2", f"{fx}/fc2", _LINEAR),
        ]
    entries.append((f"{p}.final_layer_norm", "final_ln", _NORM))
    if cfg.proj_dim is not None:
        if projection_layout == "linear":
            # HF CLIPTextModelWithProjection: text_projection is a
            # SIBLING of text_model, not nested inside it (for a
            # standalone file with bare `text_model.*` keys the
            # sibling sits at the root)
            if p.endswith(".text_model"):
                base = p[: -len(".text_model")]
            elif p == "text_model":
                base = ""
            else:
                base = p
            key = f"{base}.text_projection" if base else "text_projection"
            entries.append((key, "text_projection", "bare_linear_w"))
        else:
            entries.append(
                (f"{p}.text_projection", "text_projection", "param_bare")
            )
    return entries


def open_clip_schedule(
    cfg, prefix: str = "conditioner.embedders.1.model"
) -> list[Entry]:
    """OpenCLIP-layout text transformer (SDXL's bigG half) →
    TextEncoder flax tree. Differs from the HF layout: bare-parameter
    positional embedding / text_projection, fused qkv in_proj, and
    resblock naming."""
    p = prefix
    entries: list[Entry] = [
        (f"{p}.token_embedding", "token_embedding", "embedding"),
        (f"{p}.positional_embedding", "position_embedding", "param_bare"),
    ]
    for i in range(cfg.layers):
        sd, fx = f"{p}.transformer.resblocks.{i}", f"block_{i}"
        entries += [
            (f"{sd}.ln_1", f"{fx}/LayerNorm_0", _NORM),
            (f"{sd}.attn.in_proj", f"{fx}", "fused_qkv"),
            (f"{sd}.attn.out_proj", f"{fx}/proj", _LINEAR),
            (f"{sd}.ln_2", f"{fx}/LayerNorm_1", _NORM),
            (f"{sd}.mlp.c_fc", f"{fx}/fc1", _LINEAR),
            (f"{sd}.mlp.c_proj", f"{fx}/fc2", _LINEAR),
        ]
    entries.append((f"{p}.ln_final", "final_ln", _NORM))
    if cfg.proj_dim is not None:
        entries.append((f"{p}.text_projection", "text_projection", "param_bare"))
    return entries


def wan_schedule(cfg, prefix: str = "") -> list[Entry]:
    """WAN 2.x video DiT state dict (`blocks.N.*`, `patch_embedding`,
    `time_embedding`, `time_projection`, `text_embedding`, `head.*`) →
    VideoDiT flax tree (models/dit.py). The capability the reference
    gets from ComfyUI's WAN loader (reference workflows/distributed-wan*.json
    rely on CheckpointLoaderSimple/UNETLoader).

    `prefix` handles ComfyUI-repacked checkpoints that nest the DiT
    under `model.diffusion_model.` — pass it with the trailing dot.
    """
    p = prefix
    pf, ph, pw = cfg.patch_size
    conv3d = f"conv3d:{pf}:{ph}:{pw}:{cfg.in_channels}"
    entries: list[Entry] = [
        (f"{p}patch_embedding", "patch_embed", conv3d),
        (f"{p}text_embedding.0", "text_embed_0", _LINEAR),
        (f"{p}text_embedding.2", "text_embed_2", _LINEAR),
        (f"{p}time_embedding.0", "time_embed_0", _LINEAR),
        (f"{p}time_embedding.2", "time_embed_2", _LINEAR),
        (f"{p}time_projection.1", "time_proj", _LINEAR),
    ]
    for i in range(cfg.depth):
        sd, fx = f"{p}blocks.{i}", f"block_{i}"
        for attn in ("self_attn", "cross_attn"):
            for leaf in ("q", "k", "v", "o"):
                entries.append((f"{sd}.{attn}.{leaf}", f"{fx}/{attn}_{leaf}", _LINEAR))
            for leaf in ("norm_q", "norm_k"):
                entries.append((f"{sd}.{attn}.{leaf}", f"{fx}/{attn}_{leaf}", "rms"))
        if getattr(cfg, "i2v", False):
            entries += [
                (f"{sd}.cross_attn.k_img", f"{fx}/cross_attn_k_img", _LINEAR),
                (f"{sd}.cross_attn.v_img", f"{fx}/cross_attn_v_img", _LINEAR),
                (f"{sd}.cross_attn.norm_k_img", f"{fx}/cross_attn_norm_k_img", "rms"),
            ]
        entries += [
            (f"{sd}.norm3", f"{fx}/norm3", _NORM),
            (f"{sd}.ffn.0", f"{fx}/ffn_0", _LINEAR),
            (f"{sd}.ffn.2", f"{fx}/ffn_2", _LINEAR),
            (f"{sd}.modulation", f"{fx}/modulation", "param_bare"),
        ]
    if getattr(cfg, "i2v", False):
        entries += [
            (f"{p}img_emb.proj.0", "img_emb_norm_in", _NORM),
            (f"{p}img_emb.proj.1", "img_emb_fc1", _LINEAR),
            (f"{p}img_emb.proj.3", "img_emb_fc2", _LINEAR),
            (f"{p}img_emb.proj.4", "img_emb_norm_out", _NORM),
        ]
    entries += [
        (f"{p}head.head", "head", _LINEAR),
        (f"{p}head.modulation", "head_modulation", "param_bare"),
    ]
    return entries


def _wan_vae_resblock(sd: str, fx: str, in_dim: int, out_dim: int) -> list[Entry]:
    entries = [
        (f"{sd}.residual.0", f"{fx}/residual_0", "gamma3"),
        (f"{sd}.residual.2", f"{fx}/residual_2/conv", "causal3"),
        (f"{sd}.residual.3", f"{fx}/residual_3", "gamma3"),
        (f"{sd}.residual.6", f"{fx}/residual_6/conv", "causal3"),
    ]
    if in_dim != out_dim:
        entries.append((f"{sd}.shortcut", f"{fx}/shortcut/conv", "causal3"))
    return entries


def _wan_vae_attn(sd: str, fx: str) -> list[Entry]:
    return [
        (f"{sd}.norm", f"{fx}/norm", "gamma2"),
        (f"{sd}.to_qkv", f"{fx}/to_qkv", _CONV),
        (f"{sd}.proj", f"{fx}/proj", _CONV),
    ]


def wan_vae_schedule(cfg) -> list[Entry]:
    """Official Wan2.1 VAE state dict → VideoVAE flax tree
    (models/video_vae.py). Mirrors the original's flattened Sequential
    indices: `encoder.downsamples.N` / `decoder.upsamples.N` run over
    resblocks and resamples in construction order; RMS gammas are bare
    `.gamma` params with trailing singleton dims."""
    entries: list[Entry] = []

    # --- encoder ---
    enc_dims = [cfg.base_dim * m for m in (1,) + tuple(cfg.dim_mult)]
    entries.append(("encoder.conv1", "encoder/conv1/conv", "causal3"))
    idx = 0
    in_dim = enc_dims[0]
    for level in range(len(cfg.dim_mult)):
        out_dim = enc_dims[level + 1]
        for _ in range(cfg.num_res_blocks):
            entries += _wan_vae_resblock(
                f"encoder.downsamples.{idx}", f"encoder/down_{idx}",
                in_dim, out_dim,
            )
            in_dim = out_dim
            idx += 1
        if level != len(cfg.dim_mult) - 1:
            sd, fx = f"encoder.downsamples.{idx}", f"encoder/down_{idx}"
            entries.append((f"{sd}.resample.1", f"{fx}/resample_1", _CONV))
            if cfg.temporal_down[level]:
                entries.append((f"{sd}.time_conv", f"{fx}/time_conv/conv", "causal3"))
            idx += 1
    top = enc_dims[-1]
    entries += _wan_vae_resblock("encoder.middle.0", "encoder/middle_0", top, top)
    entries += _wan_vae_attn("encoder.middle.1", "encoder/middle_1")
    entries += _wan_vae_resblock("encoder.middle.2", "encoder/middle_2", top, top)
    entries += [
        ("encoder.head.0", "encoder/head_0", "gamma3"),
        ("encoder.head.2", "encoder/head_2/conv", "causal3"),
        ("conv1", "conv1_q/conv", "causal3"),
        ("conv2", "conv2_q/conv", "causal3"),
    ]

    # --- decoder ---
    rev = tuple(reversed(cfg.dim_mult))
    dec_dims = [cfg.base_dim * m for m in (rev[0],) + rev]
    temporal_up = tuple(reversed(cfg.temporal_down))
    entries.append(("decoder.conv1", "decoder/conv1/conv", "causal3"))
    top = dec_dims[0]
    entries += _wan_vae_resblock("decoder.middle.0", "decoder/middle_0", top, top)
    entries += _wan_vae_attn("decoder.middle.1", "decoder/middle_1")
    entries += _wan_vae_resblock("decoder.middle.2", "decoder/middle_2", top, top)
    idx = 0
    in_dim = dec_dims[0]
    for level in range(len(cfg.dim_mult)):
        out_dim = dec_dims[level + 1]
        for _ in range(cfg.num_res_blocks + 1):
            entries += _wan_vae_resblock(
                f"decoder.upsamples.{idx}", f"decoder/up_{idx}",
                in_dim, out_dim,
            )
            in_dim = out_dim
            idx += 1
        if level != len(cfg.dim_mult) - 1:
            sd, fx = f"decoder.upsamples.{idx}", f"decoder/up_{idx}"
            entries.append((f"{sd}.resample.1", f"{fx}/resample_1", _CONV))
            if temporal_up[level]:
                entries.append((f"{sd}.time_conv", f"{fx}/time_conv/conv", "causal3"))
            idx += 1
            in_dim = out_dim // 2  # upsample halves channels
    entries += [
        ("decoder.head.0", "decoder/head_0", "gamma3"),
        ("decoder.head.2", "decoder/head_2/conv", "causal3"),
    ]
    return entries


def load_clip_te_weights(
    state_dict: dict[str, np.ndarray],
    cfg,
    template: Any,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Standalone CLIP text-encoder file → TextEncoder flax tree.

    The published separate-file releases (clip_l.safetensors /
    clip_g.safetensors, the files ComfyUI's CLIPLoader /
    DualCLIPLoader / TripleCLIPLoader consume) ship the HF layout with
    bare `text_model.*` keys and — for with-projection towers — a
    root-level sibling `text_projection.weight` (nn.Linear packing) or
    a bare `text_projection` parameter; both are detected."""
    if not any(k.startswith("text_model.") for k in state_dict):
        raise ValueError(
            "unrecognized standalone CLIP layout: expected bare "
            "text_model.* keys (HF packing); got e.g. "
            + ", ".join(sorted(state_dict)[:3])
        )
    entries = text_encoder_schedule(
        cfg, prefix="text_model", projection_layout="linear"
    )
    if (
        cfg.proj_dim is not None
        and "text_projection.weight" not in state_dict
        and "text_projection" in state_dict
    ):
        # rarer packing: a root-level bare projection parameter
        entries = [
            ("text_projection", fx, "param_bare")
            if fx == "text_projection"
            else (sd, fx, how)
            for sd, fx, how in entries
        ]
    params, problems = _merge_into_template(
        state_dict, entries, template, "te"
    )
    if problems and strict:
        raise ValueError(
            f"CLIP text-encoder checkpoint mapping failed "
            f"({len(problems)} problems): " + "; ".join(problems[:12])
        )
    return params, problems


def load_vae_weights(
    state_dict: dict[str, np.ndarray],
    cfg,
    template: Any,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Map a standalone image-VAE state dict onto the VAE tree. Both
    published layouts sniff automatically: bare `encoder./decoder.`
    keys (standalone files — vae-ft-mse, Flux ae.safetensors) and a
    full checkpoint's `first_stage_model.*`."""
    prefix = (
        "first_stage_model"
        if any(k.startswith("first_stage_model.") for k in state_dict)
        else ""
    )
    params, problems = _merge_into_template(
        state_dict, vae_schedule(cfg, prefix=prefix), template, "vae"
    )
    if problems and strict:
        raise ValueError(
            f"VAE checkpoint mapping failed ({len(problems)} "
            "problems): " + "; ".join(problems[:12])
        )
    return params, problems


def load_wan_vae_weights(
    state_dict: dict[str, np.ndarray],
    cfg,
    template: Any,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Map an official Wan VAE state dict onto the VideoVAE tree."""
    params, problems = _merge_into_template(
        state_dict, wan_vae_schedule(cfg), template, "video_vae"
    )
    if problems and strict:
        raise ValueError(
            f"WAN VAE checkpoint mapping failed ({len(problems)} "
            "problems): " + "; ".join(problems[:12])
        )
    return params, problems


def clip_vision_schedule(cfg, prefix: str = "vision_model") -> list[Entry]:
    """HF CLIPVisionModel state dict → ClipVisionEncoder flax tree
    (models/clip_vision.py). Penultimate configs skip the last block
    and post LN (those checkpoint keys are simply unused). Note the
    genuine HF key spelling `pre_layrnorm`."""
    p = prefix
    entries: list[Entry] = [
        (f"{p}.embeddings.class_embedding", "class_embedding", "param_bare"),
        (f"{p}.embeddings.patch_embedding", "patch_embedding", "conv_nobias"),
        (f"{p}.embeddings.position_embedding", "position_embedding", "position"),
        (f"{p}.pre_layrnorm", "pre_ln", _NORM),
    ]
    depth = cfg.layers - 1 if cfg.penultimate_hidden else cfg.layers
    for i in range(depth):
        sd, fx = f"{p}.encoder.layers.{i}", f"block_{i}"
        entries += [
            (f"{sd}.layer_norm1", f"{fx}/LayerNorm_0", _NORM),
            (f"{sd}.self_attn.q_proj", f"{fx}/q", _LINEAR),
            (f"{sd}.self_attn.k_proj", f"{fx}/k", _LINEAR),
            (f"{sd}.self_attn.v_proj", f"{fx}/v", _LINEAR),
            (f"{sd}.self_attn.out_proj", f"{fx}/proj", _LINEAR),
            (f"{sd}.layer_norm2", f"{fx}/LayerNorm_1", _NORM),
            (f"{sd}.mlp.fc1", f"{fx}/fc1", _LINEAR),
            (f"{sd}.mlp.fc2", f"{fx}/fc2", _LINEAR),
        ]
    if not cfg.penultimate_hidden:
        entries.append((f"{p}.post_layernorm", "post_ln", _NORM))
    return entries


def load_clip_vision_weights(
    state_dict: dict[str, np.ndarray],
    cfg,
    template: Any,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Map an HF CLIPVisionModel state dict onto the ClipVisionEncoder
    param tree."""
    params, problems = _merge_into_template(
        state_dict, clip_vision_schedule(cfg), template, "clip_vision"
    )
    if problems and strict:
        raise ValueError(
            f"CLIP-vision checkpoint mapping failed ({len(problems)} "
            "problems): " + "; ".join(problems[:12])
        )
    return params, problems


def t5_encoder_schedule(cfg, prefix: str = "") -> list[Entry]:
    """UMT5 encoder state dict (HF layout: `shared`, `encoder.block.N.
    layer.{0,1}.*`, per-layer relative_attention_bias) → T5Encoder flax
    tree (models/t5_encoder.py). The text-encoder checkpoint the
    reference's WAN workflows load through ComfyUI's CLIPLoader."""
    p = prefix
    per_layer_bias = getattr(cfg, "per_layer_rel_bias", True)
    entries: list[Entry] = [
        (f"{p}shared", "token_embed", "embedding"),
    ]
    if not per_layer_bias:
        # classic T5 v1.1 (the Flux text encoder): layer 0's table is
        # shared by the whole stack → one top-level flax param
        entries.append(
            (
                f"{p}encoder.block.0.layer.0.SelfAttention.relative_attention_bias",
                "rel_bias",
                "embedding",
            )
        )
    for i in range(cfg.layers):
        sd = f"{p}encoder.block.{i}"
        fx = f"block_{i}"
        entries += [
            (f"{sd}.layer.0.layer_norm", f"{fx}/attn_norm", "rms"),
            (f"{sd}.layer.0.SelfAttention.q", f"{fx}/q", _LINEAR_NOBIAS),
            (f"{sd}.layer.0.SelfAttention.k", f"{fx}/k", _LINEAR_NOBIAS),
            (f"{sd}.layer.0.SelfAttention.v", f"{fx}/v", _LINEAR_NOBIAS),
            (f"{sd}.layer.0.SelfAttention.o", f"{fx}/o", _LINEAR_NOBIAS),
        ]
        if per_layer_bias:
            entries.append(
                (
                    f"{sd}.layer.0.SelfAttention.relative_attention_bias",
                    f"{fx}/rel_bias",
                    "embedding",
                )
            )
        entries += [
            (f"{sd}.layer.1.layer_norm", f"{fx}/ffn_norm", "rms"),
            (f"{sd}.layer.1.DenseReluDense.wi_0", f"{fx}/wi_0", _LINEAR_NOBIAS),
            (f"{sd}.layer.1.DenseReluDense.wi_1", f"{fx}/wi_1", _LINEAR_NOBIAS),
            (f"{sd}.layer.1.DenseReluDense.wo", f"{fx}/wo", _LINEAR_NOBIAS),
        ]
    entries.append((f"{p}encoder.final_layer_norm", "final_norm", "rms"))
    return entries


def flux_schedule(cfg, prefix: str = "") -> list[Entry]:
    """Flux state dict (`double_blocks.N.*`, `single_blocks.N.*`,
    `img_in`, `txt_in`, `time_in`, `vector_in`, `guidance_in`,
    `final_layer.*`) → MMDiT flax tree (models/mmdit.py). The
    capability the reference gets from ComfyUI's UNETLoader for Flux
    checkpoints.

    `prefix` handles repacked single-file checkpoints that nest the
    transformer under `model.diffusion_model.` (pass with the trailing
    dot); published flux1-*.safetensors use bare keys."""
    p = prefix
    entries: list[Entry] = [
        (f"{p}img_in", "img_in", _LINEAR),
        (f"{p}txt_in", "txt_in", _LINEAR),
        (f"{p}time_in.in_layer", "time_in/in_layer", _LINEAR),
        (f"{p}time_in.out_layer", "time_in/out_layer", _LINEAR),
        (f"{p}vector_in.in_layer", "vector_in/in_layer", _LINEAR),
        (f"{p}vector_in.out_layer", "vector_in/out_layer", _LINEAR),
    ]
    if cfg.guidance_embed:
        entries += [
            (f"{p}guidance_in.in_layer", "guidance_in/in_layer", _LINEAR),
            (f"{p}guidance_in.out_layer", "guidance_in/out_layer", _LINEAR),
        ]
    for i in range(cfg.double_depth):
        sd, fx = f"{p}double_blocks.{i}", f"double_blocks_{i}"
        for s in ("img", "txt"):
            entries += [
                (f"{sd}.{s}_mod.lin", f"{fx}/{s}_mod_lin", _LINEAR),
                (f"{sd}.{s}_attn.qkv", f"{fx}/{s}_attn_qkv", _LINEAR),
                (
                    f"{sd}.{s}_attn.norm.query_norm",
                    f"{fx}/{s}_attn_norm_q",
                    "rms_scale",
                ),
                (
                    f"{sd}.{s}_attn.norm.key_norm",
                    f"{fx}/{s}_attn_norm_k",
                    "rms_scale",
                ),
                (f"{sd}.{s}_attn.proj", f"{fx}/{s}_attn_proj", _LINEAR),
                (f"{sd}.{s}_mlp.0", f"{fx}/{s}_mlp_0", _LINEAR),
                (f"{sd}.{s}_mlp.2", f"{fx}/{s}_mlp_2", _LINEAR),
            ]
    for i in range(cfg.single_depth):
        sd, fx = f"{p}single_blocks.{i}", f"single_blocks_{i}"
        entries += [
            (f"{sd}.modulation.lin", f"{fx}/modulation_lin", _LINEAR),
            (f"{sd}.linear1", f"{fx}/linear1", _LINEAR),
            (f"{sd}.linear2", f"{fx}/linear2", _LINEAR),
            (f"{sd}.norm.query_norm", f"{fx}/norm_q", "rms_scale"),
            (f"{sd}.norm.key_norm", f"{fx}/norm_k", "rms_scale"),
        ]
    entries += [
        (f"{p}final_layer.adaLN_modulation.1", "final_layer_adaLN_lin", _LINEAR),
        (f"{p}final_layer.linear", "final_layer_linear", _LINEAR),
    ]
    return entries


def sd3_schedule(cfg, prefix: str = "model.diffusion_model.") -> list[Entry]:
    """SD3/SD3.5 MMDiT state dict (`joint_blocks.N.{context_block,
    x_block}.*`, `x_embedder.proj`, `pos_embed`, `context_embedder`,
    `t_embedder`/`y_embedder` MLPs, `final_layer.*`) → SD3MMDiT flax
    tree (models/sd3.py). The final block's context side is pre_only:
    qkv + a 2-way adaLN, no proj/MLP. SD3.5 configs add per-head RMS
    ln_q/ln_k."""
    p = prefix
    conv2d = f"conv2d:{cfg.patch_size}:{cfg.in_channels}"
    entries: list[Entry] = [
        (f"{p}x_embedder.proj", "x_embedder_proj", conv2d),
        (f"{p}pos_embed", "pos_embed", "param_bare"),
        (f"{p}context_embedder", "context_embedder", _LINEAR),
        (f"{p}t_embedder.mlp.0", "t_embedder_mlp_0", _LINEAR),
        (f"{p}t_embedder.mlp.2", "t_embedder_mlp_2", _LINEAR),
        (f"{p}y_embedder.mlp.0", "y_embedder_mlp_0", _LINEAR),
        (f"{p}y_embedder.mlp.2", "y_embedder_mlp_2", _LINEAR),
    ]
    for i in range(cfg.depth):
        sd, fx = f"{p}joint_blocks.{i}", f"joint_blocks_{i}"
        pre_only = i == cfg.depth - 1
        for tb, fb in (("context_block", "ctx"), ("x_block", "x")):
            entries.append(
                (f"{sd}.{tb}.attn.qkv", f"{fx}/{fb}_attn_qkv", _LINEAR)
            )
            if cfg.qk_norm:
                entries += [
                    (f"{sd}.{tb}.attn.ln_q", f"{fx}/{fb}_attn_ln_q", "rms"),
                    (f"{sd}.{tb}.attn.ln_k", f"{fx}/{fb}_attn_ln_k", "rms"),
                ]
            entries.append(
                (
                    f"{sd}.{tb}.adaLN_modulation.1",
                    f"{fx}/{fb}_mod_lin",
                    _LINEAR,
                )
            )
            if tb == "context_block" and pre_only:
                continue
            entries += [
                (f"{sd}.{tb}.attn.proj", f"{fx}/{fb}_attn_proj", _LINEAR),
                (f"{sd}.{tb}.mlp.fc1", f"{fx}/{fb}_mlp_fc1", _LINEAR),
                (f"{sd}.{tb}.mlp.fc2", f"{fx}/{fb}_mlp_fc2", _LINEAR),
            ]
            # MMDiT-X (SD3.5-medium): the first dual_attn_blocks
            # x_blocks carry a second image-only attention (attn2.*;
            # the block's adaLN linear above is 9-way instead of 6-way
            # — same key, wider tensor)
            if tb == "x_block" and i < getattr(cfg, "dual_attn_blocks", 0):
                entries += [
                    (f"{sd}.x_block.attn2.qkv", f"{fx}/x2_attn_qkv", _LINEAR),
                    (f"{sd}.x_block.attn2.proj", f"{fx}/x2_attn_proj", _LINEAR),
                ]
                if cfg.qk_norm:
                    entries += [
                        (f"{sd}.x_block.attn2.ln_q", f"{fx}/x2_attn_ln_q", "rms"),
                        (f"{sd}.x_block.attn2.ln_k", f"{fx}/x2_attn_ln_k", "rms"),
                    ]
    entries += [
        (
            f"{p}final_layer.adaLN_modulation.1",
            "final_layer_adaLN_mod_lin",
            _LINEAR,
        ),
        (f"{p}final_layer.linear", "final_layer_linear", _LINEAR),
    ]
    return entries


def load_sd3_weights(
    state_dict: dict[str, np.ndarray],
    unet_cfg,
    vae_cfg,
    te_cfg,
    templates: dict[str, Any],
    strict: bool = True,
    te2_cfg: Any = None,
    te3_cfg: Any = None,
) -> tuple[dict[str, Any], list[str]]:
    """SD3/SD3.5 checkpoint(s) → {'unet','vae','te','te2','te3'}.

    Single-file layout: `model.diffusion_model.*` +
    `first_stage_model.*` and — in the `*_incl_clips*` variants —
    `text_encoders.{clip_l,clip_g,t5xxl}.transformer.*` (HF packing:
    text_projection is an nn.Linear). Maps whichever parts are present
    and leaves the rest at init."""
    parts: dict[str, list[Entry]] = {}
    if any(
        k.startswith("model.diffusion_model.joint_blocks.") for k in state_dict
    ):
        parts["unet"] = sd3_schedule(unet_cfg)
    elif any(k.startswith("joint_blocks.") for k in state_dict):
        parts["unet"] = sd3_schedule(unet_cfg, prefix="")
    if any(k.startswith("first_stage_model.") for k in state_dict):
        parts["vae"] = vae_schedule(vae_cfg)
    if te_cfg is not None and any(
        k.startswith("text_encoders.clip_l.") for k in state_dict
    ):
        parts["te"] = text_encoder_schedule(
            te_cfg, prefix="text_encoders.clip_l.transformer.text_model",
            projection_layout="linear",
        )
    if te2_cfg is not None and any(
        k.startswith("text_encoders.clip_g.") for k in state_dict
    ):
        parts["te2"] = text_encoder_schedule(
            te2_cfg, prefix="text_encoders.clip_g.transformer.text_model",
            projection_layout="linear",
        )
    if te3_cfg is not None and any(
        k.startswith("text_encoders.t5xxl.") for k in state_dict
    ):
        parts["te3"] = t5_encoder_schedule(
            te3_cfg, prefix="text_encoders.t5xxl.transformer."
        )

    result = dict(templates)
    problems: list[str] = []
    for part, entries in parts.items():
        result[part], part_problems = _merge_into_template(
            state_dict, entries, templates[part], part
        )
        problems += part_problems
    if not parts:
        problems.append("sd3: no mappable part found in checkpoint")
    if problems and strict:
        raise ValueError(
            f"sd3 checkpoint mapping failed ({len(problems)} problems): "
            + "; ".join(problems[:12])
        )
    return result, problems


def load_flux_weights(
    state_dict: dict[str, np.ndarray],
    unet_cfg,
    vae_cfg,
    te_cfg,
    templates: dict[str, Any],
    strict: bool = True,
    te2_cfg: Any = None,
) -> tuple[dict[str, Any], list[str]]:
    """Flux-class checkpoint(s) → {'unet','vae','te','te2'} trees.

    Published Flux weights ship as SEPARATE files (transformer +
    ae.safetensors + t5xxl + clip_l), so this loader maps whichever
    parts the state dict carries and leaves the rest at init —
    problems are recorded (and strict raises) only for parts that are
    present. Layouts per part: transformer bare or under
    `model.diffusion_model.`; AE bare (`encoder.*`) or under
    `first_stage_model.`; T5 and CLIP in their HF layouts."""
    unet_prefix = (
        "model.diffusion_model."
        if any(k.startswith("model.diffusion_model.double_blocks.") for k in state_dict)
        else ""
    )
    parts: dict[str, list[Entry]] = {}
    if any(k.startswith(f"{unet_prefix}double_blocks.") for k in state_dict):
        parts["unet"] = flux_schedule(unet_cfg, prefix=unet_prefix)
    if any(k.startswith("first_stage_model.") for k in state_dict):
        parts["vae"] = vae_schedule(vae_cfg)
    elif any(k.startswith("encoder.conv_in") for k in state_dict):
        parts["vae"] = vae_schedule(vae_cfg, prefix="")
    if any("layer.0.SelfAttention.q.weight" in k for k in state_dict):
        t5_prefix = next(
            (
                k[: k.index("encoder.block.")]
                for k in state_dict
                if "encoder.block.0.layer.0.SelfAttention.q.weight" in k
            ),
            "",
        )
        parts["te"] = t5_encoder_schedule(te_cfg, prefix=t5_prefix)
    if te2_cfg is not None and any(
        "text_model.encoder.layers.0" in k for k in state_dict
    ):
        clip_prefix = next(
            k[: k.index("text_model.encoder.layers.0")] + "text_model"
            for k in state_dict
            if "text_model.encoder.layers.0" in k
        )
        parts["te2"] = text_encoder_schedule(te2_cfg, prefix=clip_prefix)

    result = dict(templates)
    problems: list[str] = []
    for part, entries in parts.items():
        result[part], part_problems = _merge_into_template(
            state_dict, entries, templates[part], part
        )
        problems += part_problems
    if not parts:
        problems.append("flux: no mappable part found in checkpoint")
    if problems and strict:
        raise ValueError(
            f"flux checkpoint mapping failed ({len(problems)} problems): "
            + "; ".join(problems[:12])
        )
    return result, problems


def _merge_into_template(
    state_dict: dict[str, np.ndarray],
    entries: Iterable[Entry],
    template: Any,
    part: str,
) -> tuple[Any, list[str]]:
    """Convert `state_dict` through `entries` and merge onto the
    template tree: every template leaf takes the converted value when
    present with a matching shape, else keeps its init value and a
    problem line is recorded. The one merge loop all loaders share."""
    from .io import flatten_params, unflatten_params
    import jax

    template_flat = flatten_params(jax.device_get(template))
    converted, missing = convert_state_dict(state_dict, entries)
    problems = [f"{part}: checkpoint lacks {k}" for k in missing]
    merged: dict[str, np.ndarray] = {}
    for key, tval in template_flat.items():
        cval = converted.get(key)
        if cval is None:
            problems.append(f"{part}: schedule lacks {key}")
            merged[key] = tval
        elif tuple(cval.shape) != tuple(tval.shape):
            problems.append(
                f"{part}: shape mismatch {key}: "
                f"ckpt {cval.shape} vs model {tval.shape}"
            )
            merged[key] = tval
        else:
            merged[key] = cval.astype(tval.dtype)
    return unflatten_params(merged), problems


def load_t5_weights(
    state_dict: dict[str, np.ndarray],
    te_cfg,
    template: Any,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Map a UMT5 encoder state dict onto the T5Encoder param tree."""
    params, problems = _merge_into_template(
        state_dict, t5_encoder_schedule(te_cfg), template, "t5"
    )
    if problems and strict:
        raise ValueError(
            f"T5 checkpoint mapping failed ({len(problems)} problems): "
            + "; ".join(problems[:12])
        )
    return params, problems


# --- conversion -----------------------------------------------------------

def _expand(entries: Iterable[Entry]) -> list[tuple[str, str, str]]:
    """Entry list → per-tensor (sd_key, flax_path, transform)."""
    out: list[tuple[str, str, str]] = []
    for sd, fx, kind in entries:
        if kind == _NORM:
            out.append((f"{sd}.weight", f"{fx}/scale", "id"))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind == _CONV:
            out.append((f"{sd}.weight", f"{fx}/kernel", "conv"))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind == "conv_nobias":
            out.append((f"{sd}.weight", f"{fx}/kernel", "conv"))
        elif kind == _LINEAR:
            out.append((f"{sd}.weight", f"{fx}/kernel", "linear"))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind == _LINEAR_NOBIAS:
            out.append((f"{sd}.weight", f"{fx}/kernel", "linear"))
        elif kind == _PROJ:
            out.append((f"{sd}.weight", f"{fx}/kernel", "proj"))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind == "embedding":
            out.append((f"{sd}.weight", f"{fx}/embedding", "id"))
        elif kind == "position":
            out.append((f"{sd}.weight", fx, "id"))
        elif kind == "param_bare":  # bare nn.Parameter, no .weight suffix
            out.append((sd, fx, "id"))
        elif kind == "rms":  # RMSNorm: weight only → scale
            out.append((f"{sd}.weight", f"{fx}/scale", "id"))
        elif kind == "rms_scale":  # RMSNorm stored as .scale (Flux QKNorm)
            out.append((f"{sd}.scale", f"{fx}/scale", "id"))
        elif kind == "bare_linear_w":  # nn.Linear weight → bare [I,O] param
            out.append((f"{sd}.weight", fx, "linear"))
        elif kind == "causal3":  # Conv3d (causal wrapper): weight+bias
            out.append((f"{sd}.weight", f"{fx}/kernel", "conv3d_k"))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind in ("gamma3", "gamma2"):  # bare RMS gamma w/ 1-dims
            out.append((f"{sd}.gamma", f"{fx}/scale", kind))
        elif kind.startswith("conv3d"):  # 3D patch conv → patchify dense
            out.append((f"{sd}.weight", f"{fx}/kernel", kind))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind.startswith("conv2d"):  # 2D patch conv → patchify dense
            out.append((f"{sd}.weight", f"{fx}/kernel", kind))
            out.append((f"{sd}.bias", f"{fx}/bias", "id"))
        elif kind == "fused_qkv":
            # OpenCLIP in_proj: one [3W, W] weight / [3W] bias → the
            # three q/k/v Dense params
            for slot, name in enumerate(("q", "k", "v")):
                out.append((f"{sd}_weight", f"{fx}/{name}/kernel", f"qkv{slot}_w"))
                out.append((f"{sd}_bias", f"{fx}/{name}/bias", f"qkv{slot}_b"))
        else:  # pragma: no cover
            raise ValueError(f"unknown kind {kind}")
    return out


def _transform(value: np.ndarray, how: str) -> np.ndarray:
    if how == "conv":
        return np.transpose(value, (2, 3, 1, 0))
    if how == "linear":
        return np.transpose(value, (1, 0))
    if how == "proj":
        if value.ndim == 4:  # conv 1x1 → dense
            return np.transpose(value[:, :, 0, 0], (1, 0))
        return np.transpose(value, (1, 0))
    if how.startswith("qkv"):
        slot = int(how[3])
        third = value.shape[0] // 3
        part = value[slot * third : (slot + 1) * third]
        return np.transpose(part, (1, 0)) if how.endswith("_w") else part
    if how == "conv3d_k":  # torch Conv3d → flax Conv kernel
        return np.transpose(value, (2, 3, 4, 1, 0))
    if how in ("gamma3", "gamma2"):  # [C,1,1(,1)] → [C]
        return value.reshape(-1)
    if how.startswith("conv3d"):
        # torch Conv3d [O, C, pf, ph, pw] → patchify Dense
        # [pf*ph*pw*C, O]: row order must match the DiT's
        # (pf, ph, pw, c) token flatten order
        return np.transpose(value, (2, 3, 4, 1, 0)).reshape(-1, value.shape[0])
    if how.startswith("conv2d"):
        # torch Conv2d [O, C, ph, pw] → patchify Dense [C*ph*pw, O]:
        # row order matches the SD3 MMDiT (c, ph, pw) token flatten
        return np.transpose(value, (1, 2, 3, 0)).reshape(-1, value.shape[0])
    return value


def _inverse_transform(value: np.ndarray, how: str) -> np.ndarray:
    if how == "conv":
        return np.transpose(value, (3, 2, 0, 1))
    if how in ("linear", "proj"):
        return np.transpose(value, (1, 0))
    if how == "conv3d_k":
        return np.transpose(value, (4, 3, 0, 1, 2))
    if how == "gamma3":
        return value.reshape(-1, 1, 1, 1)
    if how == "gamma2":
        return value.reshape(-1, 1, 1)
    if how.startswith("conv3d"):
        pf, ph, pw, cin = (int(x) for x in how.split(":")[1:])
        out = value.shape[-1]
        return np.transpose(
            value.reshape(pf, ph, pw, cin, out), (4, 3, 0, 1, 2)
        )
    if how.startswith("conv2d"):
        p, cin = (int(x) for x in how.split(":")[1:])
        out = value.shape[-1]
        return np.transpose(value.reshape(cin, p, p, out), (3, 0, 1, 2))
    return value


def convert_state_dict(
    state_dict: dict[str, np.ndarray], entries: Iterable[Entry]
) -> tuple[dict[str, np.ndarray], list[str]]:
    """SD state dict → flat flax param dict ('/'-joined paths) under the
    'params' root, plus the list of sd keys the schedule expected but
    the checkpoint lacks."""
    flat: dict[str, np.ndarray] = {}
    missing: list[str] = []
    for sd_key, fx_path, how in _expand(entries):
        value = state_dict.get(sd_key)
        if value is None:
            missing.append(sd_key)
            continue
        flat[f"params/{fx_path}"] = _transform(np.asarray(value), how)
    return flat, missing


def synthesize_state_dict(
    flat_params: dict[str, np.ndarray], entries: Iterable[Entry]
) -> dict[str, np.ndarray]:
    """Inverse of convert_state_dict for tests: flax tree → SD-format
    state dict with torch layouts."""
    out: dict[str, np.ndarray] = {}
    fused: dict[str, list] = {}
    for sd_key, fx_path, how in _expand(entries):
        value = flat_params.get(f"params/{fx_path}")
        if value is None:
            raise KeyError(f"flax template lacks {fx_path} (for {sd_key})")
        value = np.asarray(value)
        if how.startswith("qkv"):
            slot = int(how[3])
            part = np.transpose(value, (1, 0)) if how.endswith("_w") else value
            fused.setdefault(sd_key, [None, None, None])[slot] = part
        else:
            out[sd_key] = _inverse_transform(value, how)
    for sd_key, parts in fused.items():
        out[sd_key] = np.concatenate(parts, axis=0)
    return out


# --- loading --------------------------------------------------------------

def read_checkpoint(path: str) -> dict[str, np.ndarray]:
    """Read a single-file SD checkpoint (.safetensors, torch .ckpt, or
    quantized .gguf)."""
    if path.endswith(".gguf"):
        from .gguf import read_gguf

        return read_gguf(path)
    if path.endswith(".safetensors"):
        # framework="pt": numpy can't materialize bfloat16 tensors,
        # which bf16 fine-tune checkpoints commonly carry
        import torch
        from safetensors import safe_open

        out: dict[str, np.ndarray] = {}
        with safe_open(path, framework="pt") as fh:
            for key in fh.keys():
                t = fh.get_tensor(key)
                if t.dtype == torch.bfloat16:
                    t = t.float()
                out[key] = t.numpy()
        return out
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=True)
    if "state_dict" in raw:
        raw = raw["state_dict"]
    return {k: v.float().numpy() for k, v in raw.items() if hasattr(v, "numpy")}


def find_checkpoint(model_name: str) -> str | None:
    """Resolve a checkpoint file for `model_name` from
    CDT_CHECKPOINT_DIR. The var may also point directly at a file, in
    which case it applies only when its stem matches `model_name` —
    otherwise a second model loaded in the same process would get the
    wrong weights forced onto it. Arbitrary filenames go through the
    explicit `checkpoint=` argument of load_pipeline instead."""
    root = os.environ.get("CDT_CHECKPOINT_DIR")
    if not root:
        return None
    if os.path.isfile(root):
        stem = os.path.splitext(os.path.basename(root))[0]
        return root if stem == model_name else None
    for ext in (".safetensors", ".ckpt", ".gguf"):
        candidate = os.path.join(root, model_name + ext)
        if os.path.exists(candidate):
            return candidate
    return None


def load_wan_weights(
    state_dict: dict[str, np.ndarray],
    dit_cfg,
    template: Any,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Map a WAN DiT state dict onto the VideoDiT param tree.

    Accepts both the original bare layout (`blocks.0....`) and
    ComfyUI-repacked files (`model.diffusion_model.blocks.0....`).
    Returns (params, problems); template leaves the checkpoint lacks
    are kept at init (or raise when strict).
    """
    prefix = (
        "model.diffusion_model."
        if any(k.startswith("model.diffusion_model.blocks.") for k in state_dict)
        else ""
    )
    params, problems = _merge_into_template(
        state_dict, wan_schedule(dit_cfg, prefix=prefix), template, "dit"
    )
    if problems and strict:
        raise ValueError(
            f"WAN checkpoint mapping failed ({len(problems)} problems): "
            + "; ".join(problems[:12])
        )
    return params, problems


def load_diffusion_weights(
    state_dict: dict[str, np.ndarray],
    unet_cfg,
    template: Any,
    family: str,
    strict: bool = True,
) -> tuple[Any, list[str]]:
    """Map a diffusion-model-only file onto the backbone param tree —
    the ComfyUI UNETLoader format (diffusion_models/ folder: published
    flux1-*.safetensors, sd3.5 transformer repacks, extracted SD
    UNets). Both key layouts load: bare keys and keys nested under
    `model.diffusion_model.` (the single-file-checkpoint interior)."""
    prefixed = any(k.startswith("model.diffusion_model.") for k in state_dict)
    if family == "mmdit":
        entries = flux_schedule(
            unet_cfg, prefix="model.diffusion_model." if prefixed else ""
        )
    elif family == "sd3":
        entries = sd3_schedule(
            unet_cfg, prefix="model.diffusion_model." if prefixed else ""
        )
    else:
        # unet_schedule hard-codes the single-file prefix; bare
        # separate-file keys gain it instead of forking the schedule
        if not prefixed:
            state_dict = {
                f"model.diffusion_model.{k}": v for k, v in state_dict.items()
            }
        entries = unet_schedule(unet_cfg)
    params, problems = _merge_into_template(
        state_dict, entries, template, "unet"
    )
    if problems and strict:
        raise ValueError(
            f"diffusion-model mapping failed ({len(problems)} problems): "
            + "; ".join(problems[:12])
        )
    return params, problems


def load_sd_weights(
    state_dict: dict[str, np.ndarray],
    unet_cfg,
    vae_cfg,
    te_cfg,
    templates: dict[str, Any],
    strict: bool = True,
    te2_cfg: Any = None,
    te3_cfg: Any = None,
    family: str | None = None,
) -> tuple[dict[str, Any], list[str]]:
    """Map a full SD checkpoint onto {'unet','vae','te'} param trees.

    `templates` carries the random-init trees; every template leaf must
    be covered by the checkpoint with a matching shape (strict) or is
    kept at its init value (non-strict). Returns (trees, problems).
    """
    if family == "mmdit":
        return load_flux_weights(
            state_dict, unet_cfg, vae_cfg, te_cfg, templates,
            strict=strict, te2_cfg=te2_cfg,
        )
    if family == "sd3":
        return load_sd3_weights(
            state_dict, unet_cfg, vae_cfg, te_cfg, templates,
            strict=strict, te2_cfg=te2_cfg, te3_cfg=te3_cfg,
        )
    sdxl_layout = any(k.startswith("conditioner.embedders.") for k in state_dict)
    # SD2.x packs an OpenCLIP text tower under cond_stage_model.model.*
    # (bare positional embedding, fused in_proj) — a third layout next
    # to SD1.x's HF-CLIP and SDXL's conditioner.embedders.*
    sd2_layout = not sdxl_layout and any(
        k.startswith("cond_stage_model.model.") for k in state_dict
    )
    if sd2_layout:
        te_entries = open_clip_schedule(te_cfg, prefix="cond_stage_model.model")
    else:
        te_prefix = (
            "conditioner.embedders.0.transformer.text_model"
            if sdxl_layout
            else "cond_stage_model.transformer.text_model"
        )
        te_entries = text_encoder_schedule(te_cfg, prefix=te_prefix)
    schedules = {
        "unet": unet_schedule(unet_cfg),
        "vae": vae_schedule(vae_cfg),
        "te": te_entries,
    }
    if "te2" in templates:
        schedules["te2"] = open_clip_schedule(te2_cfg)
    result: dict[str, Any] = {}
    problems: list[str] = []
    for part, entries in schedules.items():
        result[part], part_problems = _merge_into_template(
            state_dict, entries, templates[part], part
        )
        problems += part_problems
    if problems and strict:
        raise ValueError(
            f"checkpoint mapping failed ({len(problems)} problems): "
            + "; ".join(problems[:12])
        )
    return result, problems
