"""Shared neural blocks for the diffusion model zoo.

Design rules (TPU-first):
- NHWC everywhere; convs lower to MXU-friendly layouts.
- Params live in float32, activations compute in bfloat16 by default
  (`dtype` argument), matmuls request float32 accumulation.
- No python control flow on traced values; everything static-shape.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import dot_product_attention


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal timestep embedding [B] → [B, dim] (float32 for range)."""
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class GroupNorm32(nn.Module):
    """GroupNorm computed in float32 regardless of activation dtype."""

    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig_dtype = x.dtype
        groups = min(self.num_groups, x.shape[-1])
        while x.shape[-1] % groups != 0:
            groups -= 1
        out = nn.GroupNorm(
            num_groups=groups, epsilon=self.epsilon, dtype=jnp.float32
        )(x.astype(jnp.float32))
        return out.astype(orig_dtype)


class AttentionBlock(nn.Module):
    """Multi-head attention over flattened tokens.

    Self-attention when `context` is None, cross-attention otherwise.
    identity_self=True replaces the self-attention matrix with
    identity (out_i = v_i — the PAG perturbation, Ahn et al. 2024);
    the q/k projections become dead code XLA eliminates.
    """

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    identity_self: bool = False
    sow_attn: bool = False  # sow softmax probs (SAG capture pass):
    # explicit scores instead of the flash kernel — one mid-block
    # eval at 1/64 the latent tokens, so materializing is cheap

    @nn.compact
    def __call__(
        self, x: jax.Array, context: Optional[jax.Array] = None
    ) -> jax.Array:
        inner = self.num_heads * self.head_dim
        ctx = x if context is None else context
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(ctx)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(ctx)

        b, n, _ = q.shape
        m = k.shape[1]
        q = q.reshape(b, n, self.num_heads, self.head_dim)
        k = k.reshape(b, m, self.num_heads, self.head_dim)
        v = v.reshape(b, m, self.num_heads, self.head_dim)
        if self.identity_self and context is None:
            out = v
        elif self.sow_attn and context is None:
            scores = jnp.einsum(
                "bnhd,bmhd->bhnm", q.astype(jnp.float32),
                k.astype(jnp.float32),
            ) * (1.0 / math.sqrt(self.head_dim))
            probs = jax.nn.softmax(scores, axis=-1)
            self.sow("intermediates", "attn_probs", probs)
            out = jnp.einsum(
                "bhnm,bmhd->bnhd", probs.astype(self.dtype), v
            )
        else:
            out = dot_product_attention(q, k, v)
        out = out.reshape(b, n, inner)
        return nn.Dense(inner, dtype=self.dtype, name="to_out")(out)


class GEGLU(nn.Module):
    dim_out: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # half order + exact gelu match the SD checkpoint convention
        # (value half first, gate half second) so real ff.net.0.proj
        # weights load without permutation
        x = nn.Dense(self.dim_out * 2, dtype=self.dtype)(x)
        val, gate = jnp.split(x, 2, axis=-1)
        return val * nn.gelu(gate, approximate=False)


class FeedForward(nn.Module):
    mult: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dim = x.shape[-1]
        x = GEGLU(dim * self.mult, dtype=self.dtype)(x)
        return nn.Dense(dim, dtype=self.dtype)(x)


class TransformerBlock(nn.Module):
    """Self-attn → cross-attn → FF with pre-LayerNorm (SD-style).
    pag=True runs attn1 as identity attention (the PAG perturbed
    pass) — parameters are shared with the normal pass."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    pag: bool = False
    sow_attn: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array]) -> jax.Array:
        # eps=1e-5 matches torch LayerNorm (flax default is 1e-6) so
        # real SD weights reproduce reference activations
        x = x + AttentionBlock(
            self.num_heads, self.head_dim, self.dtype,
            identity_self=self.pag, sow_attn=self.sow_attn, name="attn1",
        )(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype)
        )
        x = x + AttentionBlock(self.num_heads, self.head_dim, self.dtype, name="attn2")(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype), context
        )
        x = x + FeedForward(dtype=self.dtype, name="ff")(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)(x).astype(self.dtype)
        )
        return x


class SpatialTransformer(nn.Module):
    """[B,H,W,C] → tokens → N transformer blocks → [B,H,W,C] + residual."""

    num_heads: int
    head_dim: int
    depth: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    pag: bool = False
    sow_attn: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array]) -> jax.Array:
        b, h, w, c = x.shape
        residual = x
        x = GroupNorm32(name="norm")(x)
        x = nn.Dense(c, dtype=self.dtype, name="proj_in")(x)
        x = x.reshape(b, h * w, c)
        for i in range(self.depth):
            x = TransformerBlock(
                self.num_heads, self.head_dim, self.dtype,
                pag=self.pag,
                # ComfyUI's SAG captures block 0 of the middle stack
                sow_attn=self.sow_attn and i == 0,
                name=f"block_{i}",
            )(x, context)
        x = x.reshape(b, h, w, c)
        x = nn.Dense(c, dtype=self.dtype, name="proj_out")(x)
        return x + residual


class ResBlock(nn.Module):
    """Conv residual block with timestep-embedding modulation."""

    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, emb: jax.Array) -> jax.Array:
        h = GroupNorm32(name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), dtype=self.dtype, name="conv1")(h)
        emb_out = nn.Dense(self.out_channels, dtype=self.dtype, name="emb_proj")(
            nn.silu(emb)
        )
        h = h + emb_out[:, None, None, :]
        h = GroupNorm32(name="norm2")(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class Downsample(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # symmetric (1,1) padding = the SD UNet downsample convention
        # (torch Conv2d padding=1); flax SAME would pad (0,1) and
        # misalign real checkpoint weights
        return nn.Conv(
            x.shape[-1], (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
            dtype=self.dtype, name="op",
        )(x)


class Upsample(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, x: jax.Array, out_hw: Optional[tuple[int, int]] = None
    ) -> jax.Array:
        # out_hw overrides the 2x default so the up path can land
        # exactly on the skip connection's spatial dims when the
        # latent isn't divisible by 2^depth (e.g. 4x4 latents through
        # three downsamples: 4→2→1, back up 1→2→4)
        b, h, w, c = x.shape
        th, tw = out_hw if out_hw is not None else (h * 2, w * 2)
        x = jax.image.resize(x, (b, th, tw, c), method="nearest")
        return nn.Conv(c, (3, 3), dtype=self.dtype, name="conv")(x)
