"""KL autoencoder (VAE) for latent diffusion — flax.linen, NHWC.

The encode/decode pair the reference reaches through ComfyUI's
VAEEncode/VAEDecode nodes (reference upscale/tile_ops.py:168). 8x
spatial compression, 4-channel latents, GroupNorm/SiLU ResBlocks with
a mid self-attention, `scaling_factor` applied at the latent boundary
so samplers see unit-variance latents.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import GroupNorm32
from ..ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_mult: Sequence[int] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    scaling_factor: float = 0.18215
    # Flux-class AE boundary: z = (mean - shift) * scale; the published
    # flux autoencoder also drops the SD 1x1 quant/post_quant convs
    shift_factor: float = 0.0
    use_quant_conv: bool = True
    dtype: str = "bfloat16"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.channel_mult) - 1)


class _VAEResBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = GroupNorm32(epsilon=1e-6, name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), dtype=self.dtype, name="conv1")(h)
        h = GroupNorm32(epsilon=1e-6, name="norm2")(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class _MidAttention(nn.Module):
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, hh, ww, c = x.shape
        h = GroupNorm32(epsilon=1e-6, name="norm")(x)
        tokens = h.reshape(b, hh * ww, c)
        q = nn.Dense(c, dtype=self.dtype, name="q")(tokens)
        k = nn.Dense(c, dtype=self.dtype, name="k")(tokens)
        v = nn.Dense(c, dtype=self.dtype, name="v")(tokens)
        out = dot_product_attention(
            q[:, :, None, :], k[:, :, None, :], v[:, :, None, :]
        )[:, :, 0, :]
        out = nn.Dense(c, dtype=self.dtype, name="proj")(out)
        return x + out.reshape(b, hh, ww, c)


class Encoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        x = x.astype(dt)
        h = nn.Conv(cfg.base_channels, (3, 3), dtype=dt, name="conv_in")(x)
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = cfg.base_channels * mult
            for i in range(cfg.num_res_blocks):
                h = _VAEResBlock(out_ch, dt, name=f"down_{level}_res_{i}")(h)
            if level != len(cfg.channel_mult) - 1:
                h = nn.Conv(
                    out_ch, (3, 3), strides=(2, 2), dtype=dt, name=f"down_{level}_ds"
                )(h)
        h = _VAEResBlock(h.shape[-1], dt, name="mid_res_0")(h)
        h = _MidAttention(dt, name="mid_attn")(h)
        h = _VAEResBlock(h.shape[-1], dt, name="mid_res_1")(h)
        h = GroupNorm32(epsilon=1e-6, name="norm_out")(h)
        h = nn.silu(h)
        # mean + logvar
        return nn.Conv(
            2 * cfg.latent_channels, (3, 3), dtype=jnp.float32, name="conv_out"
        )(h.astype(jnp.float32))


class Decoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        z = z.astype(dt)
        ch = cfg.base_channels * cfg.channel_mult[-1]
        h = nn.Conv(ch, (3, 3), dtype=dt, name="conv_in")(z)
        h = _VAEResBlock(ch, dt, name="mid_res_0")(h)
        h = _MidAttention(dt, name="mid_attn")(h)
        h = _VAEResBlock(ch, dt, name="mid_res_1")(h)
        for level, mult in reversed(list(enumerate(cfg.channel_mult))):
            out_ch = cfg.base_channels * mult
            for i in range(cfg.num_res_blocks + 1):
                h = _VAEResBlock(out_ch, dt, name=f"up_{level}_res_{i}")(h)
            if level != 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), method="nearest")
                h = nn.Conv(c, (3, 3), dtype=dt, name=f"up_{level}_us")(h)
        h = GroupNorm32(epsilon=1e-6, name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(cfg.in_channels, (3, 3), dtype=jnp.float32, name="conv_out")(
            h.astype(jnp.float32)
        )


class VAE(nn.Module):
    """Encode/decode with method switching:
    `apply(params, x, method="encode")` → latents (mean, scaled);
    `apply(params, z, method="decode")` → images in [0, 1]."""

    config: VAEConfig

    def setup(self):
        self.encoder = Encoder(self.config)
        self.decoder = Decoder(self.config)
        # 1x1 moment/latent projections from the SD AutoencoderKL
        # (quant_conv / post_quant_conv) so real checkpoints map 1:1;
        # Flux-class AEs ship without them
        if self.config.use_quant_conv:
            self.quant_conv = nn.Conv(
                2 * self.config.latent_channels, (1, 1), dtype=jnp.float32,
                name="quant_conv",
            )
            self.post_quant_conv = nn.Conv(
                self.config.latent_channels, (1, 1), dtype=jnp.float32,
                name="post_quant_conv",
            )

    def encode(self, x: jax.Array, rng: jax.Array | None = None) -> jax.Array:
        """[B,H,W,3] in [0,1] → [B,H/8,W/8,C] scaled latents (mean; pass
        rng to sample from the posterior instead)."""
        moments = self.encoder(x * 2.0 - 1.0)
        if self.config.use_quant_conv:
            moments = self.quant_conv(moments)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        if rng is not None:
            std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
            mean = mean + std * jax.random.normal(rng, mean.shape)
        return (mean - self.config.shift_factor) * self.config.scaling_factor

    def decode(self, z: jax.Array) -> jax.Array:
        """[B,h,w,C] scaled latents → [B,H,W,3] images in [0,1]."""
        z = z / self.config.scaling_factor + self.config.shift_factor
        if self.config.use_quant_conv:
            z = self.post_quant_conv(z)
        x = self.decoder(z)
        return jnp.clip((x + 1.0) / 2.0, 0.0, 1.0)

    def __call__(self, x: jax.Array) -> jax.Array:
        return self.decode(self.encode(x))
