"""Model weight IO: safetensors checkpoints + orbax run state.

The reference free-rides on ComfyUI's checkpoint loaders (GGUF/
safetensors); here:

- `save_params` / `load_params` — flat safetensors round-trip of a
  flax param pytree ('/'-joined keys), the interchange format for
  bringing real weights in;
- `save_run_state` / `load_run_state` — orbax checkpointing of
  arbitrarily sharded pytrees for resumable long runs (checkpoint/
  resume is absent in the reference, SURVEY §5) — sharded params are
  saved from and restored onto their mesh placement.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np


def flatten_params(params: Any, prefix: str = "") -> dict[str, np.ndarray]:
    flat: dict[str, np.ndarray] = {}

    def walk(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for key, value in node.items():
                walk(value, f"{path}/{key}" if path else str(key))
        else:
            flat[path] = np.asarray(node)

    walk(params, prefix)
    return flat


def unflatten_params(flat: dict[str, np.ndarray]) -> dict[str, Any]:
    tree: dict[str, Any] = {}
    for path, value in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree


def save_params(params: Any, path: str) -> None:
    from safetensors.numpy import save_file

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    flat = flatten_params(jax.device_get(params))
    save_file(flat, path)


def load_params(path: str) -> dict[str, Any]:
    from safetensors.numpy import load_file

    return unflatten_params(load_file(path))


def load_params_into(template: Any, path: str, strict: bool = True) -> Any:
    """Load a checkpoint shaped like `template`; mismatched/missing
    entries raise (strict) or keep the template value."""
    loaded = load_params(path)
    flat_t = flatten_params(jax.device_get(template))
    flat_l = flatten_params(loaded)
    merged: dict[str, np.ndarray] = {}
    problems: list[str] = []
    for key, tval in flat_t.items():
        lval = flat_l.get(key)
        if lval is None:
            problems.append(f"missing {key}")
            merged[key] = tval
        elif tuple(lval.shape) != tuple(tval.shape):
            problems.append(f"shape mismatch {key}: {lval.shape} vs {tval.shape}")
            merged[key] = tval
        else:
            merged[key] = lval.astype(tval.dtype)
    extra = set(flat_l) - set(flat_t)
    if extra:
        problems.append(f"unused keys: {sorted(extra)[:5]}...")
    if problems and strict:
        raise ValueError("checkpoint mismatch: " + "; ".join(problems[:10]))
    return unflatten_params(merged)


# --- orbax run state ------------------------------------------------------

def save_run_state(state: Any, directory: str, step: int) -> None:
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    with ocp.CheckpointManager(path) as manager:
        manager.save(step, args=ocp.args.StandardSave(state))
        manager.wait_until_finished()


def load_run_state(template: Any, directory: str, step: int | None = None) -> Any:
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    with ocp.CheckpointManager(path) as manager:
        step = manager.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
        return manager.restore(
            step, args=ocp.args.StandardRestore(template)
        )
