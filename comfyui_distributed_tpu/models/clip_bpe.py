"""CLIP byte-level BPE tokenizer (reference-exact semantics).

The reference free-rides on ComfyUI's bundled CLIP tokenizer for all
text conditioning (reference workflows' CLIPTextEncode nodes); here
the algorithm is implemented natively and the vocab is a committed
asset.

Semantics mirror the canonical CLIP tokenizer in its no-ftfy
configuration (the one transformers falls back to when ftfy is not
installed): control-char removal + whitespace cleanup + NFC
normalization + lowercasing (accents kept, punctuation kept attached),
then the CLIP pre-tokenization regex, GPT-2 byte→unicode encoding, and
greedy rank-ordered BPE merges with a ``</w>`` end-of-word suffix.
Parity is enforced by tests/models/test_clip_bpe.py, which runs
``transformers.CLIPTokenizer`` over the same vocab files and asserts
identical ids.

Vocab files: standard CLIP pair ``vocab.json`` + ``merges.txt``
(gzipped variants supported). The committed fallback pair under
``models/assets/clip_vocab/`` has CLIP's exact id layout (512 byte
units, 48894 merges, BOS=49406, EOS=49407) but merges trained on
build-host prose (this build environment has no network egress, so the
real table cannot be fetched from here). ``scripts/fetch_clip_vocab.py``
installs OpenAI's published table (pinned hash + canonical-token-id
validation) in one command; ``ClipBPE.is_canonical`` reports which pair
is active and ``get_bpe`` warns loudly when serving the stand-in.
"""

from __future__ import annotations

import functools
import gzip
import json
import os
import unicodedata

import regex

_ASSET_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "assets", "clip_vocab")

# CLIP's pre-tokenization pattern (case-insensitive).
_PATTERN = regex.compile(
    r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|"""
    r"""[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""",
    regex.IGNORECASE,
)

# CLIP caps the merge table at 49152-256-2 entries regardless of file length.
_MAX_MERGES = 49152 - 256 - 2


@functools.lru_cache
def bytes_to_unicode() -> dict[int, str]:
    """GPT-2/CLIP reversible byte→printable-unicode table."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(2**8):
        if b not in bs:
            bs.append(b)
            cs.append(2**8 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rt", encoding="utf-8")
    return open(path, encoding="utf-8")


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F
        or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF
        or 0x2F800 <= cp <= 0x2FA1F
    )


def clean_text(text: str) -> str:
    """CLIP's no-ftfy normalization: strip control chars, space out CJK,
    NFC-normalize, collapse whitespace, lowercase (accents kept)."""
    out = []
    for ch in text:
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or _is_control(ch):
            continue
        if ch.isspace() or unicodedata.category(ch) == "Zs":
            out.append(" ")
        elif _is_cjk(cp):
            out.append(f" {ch} ")
        else:
            out.append(ch)
    text = unicodedata.normalize("NFC", "".join(out))
    return " ".join(text.lower().split())


class ClipBPE:
    """Encoder over a CLIP-format vocab.json + merges.txt pair."""

    def __init__(self, vocab_dir: str | None = None):
        vocab_dir = vocab_dir or _ASSET_DIR
        self.vocab_dir = vocab_dir
        with _open_maybe_gz(os.path.join(vocab_dir, "vocab.json")) as fh:
            self.encoder: dict[str, int] = json.load(fh)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with _open_maybe_gz(os.path.join(vocab_dir, "merges.txt")) as fh:
            lines = fh.read().strip().split("\n")
        merges = [
            tuple(ln.split()) for ln in lines[1 : _MAX_MERGES + 1]
        ]  # line 0 is the "#version" header
        self.bpe_ranks: dict[tuple[str, str], int] = {
            m: i for i, m in enumerate(merges)
        }
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.bos_id = self.encoder["<|startoftext|>"]
        self.eos_id = self.encoder["<|endoftext|>"]
        # specials pass through BPE unsplit (canonical CLIP cache seed)
        self._cache: dict[str, str] = {
            "<|startoftext|>": "<|startoftext|>",
            "<|endoftext|>": "<|endoftext|>",
        }

    def _bpe(self, token: str) -> str:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        if len(word) == 1:
            self._cache[token] = word[0]
            return word[0]
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            merged: list[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    merged.extend(word[i:])
                    break
                merged.extend(word[i:j])
                i = j
                if word[i] == first and i < len(word) - 1 and word[i + 1] == second:
                    merged.append(first + second)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        result = " ".join(word)
        self._cache[token] = result
        return result

    @functools.cached_property
    def is_canonical(self) -> bool:
        """True when this vocab behaves as OpenAI's published CLIP
        vocabulary — checked against token ids from the official CLIP
        notebook (`tokenize("hello world!")` → 3306/1002/256). The
        committed prose-trained stand-in reports False; the operator
        installs the real table via scripts/fetch_clip_vocab.py."""
        try:
            return (
                self.encode_text("hello world!") == [3306, 1002, 256]
                and self.encode_text("a photo of a cat")
                == [320, 1125, 539, 320, 2368]
            )
        except Exception:
            return False

    def encode_text(self, text: str) -> list[int]:
        """Text → BPE ids (no specials, no padding)."""
        ids: list[int] = []
        for token in _PATTERN.findall(clean_text(text)):
            mapped = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            for piece in self._bpe(mapped).split(" "):
                ids.append(self.encoder.get(piece, self.eos_id))
        return ids

    def decode(self, ids: list[int]) -> str:
        text = "".join(
            self.decoder.get(i, "") for i in ids
            if i not in (self.bos_id, self.eos_id)
        )
        data = bytearray(
            self.byte_decoder[c] for c in text if c in self.byte_decoder
        )
        return data.decode("utf-8", errors="replace").replace("</w>", " ").strip()


@functools.lru_cache(maxsize=4)
def _get_bpe_cached(vocab_dir: str) -> ClipBPE:
    bpe = ClipBPE(vocab_dir)
    if not bpe.is_canonical:
        import logging

        logging.getLogger("cdt.clip_bpe").warning(
            "CLIP vocab at %s is NOT OpenAI's published table (canonical "
            "token-id check failed): real SD/SDXL checkpoints will "
            "receive wrong token ids and produce wrong images. Install "
            "the exact vocab with scripts/fetch_clip_vocab.py or point "
            "CDT_CLIP_VOCAB at OpenAI's vocab.json/merges.txt pair.",
            vocab_dir,
        )
    return bpe


def get_bpe(vocab_dir: str | None = None) -> ClipBPE:
    # env var resolved here, outside the cache key, so setting
    # CDT_CLIP_VOCAB between pipeline builds takes effect
    resolved = vocab_dir or os.environ.get("CDT_CLIP_VOCAB") or _ASSET_DIR
    return _get_bpe_cached(resolved)
