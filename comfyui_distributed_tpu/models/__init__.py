"""Model zoo: latent-diffusion UNets, video DiT, VAE, text encoder.

The reference delegates all model compute to ComfyUI/PyTorch
(reference upscale/tile_ops.py:168 imports common_ksampler/VAEEncode/
VAEDecode); this package is the from-scratch JAX substrate those
capabilities run on here. All models are flax.linen modules designed
mesh-first: bfloat16 compute on the MXU, channel-last NHWC layouts,
shapes static under jit, and parameter trees whose largest axes
divide cleanly for FSDP sharding.

Families (configs in registry.py):
    sd15  — 4-ch latent UNet, 768-d text context  (SD1.5 class)
    sdxl  — 4-ch latent UNet, 2048-d context, deeper transformers
    wan   — video DiT (3D patches, AdaLN, RoPE) in 1.3B/14B configs
    vae   — KL autoencoder (8x spatial, 4-ch latents)
    te    — CLIP-class causal text transformer
Each family also ships a `tiny` config for hermetic CPU tests.
"""

from .registry import MODEL_REGISTRY, create_model, get_config  # noqa: F401
