"""Latent-diffusion UNet (SD1.5 / SDXL class), flax.linen, NHWC.

Architecture-faithful to the SD UNet family the reference drives via
ComfyUI's `common_ksampler` (reference upscale/tile_ops.py:239-287):
timestep + optional pooled-vector conditioning, down/mid/up ResBlock
stacks with spatial transformers cross-attending to text context, skip
connections across the U. Config-driven so SD1.5 (320ch, 768-d ctx),
SDXL (2048-d ctx, deep mid transformers) and tiny test variants are
the same code.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .layers import (
    Downsample,
    GroupNorm32,
    ResBlock,
    SpatialTransformer,
    Upsample,
    timestep_embedding,
)


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    channel_mult: Sequence[int] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    # transformer depth per resolution level (0 = no attention there)
    transformer_depth: Sequence[int] = (1, 1, 1, 0)
    context_dim: int = 768
    num_heads: int = 8
    # fixed per-head width (SDXL's num_head_channels=64 convention):
    # when set, each level uses out_ch // head_dim heads, overriding
    # num_heads — required for real SDXL attention semantics
    head_dim: Optional[int] = None
    # SDXL-style pooled text + size conditioning vector (0 = disabled)
    adm_in_channels: int = 0
    # what the network predicts: "eps" (noise; SD1.x/SDXL base) or "v"
    # (velocity; SD2.x-768 and v-pred finetunes). The pipeline converts
    # v outputs to the sampler's eps contract exactly.
    parameterization: str = "eps"
    dtype: str = "bfloat16"
    # rematerialise attention blocks: trades recompute for HBM, the
    # standard lever for big latents on 16GB chips
    remat: bool = False
    # FreeU patch (the FreeU / FreeU_V2 nodes): (b1, b2, s1, s2, v2)
    # — backbone-half scaling + Fourier low-pass skip scaling at the
    # model_channels*4 / *2 up-path joins. None = unpatched. Carried
    # on the config so the patched module recompiles exactly once and
    # adds zero cost when absent.
    freeu: Optional[tuple] = None

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def _fourier_lowpass_scale(x: jax.Array, threshold: int, scale) -> jax.Array:
    """Scale the centered low-frequency box of a [B, H, W, C] plane
    (the reference stack's Fourier_filter: fft2 → shift → scale the
    (2*threshold)^2 center → inverse). Computed in float32 — FFT of a
    bf16 plane would quantize the whole spectrum."""
    xf = jnp.fft.fftn(x.astype(jnp.float32), axes=(1, 2))
    xf = jnp.fft.fftshift(xf, axes=(1, 2))
    b, hh, ww, c = x.shape
    crow, ccol = hh // 2, ww // 2
    mask = jnp.ones((1, hh, ww, 1), jnp.float32)
    y0, y1 = max(0, crow - threshold), min(hh, crow + threshold)
    x0, x1 = max(0, ccol - threshold), min(ww, ccol + threshold)
    mask = mask.at[:, y0:y1, x0:x1, :].set(scale)
    xf = xf * mask
    xf = jnp.fft.ifftshift(xf, axes=(1, 2))
    return jnp.fft.ifftn(xf, axes=(1, 2)).real.astype(x.dtype)


def _apply_freeu(cfg, ch: int, h: jax.Array, skip: jax.Array):
    """FreeU at one up-path join: backbone half-channel scaling (b) +
    Fourier low-pass scaling of the skip (s), keyed on the backbone
    width exactly like the reference patch (model_channels*4 → b1/s1,
    model_channels*2 → b2/s2). v2 scales adaptively by the normalized
    per-pixel hidden mean instead of a constant."""
    b1, b2, s1, s2, v2 = cfg.freeu
    scale_map = {ch * 4: (b1, s1), ch * 2: (b2, s2)}
    pair = scale_map.get(h.shape[-1])
    if pair is None:
        return h, skip
    b, s = pair
    half = h.shape[-1] // 2
    if v2:
        hidden_mean = jnp.mean(h.astype(jnp.float32), axis=-1, keepdims=True)
        hmax = jnp.max(hidden_mean, axis=(1, 2), keepdims=True)
        hmin = jnp.min(hidden_mean, axis=(1, 2), keepdims=True)
        hidden_mean = (hidden_mean - hmin) / jnp.maximum(hmax - hmin, 1e-8)
        factor = ((b - 1.0) * hidden_mean + 1.0).astype(h.dtype)
    else:
        factor = jnp.asarray(b, h.dtype)
    h = jnp.concatenate([h[..., :half] * factor, h[..., half:]], axis=-1)
    skip = _fourier_lowpass_scale(skip, 1, s)
    return h, skip


class UNet(nn.Module):
    config: UNetConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,            # [B, H, W, C_in] noisy latents
        timesteps: jax.Array,    # [B]
        context: jax.Array,      # [B, T, context_dim] text tokens
        y: Optional[jax.Array] = None,  # [B, adm_in_channels] pooled cond
        control: Optional[jax.Array] = None,  # [B, H, W, model_channels]
        pag: bool = False,  # identity self-attention in the middle
        # block (the PAG perturbed pass; ComfyUI's simple-PAG patches
        # exactly the middle-block attn1)
        sag_capture: bool = False,  # sow the middle-block attn1
        # softmax probs (SAG capture pass); apply with
        # mutable=["intermediates"] to harvest them
    ) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        ch = cfg.model_channels
        SpatialT = (
            nn.remat(SpatialTransformer, static_argnums=())
            if cfg.remat
            else SpatialTransformer
        )

        def head_split(width: int) -> tuple[int, int]:
            if cfg.head_dim:
                return width // cfg.head_dim, cfg.head_dim
            return cfg.num_heads, width // cfg.num_heads

        emb = nn.Dense(ch * 4, dtype=dt, name="time_embed_0")(
            timestep_embedding(timesteps, ch).astype(dt)
        )
        emb = nn.Dense(ch * 4, dtype=dt, name="time_embed_2")(nn.silu(emb))
        if cfg.adm_in_channels:
            if y is None:
                y = jnp.zeros((x.shape[0], cfg.adm_in_channels), dt)
            label = nn.Dense(ch * 4, dtype=dt, name="label_embed_0")(y.astype(dt))
            label = nn.Dense(ch * 4, dtype=dt, name="label_embed_2")(nn.silu(label))
            emb = emb + label

        context = context.astype(dt)
        x = x.astype(dt)

        h = nn.Conv(ch, (3, 3), dtype=dt, name="input_conv")(x)
        if control is not None:
            # ControlNet residual injection (hint encoder output at
            # latent resolution, zero-init ⇒ identity when untrained)
            h = h + control.astype(dt)
        skips = [h]

        # --- down path ---
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks):
                h = ResBlock(out_ch, dt, name=f"down_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level] > 0:
                    heads, hdim = head_split(out_ch)
                    h = SpatialT(
                        heads,
                        hdim,
                        cfg.transformer_depth[level],
                        dt,
                        name=f"down_{level}_attn_{i}",
                    )(h, context)
                skips.append(h)
            if level != len(cfg.channel_mult) - 1:
                h = Downsample(dt, name=f"down_{level}_ds")(h)
                skips.append(h)

        # --- middle ---
        mid_ch = ch * cfg.channel_mult[-1]
        mid_depth = max(cfg.transformer_depth[-1], 1)
        h = ResBlock(mid_ch, dt, name="mid_res_0")(h, emb)
        mid_heads, mid_hdim = head_split(mid_ch)
        # capture bypasses remat for the mid block only: sown
        # intermediates don't survive nn.remat, and the mid block's
        # activations are 1/64 of the latent tokens anyway
        MidT = SpatialTransformer if sag_capture else SpatialT
        h = MidT(
            mid_heads, mid_hdim, mid_depth, dt, pag=pag,
            sow_attn=sag_capture, name="mid_attn",
        )(h, context)
        h = ResBlock(mid_ch, dt, name="mid_res_1")(h, emb)

        # --- up path ---
        for level, mult in reversed(list(enumerate(cfg.channel_mult))):
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks + 1):
                skip = skips.pop()
                if cfg.freeu is not None:
                    h, skip = _apply_freeu(cfg, ch, h, skip)
                h = jnp.concatenate([h, skip], axis=-1)
                h = ResBlock(out_ch, dt, name=f"up_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level] > 0:
                    heads, hdim = head_split(out_ch)
                    h = SpatialT(
                        heads,
                        hdim,
                        cfg.transformer_depth[level],
                        dt,
                        name=f"up_{level}_attn_{i}",
                    )(h, context)
            if level != 0:
                # land exactly on the next skip's spatial dims (small /
                # odd latents don't round-trip through stride-2 convs)
                target = skips[-1].shape[1:3]
                h = Upsample(dt, name=f"up_{level}_us")(h, target)

        h = GroupNorm32(name="out_norm")(h)
        h = nn.silu(h)
        h = nn.Conv(
            cfg.out_channels,
            (3, 3),
            dtype=jnp.float32,
            kernel_init=nn.initializers.zeros,
            name="out_conv",
        )(h.astype(jnp.float32))
        return h
