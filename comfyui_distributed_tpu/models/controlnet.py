"""ControlNet-style hint conditioning.

The role ControlNet tile plays in the reference's upscale workflow
(reference workflows image upscale uses a ControlNet-tile model; hint
cropping parity in utils/usdu_utils.py crop_cond): a pixel-space hint
image is encoded by a conv stack to a latent-resolution residual that
is injected into the UNet after its input conv, scaled by strength.
Zero-initialised output so an untrained ControlNet is a no-op — the
standard ControlNet trick, and what makes random-init tests exact.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from .pipeline import maybe_cast_params


@dataclasses.dataclass(frozen=True)
class ControlNetConfig:
    hint_channels: int = 3
    model_channels: int = 320   # must match the target UNet
    downscale: int = 8          # must match the VAE spatial factor
    dtype: str = "bfloat16"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


class ControlNetEncoder(nn.Module):
    config: ControlNetConfig

    @nn.compact
    def __call__(self, hint: jax.Array) -> jax.Array:
        """[B, H, W, hint_ch] in [0,1] → [B, H/8, W/8, model_channels]."""
        cfg = self.config
        dt = cfg.compute_dtype
        h = (hint.astype(dt) * 2.0 - 1.0)
        ch = 16
        levels = max(0, int(cfg.downscale).bit_length() - 1)  # log2(downscale)
        h = nn.Conv(ch, (3, 3), dtype=dt, name="conv_in")(h)
        h = nn.silu(h)
        for i in range(levels):
            ch = min(ch * 2, cfg.model_channels)
            h = nn.Conv(ch, (3, 3), strides=(2, 2), dtype=dt, name=f"down_{i}")(h)
            h = nn.silu(h)
        h = nn.Conv(ch, (3, 3), dtype=dt, name="mid")(h)
        h = nn.silu(h)
        return nn.Conv(
            cfg.model_channels, (3, 3), dtype=jnp.float32,
            kernel_init=nn.initializers.zeros, name="conv_out",
        )(h.astype(jnp.float32))


@dataclasses.dataclass
class ControlNetBundle:
    """Loader product: module + params (the CONTROL_NET node type)."""

    name: str
    module: ControlNetEncoder
    params: dict

    def encode(self, hint: jax.Array) -> jax.Array:
        return self.module.apply(self.params, hint)


def load_controlnet(
    name: str = "tile", model_channels: int = 320, downscale: int = 8, seed: int = 0
) -> ControlNetBundle:
    cfg = ControlNetConfig(model_channels=model_channels, downscale=downscale)
    module = ControlNetEncoder(cfg)
    params = module.init(
        jax.random.key(seed), jnp.zeros((1, downscale * 8, downscale * 8, 3))
    )
    return ControlNetBundle(
        name=name, module=module, params=maybe_cast_params(params)
    )
