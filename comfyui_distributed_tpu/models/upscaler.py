"""Feed-forward super-resolution model (ESRGAN class).

The reference's upscale workflows run an upscale model before tiled
re-diffusion (ComfyUI UpscaleModelLoader + ImageUpscaleWithModel);
this is the JAX equivalent: an RRDB-lite residual conv net with
pixel-shuffle upsampling. Residual-to-bilinear output with zero-init
final conv, so a random-init model reproduces bilinear resize exactly
— distributed behavior stays testable without trained weights.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from .pipeline import maybe_cast_params


@dataclasses.dataclass(frozen=True)
class UpscalerConfig:
    scale: int = 4
    channels: int = 64
    num_blocks: int = 6
    dtype: str = "bfloat16"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


class _ResidualBlock(nn.Module):
    channels: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype, name="conv1")(x)
        h = nn.leaky_relu(h, 0.2)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype, name="conv2")(h)
        return x + 0.2 * h


class SuperResolver(nn.Module):
    config: UpscalerConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        """[B, H, W, 3] in [0,1] → [B, H*scale, W*scale, 3]."""
        cfg = self.config
        dt = cfg.compute_dtype
        b, h, w, c = x.shape
        base = jax.image.resize(
            x, (b, h * cfg.scale, w * cfg.scale, c), method="linear"
        )
        feat = nn.Conv(cfg.channels, (3, 3), dtype=dt, name="head")(
            x.astype(dt) * 2.0 - 1.0
        )
        for i in range(cfg.num_blocks):
            feat = _ResidualBlock(cfg.channels, dt, name=f"block_{i}")(feat)
        # pixel-shuffle upsample
        feat = nn.Conv(
            c * cfg.scale * cfg.scale, (3, 3), dtype=jnp.float32,
            kernel_init=nn.initializers.zeros, name="tail",
        )(feat.astype(jnp.float32))
        feat = feat.reshape(b, h, w, cfg.scale, cfg.scale, c)
        residual = feat.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, h * cfg.scale, w * cfg.scale, c
        )
        return jnp.clip(base + residual, 0.0, 1.0)


@dataclasses.dataclass
class UpscaleModelBundle:
    name: str
    module: SuperResolver
    params: dict
    scale: int

    def upscale(self, image: jax.Array) -> jax.Array:
        return self.module.apply(self.params, image)


def load_upscale_model(name: str = "4x-generic", seed: int = 0) -> UpscaleModelBundle:
    scale = 4
    if name and name[0].isdigit() and "x" in name:
        try:
            scale = int(name.split("x")[0])
        except ValueError:
            scale = 4
    cfg = UpscalerConfig(scale=scale)
    module = SuperResolver(cfg)
    params = module.init(jax.random.key(seed), jnp.zeros((1, 16, 16, 3)))
    return UpscaleModelBundle(
        name=name, module=module, params=maybe_cast_params(params), scale=scale
    )
