"""Model family registry: named configs → constructors.

The TPU analog of ComfyUI's checkpoint loader surface the reference
leans on (CheckpointLoaderSimple in reference workflows/*.json): a
model name resolves to (module, config). Weights load from safetensors
when present (utils in io.py), else deterministic random init — the
distributed machinery is weight-agnostic.

`tiny-*` variants are real instances of the same code small enough for
hermetic CPU tests and multi-chip dry runs.
"""

from __future__ import annotations

from typing import Any, Callable

from .clip_vision import ClipVisionConfig, ClipVisionEncoder
from .dit import DiTConfig, VideoDiT
from .mmdit import MMDiT, MMDiTConfig
from .sd3 import SD3Config, SD3MMDiT
from .t5_encoder import T5Encoder, T5EncoderConfig
from .text_encoder import TextEncoder, TextEncoderConfig
from .unet import UNet, UNetConfig
from .vae import VAE, VAEConfig
from .video_vae import VideoVAE, VideoVAEConfig

MODEL_REGISTRY: dict[str, dict[str, Any]] = {
    # --- UNet diffusion backbones ---
    "sd15": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=320,
            channel_mult=(1, 2, 4, 4),
            transformer_depth=(1, 1, 1, 0),
            context_dim=768,
            num_heads=8,
            remat=True,
        ),
    },
    # SD1.5 inpainting UNet (runwayml sd-v1-5-inpainting layout):
    # input = concat(noisy latents 4, mask 1, masked-image latents 4)
    # — the InpaintModelConditioning node assembles the extra channels
    "sd15-inpaint": {
        "family": "unet",
        "config": UNetConfig(
            in_channels=9,
            model_channels=320,
            channel_mult=(1, 2, 4, 4),
            transformer_depth=(1, 1, 1, 0),
            context_dim=768,
            num_heads=8,
            remat=True,
        ),
    },
    "sdxl": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=320,
            channel_mult=(1, 2, 4),
            transformer_depth=(0, 2, 10),
            context_dim=2048,
            head_dim=64,  # SDXL num_head_channels convention
            adm_in_channels=2816,
            remat=True,
        ),
    },
    # SD2.1-768-v: SD1.x topology with OpenCLIP-H conditioning
    # (context 1024), num_head_channels=64, velocity prediction
    "sd21": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=320,
            channel_mult=(1, 2, 4, 4),
            transformer_depth=(1, 1, 1, 0),
            context_dim=1024,
            head_dim=64,
            parameterization="v",
            remat=True,
        ),
    },
    # SD2.1-base (512px): same network, epsilon prediction
    "sd21-base": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=320,
            channel_mult=(1, 2, 4, 4),
            transformer_depth=(1, 1, 1, 0),
            context_dim=1024,
            head_dim=64,
            remat=True,
        ),
    },
    "tiny-unet": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=32,
            channel_mult=(1, 2),
            num_res_blocks=1,
            transformer_depth=(1, 1),
            context_dim=64,
            num_heads=2,
        ),
    },
    # tiny inpaint-model variant (9-channel input): exercises the
    # concat-conditioning path of InpaintModelConditioning
    "tiny-unet-inpaint": {
        "family": "unet",
        "config": UNetConfig(
            in_channels=9,
            model_channels=32,
            channel_mult=(1, 2),
            num_res_blocks=1,
            transformer_depth=(1, 1),
            context_dim=64,
            num_heads=2,
        ),
    },
    # tiny v-prediction variant (SD2.x-768-class parameterization):
    # exercises the v->eps conversion through every sampler path
    "tiny-unet-v": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=32,
            channel_mult=(1, 2),
            num_res_blocks=1,
            transformer_depth=(1, 1),
            context_dim=64,
            num_heads=2,
            parameterization="v",
        ),
    },
    # tiny SDXL-shaped variant: dual text encoders + pooled/size adm
    # conditioning (context 64+96, adm = 96 pooled + 6x256 size embs)
    "tiny-unet-adm": {
        "family": "unet",
        "config": UNetConfig(
            model_channels=32,
            channel_mult=(1, 2),
            num_res_blocks=1,
            transformer_depth=(1, 1),
            context_dim=160,
            num_heads=2,
            adm_in_channels=96 + 6 * 256,
        ),
    },
    # --- image MMDiT backbones (Flux checkpoint-faithful dims) ---
    # guidance-distilled dev config; flow_shift 3.0 ~= the published
    # dynamic shift at 1MP resolution
    "flux-dev": {
        "family": "mmdit",
        "config": MMDiTConfig(remat=True),
    },
    # timestep-distilled schnell: no guidance embedding, unshifted
    # schedule, 1-4 steps typical
    "flux-schnell": {
        "family": "mmdit",
        "config": MMDiTConfig(
            guidance_embed=False, flow_shift=1.0, remat=True
        ),
    },
    "tiny-flux": {
        "family": "mmdit",
        "config": MMDiTConfig(
            hidden_dim=32, double_depth=1, single_depth=1, heads=2,
            axes_dim=(4, 6, 6), context_dim=64, vec_dim=64,
            flow_shift=1.0,
        ),
    },
    # --- SD3-class image MMDiT (joint blocks, learned pos table) ---
    # SD3-medium (2B): depth 24 -> hidden 1536, no QK norm
    "sd3-medium": {
        "family": "sd3",
        "config": SD3Config(depth=24, remat=True),
    },
    # SD3.5-large (8B): depth 38, hidden 2432, per-head RMS QK norm
    "sd35-large": {
        "family": "sd3",
        "config": SD3Config(
            depth=38, hidden_dim=2432, heads=38, qk_norm=True, remat=True
        ),
    },
    # SD3.5-medium (2.5B, MMDiT-X): depth 24 -> hidden 1536, QK norm,
    # 384-wide learned pos table, and a second image-only attention
    # branch (attn2, 9-way adaLN) in the first 13 x_blocks
    "sd35-medium": {
        "family": "sd3",
        "config": SD3Config(
            depth=24, qk_norm=True, pos_embed_max=384,
            dual_attn_blocks=13, remat=True,
        ),
    },
    # tiny MMDiT-X: one dual-attention block + one plain, for hermetic
    # forward/schedule/golden coverage of the attn2 branch
    "tiny-sd35m": {
        "family": "sd3",
        "config": SD3Config(
            depth=2, hidden_dim=32, heads=2, context_dim=160,
            pooled_dim=160, pos_embed_max=32, qk_norm=True,
            dual_attn_blocks=1, flow_shift=1.0,
        ),
    },
    # tiny: context 160 = tiny CLIP-L(64) ++ CLIP-G(96) = T5 width;
    # pos table covers USDU's padded 96px tiles (latent 48 / patch 2)
    "tiny-sd3": {
        "family": "sd3",
        "config": SD3Config(
            depth=2, hidden_dim=32, heads=2, context_dim=160,
            pooled_dim=160, pos_embed_max=32, qk_norm=True,
            flow_shift=1.0,
        ),
    },
    # --- video DiT backbones (WAN 2.x checkpoint-faithful dims) ---
    "wan-1.3b": {
        "family": "dit",
        "config": DiTConfig(
            hidden_dim=1536, ffn_dim=8960, depth=30, heads=12, context_dim=4096
        ),
    },
    "wan-14b": {
        "family": "dit",
        "config": DiTConfig(
            hidden_dim=5120, ffn_dim=13824, depth=40, heads=40, context_dim=4096
        ),
    },
    "tiny-dit": {
        "family": "dit",
        "config": DiTConfig(hidden_dim=64, depth=2, heads=2, context_dim=64),
    },
    # i2v variants: [noise 16 | mask 4 | cond latent 16] = 36 input
    # channels, 16 output; image cross-attention branch over CLIP
    # ViT-H penultimate tokens (WAN 2.x i2v checkpoint layout)
    "wan-14b-i2v": {
        "family": "dit",
        "config": DiTConfig(
            hidden_dim=5120, ffn_dim=13824, depth=40, heads=40,
            context_dim=4096, in_channels=36, out_channels=16, i2v=True,
        ),
    },
    "tiny-dit-i2v": {
        "family": "dit",
        "config": DiTConfig(
            hidden_dim=64, depth=2, heads=2, context_dim=64,
            in_channels=36, out_channels=16, i2v=True, img_dim=48,
        ),
    },
    # --- VAEs ---
    "vae-sd": {"family": "vae", "config": VAEConfig()},
    # 16-channel latent 2D VAE (per-frame fallback for the WAN-class
    # DiT latent space; the real WAN VAE is wan-vae below)
    "vae-video": {
        "family": "vae",
        "config": VAEConfig(latent_channels=16, scaling_factor=1.0),
    },
    # causal 3D WAN VAE: 8x spatial / 4x temporal, 4n+1 frame contract.
    # latents_mean/std are the fixed per-channel constants the official
    # Wan2.1 wrapper normalizes with before the DiT.
    "wan-vae": {
        "family": "video_vae",
        "config": VideoVAEConfig(
            latents_mean=(
                -0.7571, -0.7089, -0.9113, 0.1075, -0.1745, 0.9653,
                -0.1517, 1.5508, 0.4134, -0.0715, 0.5517, -0.3632,
                -0.1922, -0.9497, 0.2503, -0.2921,
            ),
            latents_std=(
                2.8184, 1.4541, 2.3275, 2.5017, 2.3632, 2.0435,
                3.3086, 3.0723, 2.0365, 1.9887, 2.6244, 2.0905,
                2.3852, 1.4049, 2.5648, 2.7630,
            ),
        ),
    },
    "tiny-video-vae-3d": {
        "family": "video_vae",
        "config": VideoVAEConfig(
            base_dim=16, dim_mult=(1, 2), num_res_blocks=1,
            temporal_down=(True,),
        ),
    },
    # Flux-class 16-channel AE: (mean - shift) * scale boundary, no
    # 1x1 quant convs in the published layout
    "vae-flux": {
        "family": "vae",
        "config": VAEConfig(
            latent_channels=16, scaling_factor=0.3611, shift_factor=0.1159,
            use_quant_conv=False,
        ),
    },
    "tiny-vae-flux": {
        "family": "vae",
        "config": VAEConfig(
            base_channels=16, channel_mult=(1, 2), num_res_blocks=1,
            latent_channels=16, scaling_factor=0.3611, shift_factor=0.1159,
            use_quant_conv=False,
        ),
    },
    # SD3-class 16ch AE: scale 1.5305, shift 0.0609, no quant convs
    "vae-sd3": {
        "family": "vae",
        "config": VAEConfig(
            latent_channels=16, scaling_factor=1.5305, shift_factor=0.0609,
            use_quant_conv=False,
        ),
    },
    "tiny-vae-sd3": {
        "family": "vae",
        "config": VAEConfig(
            base_channels=16, channel_mult=(1, 2), num_res_blocks=1,
            latent_channels=16, scaling_factor=1.5305, shift_factor=0.0609,
            use_quant_conv=False,
        ),
    },
    "tiny-vae": {
        "family": "vae",
        "config": VAEConfig(base_channels=16, channel_mult=(1, 2), num_res_blocks=1),
    },
    "tiny-vae-video": {
        "family": "vae",
        "config": VAEConfig(
            base_channels=16, channel_mult=(1, 2), num_res_blocks=1,
            latent_channels=16, scaling_factor=1.0,
        ),
    },
    # --- text encoders ---
    "clip-l": {"family": "text_encoder", "config": TextEncoderConfig()},
    # SDXL pair: CLIP-L penultimate + OpenCLIP bigG penultimate w/
    # text projection (pooled source)
    "clip-l-sdxl": {
        "family": "text_encoder",
        "config": TextEncoderConfig(penultimate_hidden=True),
    },
    # SD3's CLIP-L half: penultimate hidden + PROJECTED pooled (the
    # files bundle CLIPTextModelWithProjection with a 768x768 table)
    "clip-l-sd3": {
        "family": "text_encoder",
        "config": TextEncoderConfig(penultimate_hidden=True, proj_dim=768),
    },
    "clip-g": {
        "family": "text_encoder",
        "config": TextEncoderConfig(
            width=1280, layers=32, heads=20, activation="gelu",
            penultimate_hidden=True, proj_dim=1280,
            pad_token_id=0,  # open_clip.tokenize pads with 0, not EOS
        ),
    },
    # OpenCLIP ViT-H/14 text tower (SD2.x conditioning; packed under
    # cond_stage_model.model.* in SD2 single-file checkpoints).
    # final_ln_on_hidden: SD2 norms the penultimate context (ComfyUI
    # SD2ClipHModel layer_norm_hidden_state=True); SDXL's bigG doesn't.
    "clip-h": {
        "family": "text_encoder",
        "config": TextEncoderConfig(
            width=1024, layers=24, heads=16, activation="gelu",
            penultimate_hidden=True, proj_dim=1024,
            final_ln_on_hidden=True, pad_token_id=0,
        ),
    },
    "tiny-te": {
        "family": "text_encoder",
        "config": TextEncoderConfig(width=64, layers=2, heads=2, max_length=16),
    },
    # tiny SDXL-shaped dual pair (concat width 64+96=160)
    "tiny-te-l": {
        "family": "text_encoder",
        "config": TextEncoderConfig(
            width=64, layers=2, heads=2, max_length=16, penultimate_hidden=True
        ),
    },
    "tiny-te-g": {
        "family": "text_encoder",
        "config": TextEncoderConfig(
            width=96, layers=2, heads=2, max_length=16, activation="gelu",
            penultimate_hidden=True, proj_dim=96, pad_token_id=0,
        ),
    },
    # --- T5-class encoders (WAN conditioning; UMT5-XXL dims) ---
    "umt5-xxl": {
        "family": "t5_encoder",
        "config": T5EncoderConfig(
            d_model=4096, d_ff=10240, layers=24, heads=64, d_kv=64,
        ),
    },
    # classic T5 v1.1 XXL (the Flux text encoder): stack-shared
    # relative-position bias, sentencepiece vocab 32128
    "t5-xxl": {
        "family": "t5_encoder",
        "config": T5EncoderConfig(
            vocab_size=32128, d_model=4096, d_ff=10240, layers=24,
            heads=64, d_kv=64, per_layer_rel_bias=False,
        ),
    },
    # SD3's T5 slot: same weights, 77-token padding (the reference
    # stack pads T5 to 77 for SD3; Flux uses the long padding)
    "t5-xxl-sd3": {
        "family": "t5_encoder",
        "config": T5EncoderConfig(
            vocab_size=32128, d_model=4096, d_ff=10240, layers=24,
            heads=64, d_kv=64, per_layer_rel_bias=False, max_length=77,
        ),
    },
    # tiny T5 at the tiny-SD3 context width (160 = tiny CLIP concat)
    "tiny-t5-sd3": {
        "family": "t5_encoder",
        "config": T5EncoderConfig(
            vocab_size=49408, d_model=160, d_ff=320, layers=2, heads=2,
            d_kv=32, max_length=16, per_layer_rel_bias=False,
        ),
    },
    # tiny shared-bias variant (Flux layout) for hermetic tests; vocab
    # covers the CLIP-BPE fallback id space like tiny-t5
    "tiny-t5-shared": {
        "family": "t5_encoder",
        "config": T5EncoderConfig(
            vocab_size=49408, d_model=64, d_ff=128, layers=2, heads=2,
            d_kv=32, max_length=16, per_layer_rel_bias=False,
        ),
    },
    # tiny variant: vocab covers the CLIP-BPE fallback id space so the
    # placeholder tokenizer can't index out of the embedding table
    "tiny-t5": {
        "family": "t5_encoder",
        "config": T5EncoderConfig(
            vocab_size=49408, d_model=64, d_ff=128, layers=2, heads=2,
            d_kv=32, max_length=16,
        ),
    },
    # --- CLIP vision towers (WAN i2v image conditioning; ViT-H/14) ---
    "clip-vision-h": {
        "family": "clip_vision",
        "config": ClipVisionConfig(),
    },
    "tiny-clip-vision": {
        "family": "clip_vision",
        "config": ClipVisionConfig(
            image_size=32, patch_size=8, width=48, layers=3, heads=2,
        ),
    },
}

# Models whose conditioning comes from TWO encoders (SDXL layout):
# context = concat(hidden_1, hidden_2); pooled = projected pooled_2.
DUAL_TEXT_ENCODERS: dict[str, tuple[str, str]] = {
    "sdxl": ("clip-l-sdxl", "clip-g"),
    "tiny-unet-adm": ("tiny-te-l", "tiny-te-g"),
}

# Single-encoder models whose default differs from the CLIP-L fallback.
DEFAULT_TEXT_ENCODERS: dict[str, str] = {
    "sd21": "clip-h",
    "sd21-base": "clip-h",
}

# Flux-layout conditioning: hidden states from a T5-class encoder,
# pooled vector from a CLIP-class encoder — no concat, no padding
# (models/pipeline._encode_raw).
HIDDEN_POOLED_ENCODERS: dict[str, tuple[str, str]] = {
    "flux-dev": ("t5-xxl", "clip-l"),
    "flux-schnell": ("t5-xxl", "clip-l"),
    "tiny-flux": ("tiny-t5-shared", "tiny-te"),
}

# SD3-layout conditioning: (CLIP-L, CLIP-G, T5) — CLIP hiddens concat
# on features, zero-pad to the T5 width, sequence-concat with T5;
# pooled = CLIP-L pooled ++ CLIP-G pooled (models/pipeline._encode_raw).
TRIPLE_TEXT_ENCODERS: dict[str, tuple[str, str, str]] = {
    "sd3-medium": ("clip-l-sd3", "clip-g", "t5-xxl-sd3"),
    "sd35-large": ("clip-l-sd3", "clip-g", "t5-xxl-sd3"),
    "sd35-medium": ("clip-l-sd3", "clip-g", "t5-xxl-sd3"),
    "tiny-sd3": ("tiny-te-l", "tiny-te-g", "tiny-t5-sd3"),
    "tiny-sd35m": ("tiny-te-l", "tiny-te-g", "tiny-t5-sd3"),
}

_CONSTRUCTORS: dict[str, Callable[[Any], Any]] = {
    "unet": lambda cfg: UNet(cfg),
    "dit": lambda cfg: VideoDiT(cfg),
    "mmdit": lambda cfg: MMDiT(cfg),
    "sd3": lambda cfg: SD3MMDiT(cfg),
    "vae": lambda cfg: VAE(cfg),
    "text_encoder": lambda cfg: TextEncoder(cfg),
    "t5_encoder": lambda cfg: T5Encoder(cfg),
    "clip_vision": lambda cfg: ClipVisionEncoder(cfg),
    "video_vae": lambda cfg: VideoVAE(cfg),
}


def model_family(name: str) -> str:
    return _entry(name)["family"]


def _entry(name: str) -> dict[str, Any]:
    if name not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[name]


def get_config(name: str) -> Any:
    return _entry(name)["config"]


def create_model(name: str) -> Any:
    entry = _entry(name)
    return _CONSTRUCTORS[entry["family"]](entry["config"])
