"""Causal 3D video VAE (WAN class), flax.linen.

The temporal-compression VAE behind the reference's WAN workflows
(loaded there via ComfyUI's VAELoader; reference
workflows/distributed-wan*.json): 3D *causal* convolutions (temporal
pads look backward only), 8x spatial and 4x temporal compression with
the WAN frame contract `T_latent = (T - 1) / 4 + 1` (the 4n+1 batch
rule the reference's USDU node validates), RMS-normed residual blocks,
single-head spatial mid attention, and 16 latent channels matching the
WAN DiT.

The module tree mirrors the official Wan2.1 VAE state dict
(`encoder.downsamples.N.residual.*`, `decoder.upsamples.N.*`,
`middle.{0,1,2}`, `head.{0,2}`, quant convs `conv1`/`conv2`) so real
checkpoints map key-by-key via sd_checkpoint.wan_vae_schedule.

Whole-clip processing: for the plain causal convolutions, zero
temporal front-pads over the full clip compute the same function the
original's streaming feature-cache computes chunk-by-chunk (the cache
merely carries the previous chunk's trailing frames).  The Resample
time convs are the exception — their first chunk is *cached, not
convolved* — so the clip-boundary semantics are reproduced
explicitly: in downsample3d, frame 0 bypasses the temporal conv
(identity) and windows start at [x0,x1,x2]; in upsample3d, z0 is
emitted un-doubled and never enters a conv window (its slot reads as
zeros — the original marks the first chunk 'Rep' and later prepends
zeros, never z0).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class VideoVAEConfig:
    base_dim: int = 96
    z_dim: int = 16
    dim_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    # which encoder levels also downsample time (WAN: last two of the
    # three resample stages); decoder mirrors in reverse
    temporal_down: tuple[bool, ...] = (False, True, True)
    # per-channel latent normalization (the WAN wrapper's mean/std
    # vectors); None = identity. Supply alongside real weights.
    latents_mean: tuple[float, ...] | None = None
    latents_std: tuple[float, ...] | None = None
    dtype: str = "float32"

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.dim_mult) - 1)

    @property
    def temporal_downscale(self) -> int:
        return 2 ** sum(self.temporal_down)

    @property
    def latent_channels(self) -> int:
        return self.z_dim


class _CausalConv3d(nn.Module):
    """Conv3d whose temporal receptive field looks backward only:
    front-pad (kt-1) zeros, valid temporally, SAME spatially."""

    features: int
    kernel: tuple[int, int, int] = (3, 3, 3)
    strides: tuple[int, int, int] = (1, 1, 1)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kt, kh, kw = self.kernel
        pads = (
            (0, 0),
            (kt - 1, 0),
            (kh // 2, kh // 2),
            (kw // 2, kw // 2),
            (0, 0),
        )
        x = jnp.pad(x, pads)
        return nn.Conv(
            self.features, self.kernel, strides=self.strides,
            padding="VALID", dtype=self.dtype, name="conv",
        )(x)


class _RMSNormChannels(nn.Module):
    """WAN VAE RMS_norm: F.normalize over the channel dim * sqrt(C) *
    per-channel gamma."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        gamma = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        unit = xf * jax.lax.rsqrt(
            jnp.sum(xf * xf, axis=-1, keepdims=True) + 1e-12
        )
        return unit * jnp.sqrt(jnp.asarray(x.shape[-1], jnp.float32)) * gamma


class _ResBlock3d(nn.Module):
    """WAN ResidualBlock: RMS → SiLU → causal conv → RMS → SiLU →
    causal conv, 1x1x1 causal shortcut on channel change. Child names
    match the Sequential indices of the original (residual.0/2/3/6)."""

    out_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = _RMSNormChannels(name="residual_0")(x)
        h = _CausalConv3d(self.out_dim, dtype=self.dtype, name="residual_2")(
            nn.silu(h).astype(self.dtype)
        )
        h = _RMSNormChannels(name="residual_3")(h)
        h = _CausalConv3d(self.out_dim, dtype=self.dtype, name="residual_6")(
            nn.silu(h).astype(self.dtype)
        )
        if x.shape[-1] != self.out_dim:
            x = _CausalConv3d(
                self.out_dim, kernel=(1, 1, 1), dtype=self.dtype,
                name="shortcut",
            )(x)
        return x + h


class _SpatialAttention(nn.Module):
    """WAN AttentionBlock: single-head per-frame spatial attention with
    1x1 conv qkv/proj."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, f, hh, ww, c = x.shape
        h = _RMSNormChannels(name="norm")(x)
        qkv = nn.Conv(3 * c, (1, 1), dtype=jnp.float32, name="to_qkv")(
            h.reshape(b * f, hh, ww, c)
        ).reshape(b * f, hh * ww, 3, c)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = jax.nn.softmax(
            jnp.einsum("bnc,bmc->bnm", q, k) / jnp.sqrt(float(c)), axis=-1
        )
        out = jnp.einsum("bnm,bmc->bnc", attn, v).reshape(b * f, hh, ww, c)
        out = nn.Conv(c, (1, 1), dtype=jnp.float32, name="proj")(out)
        return x + out.reshape(b, f, hh, ww, c)


class _Downsample(nn.Module):
    """WAN Resample (downsample2d/3d): zero-pad right/bottom + stride-2
    spatial conv; 3d then applies a stride-2 causal temporal conv whose
    first output frame is the cache-bypass identity (the original's
    streaming path only caches the first chunk, it never convolves
    it)."""

    dim: int
    temporal: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, f, hh, ww, c = x.shape
        flat = x.reshape(b * f, hh, ww, c)
        flat = jnp.pad(flat, ((0, 0), (0, 1), (0, 1), (0, 0)))
        flat = nn.Conv(
            self.dim, (3, 3), strides=(2, 2), padding="VALID",
            dtype=self.dtype, name="resample_1",
        )(flat)
        x = flat.reshape((b, f) + flat.shape[1:])
        if self.temporal:
            y = _CausalConv3d(
                self.dim, kernel=(3, 1, 1), strides=(2, 1, 1),
                dtype=self.dtype, name="time_conv",
            )(x)
            # Drop the [0,0,x0] window; frame 0 passes through untouched.
            x = jnp.concatenate([x[:, :1], y[:, 1:]], axis=1)
        return x


class _Upsample(nn.Module):
    """WAN Resample (upsample2d/3d): 2x nearest spatial + conv to
    dim//2; 3d first doubles frames 1..L-1 via a 2C time_conv whose
    channel pairs interleave into frame pairs, while z0 is emitted
    un-doubled and excluded from every conv window (the original's
    'Rep' cache marker: the first chunk passes through untouched and
    later windows see zeros in its slot, never z0)."""

    dim: int
    temporal: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, f, hh, ww, c = x.shape
        if self.temporal:
            t = _CausalConv3d(
                self.dim * 2, kernel=(3, 1, 1), dtype=self.dtype,
                name="time_conv",
            )(x.at[:, 0].set(0.0))
            t = t[:, 1:]  # the z0 window produces no frames
            t = t.reshape(b, f - 1, hh, ww, 2, self.dim)
            doubled = t.transpose(0, 1, 4, 2, 3, 5).reshape(
                b, 2 * (f - 1), hh, ww, self.dim
            )
            x = jnp.concatenate([x[:, :1].astype(doubled.dtype), doubled],
                                axis=1)
            f = x.shape[1]
            c = self.dim
        flat = x.reshape(b * f, hh, ww, c)
        flat = jax.image.resize(
            flat, (b * f, hh * 2, ww * 2, c), method="nearest"
        )
        flat = nn.Conv(
            self.dim // 2, (3, 3), dtype=self.dtype, name="resample_1",
        )(flat)
        return flat.reshape((b, f) + flat.shape[1:])


class VideoEncoder(nn.Module):
    config: VideoVAEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        dims = [cfg.base_dim * m for m in (1,) + tuple(cfg.dim_mult)]
        x = _CausalConv3d(dims[0], dtype=dt, name="conv1")(x.astype(dt))
        idx = 0
        for level in range(len(cfg.dim_mult)):
            out_dim = dims[level + 1]
            for _ in range(cfg.num_res_blocks):
                x = _ResBlock3d(out_dim, dtype=dt, name=f"down_{idx}")(x)
                idx += 1
            if level != len(cfg.dim_mult) - 1:
                x = _Downsample(
                    out_dim, temporal=cfg.temporal_down[level], dtype=dt,
                    name=f"down_{idx}",
                )(x)
                idx += 1
        x = _ResBlock3d(dims[-1], dtype=dt, name="middle_0")(x)
        x = _SpatialAttention(name="middle_1")(x)
        x = _ResBlock3d(dims[-1], dtype=dt, name="middle_2")(x)
        x = _RMSNormChannels(name="head_0")(x)
        return _CausalConv3d(
            2 * cfg.z_dim, dtype=jnp.float32, name="head_2"
        )(nn.silu(x).astype(jnp.float32))


class VideoDecoder(nn.Module):
    config: VideoVAEConfig

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        rev = tuple(reversed(cfg.dim_mult))
        dims = [cfg.base_dim * m for m in (rev[0],) + rev]
        temporal_up = tuple(reversed(cfg.temporal_down))
        x = _CausalConv3d(dims[0], dtype=dt, name="conv1")(z.astype(dt))
        x = _ResBlock3d(dims[0], dtype=dt, name="middle_0")(x)
        x = _SpatialAttention(name="middle_1")(x)
        x = _ResBlock3d(dims[0], dtype=dt, name="middle_2")(x)
        idx = 0
        for level in range(len(cfg.dim_mult)):
            out_dim = dims[level + 1]
            for _ in range(cfg.num_res_blocks + 1):
                x = _ResBlock3d(out_dim, dtype=dt, name=f"up_{idx}")(x)
                idx += 1
            if level != len(cfg.dim_mult) - 1:
                x = _Upsample(
                    out_dim, temporal=temporal_up[level], dtype=dt,
                    name=f"up_{idx}",
                )(x)
                idx += 1
        x = _RMSNormChannels(name="head_0")(x)
        return _CausalConv3d(3, dtype=jnp.float32, name="head_2")(
            nn.silu(x).astype(jnp.float32)
        )


class VideoVAE(nn.Module):
    """encode: [B, F, H, W, 3] (F = 4n+1) → [B, (F-1)/4+1, H/8, W/8, z];
    decode inverts. Latents are mean-of-gaussian (deterministic) with
    optional per-channel normalization."""

    config: VideoVAEConfig

    def setup(self):
        cfg = self.config
        self.encoder = VideoEncoder(cfg)
        self.decoder = VideoDecoder(cfg)
        # WAN quant convs (1x1x1)
        self.conv1 = _CausalConv3d(2 * cfg.z_dim, kernel=(1, 1, 1), name="conv1_q")
        self.conv2 = _CausalConv3d(cfg.z_dim, kernel=(1, 1, 1), name="conv2_q")

    def _norm(self, z: jax.Array, inverse: bool) -> jax.Array:
        cfg = self.config
        if cfg.latents_mean is None or cfg.latents_std is None:
            return z
        mean = jnp.asarray(cfg.latents_mean, z.dtype)
        std = jnp.asarray(cfg.latents_std, z.dtype)
        return z * std + mean if inverse else (z - mean) / std

    def encode(self, x: jax.Array) -> jax.Array:
        if (x.shape[1] - 1) % self.config.temporal_downscale != 0:
            raise ValueError(
                f"frame count {x.shape[1]} must be "
                f"{self.config.temporal_downscale}n+1 (WAN causal contract)"
            )
        moments = self.conv1(self.encoder(x * 2.0 - 1.0))
        mean = moments[..., : self.config.z_dim]
        return self._norm(mean, inverse=False)

    def decode(self, z: jax.Array) -> jax.Array:
        z = self._norm(z, inverse=True)
        x = self.decoder(self.conv2(z))
        return jnp.clip((x + 1.0) / 2.0, 0.0, 1.0)

    def __call__(self, x: jax.Array) -> jax.Array:
        return self.decode(self.encode(x))
