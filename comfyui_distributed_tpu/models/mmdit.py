"""Image multimodal diffusion transformer (Flux class), flax.linen.

The rectified-flow image family the reference serves through ComfyUI's
model zoo (its conditioning utilities explicitly handle Flux-class
`reference_latents`, reference utils/usdu_utils.py crop_cond), rebuilt
TPU-native and *checkpoint-faithful* to the published Flux layout:

- 2x2 patchified 16-channel latents and T5 text tokens run as two
  streams through `double_blocks` (separate modulation/attention/MLP
  params, one joint attention over [txt; img]), then concatenated
  through fused `single_blocks` (qkv+MLP in one linear pair);
- per-head RMS Q/K norm (query_norm/key_norm.scale over head_dim —
  unlike WAN's full-width norms, dit.py);
- 3-axis rotary embeddings with an explicit per-axis frequency budget
  (`axes_dim`, default 16/56/56 of head_dim 128): text tokens sit at
  position 0 of every axis, image tokens at (0, y, x);
- conditioning vector = time MLP + CLIP pooled MLP (+ distilled
  guidance MLP when `guidance_embed`), modulating every block (adaLN)
  and the final layer.

Flax submodule names mirror the original state-dict keys
(double_blocks_N/img_attn_qkv ↔ double_blocks.N.img_attn.qkv, ...) so
the key schedule in sd_checkpoint stays a straight rename.

The model predicts rectified-flow velocity v = noise - x0; with the
sampler eps contract (denoised = x - sigma*eps) v IS eps, so the
deterministic k-diffusion samplers (euler, ddim, heun, dpmpp_2m, ...)
apply unchanged — models/pipeline.py selects the flow sigma schedule
and interpolation noising via `parameterization == "flow"`. Stochastic
renoising is a different story: the VE rule (x += noise*sigma_up) is
off the flow marginal x_t = (1-s)x0 + s*n, so ops/samplers.sample
routes euler_ancestral to an RF-correct rule and rejects the other
stochastic samplers for flow models.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .layers import timestep_embedding
from .dit import _axis_freqs, apply_rope
from ..ops.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class MMDiTConfig:
    in_channels: int = 16          # VAE latent channels
    patch_size: int = 2
    hidden_dim: int = 3072
    double_depth: int = 19
    single_depth: int = 38
    heads: int = 24
    # rope frequency budget per (const, y, x) axis; must sum to head_dim
    axes_dim: tuple[int, int, int] = (16, 56, 56)
    context_dim: int = 4096        # T5 hidden width
    vec_dim: int = 768             # CLIP pooled width
    mlp_ratio: float = 4.0
    freq_dim: int = 256            # sinusoidal embedding width
    theta: float = 10000.0
    # guidance-distilled variants (flux-dev) embed the guidance scale;
    # schnell-class models don't
    guidance_embed: bool = True
    guidance_default: float = 3.5
    # rectified flow: pipeline selects flow sigmas + interpolation
    # noising off this marker (models/pipeline.py, ops/samplers.py)
    parameterization: str = "flow"
    # static timestep-shift of the flow schedule (t' = s*t/(1+(s-1)t));
    # ~= the 1MP-resolution shift of the published dev config
    flow_shift: float = 3.0
    dtype: str = "bfloat16"
    remat: bool = False

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def head_dim(self) -> int:
        return self.hidden_dim // self.heads

    @property
    def adm_in_channels(self) -> int:
        """Hooks the pooled-text plumbing in pipeline._make_model_fn:
        the CLIP pooled vector feeds vector_in."""
        return self.vec_dim

    @property
    def mlp_width(self) -> int:
        return int(self.hidden_dim * self.mlp_ratio)


def rope_freqs_image(
    axes_dim: tuple[int, int, int],
    txt_len: int,
    gh: int,
    gw: int,
    theta: float = 10000.0,
    ref_grids: tuple[tuple[int, int], ...] = (),
) -> np.ndarray:
    """[txt_len + gh*gw + sum(ref), head_dim/2, 2] cos/sin table: text
    tokens at position 0 of every axis (identity rotation), image
    tokens at (0, y, x), and each reference-latent grid at
    (1 + ref_index, y, x) — the Flux / Flux-Kontext position-id
    convention (reference images are offset along the first axis)."""
    k0, kh, kw = axes_dim[0] // 2, axes_dim[1] // 2, axes_dim[2] // 2
    t0 = _axis_freqs(2 * k0, len(ref_grids) + 1, theta)

    def grid(g_h: int, g_w: int, idx0: int) -> np.ndarray:
        th = _axis_freqs(2 * kh, g_h, theta)
        tw = _axis_freqs(2 * kw, g_w, theta)
        return np.concatenate(
            [
                np.broadcast_to(t0[idx0][None, None], (g_h, g_w, k0, 2)),
                np.broadcast_to(th[:, None], (g_h, g_w, kh, 2)),
                np.broadcast_to(tw[None, :], (g_h, g_w, kw, 2)),
            ],
            axis=2,
        ).reshape(g_h * g_w, -1, 2)

    img = grid(gh, gw, 0)
    pairs = img.shape[1]
    txt = np.broadcast_to(
        np.stack([np.ones(pairs), np.zeros(pairs)], axis=-1)[None],
        (txt_len, pairs, 2),
    )
    sections = [txt, img] + [
        grid(rh, rw, i + 1) for i, (rh, rw) in enumerate(ref_grids)
    ]
    return np.concatenate(sections, axis=0)


class _MLPEmbedder(nn.Module):
    """Flux MLPEmbedder: in_layer → silu → out_layer (time_in /
    vector_in / guidance_in)."""

    width: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.Dense(self.width, dtype=jnp.float32, name="in_layer")(
            x.astype(jnp.float32)
        )
        return nn.Dense(self.width, dtype=jnp.float32, name="out_layer")(
            nn.silu(h)
        )


def _modulation(vec: jax.Array, n: int, width: int, name: str) -> list[jax.Array]:
    """silu(vec) → Dense(n*width) → n [B, 1, width] chunks (Flux
    Modulation; name maps <name>.lin)."""
    out = nn.Dense(n * width, dtype=jnp.float32, name=f"{name}_lin")(
        nn.silu(vec.astype(jnp.float32))
    )
    return [out[:, None, i * width:(i + 1) * width] for i in range(n)]


def _qk_norm(q: jax.Array, k: jax.Array, name: str) -> tuple[jax.Array, jax.Array]:
    """Per-head RMS norm over head_dim ([..., H, D] inputs); scale
    params are [D] — the Flux query_norm/key_norm.scale layout."""
    prefix = f"{name}_" if name else ""
    qn = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name=f"{prefix}norm_q")(q)
    kn = nn.RMSNorm(epsilon=1e-6, dtype=jnp.float32, name=f"{prefix}norm_k")(k)
    return qn, kn


class _DoubleBlock(nn.Module):
    """Flux DoubleStreamBlock: separate img/txt streams, one joint
    attention over [txt; img] tokens."""

    heads: int
    mlp_width: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(
        self,
        img: jax.Array,     # [B, Ni, H]
        txt: jax.Array,     # [B, Nt, H]
        vec: jax.Array,     # [B, H]
        freqs: jax.Array,   # [Nt+Ni, D/2, 2]
    ) -> tuple[jax.Array, jax.Array]:
        dim = img.shape[-1]
        hd = dim // self.heads
        b, ni, _ = img.shape
        nt = txt.shape[1]

        i_sh1, i_sc1, i_g1, i_sh2, i_sc2, i_g2 = _modulation(vec, 6, dim, "img_mod")
        t_sh1, t_sc1, t_g1, t_sh2, t_sc2, t_g2 = _modulation(vec, 6, dim, "txt_mod")

        def qkv(x, n, sh, sc, name):
            h = nn.LayerNorm(
                use_bias=False, use_scale=False, dtype=jnp.float32,
                name=f"{name}_norm1",
            )(x.astype(jnp.float32))
            h = (h * (1 + sc) + sh).astype(self.dtype)
            proj = nn.Dense(3 * dim, dtype=self.dtype, name=f"{name}_attn_qkv")(h)
            q, k, v = jnp.split(proj, 3, axis=-1)
            q = q.reshape(b, n, self.heads, hd)
            k = k.reshape(b, n, self.heads, hd)
            v = v.reshape(b, n, self.heads, hd)
            q, k = _qk_norm(q, k, f"{name}_attn")
            return q.astype(self.dtype), k.astype(self.dtype), v

        iq, ik, iv = qkv(img, ni, i_sh1, i_sc1, "img")
        tq, tk, tv = qkv(txt, nt, t_sh1, t_sc1, "txt")

        # joint attention, text tokens first (Flux token order)
        q = apply_rope(jnp.concatenate([tq, iq], axis=1), freqs)
        k = apply_rope(jnp.concatenate([tk, ik], axis=1), freqs)
        v = jnp.concatenate([tv, iv], axis=1)
        attn = dot_product_attention(q, k, v).reshape(b, nt + ni, dim)
        t_attn, i_attn = attn[:, :nt], attn[:, nt:]

        def stream(x, a, sh2, sc2, g1, g2, name):
            x = (
                x.astype(jnp.float32)
                + nn.Dense(dim, dtype=self.dtype, name=f"{name}_attn_proj")(
                    a
                ).astype(jnp.float32) * g1
            )
            h = nn.LayerNorm(
                use_bias=False, use_scale=False, dtype=jnp.float32,
                name=f"{name}_norm2",
            )(x)
            h = (h * (1 + sc2) + sh2).astype(self.dtype)
            h = nn.Dense(self.mlp_width, dtype=self.dtype, name=f"{name}_mlp_0")(h)
            h = nn.gelu(h, approximate=True)
            y = nn.Dense(dim, dtype=self.dtype, name=f"{name}_mlp_2")(h)
            return (x + y.astype(jnp.float32) * g2).astype(self.dtype)

        img = stream(img, i_attn, i_sh2, i_sc2, i_g1, i_g2, "img")
        txt = stream(txt, t_attn, t_sh2, t_sc2, t_g1, t_g2, "txt")
        return img, txt


class _SingleBlock(nn.Module):
    """Flux SingleStreamBlock: fused qkv+MLP linear over the
    concatenated [txt; img] stream."""

    heads: int
    mlp_width: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(
        self, x: jax.Array, vec: jax.Array, freqs: jax.Array
    ) -> jax.Array:
        dim = x.shape[-1]
        hd = dim // self.heads
        b, n, _ = x.shape

        sh, sc, gate = _modulation(vec, 3, dim, "modulation")
        h = nn.LayerNorm(
            use_bias=False, use_scale=False, dtype=jnp.float32, name="pre_norm"
        )(x.astype(jnp.float32))
        h = (h * (1 + sc) + sh).astype(self.dtype)
        fused = nn.Dense(
            3 * dim + self.mlp_width, dtype=self.dtype, name="linear1"
        )(h)
        qkv, mlp = fused[..., : 3 * dim], fused[..., 3 * dim:]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, n, self.heads, hd)
        k = k.reshape(b, n, self.heads, hd)
        v = v.reshape(b, n, self.heads, hd)
        q, k = _qk_norm(q, k, "")  # single_blocks.N.norm.{query,key}_norm
        q = apply_rope(q.astype(self.dtype), freqs)
        k = apply_rope(k.astype(self.dtype), freqs)
        attn = dot_product_attention(q, k, v).reshape(b, n, dim)
        out = nn.Dense(dim, dtype=self.dtype, name="linear2")(
            jnp.concatenate([attn, nn.gelu(mlp, approximate=True)], axis=-1)
        )
        return (x.astype(jnp.float32) + out.astype(jnp.float32) * gate).astype(
            x.dtype
        )


class MMDiT(nn.Module):
    config: MMDiTConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,           # [B, h, w, C] noisy latents (NHWC)
        timesteps: jax.Array,   # [B] flow time in [0, 1]
        context: jax.Array,     # [B, T, context_dim] T5 hidden states
        y: jax.Array | None = None,        # [B, vec_dim] CLIP pooled
        control: jax.Array | None = None,  # rejected (Flux ControlNet
        #                                    is a separate architecture)
        guidance: jax.Array | None = None,  # [B] distilled guidance
        ref_latents: list | None = None,   # Kontext: [B, h, w, C] each
    ) -> jax.Array:
        cfg = self.config
        dt = cfg.compute_dtype
        if control is not None:
            # silent no-op would waste the caller's ControlNet compute
            # and produce an uncontrolled image with no explanation
            raise ValueError(
                "Flux-class MMDiT has no ControlNet input path "
                "(Flux ControlNets are a separate architecture)"
            )
        b, hh, ww, c = x.shape
        p = cfg.patch_size
        assert hh % p == 0 and ww % p == 0, "patch misalign"
        assert sum(cfg.axes_dim) == cfg.head_dim, "axes_dim != head_dim"
        gh, gw = hh // p, ww // p
        ni = gh * gw

        def patchify(arr):
            bb, ah, aw, ac = arr.shape
            assert ah % p == 0 and aw % p == 0, "ref patch misalign"
            t = arr.reshape(bb, ah // p, p, aw // p, p, ac)
            return t.transpose(0, 1, 3, 5, 2, 4).reshape(
                bb, (ah // p) * (aw // p), ac * p * p
            )

        # 2x2 patchify; flatten order (c, ph, pw) matches the original
        # rearrange 'b c (h ph) (w pw) -> b (h w) (c ph pw)'
        img_in = nn.Dense(cfg.hidden_dim, dtype=dt, name="img_in")
        img = img_in(patchify(x).astype(dt))
        ref_grids: tuple = ()
        if ref_latents:
            # Flux-Kontext editing: reference latents ride as extra
            # image-stream tokens (same img_in projection, first rope
            # axis offset per reference); only the main image's tokens
            # are unpatchified at the output
            refs = []
            grids = []
            for r in ref_latents:
                # edge-pad odd ref grids to the patch multiple (the
                # parity behavior; the main latent stays strict)
                ph_pad = (-r.shape[1]) % p
                pw_pad = (-r.shape[2]) % p
                if ph_pad or pw_pad:
                    r = jnp.pad(
                        r, ((0, 0), (0, ph_pad), (0, pw_pad), (0, 0)),
                        mode="edge",
                    )
                grids.append((r.shape[1] // p, r.shape[2] // p))
                refs.append(img_in(patchify(r).astype(dt)))
            ref_grids = tuple(grids)
            img = jnp.concatenate([img] + refs, axis=1)
        txt = nn.Dense(cfg.hidden_dim, dtype=dt, name="txt_in")(
            context.astype(dt)
        )
        nt = txt.shape[1]

        # conditioning vector: time + pooled text (+ distilled guidance)
        vec = _MLPEmbedder(cfg.hidden_dim, name="time_in")(
            timestep_embedding(timesteps.astype(jnp.float32) * 1000.0, cfg.freq_dim)
        )
        if cfg.guidance_embed:
            g = (
                guidance
                if guidance is not None
                else jnp.full((b,), cfg.guidance_default, jnp.float32)
            )
            vec = vec + _MLPEmbedder(cfg.hidden_dim, name="guidance_in")(
                timestep_embedding(g.astype(jnp.float32) * 1000.0, cfg.freq_dim)
            )
        if y is None:
            y = jnp.zeros((b, cfg.vec_dim), jnp.float32)
        vec = vec + _MLPEmbedder(cfg.hidden_dim, name="vector_in")(y)

        freqs = jnp.asarray(
            rope_freqs_image(
                cfg.axes_dim, nt, gh, gw, cfg.theta, ref_grids=ref_grids
            ),
            jnp.float32,
        )

        double_cls = (
            nn.remat(_DoubleBlock, static_argnums=()) if cfg.remat else _DoubleBlock
        )
        single_cls = (
            nn.remat(_SingleBlock, static_argnums=()) if cfg.remat else _SingleBlock
        )
        for i in range(cfg.double_depth):
            img, txt = double_cls(
                cfg.heads, cfg.mlp_width, dt, name=f"double_blocks_{i}"
            )(img, txt, vec, freqs)
        stream = jnp.concatenate([txt, img], axis=1)
        for i in range(cfg.single_depth):
            stream = single_cls(
                cfg.heads, cfg.mlp_width, dt, name=f"single_blocks_{i}"
            )(stream, vec, freqs)
        img = stream[:, nt:nt + ni]  # reference tokens are dropped

        # final layer: adaLN (shift, scale) then linear to patch pixels
        sh, sc = _modulation(vec, 2, cfg.hidden_dim, "final_layer_adaLN")
        h = nn.LayerNorm(
            use_bias=False, use_scale=False, dtype=jnp.float32
        )(img.astype(jnp.float32))
        h = h * (1 + sc) + sh
        out = nn.Dense(
            c * p * p, dtype=jnp.float32, name="final_layer_linear"
        )(h)
        out = out.reshape(b, gh, gw, c, p, p)
        out = out.transpose(0, 1, 4, 2, 5, 3).reshape(b, hh, ww, c)
        return out
