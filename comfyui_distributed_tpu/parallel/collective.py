"""Collective result collection — the in-slice collector.

The reference's DistributedCollector moves every worker's images to the
master as base64 PNG over HTTP (nodes/collector.py:84-119). Inside a
pod slice that entire path collapses into an all-gather over ICI: each
participant's batch lives sharded along the data axis, and "collection"
is materialising the global array (ordered master-first by construction
— participant 0 is the master's mesh index).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from .mesh import DATA_AXIS


def all_gather_batch(x: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    """Inside shard_map: gather every participant's batch, concatenated
    along the leading axis in participant order."""
    return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)


def psum_scalar(x: jax.Array, axis_name: str = DATA_AXIS) -> jax.Array:
    return jax.lax.psum(x, axis_name)


def host_collect(sharded: jax.Array) -> np.ndarray:
    """Materialise a (possibly sharded) global array on the host.

    Single-process: device_get handles cross-device gathering over ICI.
    Multi-process meshes require fully-addressable arrays; callers on
    multihost meshes should keep outputs replicated or use
    multihost_utils.process_allgather (gated: not needed single-host).
    """
    import time

    from ..telemetry.profiling import D2H, ledger_if_enabled

    started = time.monotonic()
    if not sharded.is_fully_addressable:
        from jax.experimental import multihost_utils

        host = np.asarray(multihost_utils.process_allgather(sharded, tiled=True))
    else:
        host = np.asarray(jax.device_get(sharded))
    ledger = ledger_if_enabled()
    if ledger is not None:
        ledger.note_transfer(
            D2H, int(host.nbytes), time.monotonic() - started
        )
    return host


def reorder_participant_first(
    batches: dict[int, Any], enabled_order: list[int]
) -> list[Any]:
    """Deterministic ordering for the elastic (HTTP) tier: master (index
    0) first, then enabled workers in configured order, then stragglers
    sorted — parity with nodes/collector.py:193-236."""
    ordered: list[Any] = []
    seen: set[int] = set()
    for idx in [0, *enabled_order]:
        if idx in batches and idx not in seen:
            ordered.append(batches[idx])
            seen.add(idx)
    for idx in sorted(batches):
        if idx not in seen:
            ordered.append(batches[idx])
            seen.add(idx)
    return ordered
