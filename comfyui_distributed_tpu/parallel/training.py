"""Sharded diffusion training step (DP over data axis, FSDP over model
axis).

Beyond-reference capability (the reference explicitly pools no memory,
reference README.md:187-188): WAN-14B-class backbones train/fine-tune
with parameters FSDP-sharded across the model axis and the batch
data-parallel across participants, per the BASELINE.md config matrix
(wan-2.2 14B FSDP on v5p-16). Written pjit-style: shardings annotate
inputs/outputs, XLA inserts the all-gathers / reduce-scatters /
gradient psums over ICI.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import samplers as smp
from .mesh import DATA_AXIS
from .sharding import shard_params


@dataclasses.dataclass
class TrainState:
    params: Any
    step: int = 0


def make_train_step(model: Any, mesh: Mesh, learning_rate: float = 1e-4):
    """Build a jitted SGD denoising-loss step.

    batch = {"latents": [B,...,C], "t": [B], "context": [B,T,D],
    "noise": [B,...,C]} with B sharded over the data axis; params
    FSDP-sharded over the model axis. Returns (params, loss) with
    params kept in their sharded placement.
    """

    def step(params, batch):
        sigmas = jnp.take(
            jnp.asarray(smp._vp_sigmas(), dtype=jnp.float32),
            batch["t"].astype(jnp.int32),
        )
        sig = sigmas.reshape((-1,) + (1,) * (batch["latents"].ndim - 1))
        x_noisy = batch["latents"] + batch["noise"] * sig
        c_in = 1.0 / jnp.sqrt(sig**2 + 1.0)

        def loss_fn(p):
            pred = model.apply(p, x_noisy * c_in, batch["t"], batch["context"])
            return jnp.mean((pred.astype(jnp.float32) - batch["noise"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g.astype(p.dtype)), params, grads
        )
        return new_params, loss

    @partial(jax.jit, donate_argnums=(0,))
    def jit_step(params, batch):
        return step(params, batch)

    def run(params, batch):
        # Place inputs: params FSDP, batch data-parallel, context/t follow batch.
        placed_params = shard_params(params, mesh)
        data_sharding = {
            "latents": NamedSharding(mesh, P(DATA_AXIS)),
            "t": NamedSharding(mesh, P(DATA_AXIS)),
            "context": NamedSharding(mesh, P(DATA_AXIS)),
            "noise": NamedSharding(mesh, P(DATA_AXIS)),
        }
        placed_batch = {
            k: jax.device_put(v, data_sharding[k]) for k, v in batch.items()
        }
        return jit_step(placed_params, placed_batch)

    return run
