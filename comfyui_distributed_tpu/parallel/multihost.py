"""Multi-host (pod) initialization over DCN.

The reference scales across hosts by running independent ComfyUI
processes and shipping PNGs over HTTP; a TPU pod instead joins all
hosts into one JAX runtime: `jax.distributed.initialize` connects
processes over DCN, after which `jax.devices()` spans the pod and the
same mesh/sharding code paths drive ICI within a host and DCN across
hosts. The elastic HTTP tier then treats the whole pod as ONE
participant.

Configuration via env (set by the pod launcher) or explicit args:
    CDT_COORDINATOR        host:port of process 0
    CDT_NUM_PROCESSES      total process count
    CDT_PROCESS_ID         this process's index
On Cloud TPU pods, bare `jax.distributed.initialize()` autodetects
from the TPU metadata; that path is used when no env/args are given
but CDT_MULTIHOST=1 is set.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.logging import log

_initialized = False


def maybe_init_multihost(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the distributed runtime if configured; returns True
    when multi-host mode is active. Safe to call more than once."""
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("CDT_COORDINATOR")
    num_str = os.environ.get("CDT_NUM_PROCESSES")
    pid_str = os.environ.get("CDT_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(num_str) if num_str else None
    )
    process_id = process_id if process_id is not None else (
        int(pid_str) if pid_str else None
    )

    import jax

    if coordinator and num_processes is not None and process_id is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
        log(
            f"multi-host runtime up: process {process_id}/{num_processes} "
            f"via {coordinator}; {jax.device_count()} global device(s)"
        )
        return True
    if os.environ.get("CDT_MULTIHOST") == "1":
        # Cloud TPU pod autodetection path
        jax.distributed.initialize()
        _initialized = True
        log(
            f"multi-host runtime up (autodetected): process "
            f"{jax.process_index()}/{jax.process_count()}"
        )
        return True
    return False


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1
