"""Seed-parallel distributed generation over the mesh.

This is the reference's headline feature — "generate multiple images
in the time it takes to generate one" via workflow replication with
per-worker seed offsets and an HTTP collector (reference
README.md:84-85, nodes/utilities.py DistributedSeed,
nodes/collector.py) — collapsed into a single SPMD program: every
mesh participant renders from a fold_in-derived key, and the collector
is the output sharding itself (participant-ordered along the leading
batch axis). No prompt rewriting, no HTTP, no base64.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import pipeline as pl
from ..ops import samplers as smp
from .mesh import DATA_AXIS, data_axis_size, shard_map_compat
from .seeds import participant_keys


@partial(
    jax.jit,
    static_argnames=(
        "bundle_static", "mesh_static", "height", "width", "steps", "sampler",
        "scheduler", "cfg_scale", "batch_per_device",
    ),
)
def _parallel_txt2img_jit(
    bundle_static,
    mesh_static,
    params,
    keys,            # [n_participants] stacked PRNG keys
    context_pos,     # [batch, T, D] (replicated; same prompt everywhere)
    context_neg,
    height: int,
    width: int,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg_scale: float,
    batch_per_device: int,
):
    bundle = bundle_static.value
    mesh = mesh_static.value
    param, shift = pl.model_schedule_info(bundle)
    sigmas = smp.get_model_sigmas(param, scheduler, steps, flow_shift=shift)
    lh, lw = height // bundle.latent_scale, width // bundle.latent_scale
    chans = bundle.latent_channels

    def per_chip(keys_shard, params, pos, neg):
        key = keys_shard[0]
        noise_key, anc_key = jax.random.split(key)
        x = jax.random.normal(
            noise_key, (batch_per_device, lh, lw, chans)
        ) * sigmas[0]
        model = pl.guided_model(bundle, params, cfg_scale)
        latents = smp.sample(
            model, x, sigmas, (pos, neg), sampler, anc_key,
            flow=(param == "flow"),
        )
        return bundle.vae.apply(params["vae"], latents, method="decode")

    return shard_map_compat(
        per_chip,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(), P(), P()),
        out_specs=P(DATA_AXIS),
        check=False,
    )(keys, params, context_pos, context_neg)


def txt2img_parallel(
    bundle: pl.PipelineBundle,
    mesh: Mesh,
    prompt: str,
    negative_prompt: str = "",
    height: int = 512,
    width: int = 512,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg_scale: float = 7.0,
    seed: int = 0,
    batch_per_device: int = 1,
) -> jax.Array:
    """All mesh participants generate concurrently from independent
    seeds; returns [n_participants * batch_per_device, H, W, 3] ordered
    master-first (participant 0 = master, parity with the reference's
    collector ordering)."""
    n = data_axis_size(mesh)
    keys = participant_keys(jax.random.key(seed), n)
    keys = jax.device_put(keys, NamedSharding(mesh, P(DATA_AXIS)))

    # pooled conditioning rides along for SDXL-adm / Flux-vector models
    pos = pl.encode_text_pooled(bundle, [prompt] * batch_per_device)
    neg = pl.encode_text_pooled(bundle, [negative_prompt] * batch_per_device)
    params = jax.device_put(bundle.params, NamedSharding(mesh, P()))
    pos = jax.device_put(pos, NamedSharding(mesh, P()))
    neg = jax.device_put(neg, NamedSharding(mesh, P()))

    return _parallel_txt2img_jit(
        pl._Static(bundle),
        pl._Static(mesh),
        params,
        keys,
        pos,
        neg,
        height,
        width,
        steps,
        sampler,
        scheduler,
        float(cfg_scale),
        batch_per_device,
    )
