"""SPMD parallelism layer: mesh/topology, sharding rules, collectives.

This layer replaces the reference's process-per-GPU + HTTP fabric for
all participants that live inside one pod slice. A "worker" here is an
index along the mesh's data axis; dispatch is sharding; collection is
an all-gather over ICI.
"""

from .mesh import (  # noqa: F401
    MeshSpec,
    advertised_capacity,
    build_mesh,
    data_axis_size,
    describe_topology,
    local_device_count,
    mesh_summary,
    model_axis_size,
    worker_mesh,
)
from .seeds import fold_seed_for_participant, participant_keys  # noqa: F401
