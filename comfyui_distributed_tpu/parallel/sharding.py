"""Parameter and activation sharding rules (tensor parallel / FSDP).

The reference has no model sharding at all ("does not combine VRAM",
reference README.md:186-194); on TPU it is table stakes: WAN-14B-class
models need FSDP across a v5p-16 (BASELINE.md config matrix). Rules
here are deliberately simple and compiler-friendly: pick one axis of
each parameter to shard along the model axis, let XLA insert the
all-gathers/reduce-scatters.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MODEL_AXIS


def fsdp_spec_for(shape: tuple[int, ...], model_axis_size: int) -> P:
    """Shard the largest divisible axis; replicate scalars/vectors that
    don't divide. Deterministic given shape, so save/restore agree."""
    if model_axis_size <= 1 or not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for axis in order:
        if shape[axis] % model_axis_size == 0 and shape[axis] >= model_axis_size:
            spec: list[Any] = [None] * len(shape)
            spec[axis] = MODEL_AXIS
            return P(*spec)
    return P()


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place a parameter pytree onto the mesh with FSDP sharding."""
    model_size = int(mesh.shape.get(MODEL_AXIS, 1))

    def place(leaf):
        arr = np.asarray(leaf) if not isinstance(leaf, jax.Array) else leaf
        spec = fsdp_spec_for(tuple(arr.shape), model_size)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, params)


def param_specs(params: Any, mesh: Mesh) -> Any:
    """PartitionSpec pytree matching `shard_params` placement (for use
    as in_shardings of a jitted train/sample step)."""
    model_size = int(mesh.shape.get(MODEL_AXIS, 1))
    return jax.tree_util.tree_map(
        lambda leaf: fsdp_spec_for(tuple(np.shape(leaf)), model_size), params
    )


def replicate(tree: Any, mesh: Mesh) -> Any:
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda leaf: jax.device_put(leaf, sharding), tree)


def params_byte_size(params: Any) -> int:
    """Total parameter bytes (as stored) — the numerator of the
    CDT_MESH_HBM_GB auto-TP budget rule."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        total += size * itemsize
    return total


def maybe_shard_params(params: Any, mesh: Mesh | None) -> Any:
    """Shard a checkpoint's parameters along the mesh's model axis
    (tensor parallel) when the mesh has one; otherwise return params
    unchanged. This is how checkpoints exceeding one chip's HBM load
    at all: each chip holds a 1/TP slice and XLA inserts the gathers
    under the same jitted tile processor (docs/performance.md, mesh
    section — TP outputs are allclose, not bit-identical: sharded
    contractions change the reduction order)."""
    if mesh is None or int(mesh.shape.get(MODEL_AXIS, 1)) <= 1:
        return params
    return shard_params(params, mesh)
