"""Context (sequence) parallelism for video models.

Shards the FRAME axis of [B, F, H, W, C] video latents across the
mesh's data axis and runs the DiT with ring attention (ops/
ring_attention.py), so sequences longer than one chip's memory are
first-class — the capability gap called out in SURVEY §5 (the
reference can only split frame batches across independent workers,
changing results; this is exact).

The same params serve sharded and unsharded calls: seq_axis only
changes how attention is computed, not the parameter tree.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.dit import DiTConfig, VideoDiT
from .mesh import DATA_AXIS, shard_map_compat


@partial(jax.jit, static_argnames=("config", "mesh_static", "axis"))
def _cp_forward_jit(config, mesh_static, axis, params, x, t, context):
    mesh = mesh_static.value
    sharded_cfg = dataclasses.replace(config, seq_axis=axis)
    model = VideoDiT(sharded_cfg)

    def per_chip(params, x_shard, t, context):
        return model.apply(params, x_shard, t, context)

    return shard_map_compat(
        per_chip,
        mesh=mesh,
        in_specs=(P(), P(None, axis), P(), P()),
        out_specs=P(None, axis),
        check=False,
    )(params, x, t, context)


def video_forward_context_parallel(
    config: DiTConfig,
    params: Any,
    x: jax.Array,          # [B, F, H, W, C], F divisible by mesh axis
    timesteps: jax.Array,
    context: jax.Array,
    mesh: Mesh,
    axis: str = DATA_AXIS,
) -> jax.Array:
    """Exact DiT forward with the frame axis sharded over `axis`."""
    n = int(mesh.shape[axis])
    f = x.shape[1]
    if f % (n * config.patch_size[0]) != 0:
        raise ValueError(
            f"frame count {f} must divide mesh axis {axis}={n} x patch {config.patch_size[0]}"
        )
    from ..models.pipeline import _Static

    x = jax.device_put(x, NamedSharding(mesh, P(None, axis)))
    params = jax.device_put(params, NamedSharding(mesh, P()))
    return _cp_forward_jit(config, _Static(mesh), axis, params, x, timesteps, context)
