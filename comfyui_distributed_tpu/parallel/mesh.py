"""Device mesh construction and TPU topology enumeration.

The TPU-native replacement for the reference's worker registry of
CUDA devices (reference workers/detection.py + api/worker_routes.py
`_get_cuda_info`): participants inside a slice are logical indices
along the mesh's "data" axis, and model sharding (tensor / FSDP) uses
the "model" axis. Multi-host pods extend the same mesh over DCN via
jax.distributed initialization.

Axis conventions used throughout the framework:
    data   — seed/batch replication axis (one "worker" per index)
    model  — tensor/FSDP sharding axis within a participant
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.exceptions import MeshError

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name → size (-1 = infer remainder)."""

    axes: dict[str, int]

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dict(self.axes)
        unknown = [name for name, size in sizes.items() if size == -1]
        if len(unknown) > 1:
            raise MeshError(f"at most one -1 axis allowed, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if known == 0 or n_devices % known != 0:
                raise MeshError(
                    f"cannot infer axis {unknown[0]}: {n_devices} devices not divisible by {known}"
                )
            sizes[unknown[0]] = n_devices // known
        if math.prod(sizes.values()) != n_devices:
            raise MeshError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return sizes


def local_device_count() -> int:
    return jax.local_device_count()


def build_mesh(
    spec: MeshSpec | dict[str, int] | None = None,
    devices: Sequence[Any] | None = None,
) -> Mesh:
    """Build a named mesh over the given (default: all) devices.

    Default layout is a pure data mesh — every chip is one participant,
    the TPU analog of the reference's one-worker-per-GPU auto-populate
    (reference web/masterDetection.js:36-104, done UI-side there;
    runtime-side here).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if not devices:
        raise MeshError("no devices available")
    if spec is None:
        spec = MeshSpec({DATA_AXIS: -1, MODEL_AXIS: 1})
    elif isinstance(spec, dict):
        spec = MeshSpec(dict(spec))
    sizes = spec.resolve(len(devices))
    names = tuple(sizes.keys())
    shape = tuple(sizes[n] for n in names)
    dev_array = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(dev_array, names)


def data_axis_size(mesh: Mesh) -> int:
    return int(mesh.shape.get(DATA_AXIS, 1))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Shard the leading (batch) axis across participants."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def describe_topology() -> dict[str, Any]:
    """Enumerate local accelerator topology for the control plane.

    The TPU replacement for the reference's `/distributed/system_info`
    CUDA enumeration (api/worker_routes.py:237-274): chip ids, platform,
    coords, process index, and any chip-visibility pinning.
    """
    devices = jax.devices()
    local = jax.local_devices()
    info: dict[str, Any] = {
        "platform": devices[0].platform if devices else "none",
        "device_count": len(devices),
        "local_device_count": len(local),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "visible_chips": os.environ.get("TPU_VISIBLE_CHIPS"),
        "devices": [],
    }
    for dev in local:
        entry: dict[str, Any] = {
            "id": dev.id,
            "platform": dev.platform,
            "process_index": dev.process_index,
        }
        for attr in ("coords", "core_on_chip", "device_kind", "memory_stats"):
            try:
                value = getattr(dev, attr, None)
                value = value() if callable(value) else value
            except Exception:
                value = None
            if value is not None:
                entry[attr] = value
        info["devices"].append(entry)
    return info
