"""Device mesh construction and TPU topology enumeration.

The TPU-native replacement for the reference's worker registry of
CUDA devices (reference workers/detection.py + api/worker_routes.py
`_get_cuda_info`): participants inside a slice are logical indices
along the mesh's "data" axis, and model sharding (tensor / FSDP) uses
the "model" axis. Multi-host pods extend the same mesh over DCN via
jax.distributed initialization.

Axis conventions used throughout the framework:
    data   — seed/batch replication axis (one "worker" per index)
    model  — tensor/FSDP sharding axis within a participant
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.exceptions import MeshError
from ..utils.logging import debug_log

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name → size (-1 = infer remainder)."""

    axes: dict[str, int]

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dict(self.axes)
        unknown = [name for name, size in sizes.items() if size == -1]
        if len(unknown) > 1:
            raise MeshError(f"at most one -1 axis allowed, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if known == 0 or n_devices % known != 0:
                raise MeshError(
                    f"cannot infer axis {unknown[0]}: {n_devices} devices not divisible by {known}"
                )
            sizes[unknown[0]] = n_devices // known
        if math.prod(sizes.values()) != n_devices:
            raise MeshError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return sizes


def local_device_count() -> int:
    return jax.local_device_count()


def build_mesh(
    spec: MeshSpec | dict[str, int] | None = None,
    devices: Sequence[Any] | None = None,
) -> Mesh:
    """Build a named mesh over the given (default: all) devices.

    Default layout is a pure data mesh — every chip is one participant,
    the TPU analog of the reference's one-worker-per-GPU auto-populate
    (reference web/masterDetection.js:36-104, done UI-side there;
    runtime-side here).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if not devices:
        raise MeshError("no devices available")
    if spec is None:
        spec = MeshSpec({DATA_AXIS: -1, MODEL_AXIS: 1})
    elif isinstance(spec, dict):
        spec = MeshSpec(dict(spec))
    sizes = spec.resolve(len(devices))
    names = tuple(sizes.keys())
    shape = tuple(sizes[n] for n in names)
    dev_array = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(dev_array, names)


def shard_map_compat(fn, *, mesh: Mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across jax versions. jax >= 0.5 exposes it at
    the top level with ``check_vma``; 0.4.x has only
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Every
    mesh-tier call site routes through here — without the shim the
    whole sharded path raises AttributeError on 0.4.x runtimes the
    moment a multi-device mesh exists."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


def data_axis_size(mesh: Mesh) -> int:
    return int(mesh.shape.get(DATA_AXIS, 1))


def model_axis_size(mesh: Mesh) -> int:
    return int(mesh.shape.get(MODEL_AXIS, 1))


def _parse_mesh_shape(raw: str | None) -> dict[str, int] | None:
    """``CDT_MESH_SHAPE`` grammar: ``"<data>,<model>"`` (e.g. ``"4,1"``,
    ``"-1,2"``; -1 infers the remainder) or a single ``"<data>"``.
    Malformed values fall back to None (auto layout) rather than
    refusing to serve."""
    if not raw:
        return None
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    try:
        sizes = [int(p) for p in parts]
    except ValueError:
        return None
    if not sizes or len(sizes) > 2:
        return None
    if len(sizes) == 1:
        sizes.append(1)
    return {DATA_AXIS: sizes[0], MODEL_AXIS: sizes[1]}


def worker_mesh(
    params_bytes: int | None = None,
    devices: Sequence[Any] | None = None,
) -> Mesh | None:
    """The production tile tier's local mesh, resolved from the
    CDT_MESH_SHAPE / CDT_TP_SIZE knob pair (plus the CDT_MESH_HBM_GB
    auto-TP budget rule when ``params_bytes`` is known).

    Default (no knobs set): a pure data mesh over all local chips on
    accelerator platforms — every chip services tile grants, so a
    4-chip worker advertises 4x grant capacity. On CPU the default is
    None (single-participant, the historical loop): forced host
    devices are a test construction, and auto-fanning the elastic tier
    across them would silently change the golden-exact K=1 path. CPU
    meshes are opt-in via the knobs (the mesh-parity suite does).

    Returns None when the resolved mesh would be a single participant
    with no model sharding — callers then take the unsharded path.
    """
    if devices is None:
        try:
            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 - backend not available
            return None
    devices = list(devices)
    if not devices:
        return None
    n = len(devices)
    shape = _parse_mesh_shape(os.environ.get("CDT_MESH_SHAPE"))
    try:
        tp = int(os.environ.get("CDT_TP_SIZE", "0"))
    except ValueError:
        tp = 0
    if tp <= 0 and params_bytes:
        tp = auto_tp_size(params_bytes, n)
    if shape is None:
        if devices[0].platform == "cpu" and tp <= 1:
            return None  # opt-in only on CPU (see docstring)
        if n <= 1 and tp <= 1:
            return None
        shape = {DATA_AXIS: -1, MODEL_AXIS: max(1, tp)}
    elif tp > 1:
        # CDT_TP_SIZE overrides only the model entry — an explicit
        # data pin survives unless the combination exceeds the host,
        # in which case the data axis reverts to inferred
        shape = dict(shape, **{MODEL_AXIS: tp})
        if shape[DATA_AXIS] != -1 and shape[DATA_AXIS] * tp > n:
            shape[DATA_AXIS] = -1
    # an explicit shape smaller than the host uses the leading subset
    # of devices (chip pinning for shared hosts); -1 axes span them all
    explicit = math.prod(s for s in shape.values() if s != -1)
    if all(s != -1 for s in shape.values()) and 0 < explicit < n:
        devices = devices[:explicit]
    try:
        mesh = build_mesh(shape, devices)
    except MeshError as exc:
        # mesh knobs are advisory, like capacity: a non-divisible
        # combination must not kill the worker before its first pull
        debug_log(f"worker_mesh: {shape} over {len(devices)} devices: {exc}")
        return None
    if data_axis_size(mesh) <= 1 and model_axis_size(mesh) <= 1:
        return None
    return mesh


def auto_tp_size(params_bytes: int, n_devices: int) -> int:
    """The HBM budget rule: the smallest power-of-two model-axis size
    (<= n_devices) whose per-chip parameter share fits CDT_MESH_HBM_GB
    GiB. 0/unset budget disables auto-TP (returns 1) — checkpoints
    that don't fit then fail to load exactly as before, loudly."""
    try:
        budget_gb = float(os.environ.get("CDT_MESH_HBM_GB", "0"))
    except ValueError:
        budget_gb = 0.0
    if budget_gb <= 0 or params_bytes <= 0:
        return 1
    budget = budget_gb * (1 << 30)
    # the data axis infers as n/tp, so tp must also divide n — on a
    # 6-chip host the ladder is 1, 2, never 4 or 8
    max_tp = 1
    while max_tp * 2 <= n_devices and n_devices % (max_tp * 2) == 0:
        max_tp *= 2
    tp = 1
    while tp < max_tp and params_bytes / tp > budget:
        tp *= 2
    if params_bytes / tp > budget:
        # even the widest divisible TP is over budget: proceed (the
        # load may still fit — the budget is a conservative rule) but
        # say so, or an OOM here looks like the rule never fired
        debug_log(
            f"auto_tp_size: {params_bytes / (1 << 30):.1f} GiB / tp={tp} "
            f"still exceeds CDT_MESH_HBM_GB={budget_gb:g} per-chip budget"
        )
    return tp


def mesh_summary(mesh: Mesh | None) -> dict[str, int]:
    """Compact mesh shape for telemetry/status surfaces."""
    if mesh is None:
        return {"data": 1, "model": 1, "devices": 1}
    return {
        "data": data_axis_size(mesh),
        "model": model_axis_size(mesh),
        "devices": int(mesh.size),
    }


_serving_mesh_summary: dict[str, int] | None = None
# knob-only fallback cache, keyed by the knob values so env changes
# (tests, operator retunes) invalidate it: (knobs, summary)
_fallback_mesh_summary: tuple[tuple, dict[str, int]] | None = None


def note_serving_mesh(mesh: Mesh | None) -> None:
    """Record the mesh actually constructed to serve tile grants (the
    elastic loops call this at startup). Status surfaces must report
    THIS shape, not a knob-only ``worker_mesh()`` re-derivation — the
    two differ exactly when the auto-TP budget rule needed
    ``params_bytes`` (a checkpoint over budget shrinks the data axis,
    and with it the advertised capacity)."""
    global _serving_mesh_summary
    _serving_mesh_summary = mesh_summary(mesh)


def serving_mesh_summary() -> dict[str, int]:
    """The recorded serving mesh, falling back to a knob-only
    ``worker_mesh()`` resolution when no elastic loop has run in this
    process yet. The fallback is cached per knob values — status
    surfaces poll continuously and must not construct a throwaway Mesh
    per request."""
    if _serving_mesh_summary is not None:
        return dict(_serving_mesh_summary)
    global _fallback_mesh_summary
    knobs = tuple(
        os.environ.get(k)
        for k in ("CDT_MESH_SHAPE", "CDT_TP_SIZE", "CDT_MESH_HBM_GB")
    )
    if _fallback_mesh_summary is None or _fallback_mesh_summary[0] != knobs:
        _fallback_mesh_summary = (knobs, mesh_summary(worker_mesh()))
    return dict(_fallback_mesh_summary[1])


def advertised_capacity(mesh: Mesh | None) -> int:
    """Grant capacity a worker reports to the master's placement
    policy: the data-axis width of its mesh (chips servicing tile
    fan-out; model-axis chips serve the same tiles, not more of them).
    1 without a mesh — the historical single-participant worker."""
    return data_axis_size(mesh) if mesh is not None else 1


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Shard the leading (batch) axis across participants."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def describe_topology() -> dict[str, Any]:
    """Enumerate local accelerator topology for the control plane.

    The TPU replacement for the reference's `/distributed/system_info`
    CUDA enumeration (api/worker_routes.py:237-274): chip ids, platform,
    coords, process index, and any chip-visibility pinning.
    """
    devices = jax.devices()
    local = jax.local_devices()
    info: dict[str, Any] = {
        "platform": devices[0].platform if devices else "none",
        "device_count": len(devices),
        "local_device_count": len(local),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "visible_chips": os.environ.get("TPU_VISIBLE_CHIPS"),
        "devices": [],
    }
    for dev in local:
        entry: dict[str, Any] = {
            "id": dev.id,
            "platform": dev.platform,
            "process_index": dev.process_index,
        }
        for attr in ("coords", "core_on_chip", "device_kind", "memory_stats"):
            try:
                value = getattr(dev, attr, None)
                value = value() if callable(value) else value
            except Exception:
                value = None
            if value is not None:
                entry[attr] = value
        info["devices"].append(entry)
    return info
