"""Deterministic per-participant seed derivation.

The reference offsets an integer seed per worker (`seed + worker_index
+ 1`, nodes/utilities.py:52-75) via prompt rewriting. TPU-native, the
same contract is a pure function of (base seed, participant index):
`jax.random.fold_in` gives statistically independent streams and works
both outside jit (per-participant dispatch) and inside shard_map (the
participant index comes from `lax.axis_index`).

Two derivations are provided:
- `offset_seed`: exact integer-offset parity with the reference, for
  the HTTP tier where remote workers receive a plain integer seed.
- `fold_seed_for_participant` / `participant_keys`: the mesh tier's
  fold_in derivation (preferred: no birthday-adjacent stream overlap
  when users sweep base seeds).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS

MAX_SEED = 2**63 - 1


def offset_seed(base_seed: int, participant_index: int) -> int:
    """Reference-parity integer seed: master keeps base, worker i gets
    base + i + 1 (wrapping at the 63-bit boundary)."""
    if participant_index <= 0:
        return int(base_seed) % (MAX_SEED + 1)
    return (int(base_seed) + participant_index) % (MAX_SEED + 1)


def fold_seed_for_participant(key: jax.Array, participant_index) -> jax.Array:
    """Derive one participant's PRNG key; traceable under jit/shard_map."""
    return jax.random.fold_in(key, participant_index)


def participant_keys(key: jax.Array, n_participants: int) -> jax.Array:
    """[n, 2] stacked keys for all participants — shard axis 0 over the
    data axis and each chip picks up its own stream."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(n_participants)
    )


def local_participant_key(key: jax.Array) -> jax.Array:
    """Inside shard_map over the data axis: this chip's key."""
    return jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))


def job_uid(job_id: str) -> int:
    """Stable 32-bit fold constant derived from a job id string
    (blake2b — NOT Python's salted hash(), which differs per process
    and would break cross-participant determinism; 32 bits because
    ``jax.random.fold_in`` folds uint32 data)."""
    import hashlib

    digest = hashlib.blake2b(
        str(job_id).encode("utf-8"), digest_size=4
    ).digest()
    return int.from_bytes(digest, "big")


def fold_job_key(key: jax.Array, job_id: str) -> jax.Array:
    """The cross-job batching tier's per-job root key: the user's base
    key folded with the job id. Two jobs sharing a user seed (common
    when tenants sweep templates) still draw independent per-tile
    streams, and a tile's key stays a pure function of
    (seed, job id, tile index) — independent of batch composition,
    which is what makes cross-tenant batch mixing safe by
    construction (graph/batch_executor.py)."""
    return jax.random.fold_in(key, job_uid(job_id))
