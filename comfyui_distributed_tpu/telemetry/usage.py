"""Tenant usage metering: chip-time attribution for every dispatch.

PR 14 made the device dispatch genuinely multi-tenant — one batched
step can hold tiles from several jobs, tenants, and lanes — yet nothing
in the repo could answer "which tenant consumed how many chip-seconds,
and how much of the fleet's device time was padding or recompute".
This module is that signal plane:

- **attribution records** — both execution tiers time every device
  dispatch (`CrossJobExecutor._step_batch` in graph/batch_executor.py,
  `GrantSampler.sample` in graph/tile_pipeline.py) and hand the
  measured time to `UsageMeter.note_dispatch` together with one entry
  per device SLOT: real slots charge their owning job (and through the
  job-attrs map, its tenant + lane), wraparound-padding slots charge
  the ``padding`` waste bucket, and slots re-running steps a preempted
  tile had already completed (a lost checkpoint) charge
  ``preempt_recompute``.

- **exact conservation** — all accounting is integer *chip-
  nanoseconds* (``measured_seconds × chips``, rounded once). A
  dispatch's chip-time divides evenly across its slots and the integer
  remainder lands in the ``overhead`` bucket, so

      attributed + waste(padding) + waste(preempt_recompute) + overhead
          == measured dispatch chip-time        (EXACTLY, per record
                                                 and cumulatively)

  — the invariant tests/test_usage_meter.py and the usage-smoke CI job
  pin on both tiers, jitted and eager-stub alike.

- **store-side waste** — work the dispatch could not know was wasted
  is charged where the verdict lands: a speculative race's LOSING
  submit (duplicate of a speculated tile) charges ``speculation`` with
  the store's measured service interval, and a quarantine-class
  requeue (the poison-tile retry path) charges ``poison_retry`` with
  the failed attempt's assignment duration. These buckets are
  *additional* measured waste — they happened on a different process's
  clock, so they ride outside the per-dispatch conservation identity
  (``totals["dispatch"]`` carries the exact family; ``waste_s`` the
  full taxonomy).

- **fleet merge** — worker meters ride the PR 12 heartbeat telemetry
  snapshot (``local_snapshot`` v2; no new RPC). The master's
  `UsageAggregator` adopts each worker's cumulative counters by DELTA
  with a counter-reset clamp (a restarted worker's smaller totals are
  adopted as a fresh baseline, never a negative delta), resolves
  job → (tenant, lane) from the job store's authoritative attrs, and
  retains per-tenant chip-seconds / waste series in the fleet
  registry's two-tier `SeriesStore`.

- **closing the loop** — `UsageAggregator.cost_ratio(tenant)` is a
  measured chip-seconds-per-tile EWMA normalized to the fleet mean;
  with ``CDT_USAGE_COST=1`` the scheduler multiplies DRR admission
  cost by it (scheduler/control.py), so fair share finally meters what
  tenants actually burn instead of the client's tile estimate.

Memory is bounded: at most `MAX_TRACKED_KEYS` job entries per role and
tenant entries per aggregator; idle entries (no activity within
``CDT_USAGE_TTL``) are swept, folding their counters into per-tenant
(then global) aggregates, and a departing tenant's retained series are
evicted through the same `evict_label` seam the fleet plane uses —
tenant-id churn cannot grow master memory (regression-tested).

Determinism: this module is in cdt-lint's CDT004 scope — attribution
order is a pure function of the slot sequence, every exported mapping
is sorted, and no ambient entropy or wall-clock seed material enters —
so two replays of the same dispatch stream produce byte-identical
rollups.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..utils.logging import debug_log

# The waste taxonomy (docs/observability.md §Usage metering).
# dispatch-family reasons participate in the per-dispatch conservation
# identity; store-family reasons are measured on the master's clock.
DISPATCH_WASTE_REASONS = ("padding", "preempt_recompute")
STORE_WASTE_REASONS = ("speculation", "poison_retry")
WASTE_REASONS = DISPATCH_WASTE_REASONS + STORE_WASTE_REASONS

# Slot kinds accepted by note_dispatch.
SLOT_REAL = "real"
SLOT_PADDING = "padding"
SLOT_RECOMPUTE = "recompute"

# Same unauthenticated-input bound the fleet registry applies to
# workers: job ids and tenant names arrive on RPCs.
MAX_TRACKED_KEYS = 1024

DEFAULT_TENANT = "default"

_NS = 1_000_000_000


def _to_ns(seconds: float) -> int:
    return max(0, int(round(float(seconds) * _NS)))


def _s(ns: int) -> float:
    return ns / _NS


class _JobUsage:
    """Cumulative counters for one (role, job): integer chip-ns."""

    __slots__ = (
        "chip_ns", "steps", "tiles", "waste_ns", "cached_tiles",
        "cached_ns", "last_active",
    )

    def __init__(self) -> None:
        self.chip_ns = 0
        self.steps = 0
        self.tiles = 0
        # recompute/store waste charged against this job's tiles
        self.waste_ns = 0
        # tiles settled from the content-addressed cache (a subset of
        # `tiles` — they bump the cost denominator at near-zero chip
        # time) and the measured lookup/settle time charged for them
        # (the `cached` bucket: OUTSIDE the dispatch conservation
        # identity, like the store-family waste — no device dispatch
        # happened)
        self.cached_tiles = 0
        self.cached_ns = 0
        self.last_active = 0.0

    def as_dict(self) -> dict[str, Any]:
        return {
            "chip_s": _s(self.chip_ns),
            "steps": self.steps,
            "tiles": self.tiles,
            "waste_s": _s(self.waste_ns),
            "cached_tiles": self.cached_tiles,
            "cached_s": _s(self.cached_ns),
        }


class UsageMeter:
    """Per-process chip-time attribution. Thread-safe; the executors'
    driver threads, the pipeline's I/O thread, and the server loop all
    write concurrently. The clock is injectable (activity timestamps
    only — never measurement: callers measure their own dispatches)."""

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        max_keys: int = MAX_TRACKED_KEYS,
    ) -> None:
        self.clock = clock
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        # role -> job_id -> _JobUsage
        self._jobs: dict[str, dict[str, _JobUsage]] = {}
        # job_id -> (tenant, lane): fed by the store (authoritative) and
        # the executors (advisory); bounded like the job maps
        self._attrs: dict[str, tuple[str, str]] = {}
        # job_id -> adapter plan id ("hash@strength[+...]"): the
        # adapter plane's attribution join — metering a personalized
        # job must say WHICH personalization burned the chip time
        self._adapter_attrs: dict[str, str] = {}
        # role -> reason -> ns
        self._waste: dict[str, dict[str, int]] = {}
        # the `cached` bucket: tiles settled from the tile cache and the
        # (near-zero) measured settle time charged for them, per role —
        # outside the dispatch conservation identity by construction
        self._cached_tiles: dict[str, int] = {}
        self._cached_ns: dict[str, int] = {}
        # exact dispatch-family totals per role (the conservation set)
        self._dispatch_ns: dict[str, int] = {}
        self._attributed_ns: dict[str, int] = {}
        self._overhead_ns: dict[str, int] = {}
        self._dispatches: dict[str, int] = {}
        # counters folded out of evicted job entries, keyed by the
        # (role, tenant, lane) resolved AT EVICTION TIME — so the
        # tenant rollup (and the scrape mirror's per-pair counters)
        # stay monotonic and role-filtered views stay separate after a
        # sweep. Bounded: overflow folds into the default key.
        self._retired: dict[tuple[str, str, str], dict[str, int]] = {}

    # --- attrs ------------------------------------------------------------

    def note_job_attrs(self, job_id: str, tenant: Any, lane: Any) -> None:
        """Record a job's owning tenant + admission lane (the store's
        init/replay path and the executors' registration both feed
        this; last write wins — the store is wired after registration
        so authoritative attrs land on top)."""
        job_id = str(job_id)
        with self._lock:
            if job_id not in self._attrs and len(self._attrs) >= self.max_keys:
                # oldest-inserted eviction: attrs are an advisory map,
                # unresolved jobs simply report the default tenant
                self._attrs.pop(next(iter(self._attrs)))
            self._attrs[job_id] = (
                str(tenant) if tenant else DEFAULT_TENANT,
                str(lane) if lane else "",
            )

    def job_attrs(self, job_id: str) -> tuple[str, str]:
        with self._lock:
            return self._attrs.get(str(job_id), (DEFAULT_TENANT, ""))

    def note_job_adapter(self, job_id: str, adapter_id: Any) -> None:
        """Record a job's adapter plan id (adapters/registry
        ``adapter_plan_key`` rendered compactly); "" clears. Bounded
        with the same oldest-inserted rule as the attrs map."""
        job_id = str(job_id)
        adapter_id = str(adapter_id or "")
        with self._lock:
            if not adapter_id:
                self._adapter_attrs.pop(job_id, None)
                return
            if (
                job_id not in self._adapter_attrs
                and len(self._adapter_attrs) >= self.max_keys
            ):
                self._adapter_attrs.pop(next(iter(self._adapter_attrs)))
            self._adapter_attrs[job_id] = adapter_id

    def job_adapter(self, job_id: str) -> str:
        with self._lock:
            return self._adapter_attrs.get(str(job_id), "")

    # --- recording --------------------------------------------------------

    def _job(self, role: str, job_id: str, now: float) -> _JobUsage:
        by_job = self._jobs.setdefault(role, {})
        entry = by_job.get(job_id)
        if entry is None:
            if len(by_job) >= self.max_keys:
                # evict the longest-idle entry, folding its counters
                # into the retired aggregate so totals stay conserved
                victim_id = min(by_job, key=lambda j: by_job[j].last_active)
                self._retire(role, victim_id, by_job.pop(victim_id))
            entry = _JobUsage()
            by_job[job_id] = entry
        entry.last_active = now
        return entry

    def _retire(self, role: str, job_id: str, entry: _JobUsage) -> None:
        """Fold an evicted job's counters into the retired aggregate
        under its (role, tenant, lane) — resolved NOW, while the attrs
        map still knows the job. Caller holds the lock."""
        tenant, lane = self._attrs.get(str(job_id), (DEFAULT_TENANT, ""))
        key = (role, tenant, lane)
        if key not in self._retired and len(self._retired) >= self.max_keys:
            key = (role, DEFAULT_TENANT, "")
        bucket = self._retired.setdefault(
            key, {"chip_ns": 0, "tiles": 0, "steps": 0, "waste_ns": 0,
                  "cached_tiles": 0, "cached_ns": 0},
        )
        bucket["chip_ns"] += entry.chip_ns
        bucket["tiles"] += entry.tiles
        bucket["steps"] += entry.steps
        bucket["waste_ns"] += entry.waste_ns
        bucket["cached_tiles"] += entry.cached_tiles
        bucket["cached_ns"] += entry.cached_ns

    def note_dispatch(
        self,
        *,
        tier: str,
        role: str,
        elapsed_s: float,
        chips: int,
        slots: list[dict[str, Any]],
    ) -> dict[str, Any]:
        """Attribute one measured device dispatch across its slots.

        ``slots`` has exactly one entry per device slot of the padded
        bucket: ``{"job_id": str, "kind": real|padding|recompute}``.
        The measured chip-time (``elapsed_s × chips``, integer ns)
        divides evenly across the slots; the division remainder charges
        ``overhead``. Returns the record's exact split (tests pin the
        conservation identity on it)."""
        del tier  # reserved for future per-tier drill-down
        if not slots:
            return {"chip_ns": 0, "attributed_ns": 0, "waste_ns": 0,
                    "overhead_ns": 0}
        chip_ns = _to_ns(elapsed_s) * max(1, int(chips))
        share = chip_ns // len(slots)
        overhead = chip_ns - share * len(slots)
        attributed = 0
        waste = 0
        now = self.clock()
        with self._lock:
            for slot in slots:
                kind = slot.get("kind", SLOT_REAL)
                if kind == SLOT_PADDING:
                    by_reason = self._waste.setdefault(role, {})
                    by_reason["padding"] = by_reason.get("padding", 0) + share
                    waste += share
                    continue
                job_id = str(slot.get("job_id", ""))
                entry = self._job(role, job_id, now)
                if kind == SLOT_RECOMPUTE:
                    by_reason = self._waste.setdefault(role, {})
                    by_reason["preempt_recompute"] = (
                        by_reason.get("preempt_recompute", 0) + share
                    )
                    entry.waste_ns += share
                    entry.steps += 1
                    waste += share
                else:
                    entry.chip_ns += share
                    entry.steps += 1
                    attributed += share
            self._dispatch_ns[role] = self._dispatch_ns.get(role, 0) + chip_ns
            self._attributed_ns[role] = (
                self._attributed_ns.get(role, 0) + attributed
            )
            self._overhead_ns[role] = self._overhead_ns.get(role, 0) + overhead
            self._dispatches[role] = self._dispatches.get(role, 0) + 1
        return {
            "chip_ns": chip_ns,
            "attributed_ns": attributed,
            "waste_ns": waste,
            "overhead_ns": overhead,
        }

    def note_tiles(self, role: str, job_id: str, n: int = 1) -> None:
        """Count finished tiles (the denominator of chip-s-per-tile)."""
        now = self.clock()
        with self._lock:
            self._job(str(role), str(job_id), now).tiles += int(n)

    def note_cached(
        self, role: str, job_id: str, tiles: int, seconds: float = 0.0
    ) -> None:
        """Charge cache-settled tiles to the ``cached`` bucket: they
        count toward the job's finished ``tiles`` (the cost-model
        denominator — this is what makes likely-hit jobs admit as
        near-free under the DRR measured-cost hook) at the near-zero
        measured lookup/settle time, which rides OUTSIDE the dispatch
        conservation identity exactly like the store-family waste — no
        device dispatch happened."""
        n = int(tiles)
        if n <= 0:
            return
        ns = _to_ns(seconds)
        now = self.clock()
        with self._lock:
            entry = self._job(str(role), str(job_id), now)
            entry.tiles += n
            entry.cached_tiles += n
            entry.cached_ns += ns
            role = str(role)
            self._cached_tiles[role] = self._cached_tiles.get(role, 0) + n
            self._cached_ns[role] = self._cached_ns.get(role, 0) + ns

    def note_waste(
        self, role: str, reason: str, seconds: float,
        job_id: Optional[str] = None, chips: int = 1,
    ) -> None:
        """Charge a store-family waste bucket (speculation loser /
        poison retry): measured on the caller's clock, outside the
        dispatch conservation identity."""
        ns = _to_ns(seconds) * max(1, int(chips))
        if ns <= 0:
            return
        now = self.clock()
        with self._lock:
            by_reason = self._waste.setdefault(str(role), {})
            by_reason[str(reason)] = by_reason.get(str(reason), 0) + ns
            if job_id is not None:
                self._job(str(role), str(job_id), now).waste_ns += ns

    # --- eviction ---------------------------------------------------------

    def sweep(self, ttl_s: float) -> list[str]:
        """Fold job entries idle longer than ``ttl_s`` into the retired
        aggregate; returns the evicted job ids (sorted)."""
        now = self.clock()
        evicted: list[str] = []
        with self._lock:
            for role in sorted(self._jobs):
                by_job = self._jobs[role]
                stale = sorted(
                    j for j, e in by_job.items()
                    if now - e.last_active > ttl_s
                )
                for job_id in stale:
                    # retire BEFORE dropping the attrs so the fold
                    # lands under the job's real tenant/lane
                    self._retire(role, job_id, by_job.pop(job_id))
                    evicted.append(job_id)
            # attrs depart only once NO role still tracks the job
            live = {
                j for by_job in self._jobs.values() for j in by_job
            }
            for job_id in sorted(set(evicted)):
                if job_id not in live:
                    self._attrs.pop(job_id, None)
                    self._adapter_attrs.pop(job_id, None)
        return evicted

    # --- export -----------------------------------------------------------

    def snapshot(self, role: str = "worker") -> dict[str, Any]:
        """This process's cumulative usage for one role — the compact
        block that rides the fleet telemetry snapshot (floats on the
        wire; ns precision is a process-local concern)."""
        with self._lock:
            jobs = {
                job_id: entry.as_dict()
                for job_id, entry in sorted(
                    self._jobs.get(role, {}).items()
                )
            }
            waste = {
                reason: _s(ns)
                for reason, ns in sorted(self._waste.get(role, {}).items())
            }
            return {
                "jobs": jobs,
                "waste_s": waste,
                "dispatch_chip_s": _s(self._dispatch_ns.get(role, 0)),
                "attributed_chip_s": _s(self._attributed_ns.get(role, 0)),
                "overhead_s": _s(self._overhead_ns.get(role, 0)),
                "dispatches": self._dispatches.get(role, 0),
                "cached_tiles": self._cached_tiles.get(role, 0),
                "cached_s": _s(self._cached_ns.get(role, 0)),
            }

    def totals(
        self, roles: Optional[tuple[str, ...]] = None
    ) -> dict[str, Any]:
        """Exact totals (all roles by default); ``conserved`` is the
        test-pinned identity over the dispatch family (integer ns —
        exact)."""

        def _keep(role: str) -> bool:
            return roles is None or role in roles

        with self._lock:
            dispatch_ns = sum(
                ns for r, ns in self._dispatch_ns.items() if _keep(r)
            )
            attributed_ns = sum(
                ns for r, ns in self._attributed_ns.items() if _keep(r)
            )
            overhead_ns = sum(
                ns for r, ns in self._overhead_ns.items() if _keep(r)
            )
            waste_ns: dict[str, int] = {}
            for role, by_reason in self._waste.items():
                if not _keep(role):
                    continue
                for reason, ns in by_reason.items():
                    waste_ns[reason] = waste_ns.get(reason, 0) + ns
            dispatch_waste_ns = sum(
                waste_ns.get(r, 0) for r in DISPATCH_WASTE_REASONS
            )
            return {
                "dispatch_chip_ns": dispatch_ns,
                "attributed_ns": attributed_ns,
                "dispatch_waste_ns": dispatch_waste_ns,
                "overhead_ns": overhead_ns,
                "waste_ns": {r: waste_ns[r] for r in sorted(waste_ns)},
                "dispatches": sum(
                    n for r, n in self._dispatches.items() if _keep(r)
                ),
                # the cached bucket rides OUTSIDE the conservation set:
                # no dispatch happened for these tiles, so adding them
                # to the identity would un-balance it by construction
                "cached_tiles": sum(
                    n for r, n in self._cached_tiles.items() if _keep(r)
                ),
                "cached_ns": sum(
                    ns for r, ns in self._cached_ns.items() if _keep(r)
                ),
                "conserved": (
                    attributed_ns + dispatch_waste_ns + overhead_ns
                    == dispatch_ns
                ),
            }

    def pair_totals(
        self, roles: Optional[tuple[str, ...]] = None
    ) -> dict[tuple[str, str], dict[str, float]]:
        """Cumulative (tenant, lane) -> {chip_s, tiles} across live AND
        retired entries — MONOTONIC per pair (eviction moves a job's
        counters into the retired fold without changing the sum), which
        is what the scrape-mirror counters delta against."""
        out: dict[tuple[str, str], dict[str, float]] = {}

        def add(
            tenant: str, lane: str, chip_ns: int, tiles: int, cached: int
        ) -> None:
            agg = out.setdefault(
                (tenant, lane), {"chip_s": 0.0, "tiles": 0.0, "cached": 0.0}
            )
            agg["chip_s"] += _s(chip_ns)
            agg["tiles"] += tiles
            agg["cached"] += cached

        with self._lock:
            for role in sorted(self._jobs):
                if roles is not None and role not in roles:
                    continue
                for job_id in sorted(self._jobs[role]):
                    entry = self._jobs[role][job_id]
                    tenant, lane = self._attrs.get(
                        job_id, (DEFAULT_TENANT, "")
                    )
                    add(
                        tenant, lane, entry.chip_ns, entry.tiles,
                        entry.cached_tiles,
                    )
            for (role, tenant, lane) in sorted(self._retired):
                if roles is not None and role not in roles:
                    continue
                bucket = self._retired[(role, tenant, lane)]
                add(
                    tenant, lane, bucket["chip_ns"], bucket["tiles"],
                    bucket.get("cached_tiles", 0),
                )
        return out

    def rollup(
        self, roles: Optional[tuple[str, ...]] = None
    ) -> dict[str, Any]:
        """Per-tenant/per-lane view across this process's roles (all by
        default; the master-side aggregator restricts to ``("master",)``
        so a co-hosted worker's records count exactly once — through its
        adopted snapshots, the PR 12 role-separation rule). Jobs resolve
        through the attrs map; retired counters fold into the default
        tenant."""
        with self._lock:
            tenants: dict[str, dict[str, Any]] = {}
            lanes: dict[str, dict[str, Any]] = {}
            adapters: dict[str, dict[str, Any]] = {}
            jobs_out: dict[str, dict[str, Any]] = {}
            for role in sorted(self._jobs):
                if roles is not None and role not in roles:
                    continue
                for job_id in sorted(self._jobs[role]):
                    entry = self._jobs[role][job_id]
                    tenant, lane = self._attrs.get(
                        job_id, (DEFAULT_TENANT, "")
                    )
                    adapter_id = self._adapter_attrs.get(job_id, "")
                    if adapter_id:
                        ad = adapters.setdefault(
                            adapter_id, {"chip_s": 0.0, "tiles": 0}
                        )
                        ad["chip_s"] += _s(entry.chip_ns)
                        ad["tiles"] += entry.tiles
                    t = tenants.setdefault(
                        tenant, {"chip_s": 0.0, "tiles": 0, "steps": 0,
                                 "waste_s": 0.0, "cached_tiles": 0}
                    )
                    t["chip_s"] += _s(entry.chip_ns)
                    t["tiles"] += entry.tiles
                    t["steps"] += entry.steps
                    t["waste_s"] += _s(entry.waste_ns)
                    t["cached_tiles"] += entry.cached_tiles
                    ln = lanes.setdefault(
                        lane, {"chip_s": 0.0, "tiles": 0}
                    )
                    ln["chip_s"] += _s(entry.chip_ns)
                    ln["tiles"] += entry.tiles
                    job_out = jobs_out.setdefault(
                        job_id,
                        {"tenant": tenant, "lane": lane,
                         "adapter": adapter_id, "chip_s": 0.0,
                         "tiles": 0, "steps": 0, "waste_s": 0.0,
                         "cached_tiles": 0, "roles": []},
                    )
                    job_out["chip_s"] += _s(entry.chip_ns)
                    job_out["tiles"] += entry.tiles
                    job_out["steps"] += entry.steps
                    job_out["waste_s"] += _s(entry.waste_ns)
                    job_out["cached_tiles"] += entry.cached_tiles
                    job_out["roles"].append(role)
            for (role, tenant, lane) in sorted(self._retired):
                if roles is not None and role not in roles:
                    continue
                bucket = self._retired[(role, tenant, lane)]
                t = tenants.setdefault(
                    tenant,
                    {"chip_s": 0.0, "tiles": 0, "steps": 0, "waste_s": 0.0,
                     "cached_tiles": 0},
                )
                t["chip_s"] += _s(bucket["chip_ns"])
                t["tiles"] += bucket["tiles"]
                t["steps"] += bucket["steps"]
                t["waste_s"] += _s(bucket["waste_ns"])
                t["cached_tiles"] += bucket.get("cached_tiles", 0)
                ln = lanes.setdefault(lane, {"chip_s": 0.0, "tiles": 0})
                ln["chip_s"] += _s(bucket["chip_ns"])
                ln["tiles"] += bucket["tiles"]
        totals = self.totals(roles)
        total_chip = _s(totals["dispatch_chip_ns"])
        for stats in tenants.values():
            stats["chip_share"] = (
                round(stats["chip_s"] / total_chip, 6) if total_chip else 0.0
            )
        return {
            "tenants": {t: tenants[t] for t in sorted(tenants)},
            "lanes": {ln: lanes[ln] for ln in sorted(lanes)},
            "adapters": {a: adapters[a] for a in sorted(adapters)},
            "jobs": jobs_out,
            "totals": {
                "chip_s": total_chip,
                "attributed_s": _s(totals["attributed_ns"]),
                "overhead_s": _s(totals["overhead_ns"]),
                "waste_s": {
                    r: _s(ns) for r, ns in totals["waste_ns"].items()
                },
                "dispatches": totals["dispatches"],
                "cached_tiles": totals["cached_tiles"],
                "cached_s": _s(totals["cached_ns"]),
                "conserved": totals["conserved"],
            },
        }


# --- process-global meter -----------------------------------------------------

_METER_LOCK = threading.Lock()
_METER: Optional[UsageMeter] = None


def get_usage_meter() -> UsageMeter:
    global _METER
    with _METER_LOCK:
        if _METER is None:
            _METER = UsageMeter()
        return _METER


def _reset_usage_meter_for_tests() -> UsageMeter:
    global _METER
    with _METER_LOCK:
        _METER = UsageMeter()
        return _METER


def set_usage_meter(meter: Optional[UsageMeter]) -> Optional[UsageMeter]:
    """Swap the process-global meter and return the previous one. The
    chaos harnesses install a fresh meter around a run so its usage is
    isolated from the process's cumulative accounting (and restore the
    previous meter on exit)."""
    global _METER
    with _METER_LOCK:
        previous, _METER = _METER, meter
        return previous


# --- master-side aggregation --------------------------------------------------

# Series names retained in the fleet SeriesStore (label vocabulary:
# tenant / reason only — per-job history stays in the live drill-down).
S_TENANT_CHIP_S = "usage_tenant_chip_s"
S_TENANT_TILES = "usage_tenant_tiles"
S_WASTE_S = "usage_waste_s"

# cost_ratio clamp: a measured-cost tenant can weigh at most 10x / at
# least 0.1x the fleet mean in DRR admission accounting.
COST_RATIO_MIN = 0.1
COST_RATIO_MAX = 10.0
_EWMA_ALPHA = 0.3


class _AdoptedJob:
    __slots__ = (
        "chip_ns", "steps", "tiles", "waste_ns", "cached_tiles",
        "cached_ns", "last_active",
    )

    def __init__(self) -> None:
        self.chip_ns = 0
        self.steps = 0
        self.tiles = 0
        self.waste_ns = 0
        self.cached_tiles = 0
        self.cached_ns = 0
        self.last_active = 0.0


class UsageAggregator:
    """Fleet-wide usage on the master: the local meter's records
    (master role) plus worker meters adopted by delta from their
    piggybacked snapshots. Owned by the FleetRegistry; read by
    ``GET /distributed/usage``, the scrape mirror, the web panel's
    ``usage_rollup`` event, incident bundles, and the scheduler's
    measured-cost hook."""

    def __init__(
        self,
        meter: Optional[UsageMeter] = None,
        store: Any = None,
        clock: Callable[[], float] = time.time,
        ttl: Optional[float] = None,
        max_keys: int = MAX_TRACKED_KEYS,
    ) -> None:
        from ..utils import constants

        self.meter = meter if meter is not None else get_usage_meter()
        self.store = store  # telemetry/timeseries.SeriesStore (optional)
        self.clock = clock
        self.ttl = ttl if ttl is not None else constants.USAGE_TTL_SECONDS
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        # adopted worker usage: job_id -> _AdoptedJob (fleet-cumulative)
        self._adopted_jobs: dict[str, _AdoptedJob] = {}
        # adopted waste: reason -> ns
        self._adopted_waste: dict[str, int] = {}
        # adopted exact dispatch-family totals
        self._adopted_dispatch_ns = 0
        self._adopted_attributed_ns = 0
        self._adopted_overhead_ns = 0
        self._adopted_dispatches = 0
        # counter-reset clamp state: worker_id -> path -> last seen
        self._worker_prev: dict[str, dict[str, float]] = {}
        # tenant cost model: tenant -> {"ewma", "prev_chip_ns", "prev_tiles"}
        self._cost: dict[str, dict[str, float]] = {}
        self._cost_global: Optional[float] = None
        # retired adopted counters (evicted jobs), keyed by the
        # (tenant, lane) resolved at eviction time — keeps the tenant
        # rollup and the per-pair scrape counters monotonic. Bounded:
        # overflow folds into the default pair.
        self._retired: dict[tuple[str, str], dict[str, int]] = {}
        # scrape mirror high-water marks (instruments.py counts deltas
        # against these so co-hosted servers' collectors never double-
        # count): path -> last mirrored value
        self.scrape_mirrored: dict[str, float] = {}
        # fired when an idle tenant departs (fleet wires series eviction)
        self.on_evict_tenant: Optional[Callable[[str], None]] = None

    # --- adoption ---------------------------------------------------------

    @staticmethod
    def _delta(prev: dict[str, float], path: str, value: float) -> float:
        """Cumulative-counter delta with the reset clamp: a value below
        the last seen one means the worker restarted — adopt the new
        total as a fresh baseline (never a negative delta)."""
        last = prev.get(path)
        prev[path] = value
        if last is None or value < last:
            return max(0.0, value)
        return value - last

    def adopt(self, worker_id: str, usage: Any) -> bool:
        """Merge one worker's cumulative usage snapshot by delta.
        Malformed payloads are dropped (False); the snapshot rode an
        unauthenticated RPC."""
        if not isinstance(usage, dict):
            return False
        worker_id = str(worker_id)
        now = self.clock()
        with self._lock:
            prev = self._worker_prev.get(worker_id)
            if prev is None:
                if len(self._worker_prev) >= self.max_keys:
                    self._worker_prev.pop(next(iter(self._worker_prev)))
                prev = {}
                self._worker_prev[worker_id] = prev
            jobs = usage.get("jobs")
            if isinstance(jobs, dict):
                # prune baselines for jobs the worker's own (bounded)
                # meter no longer reports — they cannot reappear in a
                # later snapshot, so keeping their paths would grow
                # this map one entry per job id served, forever
                current_ids = {str(j) for j in jobs}
                for path in [p for p in prev if p.startswith("job:")]:
                    if path[4:].rsplit(":", 1)[0] not in current_ids:
                        del prev[path]
                for job_id in sorted(jobs):
                    stats = jobs[job_id]
                    if not isinstance(stats, dict):
                        continue
                    entry = self._adopted_job(str(job_id), now)
                    entry.chip_ns += _to_ns(self._delta(
                        prev, f"job:{job_id}:chip_s",
                        _as_float(stats.get("chip_s")),
                    ))
                    entry.waste_ns += _to_ns(self._delta(
                        prev, f"job:{job_id}:waste_s",
                        _as_float(stats.get("waste_s")),
                    ))
                    entry.steps += int(self._delta(
                        prev, f"job:{job_id}:steps",
                        _as_float(stats.get("steps")),
                    ))
                    entry.tiles += int(self._delta(
                        prev, f"job:{job_id}:tiles",
                        _as_float(stats.get("tiles")),
                    ))
                    # version-tolerant: a pre-cache worker's snapshot
                    # simply lacks the fields (delta from 0 of 0)
                    entry.cached_tiles += int(self._delta(
                        prev, f"job:{job_id}:cached_tiles",
                        _as_float(stats.get("cached_tiles")),
                    ))
                    entry.cached_ns += _to_ns(self._delta(
                        prev, f"job:{job_id}:cached_s",
                        _as_float(stats.get("cached_s")),
                    ))
            waste = usage.get("waste_s")
            if isinstance(waste, dict):
                for reason in sorted(waste):
                    delta = self._delta(
                        prev, f"waste:{reason}", _as_float(waste[reason])
                    )
                    self._adopted_waste[str(reason)] = (
                        self._adopted_waste.get(str(reason), 0)
                        + _to_ns(delta)
                    )
            self._adopted_dispatch_ns += _to_ns(self._delta(
                prev, "dispatch_chip_s",
                _as_float(usage.get("dispatch_chip_s")),
            ))
            self._adopted_attributed_ns += _to_ns(self._delta(
                prev, "attributed_chip_s",
                _as_float(usage.get("attributed_chip_s")),
            ))
            self._adopted_overhead_ns += _to_ns(self._delta(
                prev, "overhead_s", _as_float(usage.get("overhead_s")),
            ))
            self._adopted_dispatches += int(self._delta(
                prev, "dispatches", _as_float(usage.get("dispatches")),
            ))
        return True

    def _adopted_job(self, job_id: str, now: float) -> _AdoptedJob:
        entry = self._adopted_jobs.get(job_id)
        if entry is None:
            if len(self._adopted_jobs) >= self.max_keys:
                victim = min(
                    self._adopted_jobs,
                    key=lambda j: self._adopted_jobs[j].last_active,
                )
                self._retire(victim, self._adopted_jobs.pop(victim))
            entry = _AdoptedJob()
            self._adopted_jobs[job_id] = entry
        entry.last_active = now
        return entry

    def _retire(self, job_id: str, entry: _AdoptedJob) -> None:
        tenant, lane = self.meter.job_attrs(job_id)
        key = (tenant, lane)
        if key not in self._retired and len(self._retired) >= self.max_keys:
            key = (DEFAULT_TENANT, "")
        bucket = self._retired.setdefault(
            key, {"chip_ns": 0, "tiles": 0, "steps": 0, "waste_ns": 0,
                  "cached_tiles": 0, "cached_ns": 0},
        )
        bucket["chip_ns"] += entry.chip_ns
        bucket["tiles"] += entry.tiles
        bucket["steps"] += entry.steps
        bucket["waste_ns"] += entry.waste_ns
        bucket["cached_tiles"] += entry.cached_tiles
        bucket["cached_ns"] += entry.cached_ns

    def forget_worker(self, worker_id: str) -> None:
        """Drop a departed worker's reset-clamp baselines (its adopted
        counters stay — usage already burned doesn't un-burn)."""
        with self._lock:
            self._worker_prev.pop(str(worker_id), None)

    # --- sampling (FleetRegistry.sample calls this) ------------------------

    def sample(self) -> dict[str, Any]:
        """One aggregation pass: update the tenant cost EWMAs, record
        the retained series, sweep idle entries, and return the rollup
        (published as the ``usage_rollup`` bus event)."""
        rollup = self.rollup()
        now = self.clock()
        with self._lock:
            self._update_cost_locked(rollup)
        if self.store is not None:
            for tenant in sorted(rollup["tenants"]):
                stats = rollup["tenants"][tenant]
                self.store.record(
                    S_TENANT_CHIP_S, stats["chip_s"], ts=now, tenant=tenant
                )
                self.store.record(
                    S_TENANT_TILES, stats["tiles"], ts=now, tenant=tenant
                )
            for reason in sorted(rollup["totals"]["waste_s"]):
                self.store.record(
                    S_WASTE_S, rollup["totals"]["waste_s"][reason],
                    ts=now, reason=reason,
                )
        self._sweep(now)
        return rollup

    def _update_cost_locked(self, rollup: dict[str, Any]) -> None:
        """Per-tenant chip-seconds-per-tile EWMA from the rollup's
        cumulative counters: each pass samples delta(chip)/delta(tiles)
        since the previous pass."""
        global_dchip = 0.0
        global_dtiles = 0.0
        for tenant in sorted(rollup["tenants"]):
            stats = rollup["tenants"][tenant]
            state = self._cost.setdefault(
                tenant, {"ewma": 0.0, "prev_chip_s": 0.0, "prev_tiles": 0.0}
            )
            dchip = max(0.0, stats["chip_s"] - state["prev_chip_s"])
            dtiles = max(0.0, stats["tiles"] - state["prev_tiles"])
            state["prev_chip_s"] = stats["chip_s"]
            state["prev_tiles"] = stats["tiles"]
            global_dchip += dchip
            global_dtiles += dtiles
            if dtiles > 0:
                sample = dchip / dtiles
                state["ewma"] = (
                    sample if state["ewma"] <= 0.0
                    else (1 - _EWMA_ALPHA) * state["ewma"]
                    + _EWMA_ALPHA * sample
                )
        if global_dtiles > 0:
            sample = global_dchip / global_dtiles
            self._cost_global = (
                sample if not self._cost_global
                else (1 - _EWMA_ALPHA) * self._cost_global
                + _EWMA_ALPHA * sample
            )

    def _sweep(self, now: float) -> None:
        """TTL eviction: fold idle adopted jobs into the retired
        aggregate and drop idle tenant cost entries, firing the series
        eviction seam for each departed tenant."""
        self.meter.sweep(self.ttl)
        departed: list[str] = []
        with self._lock:
            stale = sorted(
                j for j, e in self._adopted_jobs.items()
                if now - e.last_active > self.ttl
            )
            for job_id in stale:
                self._retire(job_id, self._adopted_jobs.pop(job_id))
            # a tenant with no surviving jobs in either source departs
            # the cost model (its series evict through the seam)
            live_tenants = {
                self.meter.job_attrs(j)[0]
                for j in list(self._adopted_jobs)
            }
        live_tenants |= {
            self.meter.job_attrs(j)[0]
            for j in self.meter.rollup()["jobs"]
        }
        with self._lock:
            for tenant in sorted(self._cost):
                if tenant not in live_tenants and tenant != DEFAULT_TENANT:
                    del self._cost[tenant]
                    departed.append(tenant)
        for tenant in departed:
            seam = self.on_evict_tenant
            if seam is not None:
                try:
                    seam(tenant)
                except Exception as exc:  # noqa: BLE001 - advisory seam
                    debug_log(f"usage tenant eviction seam failed: {exc}")

    # --- the measured cost model -------------------------------------------

    def cost_ratio(self, tenant: str) -> float:
        """Measured chip-s-per-tile of `tenant` relative to the fleet
        mean, clamped to [0.1, 10]; 1.0 until both EWMAs have samples.
        The CDT_USAGE_COST admission hook multiplies DRR cost by it."""
        with self._lock:
            state = self._cost.get(str(tenant))
            if (
                state is None
                or state["ewma"] <= 0.0
                or not self._cost_global
            ):
                return 1.0
            ratio = state["ewma"] / self._cost_global
        return min(COST_RATIO_MAX, max(COST_RATIO_MIN, ratio))

    # --- export -----------------------------------------------------------

    def rollup(self) -> dict[str, Any]:
        """Fleet-wide per-tenant/per-lane/per-job usage: the local
        meter's rollup plus the adopted worker counters, every job
        resolved through the meter's (store-fed) attrs map."""
        local = self.meter.rollup(roles=("master",))
        tenants = {
            t: dict(stats) for t, stats in local["tenants"].items()
        }
        lanes = {ln: dict(stats) for ln, stats in local["lanes"].items()}
        jobs = {j: dict(stats) for j, stats in local["jobs"].items()}
        with self._lock:
            adopted_jobs = sorted(self._adopted_jobs.items())
            adopted_waste = dict(self._adopted_waste)
            adopted_retired = {
                key: dict(bucket)
                for key, bucket in sorted(self._retired.items())
            }
            adopted = {
                "dispatch_ns": self._adopted_dispatch_ns,
                "attributed_ns": self._adopted_attributed_ns,
                "overhead_ns": self._adopted_overhead_ns,
                "dispatches": self._adopted_dispatches,
            }
        for job_id, entry in adopted_jobs:
            tenant, lane = self.meter.job_attrs(job_id)
            t = tenants.setdefault(
                tenant, {"chip_s": 0.0, "tiles": 0, "steps": 0,
                         "waste_s": 0.0, "cached_tiles": 0}
            )
            t["chip_s"] += _s(entry.chip_ns)
            t["tiles"] += entry.tiles
            t["steps"] += entry.steps
            t["waste_s"] += _s(entry.waste_ns)
            t["cached_tiles"] = t.get("cached_tiles", 0) + entry.cached_tiles
            ln = lanes.setdefault(lane, {"chip_s": 0.0, "tiles": 0})
            ln["chip_s"] += _s(entry.chip_ns)
            ln["tiles"] += entry.tiles
            job_out = jobs.setdefault(
                job_id,
                {"tenant": tenant, "lane": lane, "chip_s": 0.0, "tiles": 0,
                 "steps": 0, "waste_s": 0.0, "cached_tiles": 0,
                 "roles": []},
            )
            job_out["chip_s"] += _s(entry.chip_ns)
            job_out["tiles"] += entry.tiles
            job_out["steps"] += entry.steps
            job_out["waste_s"] += _s(entry.waste_ns)
            job_out["cached_tiles"] = (
                job_out.get("cached_tiles", 0) + entry.cached_tiles
            )
            if "worker(adopted)" not in job_out["roles"]:
                job_out["roles"].append("worker(adopted)")
        for (tenant, lane), bucket in adopted_retired.items():
            t = tenants.setdefault(
                tenant,
                {"chip_s": 0.0, "tiles": 0, "steps": 0, "waste_s": 0.0,
                 "cached_tiles": 0},
            )
            t["chip_s"] += _s(bucket["chip_ns"])
            t["tiles"] += bucket["tiles"]
            t["steps"] += bucket["steps"]
            t["waste_s"] += _s(bucket["waste_ns"])
            t["cached_tiles"] = (
                t.get("cached_tiles", 0) + bucket.get("cached_tiles", 0)
            )
            ln = lanes.setdefault(lane, {"chip_s": 0.0, "tiles": 0})
            ln["chip_s"] += _s(bucket["chip_ns"])
            ln["tiles"] += bucket["tiles"]
        totals = dict(local["totals"])
        totals["chip_s"] += _s(adopted["dispatch_ns"])
        totals["attributed_s"] += _s(adopted["attributed_ns"])
        totals["overhead_s"] += _s(adopted["overhead_ns"])
        totals["dispatches"] += adopted["dispatches"]
        totals["cached_tiles"] = totals.get("cached_tiles", 0) + sum(
            entry.cached_tiles for _, entry in adopted_jobs
        ) + sum(
            bucket.get("cached_tiles", 0)
            for bucket in adopted_retired.values()
        )
        totals["cached_s"] = totals.get("cached_s", 0.0) + _s(sum(
            entry.cached_ns for _, entry in adopted_jobs
        ) + sum(
            bucket.get("cached_ns", 0)
            for bucket in adopted_retired.values()
        ))
        waste_all = dict(totals["waste_s"])
        for reason, ns in sorted(adopted_waste.items()):
            waste_all[reason] = waste_all.get(reason, 0.0) + _s(ns)
        totals["waste_s"] = {r: waste_all[r] for r in sorted(waste_all)}
        total_chip = totals["chip_s"]
        for stats in tenants.values():
            stats["chip_share"] = (
                round(stats["chip_s"] / total_chip, 6) if total_chip else 0.0
            )
        dispatch_waste = sum(
            totals["waste_s"].get(r, 0.0) for r in DISPATCH_WASTE_REASONS
        )
        totals["waste_share"] = (
            round(dispatch_waste / total_chip, 6) if total_chip else 0.0
        )
        return {
            "tenants": {t: tenants[t] for t in sorted(tenants)},
            "lanes": {ln: lanes[ln] for ln in sorted(lanes)},
            "jobs": jobs,
            "totals": totals,
        }

    def pair_totals(self) -> dict[tuple[str, str], dict[str, float]]:
        """Monotonic cumulative (tenant, lane) -> {chip_s, tiles}: the
        local meter's master-role pairs plus adopted live AND retired
        counters. Job eviction moves counters between the live and
        retired folds without changing a pair's sum, so the scrape
        mirror's high-water deltas never undercount after a sweep."""
        out = self.meter.pair_totals(roles=("master",))
        with self._lock:
            live = [
                (job_id, entry.chip_ns, entry.tiles, entry.cached_tiles)
                for job_id, entry in sorted(self._adopted_jobs.items())
            ]
            retired = [
                (key, bucket["chip_ns"], bucket["tiles"],
                 bucket.get("cached_tiles", 0))
                for key, bucket in sorted(self._retired.items())
            ]
        for job_id, chip_ns, tiles, cached in live:
            pair = self.meter.job_attrs(job_id)
            agg = out.setdefault(
                pair, {"chip_s": 0.0, "tiles": 0.0, "cached": 0.0}
            )
            agg["chip_s"] += _s(chip_ns)
            agg["tiles"] += tiles
            agg["cached"] = agg.get("cached", 0.0) + cached
        for pair, chip_ns, tiles, cached in retired:
            agg = out.setdefault(
                pair, {"chip_s": 0.0, "tiles": 0.0, "cached": 0.0}
            )
            agg["chip_s"] += _s(chip_ns)
            agg["tiles"] += tiles
            agg["cached"] = agg.get("cached", 0.0) + cached
        return out

    def cost_snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "global_chip_s_per_tile": self._cost_global,
                "tenants": {
                    t: {
                        "chip_s_per_tile": state["ewma"],
                        "cost_ratio": None,
                    }
                    for t, state in sorted(self._cost.items())
                },
            }

    def status(
        self, since_s: Optional[float] = None, tenant: Optional[str] = None
    ) -> dict[str, Any]:
        """The GET /distributed/usage payload: rollup + per-tenant
        drill-down (+ windowed series history with ``?since=``)."""
        rollup = self.rollup()
        if tenant is not None:
            rollup["tenants"] = {
                t: s for t, s in rollup["tenants"].items() if t == tenant
            }
            rollup["jobs"] = {
                j: s for j, s in rollup["jobs"].items()
                if s.get("tenant") == tenant
            }
        cost = self.cost_snapshot()
        for t, entry in cost["tenants"].items():
            entry["cost_ratio"] = self.cost_ratio(t)
        out: dict[str, Any] = {
            "enabled": True,
            "rollup": rollup,
            "cost_model": cost,
            "conservation": self.meter.totals(),
        }
        if since_s is not None and self.store is not None:
            history: dict[str, Any] = {"tenants": {}, "waste": {}}
            for t in self.store.label_values(S_TENANT_CHIP_S, "tenant"):
                if tenant is not None and t != tenant:
                    continue
                history["tenants"][t] = {
                    S_TENANT_CHIP_S: self.store.window(
                        S_TENANT_CHIP_S, since_s, tenant=t
                    ),
                    S_TENANT_TILES: self.store.window(
                        S_TENANT_TILES, since_s, tenant=t
                    ),
                }
            for reason in self.store.label_values(S_WASTE_S, "reason"):
                history["waste"][reason] = self.store.window(
                    S_WASTE_S, since_s, reason=reason
                )
            out["history"] = history
            out["since_seconds"] = float(since_s)
        return out


def _as_float(value: Any) -> float:
    try:
        out = float(value)
    except (TypeError, ValueError):
        return 0.0
    return out if out == out and out not in (float("inf"), float("-inf")) else 0.0
