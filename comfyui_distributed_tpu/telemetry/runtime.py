"""JAX runtime health: compile activity, cache hits, HBM, host RSS.

Scrape-time collectors that put the *runtime* next to the *protocol*
on `/distributed/metrics`: a latency regression means nothing without
knowing whether the process was recompiling, missing the compilation
cache, or running the chip's HBM to the edge. The same snapshot is
stamped into `bench.py` output so every BENCH round carries its
profiling context.

Three sources, all optional and all failure-isolated:

- **jax.monitoring** — `install_jax_monitoring()` registers listeners
  for the backend-compile duration event and the compilation-cache
  hit/miss events. Installed once per process (idempotent), as early
  as possible (server start, bench init) so compiles are counted from
  the first program.
- **device.memory_stats()** — per-device HBM gauges
  (`bytes_in_use`, `peak_bytes_in_use`, `bytes_limit`, ...). Only
  consulted when jax is ALREADY imported: a metrics scrape must never
  be the thing that triggers backend init on a dark chip
  (docs/operator-runbook.md §4b). `CDT_RUNTIME_DEVICE_STATS=0`
  disables device enumeration at scrape entirely.
- **psutil** — host RSS of this process.

`ensure_runtime_collectors()` binds the scrape collector to the
CURRENT global registry (re-binding transparently after a test reset);
`runtime_snapshot()` returns the same numbers as a plain dict for
bench stamping.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any

from . import instruments
from .metrics import MetricsRegistry, get_metrics_registry

# Monotonic process-lifetime tallies filled by the jax.monitoring
# listeners; plain floats/ints guarded by a lock (listener callbacks
# can fire from compile threads).
_tallies_lock = threading.Lock()
_tallies = {
    "compiles": 0,
    "compile_time_s": 0.0,
    "cache_hits": 0,
    "cache_misses": 0,
}

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_monitoring_installed = False
_bound_registry: MetricsRegistry | None = None
_bind_lock = threading.Lock()


def install_jax_monitoring() -> bool:
    """Register jax.monitoring listeners for compile + cache events;
    idempotent; returns False when the API is unavailable."""
    global _monitoring_installed
    if _monitoring_installed:
        return True
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 - jax absent or too old
        return False

    def on_event(event: str, **kwargs: Any) -> None:
        with _tallies_lock:
            if event == _CACHE_HIT_EVENT:
                _tallies["cache_hits"] += 1
            elif event == _CACHE_MISS_EVENT:
                _tallies["cache_misses"] += 1

    def on_duration(event: str, duration: float, **kwargs: Any) -> None:
        if event == _BACKEND_COMPILE_EVENT:
            with _tallies_lock:
                _tallies["compiles"] += 1
                _tallies["compile_time_s"] += float(duration)

    try:
        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
    except Exception:  # noqa: BLE001 - listener API drift
        return False
    _monitoring_installed = True
    return True


def _host_rss_bytes() -> int | None:
    try:
        import psutil

        return int(psutil.Process().memory_info().rss)
    except Exception:  # noqa: BLE001 - psutil optional
        try:
            import resource

            # ru_maxrss is KiB on Linux (peak, not current — close enough
            # for a fallback gauge)
            return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
        except Exception:  # noqa: BLE001
            return None


def _device_memory() -> list[dict[str, Any]]:
    """Per-device memory stats, ONLY if jax is already initialized in
    this process (never trigger backend init from a scrape)."""
    if os.environ.get("CDT_RUNTIME_DEVICE_STATS", "1") == "0":
        return []
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001 - backend not ready
        return []
    out = []
    for device in devices:
        try:
            stats = device.memory_stats() or {}
        except Exception:  # noqa: BLE001 - CPU devices often raise
            stats = {}
        out.append(
            {
                "id": f"{device.platform}:{getattr(device, 'id', '?')}",
                "kind": str(getattr(device, "device_kind", "?")),
                "platform": device.platform,
                "memory": {k: v for k, v in stats.items() if isinstance(v, (int, float))},
            }
        )
    return out


def collect_runtime_gauges() -> None:
    """Scrape-time collector body: refresh the cdt_jax_* / host gauges
    from the monitoring tallies and live device state."""
    with _tallies_lock:
        snap = dict(_tallies)
    instruments.jax_compiles().set(snap["compiles"])
    instruments.jax_compile_time_seconds().set(snap["compile_time_s"])
    instruments.jax_cache_hits().set(snap["cache_hits"])
    instruments.jax_cache_misses().set(snap["cache_misses"])
    rss = _host_rss_bytes()
    if rss is not None:
        instruments.host_rss_bytes().set(rss)
    gauge = instruments.device_memory_bytes()
    gauge.clear()  # devices can disappear (tunnel drop); don't freeze stale series
    for device in _device_memory():
        for stat, value in device["memory"].items():
            gauge.set(value, device=device["id"], stat=stat)


def ensure_runtime_collectors() -> None:
    """Bind `collect_runtime_gauges` to the current global registry
    (idempotent per registry — a test reset re-binds on next call) and
    make sure the jax.monitoring listeners are installed."""
    global _bound_registry
    install_jax_monitoring()
    registry = get_metrics_registry()
    with _bind_lock:
        if _bound_registry is registry:
            return
        registry.register_collector(collect_runtime_gauges)
        _bound_registry = registry


def runtime_snapshot() -> dict[str, Any]:
    """The same runtime health numbers as a plain dict — stamped into
    bench.py's JSON datum so BENCH rounds carry profiling context."""
    with _tallies_lock:
        out: dict[str, Any] = dict(_tallies)
    out["compile_time_s"] = round(out["compile_time_s"], 3)
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            cache_dir = jax.config.jax_compilation_cache_dir
        except Exception:  # noqa: BLE001 - config name drift
            cache_dir = None
        if cache_dir:
            # the hit/miss tallies above say whether it actually helped
            out["compile_cache_dir"] = cache_dir
    rss = _host_rss_bytes()
    if rss is not None:
        out["host_rss_bytes"] = rss
    devices = _device_memory()
    if devices:
        out["devices"] = devices
    return out


def reset_runtime_tallies() -> None:
    """Zero the monitoring tallies (tests)."""
    with _tallies_lock:
        for key in _tallies:
            _tallies[key] = 0 if key != "compile_time_s" else 0.0
