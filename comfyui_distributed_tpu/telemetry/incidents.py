"""Incident debug bundles: auto-captured, bounded, self-describing.

When something breaks — a burn-rate alert fires (telemetry/slo.py), a
poison tile is quarantined, a job blows its end-to-end deadline, a
standby promotes — the operator needs "what was the system doing", and
by then the live surfaces have moved on. The `IncidentManager` closes
that gap: on a trigger it snapshots everything the master knows into
ONE atomically-written JSON bundle under ``CDT_INCIDENT_DIR``:

- the flight recorder's event + span rings (telemetry/flight.py) — the
  window of history from BEFORE the trigger;
- the implicated execution's trace spans (tracer retention);
- the fleet registry's windowed history around the trigger
  (``CDT_INCIDENT_WINDOW`` of `?since=`-style series, per worker);
- the SLO engine's rule evaluations + transition history;
- health-registry breaker states and placement weights/capacity;
- the resolved ``CDT_*`` knob snapshot (utils/knob_registry);
- durability/role status and job-store depth stats.

Safety properties (the reason this is not just "dump some JSON"):

- **off the serving loop**: `trigger()` is a debounce check + queue
  put; the gather/serialize/fsync runs on a dedicated single-flight
  writer thread (the PR 7 snapshot-writer idiom), so an alert storm
  can never stall an await point;
- **trigger-keyed debounce + global rate limit**: a re-firing alert
  inside ``CDT_INCIDENT_DEBOUNCE`` captures nothing, and ANY two
  automatic captures are at least ``CDT_INCIDENT_MIN_INTERVAL`` apart
  (both windows are reserved at enqueue time, so a storm racing the
  writer cannot enqueue duplicates);
- **bounded retention**: oldest bundles are pruned beyond
  ``CDT_INCIDENT_MAX`` files / ``CDT_INCIDENT_MAX_MB`` total;
- **atomic writes**: `utils/fsio.atomic_write_bytes` — a reader (or a
  crash) never observes a torn bundle.

Surfaces: ``GET /distributed/incidents`` (+ ``/{id}``,
``POST .../capture``) in api/incident_routes.py, an
``incident_captured`` bus event feeding the web panel's Incidents
card, and ``scripts/incident_report.py`` — the offline critical-path
analyzer that reads a bundle with the process long dead.
"""

from __future__ import annotations

import json
import os
import queue as queue_mod
import re
import threading
import time
from typing import Any, Callable, Optional

from ..utils import constants
from ..utils.fsio import atomic_write_bytes
from ..utils.logging import debug_log, log

BUNDLE_SCHEMA_VERSION = 1

# Trigger vocabulary (docs/observability.md documents the table).
TRIGGER_ALERT = "alert_fired"
TRIGGER_POISON = "tile_quarantined"
TRIGGER_DEADLINE = "job_deadline"
TRIGGER_FAILOVER = "failover"
TRIGGER_MANUAL = "manual"

BUNDLE_PREFIX = "incident-"
BUNDLE_SUFFIX = ".json"
# seq pads to 4 digits but keeps growing past 9999 ('{:04d}' widens),
# so the grammar accepts 4+ — a long-lived master's bundle 10000 must
# stay fetchable and schema-valid
_BUNDLE_ID_RE = re.compile(r"incident-\d{13}-\d{4,}-[a-z0-9_]+")
_KIND_SAFE_RE = re.compile(r"[^a-z0-9_]+")

# Debounce map bound: trigger keys ride unauthenticated event payloads
# (job ids), so the map must not grow without bound.
MAX_DEBOUNCE_KEYS = 256

# Bound on trace spans copied into a bundle (a 20k-span trace would
# dominate the size budget; the newest spans carry the incident).
MAX_TRACE_SPANS = 4000


class CaptureRequest:
    __slots__ = ("kind", "key", "context", "ts", "manual")

    def __init__(self, kind, key, context, ts, manual):
        self.kind = kind
        self.key = key
        self.context = context
        self.ts = ts
        self.manual = manual


class IncidentManager:
    """Trigger-driven debug-bundle capture with bounded retention."""

    def __init__(
        self,
        directory: str,
        *,
        clock: Callable[[], float] = time.time,
        debounce_s: Optional[float] = None,
        min_interval_s: Optional[float] = None,
        max_bundles: Optional[int] = None,
        max_bytes: Optional[float] = None,
        window_s: Optional[float] = None,
    ) -> None:
        self.directory = directory
        self.clock = clock
        self.debounce_s = (
            debounce_s if debounce_s is not None
            else constants.INCIDENT_DEBOUNCE_SECONDS
        )
        self.min_interval_s = (
            min_interval_s if min_interval_s is not None
            else constants.INCIDENT_MIN_INTERVAL_SECONDS
        )
        self.max_bundles = (
            max_bundles if max_bundles is not None
            else constants.INCIDENT_MAX_BUNDLES
        )
        # max_bytes is taken literally in BYTES when passed (tests pin
        # small budgets); the knob is operator-facing megabytes
        self.max_bytes = (
            int(max_bytes)
            if max_bytes is not None
            else int(constants.INCIDENT_MAX_MB * 1024 * 1024)
        )
        self.window_s = (
            window_s if window_s is not None
            else constants.INCIDENT_WINDOW_SECONDS
        )
        # Named zero-arg callables, each producing one JSON-able bundle
        # section; a failing source degrades to {"error": ...}, never
        # the whole capture. `bind_server` wires the standard set.
        self.sources: dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()
        self._debounce: dict[str, float] = {}
        self._last_capture_ts: Optional[float] = None
        self._seq = 0
        self._queue: "queue_mod.Queue[Optional[CaptureRequest]]" = (
            queue_mod.Queue(maxsize=4)
        )
        self._inflight = 0
        # serializes bundle builds: the writer thread AND a manual
        # capture_now (run off-loop by the route) go through it —
        # single-flight, the PR 7 snapshot-writer idiom
        self._capture_lock = threading.Lock()
        self._writer: Optional[threading.Thread] = None
        self._remove_tap: Optional[Callable[[], None]] = None
        self._closed = False
        self.counters = {
            "captured": 0,
            "debounced": 0,
            "rate_limited": 0,
            "overflow": 0,
            "errors": 0,
        }

    # --- wiring -----------------------------------------------------------

    def bind_server(self, server: Any) -> None:
        """Attach the standard master-side sources (every read is a
        thread-safe snapshot on the owning structure)."""
        from ..resilience.health import get_health_registry

        label = f"{'worker' if server.is_worker else 'master'}:{server.port}"
        self.sources["server"] = lambda: {"label": label, "pid": os.getpid()}
        self.sources["store"] = server.job_store.stats_unlocked
        scheduler = getattr(server, "scheduler", None)
        if scheduler is not None:
            self.sources["placement"] = scheduler.placement.snapshot
            self.sources["scheduler"] = lambda: {
                "state": scheduler.queue.state,
                "totals": dict(scheduler.queue.totals),
                "brownout": scheduler.brownout.signals(),
            }
        self.sources["health"] = lambda: get_health_registry().snapshot()
        fleet = getattr(server, "fleet", None)
        if fleet is not None:
            self.sources["fleet"] = (
                lambda: fleet.status(since_s=self.window_s)
            )
            usage = getattr(fleet, "usage", None)
            if usage is not None:
                # chip-time attribution at capture time: per-tenant
                # burn + waste breakdown + the conservation identity —
                # "who was burning the fleet when this fired"
                self.sources["usage"] = (
                    lambda: usage.status(since_s=self.window_s)
                )
        slo = getattr(server, "slo", None)
        if slo is not None:
            self.sources["slo"] = slo.status
        durability = getattr(server, "durability", None)
        if durability is not None:
            self.sources["durability"] = durability.status

    def start(self, bus: Any = None) -> None:
        """Start the writer thread and install the trigger tap on the
        event bus (alert_fired / tile_quarantined / deadline cancel /
        failover become automatic captures)."""
        self._closed = False
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, name="cdt-incident-writer",
                daemon=True,
            )
            self._writer.start()
        if self._remove_tap is None:
            from .events import get_event_bus

            bus = bus if bus is not None else get_event_bus()
            self._remove_tap = bus.add_tap(self._bus_tap, name="incidents")

    def stop(self) -> None:
        remove, self._remove_tap = self._remove_tap, None
        if remove is not None:
            remove()
        self._closed = True
        writer, self._writer = self._writer, None
        if writer is not None and writer.is_alive():
            self._queue.put(None)
            writer.join(timeout=10)

    # --- triggers ---------------------------------------------------------

    def _bus_tap(self, event: dict[str, Any]) -> None:
        """Synchronous bus tap: map trigger-class events onto capture
        requests. Must stay cheap — a debounce check and a queue put."""
        etype = event.get("type")
        data = event.get("data") or {}
        if etype == "alert_fired":
            self.trigger(TRIGGER_ALERT, key=str(data.get("slo", "")), context=data)
        elif etype == "tile_quarantined":
            self.trigger(
                TRIGGER_POISON, key=str(data.get("job_id", "")), context=data
            )
        elif etype == "job_cancelled" and data.get("reason") == "deadline":
            self.trigger(
                TRIGGER_DEADLINE, key=str(data.get("job_id", "")), context=data
            )
        elif etype == "failover":
            self.trigger(
                TRIGGER_FAILOVER, key=str(data.get("epoch", "")), context=data
            )

    def trigger(
        self,
        kind: str,
        key: str = "",
        context: Optional[dict] = None,
        manual: bool = False,
    ) -> str:
        """Request a capture; returns the disposition:
        ``queued | debounced | rate_limited | overflow | closed``.
        Never blocks, never raises — safe from the serving loop, bus
        taps, and chaos harness threads alike. Debounce + rate-limit
        windows are reserved HERE (not at write time) so a trigger
        storm racing the writer cannot enqueue duplicates; manual
        captures bypass both windows but still serialize through the
        single-flight writer."""
        if self._closed:
            return "closed"
        now = self.clock()
        debounce_key = f"{kind}:{key}"
        with self._lock:
            if not manual:
                last_any = self._last_capture_ts
                if (
                    last_any is not None
                    and now - last_any < self.min_interval_s
                ):
                    self.counters["rate_limited"] += 1
                    return "rate_limited"
                last = self._debounce.get(debounce_key)
                if last is not None and now - last < self.debounce_s:
                    # touch: a key still actively firing moves to the
                    # dict's end (window timestamp unchanged), so the
                    # bounded map evicts idle keys first, never one
                    # that is mid-storm
                    self._debounce.pop(debounce_key)
                    self._debounce[debounce_key] = last
                    self.counters["debounced"] += 1
                    return "debounced"
            prev_key_ts = self._debounce.pop(debounce_key, None)
            prev_any_ts = self._last_capture_ts
            while len(self._debounce) >= MAX_DEBOUNCE_KEYS:
                # least-recently-RESERVED first: the pop-reinsert above
                # keeps live keys at the dict's end, so a key-churn
                # storm evicts stale keys, never a just-reserved one
                self._debounce.pop(next(iter(self._debounce)))
            self._debounce[debounce_key] = now
            self._last_capture_ts = now
            self._inflight += 1
        request = CaptureRequest(kind, key, dict(context or {}), now, manual)
        try:
            self._queue.put_nowait(request)
        except queue_mod.Full:
            with self._lock:
                self.counters["overflow"] += 1
                self._inflight -= 1
                # roll the reservations back: NO capture happened, so
                # the next trigger of this key must not read as
                # debounced/rate-limited against a phantom one
                if self._debounce.get(debounce_key) == now:
                    if prev_key_ts is not None:
                        self._debounce[debounce_key] = prev_key_ts
                    else:
                        self._debounce.pop(debounce_key, None)
                if self._last_capture_ts == now:
                    self._last_capture_ts = prev_any_ts
            return "overflow"
        return "queued"

    def capture_now(
        self, kind: str = TRIGGER_MANUAL, key: str = "",
        context: Optional[dict] = None,
    ) -> dict[str, Any]:
        """Synchronous capture on the CALLING thread (the manual-POST
        route runs this via run_blocking; bench runs it inline on a
        probe crash). Serialized with the writer thread through the
        capture lock; bypasses debounce/rate-limit but records into
        both windows."""
        now = self.clock()
        debounce_key = f"{kind}:{key}"
        with self._lock:
            # same bounded-map discipline as trigger(): manual keys
            # arrive on an unauthenticated POST and must not grow the
            # debounce map without limit
            self._debounce.pop(debounce_key, None)
            while len(self._debounce) >= MAX_DEBOUNCE_KEYS:
                self._debounce.pop(next(iter(self._debounce)))
            self._debounce[debounce_key] = now
            self._last_capture_ts = now
        request = CaptureRequest(kind, key, dict(context or {}), now, True)
        try:
            return self._capture(request)
        except Exception:
            self._rollback_reservation(request)
            raise

    # --- the writer -------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            request = self._queue.get()
            if request is None:
                return
            try:
                self._capture(request)
            except Exception as exc:  # noqa: BLE001 - writer survives
                with self._lock:
                    self.counters["errors"] += 1
                # a capture that produced NO bundle must not hold its
                # windows: the incident that most needs forensics
                # would otherwise read as debounced for the full
                # window while nothing is on disk
                self._rollback_reservation(request)
                debug_log(f"incident capture failed: {exc}")
            finally:
                with self._lock:
                    if self._inflight > 0:
                        self._inflight -= 1

    def _rollback_reservation(self, request: CaptureRequest) -> None:
        """Release the debounce + rate-limit windows a FAILED capture
        reserved (only if no newer reservation replaced them)."""
        debounce_key = f"{request.kind}:{request.key}"
        with self._lock:
            if self._debounce.get(debounce_key) == request.ts:
                self._debounce.pop(debounce_key, None)
            if self._last_capture_ts == request.ts:
                self._last_capture_ts = None

    def flush(self, timeout: float = 10.0) -> bool:
        """Barrier for tests/CI: wait until every queued capture has
        been written (or the timeout passes)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = self._inflight == 0 and self._queue.empty()
            if idle:
                return True
            time.sleep(0.01)
        return False

    def _capture(self, request: CaptureRequest) -> dict[str, Any]:
        from . import instruments

        started = time.perf_counter()
        with self._capture_lock:
            with self._lock:
                self._seq += 1
                seq = self._seq
            bundle = self._build_bundle(request, seq)
            path = os.path.join(self.directory, bundle["id"] + BUNDLE_SUFFIX)
            payload = json.dumps(
                bundle, sort_keys=True, default=str
            ).encode("utf-8")
            atomic_write_bytes(path, payload)
            self._prune()
        elapsed = time.perf_counter() - started
        with self._lock:
            self.counters["captured"] += 1
        try:
            instruments.incidents_total().inc(trigger=request.kind)
            instruments.incident_capture_seconds().observe(elapsed)
        except Exception:  # noqa: BLE001 - accounting is best effort
            pass
        from .events import get_event_bus

        try:
            get_event_bus().publish(
                "incident_captured",
                id=bundle["id"],
                trigger=request.kind,
                key=request.key,
                path=path,
                bytes=len(payload),
            )
        except Exception:  # noqa: BLE001 - push side is best effort
            pass
        log(
            f"incident bundle {bundle['id']} captured "
            f"({request.kind}:{request.key}, {len(payload)} bytes, "
            f"{elapsed * 1000:.1f} ms)"
        )
        return {"id": bundle["id"], "path": path, "bytes": len(payload)}

    def _build_bundle(
        self, request: CaptureRequest, seq: int
    ) -> dict[str, Any]:
        kind_safe = _KIND_SAFE_RE.sub("_", request.kind.lower()) or "unknown"
        bundle: dict[str, Any] = {
            "schema": BUNDLE_SCHEMA_VERSION,
            "id": f"incident-{int(request.ts * 1000):013d}-{seq:04d}-{kind_safe}",
            "captured_at": self.clock(),
            "trigger": {
                "kind": request.kind,
                "key": request.key,
                "ts": request.ts,
                "manual": request.manual,
                "context": request.context,
            },
            "flight": self._flight_section(),
            "trace": self._trace_section(request.context),
            "knobs": resolved_knobs(),
            "counters": dict(self.counters),
        }
        for name, source in self.sources.items():
            try:
                bundle[name] = source()
            except Exception as exc:  # noqa: BLE001 - degrade per section
                bundle[name] = {"error": f"{type(exc).__name__}: {exc}"}
        profile = self._profile_section(kind_safe)
        if profile is not None:
            bundle["profile"] = profile
        return bundle

    def _profile_section(self, kind_safe: str) -> Optional[dict[str, Any]]:
        """Auto device-trace capture riding the incident (CDT_PROFILE_AUTO):
        grab a short bounded jax.profiler trace on the writer thread so
        the bundle points at a device-level view of the bad moment.
        Requires CDT_PROFILE_DIR; a busy profiler (operator capture in
        flight) degrades to the refusal record, never an error."""
        if not constants.PROFILE_AUTO_ENABLED:
            return None
        try:
            from .profiling import get_profiler_capture

            capture = get_profiler_capture()
            if capture is None:
                return {"error": "CDT_PROFILE_AUTO set without CDT_PROFILE_DIR"}
            started = capture.start(
                duration_s=constants.PROFILE_AUTO_SECONDS,
                tag=f"auto-{kind_safe}",
            )
            if not started.get("started"):
                return {"skipped": started.get("reason", "unavailable")}
            time.sleep(constants.PROFILE_AUTO_SECONDS)
            stopped = capture.stop()
            return {"started": started, "stopped": stopped}
        except Exception as exc:  # noqa: BLE001 - degrade per section
            return {"error": f"{type(exc).__name__}: {exc}"}

    def _flight_section(self) -> dict[str, Any]:
        from .flight import peek_flight_recorder

        recorder = peek_flight_recorder()
        if recorder is None:
            return {"enabled": False, "events": [], "spans": [],
                    "dropped": {"events": 0, "spans": 0}}
        dump = recorder.dump()
        dump["enabled"] = True
        return dump

    def _trace_section(self, context: dict) -> Optional[dict[str, Any]]:
        """The implicated execution's spans: the context's trace id
        when the trigger named one, else the most recently active
        trace (bounded copy)."""
        from .tracing import get_tracer

        tracer = get_tracer()
        trace_id = context.get("trace_id")
        if not trace_id:
            ids = tracer.trace_ids()
            trace_id = ids[-1] if ids else None
        if not trace_id:
            return None
        spans = tracer.spans(str(trace_id))
        truncated = max(0, len(spans) - MAX_TRACE_SPANS)
        if truncated:
            spans = spans[-MAX_TRACE_SPANS:]
        return {
            "trace_id": str(trace_id),
            "spans": spans,
            "truncated_spans": truncated,
        }

    # --- retention / listing ----------------------------------------------

    def _bundle_files(self) -> list[tuple[str, str]]:
        """(name, path) pairs, oldest first — names embed a zero-padded
        millisecond stamp + sequence, so lexical order IS capture
        order (never readdir order)."""
        try:
            names = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return []
        return [
            (name, os.path.join(self.directory, name))
            for name in names
            if name.startswith(BUNDLE_PREFIX) and name.endswith(BUNDLE_SUFFIX)
        ]

    def _prune(self) -> None:
        files = self._bundle_files()
        sizes: dict[str, int] = {}
        for _name, path in files:
            try:
                sizes[path] = os.path.getsize(path)
            except OSError:
                sizes[path] = 0
        total = sum(sizes.values())
        # prune-oldest, but NEVER the newest bundle — the capture that
        # just happened must survive even a pathological byte budget
        while len(files) > 1 and (
            len(files) > self.max_bundles
            or (self.max_bytes > 0 and total > self.max_bytes)
        ):
            _name, oldest = files.pop(0)
            total -= sizes.get(oldest, 0)
            try:
                os.remove(oldest)
            except OSError as exc:
                debug_log(f"incident prune of {oldest} failed: {exc}")

    def list_bundles(self) -> list[dict[str, Any]]:
        """Newest-first listing without opening the files: id, trigger
        kind (from the filename), capture timestamp, size."""
        out = []
        for name, path in reversed(self._bundle_files()):
            bundle_id = name[: -len(BUNDLE_SUFFIX)]
            parts = bundle_id.split("-", 3)
            ts_ms = 0
            kind = "unknown"
            if len(parts) == 4:
                try:
                    ts_ms = int(parts[1])
                except ValueError:
                    ts_ms = 0
                kind = parts[3]
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            out.append(
                {
                    "id": bundle_id,
                    "trigger": kind,
                    "ts": ts_ms / 1000.0,
                    "bytes": size,
                }
            )
        return out

    def read_bundle(self, bundle_id: str) -> Optional[dict[str, Any]]:
        """Load one bundle by id; None for unknown/invalid ids (the id
        grammar is validated so a hostile id can never traverse out of
        the incident directory)."""
        if not _BUNDLE_ID_RE.fullmatch(bundle_id):
            return None
        path = os.path.join(self.directory, bundle_id + BUNDLE_SUFFIX)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None

    def status(self) -> dict[str, Any]:
        with self._lock:
            counters = dict(self.counters)
            inflight = self._inflight
        return {
            "directory": self.directory,
            "debounce_s": self.debounce_s,
            "min_interval_s": self.min_interval_s,
            "max_bundles": self.max_bundles,
            "max_bytes": self.max_bytes,
            "counters": counters,
            "inflight": inflight,
        }


# --- knob snapshot -----------------------------------------------------------


def resolved_knobs() -> dict[str, dict[str, Any]]:
    """Every registered CDT_* knob with its RESOLVED value: the env
    value when set, the registry's rendered default otherwise — the
    bundle answers "what was this process actually configured as"
    without shipping the whole environ (no secrets beyond CDT_*)."""
    from ..utils.knob_registry import KNOBS

    out: dict[str, dict[str, Any]] = {}
    for knob in KNOBS:
        raw = os.environ.get(knob.name)
        out[knob.name] = {
            "value": raw if raw is not None else knob.default,
            "set": raw is not None,
        }
    return out


# --- bundle schema validation ------------------------------------------------

# Minimal JSON-schema-style description of a bundle (documented in
# docs/observability.md §Incidents; validate_bundle enforces it and CI
# runs it against the chaos-captured bundle).
BUNDLE_SCHEMA: dict[str, Any] = {
    "schema": int,
    "id": str,
    "captured_at": (int, float),
    "trigger": {
        "kind": str,
        "key": str,
        "ts": (int, float),
        "manual": bool,
        "context": dict,
    },
    "flight": {
        "events": list,
        "spans": list,
        "dropped": dict,
    },
    "knobs": dict,
    "counters": dict,
}


def _check(node: Any, spec: Any, path: str, problems: list[str]) -> None:
    if isinstance(spec, dict):
        if not isinstance(node, dict):
            problems.append(f"{path}: expected object, got {type(node).__name__}")
            return
        for key, sub in spec.items():
            if key not in node:
                problems.append(f"{path}.{key}: missing")
            else:
                _check(node[key], sub, f"{path}.{key}", problems)
    else:
        if not isinstance(node, spec):
            expected = (
                "/".join(t.__name__ for t in spec)
                if isinstance(spec, tuple)
                else spec.__name__
            )
            problems.append(
                f"{path}: expected {expected}, got {type(node).__name__}"
            )


def validate_bundle(bundle: Any) -> list[str]:
    """Structural validation against BUNDLE_SCHEMA; returns problems
    (empty = valid). Also checks the id grammar and schema version."""
    problems: list[str] = []
    if not isinstance(bundle, dict):
        return [f"bundle: expected object, got {type(bundle).__name__}"]
    _check(bundle, BUNDLE_SCHEMA, "bundle", problems)
    schema = bundle.get("schema")
    if isinstance(schema, int) and schema != BUNDLE_SCHEMA_VERSION:
        problems.append(
            f"bundle.schema: version {schema} != supported "
            f"{BUNDLE_SCHEMA_VERSION}"
        )
    bundle_id = bundle.get("id")
    if isinstance(bundle_id, str) and not _BUNDLE_ID_RE.fullmatch(bundle_id):
        problems.append(f"bundle.id: {bundle_id!r} does not match the grammar")
    return problems
