"""Fleet observability: worker telemetry aggregation on the master.

The telemetry core (PRs 2-3) is strictly per-process — every master
and worker has its own registry, and the master can see nothing about
the fleet it schedules beyond breaker states and placement weights.
This module is the master-side signal plane:

- **workers produce** a compact, versioned metrics snapshot
  (`local_snapshot()`: tile-stage p50/p95, tiles processed, pipeline
  inflight, `cdt_jax_*` compile/cache tallies, HBM watermark + host
  RSS from telemetry/runtime.py, mesh shape/device count) and
  piggyback it onto the heartbeat / `request_image` RPCs they already
  send (graph/usdu_elastic.HTTPWorkClient) — no new RPC, no new
  socket, at most one snapshot per `CDT_FLEET_SNAPSHOT_SECONDS`;

- the **`FleetRegistry`** on the master validates the snapshot version,
  merges per-worker state, derives tiles/sec rates from successive
  snapshots (master clock, never the worker's), retains the
  load-bearing series in a two-tier `SeriesStore`
  (telemetry/timeseries.py), and rolls the fleet up: worker/device
  counts, aggregate tiles/sec (and per chip), stage-p95 envelope,
  compile/cache totals, memory watermarks;

- `sample()` adds the **master-side** series the ROADMAP autoscaling
  item needs: queue-wait p95 (the brownout controller's wait window),
  journal-append p95, per-worker speed EWMAs + grant capacity from
  scheduler/placement.py, deadline-miss and shed counters — and feeds
  the cumulative admission/deadline counters into the SLO engine
  (telemetry/slo.py).

Eviction: a worker that stops snapshotting for `CDT_FLEET_TTL` seconds
— or that the placement policy / health registry forgets — has ALL its
per-worker series dropped (`forget_worker`), and the registry tracks
at most `MAX_TRACKED_WORKERS` (the PR 8 placement bound): snapshots
ride unauthenticated RPCs, so a worker-id churn storm must not grow
master memory (regression-tested with 1024 churning fake workers in
tests/test_fleet_registry.py).

Served by `GET /distributed/fleet` (rollups + per-worker drill-down +
`?since=` windowed history) and pushed as `fleet_rollup` events on the
process bus for the web panel's fleet card.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..utils import constants
from ..utils.logging import debug_log
from .timeseries import SeriesStore

# Snapshot wire-format version: the master ignores snapshots whose
# major version it does not speak (a newer worker against an older
# master degrades to "no fleet telemetry", never to a parse error).
# v2 (usage-metering PR) adds the cumulative `usage` block; v1
# snapshots stay accepted — the merge is version-gated, so an older
# worker degrades to "no usage telemetry", never to a drop.
# v3 (profiling PR) adds the cumulative `profiling` transfer-ledger
# block; same degradation rule (older worker = no host-tax telemetry).
SNAPSHOT_VERSION = 3
ACCEPTED_SNAPSHOT_VERSIONS = (1, 2, 3)

# Same bound the placement policy applies to advertised capacity
# (scheduler/placement.py): snapshots arrive on unauthenticated RPCs.
MAX_TRACKED_WORKERS = 1024

# Series names (label vocabulary: worker_id only — stage breakdowns
# stay in the latest-snapshot drill-down, not in retained series, so
# worker churn costs O(workers), not O(workers x stages)).
S_QUEUE_WAIT_P95 = "fleet_queue_wait_p95"
S_JOURNAL_P95 = "fleet_journal_p95"
S_TILES_PER_S = "fleet_tiles_per_s"
S_TILES_PER_CHIP_S = "fleet_tiles_per_chip_s"
S_DEADLINE_MISS = "fleet_deadline_miss_total"
S_SHED = "fleet_shed_total"
S_WORKER_TILES_PER_S = "fleet_worker_tiles_per_s"
S_WORKER_SPEED = "fleet_worker_speed_ratio"
S_WORKER_DEVICES = "fleet_worker_devices"

# The windowed-history series /distributed/fleet?since= serves.
HISTORY_SERIES = (
    S_QUEUE_WAIT_P95,
    S_JOURNAL_P95,
    S_TILES_PER_S,
    S_TILES_PER_CHIP_S,
    S_DEADLINE_MISS,
    S_SHED,
)
WORKER_HISTORY_SERIES = (
    S_WORKER_TILES_PER_S,
    S_WORKER_SPEED,
    S_WORKER_DEVICES,
)


# --- worker side: snapshot production --------------------------------------


def local_snapshot(role: str = "worker") -> dict[str, Any]:
    """Build this process's compact telemetry snapshot from the global
    registry + runtime tallies. Pure read — never triggers backend
    init (the runtime collectors' own guarantee). Shape documented in
    docs/observability.md §Fleet."""
    from . import instruments
    from .metrics import histogram_quantile

    snap: dict[str, Any] = {"v": SNAPSHOT_VERSION, "role": role}
    # per-stage latency quantiles from the local stage histogram
    stages: dict[str, dict[str, float]] = {}
    hist = instruments.tile_stage_seconds()
    for key, data in hist.series_snapshot().items():
        stage, sample_role = key
        if sample_role != role or not data["count"]:
            continue
        stages[stage] = {
            "p50": histogram_quantile(
                hist.bounds, data["buckets"], data["count"], 0.5
            ),
            "p95": histogram_quantile(
                hist.bounds, data["buckets"], data["count"], 0.95
            ),
            "count": data["count"],
        }
    snap["stages"] = stages
    snap["tiles_total"] = instruments.tiles_processed_total().value(role=role)
    snap["inflight"] = instruments.pipeline_inflight().value(role=role)
    # JAX runtime health (compiles/cache tallies, HBM watermark, RSS)
    try:
        from .runtime import runtime_snapshot

        rt = runtime_snapshot()
    except Exception:  # noqa: BLE001 - telemetry is best effort
        rt = {}
    snap["jax"] = {
        k: rt.get(k, 0)
        for k in ("compiles", "compile_time_s", "cache_hits", "cache_misses")
    }
    hbm_peak = 0
    for device in rt.get("devices", []) or []:
        memory = device.get("memory") or {}
        hbm_peak = max(
            hbm_peak,
            int(memory.get("peak_bytes_in_use")
                or memory.get("bytes_in_use") or 0),
        )
    snap["mem"] = {
        "hbm_peak_bytes": hbm_peak,
        "rss_bytes": int(rt.get("host_rss_bytes") or 0),
    }
    try:
        from ..parallel.mesh import serving_mesh_summary

        mesh = serving_mesh_summary()
        snap["mesh"] = dict(mesh)
        snap["devices"] = int(mesh.get("total") or mesh.get("data") or 1)
    except Exception:  # noqa: BLE001 - mesh resolution is advisory
        snap["mesh"] = {}
        snap["devices"] = 1
    # v2: this process's cumulative chip-time attribution (the master
    # adopts it by delta with a counter-reset clamp)
    if constants.USAGE_ENABLED:
        try:
            from .usage import get_usage_meter

            snap["usage"] = get_usage_meter().snapshot(role=role)
        except Exception:  # noqa: BLE001 - usage block is advisory
            pass
    # v3: this process's cumulative transfer ledger (device/host split
    # + bytes moved); rollup sums the raw cumulative blocks — host-tax
    # is recomputed fleet-wide from the summed ns, not averaged.
    if constants.PROFILING_ENABLED:
        try:
            from .profiling import get_transfer_ledger

            snap["profiling"] = get_transfer_ledger().snapshot(role=role)
        except Exception:  # noqa: BLE001 - profiling block is advisory
            pass
    return snap


# --- master side: the registry ---------------------------------------------


class FleetRegistry:
    """Per-worker snapshot merge + fleet rollups + series retention."""

    def __init__(
        self,
        store: Optional[SeriesStore] = None,
        clock: Callable[[], float] = time.time,
        ttl: Optional[float] = None,
        max_workers: int = MAX_TRACKED_WORKERS,
    ) -> None:
        self.clock = clock
        self.store = store if store is not None else SeriesStore(clock=clock)
        self.ttl = ttl if ttl is not None else constants.FLEET_TTL_SECONDS
        self.max_workers = int(max_workers)
        # chip-time attribution plane (telemetry/usage.py): adopts the
        # v2 snapshots' usage blocks, retains per-tenant series in the
        # SAME store, and serves GET /distributed/usage. None when
        # CDT_USAGE=0.
        self.usage: Optional[Any] = None
        if constants.USAGE_ENABLED:
            from .usage import UsageAggregator

            self.usage = UsageAggregator(store=self.store, clock=clock)
            self.usage.on_evict_tenant = (
                lambda tenant: self.store.evict_label("tenant", tenant)
            )
        self._lock = threading.Lock()
        # worker_id -> {"snap", "seen", "rate", "prev_tiles", "prev_ts"}
        self._workers: dict[str, dict[str, Any]] = {}
        # master-side sources (bound once by the server)
        self._scheduler: Any = None
        self._job_store: Any = None
        self._slo: Any = None
        # master's own tiles counter baseline for its rate sample
        self._master_prev: Optional[tuple[float, float]] = None
        self._last_rollup: dict[str, Any] = {}

    # --- wiring -----------------------------------------------------------

    def bind_master(
        self, scheduler: Any = None, job_store: Any = None, slo: Any = None
    ) -> None:
        """Attach the master-side signal sources `sample()` reads:
        the scheduler control (brownout windows, placement weights,
        admission totals), the job store (depth stats), and the SLO
        engine the sampled counters feed."""
        self._scheduler = scheduler
        self._job_store = job_store
        self._slo = slo

    # --- worker snapshots --------------------------------------------------

    def note_snapshot(self, worker_id: str, snap: Any) -> bool:
        """Merge one piggybacked worker snapshot; returns False (and
        counts the drop) for malformed payloads, unknown versions, or a
        new worker beyond the tracking bound with nothing to evict."""
        from . import instruments

        worker_id = str(worker_id)
        if not isinstance(snap, dict):
            instruments.fleet_snapshots_total().inc(outcome="malformed")
            return False
        try:
            version = int(snap.get("v"))
        except (TypeError, ValueError):
            version = -1
        if version not in ACCEPTED_SNAPSHOT_VERSIONS:
            instruments.fleet_snapshots_total().inc(outcome="bad_version")
            return False
        now = self.clock()
        evicted: Optional[str] = None
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is None:
                if len(self._workers) >= self.max_workers:
                    # evict the longest-unseen worker — garbage ids
                    # (never re-snapshotting) age out first
                    evicted = min(
                        self._workers, key=lambda w: self._workers[w]["seen"]
                    )
                    del self._workers[evicted]
                entry = {
                    "snap": {}, "seen": now, "rate": 0.0,
                    "prev_tiles": None, "prev_ts": None,
                }
                self._workers[worker_id] = entry
            tiles_total = _as_float(snap.get("tiles_total"))
            prev_tiles, prev_ts = entry["prev_tiles"], entry["prev_ts"]
            if (
                tiles_total is not None
                and prev_tiles is not None
                and now > prev_ts
                and tiles_total >= prev_tiles
            ):
                entry["rate"] = (tiles_total - prev_tiles) / (now - prev_ts)
            if tiles_total is not None:
                entry["prev_tiles"], entry["prev_ts"] = tiles_total, now
            entry["snap"] = snap
            entry["seen"] = now
        if evicted is not None:
            self._drop_series(evicted, reason="capacity")
        instruments.fleet_snapshots_total().inc(outcome="accepted")
        # v2: adopt the worker's cumulative usage meter by delta
        # (counter-reset clamped inside the aggregator)
        if version >= 2 and self.usage is not None and "usage" in snap:
            self.usage.adopt(worker_id, snap.get("usage"))
        # per-worker retained series (master clock, bounded vocabulary)
        rate = entry["rate"]
        self.store.record(S_WORKER_TILES_PER_S, rate, worker_id=worker_id)
        devices = _as_float(snap.get("devices")) or 1
        self.store.record(S_WORKER_DEVICES, devices, worker_id=worker_id)
        return True

    # --- eviction -----------------------------------------------------------

    def forget_worker(self, worker_id: str, reason: str = "forgotten") -> None:
        """Drop a worker's latest state AND all its retained series —
        the seam the placement policy / health registry call when they
        forget a worker, and the TTL sweep's eviction path."""
        worker_id = str(worker_id)
        with self._lock:
            self._workers.pop(worker_id, None)
        if self.usage is not None:
            self.usage.forget_worker(worker_id)
        self._drop_series(worker_id, reason=reason)

    def _drop_series(self, worker_id: str, reason: str) -> None:
        from . import instruments

        dropped = self.store.evict_label("worker_id", worker_id)
        instruments.fleet_evictions_total().inc(reason=reason)
        debug_log(
            f"fleet: evicted worker {worker_id} ({reason}; "
            f"{dropped} series dropped)"
        )

    def sweep(self) -> list[str]:
        """TTL eviction: workers whose last snapshot is older than the
        TTL depart the fleet view (their breaker state may outlive this
        — the fleet view tracks telemetry liveness, not job liveness)."""
        now = self.clock()
        with self._lock:
            stale = [
                wid for wid, entry in self._workers.items()
                if now - entry["seen"] > self.ttl
            ]
        for wid in stale:
            self.forget_worker(wid, reason="ttl")
        return stale

    # --- master-side sampling ----------------------------------------------

    def sample(self) -> dict[str, Any]:
        """One master-side sampling pass: record the load-bearing
        series, feed the SLO engine's counter-sourced specs, and cache
        the rollup. Called by the FleetMonitor every CDT_FLEET_INTERVAL
        (and directly by tests)."""
        from . import instruments

        now = self.clock()
        scheduler = self._scheduler
        if scheduler is not None:
            try:
                signals = scheduler.brownout.signals()
                self.store.record(
                    S_QUEUE_WAIT_P95, signals["wait_p95"], ts=now
                )
                self.store.record(S_JOURNAL_P95, signals["journal_p95"], ts=now)
                shed = float(sum(scheduler.brownout.shed_counts.values()))
                self.store.record(S_SHED, shed, ts=now)
                totals = scheduler.queue.totals
                admitted = float(totals.get("admitted", 0))
                # availability counts EVERY refused admission as bad —
                # brownout sheds AND saturation/drain rejections (the
                # full-queue outage is exactly the case the SLO exists
                # for), matching the spec's served description
                bad = (
                    shed
                    + float(totals.get("rejected_full", 0))
                    + float(totals.get("rejected_draining", 0))
                )
                if self._slo is not None:
                    self._slo.set_counts(
                        "availability", bad=bad, total=admitted + bad
                    )
            except Exception as exc:  # noqa: BLE001 - sampling best effort
                debug_log(f"fleet: scheduler sample failed: {exc}")
            try:
                weights = scheduler.placement.weights()
                for wid, ratio in weights.items():
                    self.store.record(S_WORKER_SPEED, ratio, worker_id=wid)
            except Exception as exc:  # noqa: BLE001
                debug_log(f"fleet: placement sample failed: {exc}")
        try:
            deadline_miss = instruments.jobs_cancelled_total().value(
                reason="deadline"
            )
            self.store.record(S_DEADLINE_MISS, deadline_miss, ts=now)
            if self._slo is not None and scheduler is not None:
                admitted = float(scheduler.queue.totals.get("admitted", 0))
                self._slo.set_counts(
                    "deadline_miss", bad=deadline_miss, total=admitted
                )
        except Exception as exc:  # noqa: BLE001
            debug_log(f"fleet: deadline sample failed: {exc}")
        # the master is a fleet participant too: derive its own rate
        # from the local tiles counter, like a worker snapshot would
        master_rate = 0.0
        try:
            tiles = instruments.tiles_processed_total().value(role="master")
            if self._master_prev is not None and now > self._master_prev[0]:
                prev_ts, prev_tiles = self._master_prev
                if tiles >= prev_tiles:
                    master_rate = (tiles - prev_tiles) / (now - prev_ts)
            self._master_prev = (now, tiles)
        except Exception:  # noqa: BLE001
            pass
        rollup = self.rollup(master_rate=master_rate)
        self.store.record(S_TILES_PER_S, rollup["tiles_per_s"], ts=now)
        self.store.record(
            S_TILES_PER_CHIP_S, rollup["tiles_per_chip_s"], ts=now
        )
        instruments.fleet_workers().set(rollup["workers"])
        instruments.fleet_series().set(self.store.series_count())
        self._last_rollup = rollup
        return rollup

    def step(self) -> dict[str, Any]:
        """sweep + sample + publish one `fleet_rollup` event (and one
        `usage_rollup` when the attribution plane is on)."""
        self.sweep()
        rollup = self.sample()
        from .events import get_event_bus

        try:
            get_event_bus().publish("fleet_rollup", **rollup)
        except Exception:  # noqa: BLE001 - push side is best effort
            pass
        if self.usage is not None:
            try:
                # one aggregation pass: tenant cost EWMAs, retained
                # per-tenant/waste series, idle-entry sweep — then the
                # web panel's usage card refreshes off the event
                usage_rollup = self.usage.sample()
                get_event_bus().publish("usage_rollup", **usage_rollup)
            except Exception as exc:  # noqa: BLE001 - best effort
                debug_log(f"fleet: usage sample failed: {exc}")
        # the tile result cache (cache/) feeds the panel's Cache card
        # the same push-side way; absent cache (CDT_CACHE=0) = no event
        try:
            from ..cache.store import get_tile_cache

            tile_cache = get_tile_cache()
            if tile_cache is not None:
                get_event_bus().publish("cache_stats", **tile_cache.stats())
        except Exception as exc:  # noqa: BLE001 - best effort
            debug_log(f"fleet: cache stats publish failed: {exc}")
        return rollup

    # --- rollups / surfaces --------------------------------------------------

    def rollup(self, master_rate: float = 0.0) -> dict[str, Any]:
        """Fleet-level aggregation of the latest worker snapshots:
        sums for rates/counters, max envelopes for latency quantiles
        and memory watermarks (the conservative roll-up — a fleet p95
        is AT MOST the worst worker's p95)."""
        with self._lock:
            entries = {
                wid: dict(entry) for wid, entry in self._workers.items()
            }
        devices = 0
        tiles_per_s = master_rate
        inflight = 0.0
        stages: dict[str, dict[str, float]] = {}
        jax_tallies = {"compiles": 0.0, "cache_hits": 0.0, "cache_misses": 0.0}
        hbm_peak = 0
        rss_max = 0
        for entry in entries.values():
            snap = entry["snap"]
            devices += int(_as_float(snap.get("devices")) or 1)
            tiles_per_s += float(entry["rate"])
            inflight += _as_float(snap.get("inflight")) or 0.0
            for stage, q in (snap.get("stages") or {}).items():
                if not isinstance(q, dict):
                    continue
                bucket = stages.setdefault(
                    str(stage), {"p95": 0.0, "count": 0}
                )
                bucket["p95"] = max(bucket["p95"], _as_float(q.get("p95")) or 0.0)
                bucket["count"] += int(_as_float(q.get("count")) or 0)
            jax = snap.get("jax") or {}
            for key in jax_tallies:
                jax_tallies[key] += _as_float(jax.get(key)) or 0.0
            mem = snap.get("mem") or {}
            hbm_peak = max(hbm_peak, int(_as_float(mem.get("hbm_peak_bytes")) or 0))
            rss_max = max(rss_max, int(_as_float(mem.get("rss_bytes")) or 0))
        # v3: sum worker transfer-ledger blocks + the master's own
        # local ledger; host_tax recomputed from summed integer ns
        profiling = None
        try:
            from .profiling import merge_profiling_blocks, peek_transfer_ledger

            blocks = [
                entry["snap"].get("profiling") for entry in entries.values()
            ]
            local = peek_transfer_ledger()
            if local is not None:
                blocks.append(local.snapshot(role="master"))
            blocks = [b for b in blocks if b]
            if blocks:
                profiling = merge_profiling_blocks(blocks)
        except Exception as exc:  # noqa: BLE001 - rollup is advisory
            debug_log(f"fleet: profiling rollup failed: {exc}")
        return {
            "workers": len(entries),
            "devices": devices,
            "tiles_per_s": round(tiles_per_s, 4),
            "tiles_per_chip_s": round(tiles_per_s / max(1, devices), 4),
            "inflight": inflight,
            "stages": stages,
            "jax": {k: v for k, v in jax_tallies.items()},
            "mem": {"hbm_peak_bytes": hbm_peak, "rss_max_bytes": rss_max},
            "profiling": profiling,
            "alerts_active": (
                sorted(self._slo.active()) if self._slo is not None else []
            ),
        }

    def worker_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._workers)

    def status(
        self, since_s: Optional[float] = None, worker: Optional[str] = None
    ) -> dict[str, Any]:
        """The /distributed/fleet payload: rollup + per-worker
        drill-down (+ windowed history when `since_s` is given; scoped
        to one worker's series with `worker`)."""
        now = self.clock()
        with self._lock:
            workers = {
                wid: {
                    "seen_ago_s": round(now - entry["seen"], 3),
                    "tiles_per_s": round(entry["rate"], 4),
                    "snapshot": entry["snap"],
                }
                for wid, entry in self._workers.items()
                if worker is None or wid == worker
            }
        out: dict[str, Any] = {
            "version": SNAPSHOT_VERSION,
            "ttl_seconds": self.ttl,
            "rollup": self._last_rollup or self.rollup(),
            "workers": workers,
            "series": {
                "count": self.store.series_count(),
                "by_name": self.store.counts_by_name(),
                "overflows": self.store.overflows,
            },
        }
        if since_s is not None:
            history: dict[str, Any] = {
                name: self.store.window(name, since_s)
                for name in HISTORY_SERIES
            }
            per_worker: dict[str, dict] = {}
            for name in WORKER_HISTORY_SERIES:
                for wid in self.store.label_values(name, "worker_id"):
                    if worker is not None and wid != worker:
                        continue
                    per_worker.setdefault(wid, {})[name] = self.store.window(
                        name, since_s, worker_id=wid
                    )
            history["workers"] = per_worker
            out["history"] = history
            out["since_seconds"] = float(since_s)
        return out


def _as_float(value: Any) -> Optional[float]:
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


# --- the monitor thread ------------------------------------------------------


class FleetMonitor:
    """Periodic driver: fleet sweep/sample + SLO evaluation on one
    background thread (watchdog idiom: `step()` is directly callable,
    the clock lives in the registry/engine, and tests never need the
    thread)."""

    def __init__(
        self,
        registry: FleetRegistry,
        slo: Any = None,
        interval: Optional[float] = None,
    ) -> None:
        self.registry = registry
        self.slo = slo
        self.interval = (
            interval if interval is not None
            else constants.FLEET_INTERVAL_SECONDS
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def step(self) -> dict[str, Any]:
        rollup = self.registry.step()
        if self.slo is not None:
            self.slo.step()
        return rollup

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.step()
                except Exception as exc:  # noqa: BLE001 - monitor survives
                    debug_log(f"fleet monitor step failed: {exc}")

        self._thread = threading.Thread(
            target=run, name="cdt-fleet-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
