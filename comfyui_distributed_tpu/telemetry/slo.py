"""Declarative SLOs evaluated with multi-window burn-rate alerting.

The Google SRE-workbook alerting idiom, built in-process and zero-dep:
an **SLO** declares what fraction of events must be *good* (the
objective — e.g. 99.9% of admissions not shed, 95% of tiles under the
latency threshold). The **error budget** is ``1 - objective``; the
**burn rate** over a window is

    burn(W) = bad(W) / total(W) / (1 - objective)

— 1.0 means the budget is being consumed exactly at the rate that
exhausts it over the SLO period; 14.4 means fourteen times faster.

Each SLO evaluates a set of **burn rules**, each pairing a *long*
window (significance: enough budget burned to matter) with a *short*
window (recency: it is STILL burning — the alert closes promptly once
the cause stops). An alert opens when ANY rule has both windows over
its threshold (with at least ``min_events`` in the long window so an
idle system can't alert on one unlucky event), and resolves when NO
rule's short window burns, sustained for ``resolve_hold_s`` — the
hysteresis that keeps a flapping boundary from ringing the pager.

Event plumbing:

- ``note_event(name, bad=...)`` — one good/bad event (ratio SLOs);
- ``note_latency(name, seconds)`` — one latency sample, classified
  against the spec's ``threshold_s`` (latency SLOs);
- ``set_counts(name, bad, total)`` — cumulative counters sampled from
  an external source (the FleetRegistry feeds admission/shed and
  deadline-miss totals this way).

All counts land as cumulative series in a `SeriesStore`
(telemetry/timeseries.py), so windowed burn rates are plain
counter-deltas over the retained history. Transitions publish
``alert_fired`` / ``alert_resolved`` on the process event bus, surface
on ``GET /distributed/alerts``, and mirror into the
``cdt_alert_active`` gauge — one signal, three consumers (stream,
poll, scrape). The clock is injectable: tier-1 tests drive the whole
fast/slow-window interplay on a fake timeline (tests/test_slo.py).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence

from ..utils import constants
from .timeseries import SeriesStore

# Series names the engine records under (label `slo` = spec name).
BAD_SERIES = "slo_bad_total"
TOTAL_SERIES = "slo_total_total"

# Bounded transition history served by /distributed/alerts.
HISTORY_LIMIT = 256


@dataclasses.dataclass(frozen=True)
class BurnRule:
    """One (long, short) window pair with its burn-rate threshold."""

    long_s: float
    short_s: float
    burn_threshold: float


# In-process defaults, scaled from the SRE workbook's 30-day idiom to a
# serving process's horizon: the fast rule pages on acute burn (5 min
# significance, 1 min recency), the slow rule on sustained burn (1 h
# significance, 5 min recency).
DEFAULT_RULES = (
    BurnRule(long_s=300.0, short_s=60.0, burn_threshold=14.4),
    BurnRule(long_s=3600.0, short_s=300.0, burn_threshold=6.0),
)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective. ``kind``:

    - ``ratio``: events arrive pre-classified (note_event/set_counts);
    - ``latency``: samples classify against ``threshold_s`` — the SLO
      reads "``objective`` of samples complete under ``threshold_s``"
      (the histogram-free way to alert on a pXX target: p95 <= T is
      exactly '>= 95% of samples under T').
    """

    name: str
    description: str
    objective: float
    kind: str = "ratio"
    threshold_s: Optional[float] = None
    rules: tuple[BurnRule, ...] = DEFAULT_RULES
    resolve_hold_s: float = 60.0
    min_events: int = 10

    def budget(self) -> float:
        return max(1e-9, 1.0 - float(self.objective))


def default_slos() -> tuple[SLOSpec, ...]:
    """The load-bearing objectives for one master (docs/observability.md
    documents the rule table; thresholds are knob-tunable)."""
    return (
        SLOSpec(
            name="availability",
            description="admissions not shed by brownout/saturation "
                        "(good = admitted, bad = shed or rejected-full)",
            objective=0.999,
        ),
        SLOSpec(
            name="tile_latency",
            description="tile pull-to-submit latency under the p95 target "
                        f"({constants.SLO_TILE_P95_SECONDS:g}s, "
                        "CDT_SLO_TILE_P95)",
            objective=0.95,
            kind="latency",
            threshold_s=constants.SLO_TILE_P95_SECONDS,
        ),
        SLOSpec(
            name="deadline_miss",
            description="jobs not cancelled for blowing their end-to-end "
                        "deadline (bad = deadline cancels, total = "
                        "admissions)",
            objective=0.999,
        ),
        SLOSpec(
            name="journal_latency",
            description="write-ahead journal appends under the latency "
                        f"target ({constants.SLO_JOURNAL_P95_SECONDS:g}s, "
                        "CDT_SLO_JOURNAL_P95)",
            objective=0.99,
            kind="latency",
            threshold_s=constants.SLO_JOURNAL_P95_SECONDS,
        ),
    )


class SLOEngine:
    """Burn-rate evaluation + alert state machine over a SeriesStore."""

    def __init__(
        self,
        specs: Optional[Sequence[SLOSpec]] = None,
        store: Optional[SeriesStore] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.clock = clock
        self.store = store if store is not None else SeriesStore(clock=clock)
        self.specs: dict[str, SLOSpec] = {
            s.name: s for s in (specs if specs is not None else default_slos())
        }
        self._lock = threading.Lock()
        # cumulative (bad, total) per spec — the authoritative counters;
        # the store retains their history for windowing
        self._counts: dict[str, list[float]] = {
            name: [0.0, 0.0] for name in self.specs
        }
        # alert state per spec: active flag + timestamps driving the
        # resolve hysteresis
        self._state: dict[str, dict] = {
            name: {"active": False, "since": None, "clear_since": None}
            for name in self.specs
        }
        self.history: collections.deque = collections.deque(
            maxlen=HISTORY_LIMIT
        )

    # --- feeds ------------------------------------------------------------

    def note_event(self, name: str, bad: bool, n: int = 1) -> None:
        """n pre-classified events for a ratio SLO."""
        if name not in self.specs or n <= 0:
            return
        with self._lock:
            counts = self._counts[name]
            counts[0] += float(n) if bad else 0.0
            counts[1] += float(n)
            bad_total, total = counts
        self._record(name, bad_total, total)

    def note_latency(self, name: str, seconds: float) -> None:
        """One latency sample for a latency SLO: bad iff it exceeds the
        spec's threshold."""
        spec = self.specs.get(name)
        if spec is None or spec.threshold_s is None:
            return
        self.note_event(name, bad=float(seconds) > spec.threshold_s)

    def set_counts(self, name: str, bad: float, total: float) -> None:
        """Adopt cumulative counters maintained elsewhere (monotonic;
        regressions — a source reset — clamp to the last seen value so
        a restarted counter never produces negative window deltas)."""
        if name not in self.specs:
            return
        with self._lock:
            counts = self._counts[name]
            counts[0] = max(counts[0], float(bad))
            counts[1] = max(counts[1], float(total))
            bad_total, total_now = counts
        self._record(name, bad_total, total_now)

    def _record(self, name: str, bad_total: float, total: float) -> None:
        self.store.record(BAD_SERIES, bad_total, slo=name)
        self.store.record(TOTAL_SERIES, total, slo=name)

    # --- evaluation -------------------------------------------------------

    def _burn(self, name: str, window_s: float) -> tuple[float, float]:
        """(burn_rate, total_events) over the last `window_s`."""
        spec = self.specs[name]
        bad = self.store.delta(BAD_SERIES, window_s, slo=name)
        total = self.store.delta(TOTAL_SERIES, window_s, slo=name)
        if total <= 0:
            return 0.0, 0.0
        return (bad / total) / spec.budget(), total

    def evaluate(self, name: str) -> dict:
        """Burn rates for every rule of one spec (no state change)."""
        spec = self.specs[name]
        rules = []
        firing = False
        for rule in spec.rules:
            burn_long, total_long = self._burn(name, rule.long_s)
            burn_short, _ = self._burn(name, rule.short_s)
            rule_firing = (
                total_long >= spec.min_events
                and burn_long >= rule.burn_threshold
                and burn_short >= rule.burn_threshold
            )
            still_burning = burn_short >= rule.burn_threshold
            firing = firing or rule_firing
            rules.append(
                {
                    "long_s": rule.long_s,
                    "short_s": rule.short_s,
                    "threshold": rule.burn_threshold,
                    "burn_long": round(burn_long, 4),
                    "burn_short": round(burn_short, 4),
                    "events_long": total_long,
                    "firing": rule_firing,
                    "still_burning": still_burning,
                }
            )
        return {
            "slo": name,
            "firing": firing,
            "still_burning": any(r["still_burning"] for r in rules),
            "rules": rules,
        }

    def step(self) -> list[dict]:
        """One evaluation pass over every spec; returns the transitions
        that happened (also published on the bus + mirrored into
        cdt_alert_active). Cheap enough for a multi-second cadence."""
        transitions: list[dict] = []
        now = self.clock()
        for name, spec in self.specs.items():
            verdict = self.evaluate(name)
            with self._lock:
                state = self._state[name]
                if not state["active"]:
                    if verdict["firing"]:
                        state["active"] = True
                        state["since"] = now
                        state["clear_since"] = None
                        transitions.append(
                            self._transition("alert_fired", spec, verdict, now)
                        )
                    continue
                # active: resolve only after a SUSTAINED clear of every
                # short window (flap suppression — a boundary bouncing
                # above/below threshold keeps resetting the hold)
                if verdict["still_burning"] or verdict["firing"]:
                    state["clear_since"] = None
                    continue
                if state["clear_since"] is None:
                    state["clear_since"] = now
                if now - state["clear_since"] >= spec.resolve_hold_s:
                    state["active"] = False
                    fired_at = state["since"]
                    state["since"] = None
                    state["clear_since"] = None
                    transitions.append(
                        self._transition(
                            "alert_resolved", spec, verdict, now,
                            fired_at=fired_at,
                        )
                    )
        for transition in transitions:
            self._publish(transition)
        if transitions:
            self._refresh_gauge()
        return transitions

    def _transition(
        self, kind: str, spec: SLOSpec, verdict: dict, now: float,
        fired_at: Optional[float] = None,
    ) -> dict:
        out = {
            "type": kind,
            "slo": spec.name,
            "description": spec.description,
            "objective": spec.objective,
            "ts": now,
            "rules": verdict["rules"],
        }
        if fired_at is not None:
            out["active_seconds"] = round(now - fired_at, 3)
        self.history.append(out)
        return out

    def _publish(self, transition: dict) -> None:
        from .events import get_event_bus

        data = {k: v for k, v in transition.items() if k != "type"}
        try:
            get_event_bus().publish(transition["type"], **data)
        except Exception:  # noqa: BLE001 - alerting must not break eval
            pass

    def _refresh_gauge(self) -> None:
        from . import instruments

        try:
            gauge = instruments.alert_active()
            for name in self.specs:
                gauge.set(
                    1.0 if self._state[name]["active"] else 0.0, slo=name
                )
        except Exception:  # noqa: BLE001 - scrape mirror is best effort
            pass

    # --- surfaces ---------------------------------------------------------

    def active(self) -> dict[str, dict]:
        with self._lock:
            return {
                name: {"since": state["since"]}
                for name, state in self._state.items()
                if state["active"]
            }

    def is_active(self, name: str) -> bool:
        with self._lock:
            state = self._state.get(name)
            return bool(state and state["active"])

    def status(self) -> dict:
        """The /distributed/alerts payload: every spec's current burn
        evaluation + alert state, plus the bounded transition history."""
        specs = []
        for name, spec in self.specs.items():
            verdict = self.evaluate(name)
            with self._lock:
                state = dict(self._state[name])
            specs.append(
                {
                    "slo": name,
                    "description": spec.description,
                    "objective": spec.objective,
                    "kind": spec.kind,
                    "threshold_s": spec.threshold_s,
                    "active": state["active"],
                    "since": state["since"],
                    "rules": verdict["rules"],
                }
            )
        with self._lock:
            # copy under the lock: the monitor thread appends
            # transitions concurrently, and iterating a mutating deque
            # raises — turning the alerts route into a 500 at exactly
            # the moment an alert fires
            history = list(self.history)
        return {
            "alerts": specs,
            "active": sorted(self.active()),
            "history": history,
        }
