"""Zero-dependency metrics registry with Prometheus text exposition.

Three instrument kinds — Counter, Gauge, Histogram (explicit buckets) —
registered by name in a `MetricsRegistry` and rendered in the
Prometheus text exposition format (version 0.0.4) by
`/distributed/metrics` (api/telemetry_routes.py).

Conventions (lint- and test-enforced, see tests/test_telemetry_metrics.py):

- every metric name starts with ``cdt_`` and is snake_case;
- counters end in ``_total``; histograms measuring time end in
  ``_seconds``;
- label values are free-form strings (worker ids, stage names); label
  NAMES come from a small fixed vocabulary per instrument.

The registry is thread-safe (instruments are updated from the server
loop, executor threads, and chaos worker threads concurrently) and
process-global via `get_metrics_registry()`; tests reset it with
`reset_metrics_registry()`.

Gauges that mirror live state (queue depth, breaker states) are filled
at scrape time by *collector* callbacks registered with
`register_collector` — the scrape pulls from the JobStore / health
registry instead of every mutation pushing a gauge update.
"""

from __future__ import annotations

import bisect
import math
import os
import re
import threading
from typing import Callable, Iterable, Optional, Sequence

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Label value every series beyond a metric's cardinality cap collapses
# into (one synthetic series per metric, not one per hostile value).
OVERFLOW_LABEL_VALUE = "_overflow"

# Distinct label sets allowed per metric before overflow collapsing
# kicks in. Worker ids / stage names are small vocabularies in healthy
# operation; the cap exists so a worker-id churn storm (or a hostile
# caller spraying ids at an RPC surface) can't grow the registry — and
# every Prometheus scrape — without bound.
DEFAULT_MAX_SERIES = 128


def _env_max_series() -> int:
    try:
        value = int(os.environ.get("CDT_METRIC_MAX_SERIES", DEFAULT_MAX_SERIES))
    except (TypeError, ValueError):
        return DEFAULT_MAX_SERIES
    return value if value > 0 else DEFAULT_MAX_SERIES


# Mutation listener: the live event bus (telemetry/events.py) installs
# one callback here that forwards every Counter/Gauge/Histogram update
# as a `metric_delta` event. Kept as a plain module global so the hot
# path pays a single None-check when nothing is listening.
_mutation_listener: Optional[Callable] = None


def set_mutation_listener(fn: Optional[Callable]) -> None:
    """Install the (kind, name, labelnames, labelvalues, value) mutation
    callback; None uninstalls. Listener errors are swallowed — pushing
    telemetry must never break the instrumented path."""
    global _mutation_listener
    _mutation_listener = fn

# Default latency buckets: 1ms .. 60s, roughly log-spaced — wide enough
# for both sub-ms store ops and multi-second dispatch/tile timings.
DEFAULT_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _format_labels(labelnames: Sequence[str], labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{name}="{_escape_label_value(value)}"'
        for name, value in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class _Metric:
    """Shared base: name/help/labelnames validation + labelled children."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        max_series: Optional[int] = None,
        on_overflow: Optional[Callable[[str], None]] = None,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for label in labelnames:
            if not _LABEL_RE.match(label):
                raise ValueError(f"invalid label name {label!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_series = max_series if max_series is not None else _env_max_series()
        self._on_overflow = on_overflow
        self._overflow_logged = False
        self._lock = threading.Lock()

    def _key(self, labels: dict[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(sorted(labels))}"
            )
        return tuple(str(labels[name]) for name in self.labelnames)

    def _bounded_key(
        self, key: tuple[str, ...], container: dict
    ) -> tuple[str, ...]:
        """Cap distinct label sets per metric: a NEW series beyond
        `max_series` collapses into the `_overflow` series instead of
        growing the registry (caller holds self._lock)."""
        if not self.labelnames or key in container or len(container) < self.max_series:
            return key
        if self._on_overflow is not None:
            try:
                self._on_overflow(self.name)
            except Exception:  # noqa: BLE001 - accounting must not break writes
                pass
        if not self._overflow_logged:
            self._overflow_logged = True
            try:
                from ..utils.logging import log

                log(
                    f"metric {self.name} hit its series cap "
                    f"({self.max_series}); further label sets collapse "
                    f"into {OVERFLOW_LABEL_VALUE!r} "
                    "(cdt_metric_series_overflow_total counts them)"
                )
            except Exception:  # noqa: BLE001 - logging is best effort
                pass
        return (OVERFLOW_LABEL_VALUE,) * len(self.labelnames)

    def _notify(self, labelvalues: tuple[str, ...], value: float) -> None:
        """Forward one mutation to the event-bus listener (no-op when
        none installed); called OUTSIDE the metric lock."""
        listener = _mutation_listener
        if listener is not None:
            try:
                listener(self.kind, self.name, self.labelnames, labelvalues, value)
            except Exception:  # noqa: BLE001 - telemetry must not break writes
                pass

    def samples(self) -> Iterable[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def header(self) -> list[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help, labelnames=(), **kwargs):
        super().__init__(name, help, labelnames, **kwargs)
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            key = self._bounded_key(key, self._values)
            self._values[key] = self._values.get(key, 0.0) + amount
        self._notify(key, amount)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for key, value in items:
            yield (
                f"{self.name}{_format_labels(self.labelnames, key)} "
                f"{_format_value(value)}"
            )


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help, labelnames=(), **kwargs):
        super().__init__(name, help, labelnames, **kwargs)
        self._values: dict[tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            key = self._bounded_key(key, self._values)
            self._values[key] = float(value)
        self._notify(key, float(value))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            key = self._bounded_key(key, self._values)
            value = self._values.get(key, 0.0) + amount
            self._values[key] = value
        self._notify(key, value)

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def clear(self) -> None:
        """Drop all labelled series (collectors re-fill at scrape)."""
        with self._lock:
            self._values.clear()

    def remove(self, **labels: str) -> None:
        """Drop one labelled series (a stopped server's gauges must not
        linger in the scrape)."""
        key = self._key(labels)
        with self._lock:
            self._values.pop(key, None)

    def samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for key, value in items:
            yield (
                f"{self.name}{_format_labels(self.labelnames, key)} "
                f"{_format_value(value)}"
            )


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self, name, help, labelnames=(), buckets=DEFAULT_TIME_BUCKETS, **kwargs
    ):
        super().__init__(name, help, labelnames, **kwargs)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.bounds = tuple(bounds)
        # per label-key: [bucket counts...], sum, count
        self._series: dict[tuple[str, ...], list] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        value = float(value)
        with self._lock:
            key = self._bounded_key(key, self._series)
            series = self._series.get(key)
            if series is None:
                series = [[0] * len(self.bounds), 0.0, 0]
                self._series[key] = series
            idx = bisect.bisect_left(self.bounds, value)
            if idx < len(self.bounds):
                series[0][idx] += 1
            series[1] += value
            series[2] += 1
        self._notify(key, value)

    def count(self, **labels: str) -> int:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            return series[2] if series else 0

    def series_snapshot(self) -> dict[tuple[str, ...], dict]:
        """Per-label-set bucket state: ``{labelvalues: {buckets, sum,
        count}}`` (buckets are per-bound counts, not cumulative).
        Consumed by the fleet snapshot builder (telemetry/fleet.py) to
        derive p50/p95 without re-parsing the text exposition."""
        with self._lock:
            return {
                key: {
                    "buckets": list(counts),
                    "sum": total,
                    "count": count,
                }
                for key, (counts, total, count) in self._series.items()
            }

    def samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(
                (key, (list(counts), total, count))
                for key, (counts, total, count) in self._series.items()
            )
        for key, (counts, total, count) in items:
            cumulative = 0
            for bound, bucket_count in zip(self.bounds, counts):
                cumulative += bucket_count
                labels = _format_labels(
                    self.labelnames + ("le",), key + (_format_value(bound),)
                )
                yield f"{self.name}_bucket{labels} {cumulative}"
            labels = _format_labels(self.labelnames + ("le",), key + ("+Inf",))
            yield f"{self.name}_bucket{labels} {count}"
            plain = _format_labels(self.labelnames, key)
            yield f"{self.name}_sum{plain} {_format_value(total)}"
            yield f"{self.name}_count{plain} {count}"


def histogram_quantile(
    bounds: Sequence[float], counts: Sequence[int], total: int, q: float
) -> Optional[float]:
    """Quantile estimate from per-bound (non-cumulative) bucket counts:
    the smallest bucket bound whose cumulative count reaches rank
    ``ceil(q * total)``. Observations above every bound clamp to the
    top bound."""
    if total <= 0:
        return None
    q = min(1.0, max(0.0, float(q)))
    rank = max(1, math.ceil(q * total))
    cumulative = 0
    for bound, count in zip(bounds, counts):
        cumulative += count
        if cumulative >= rank:
            return float(bound)
    return float(bounds[-1]) if bounds else None


class MetricsRegistry:
    """Name-indexed instrument registry + scrape-time collectors."""

    OVERFLOW_COUNTER_NAME = "cdt_metric_series_overflow_total"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []
        # Warning counter for cardinality-cap hits; created eagerly so
        # it exists (at 0 series) before any storm, and with a cap
        # bounded by metric-name count (and no on_overflow: the
        # accounting metric must not recurse into itself).
        self._overflow_counter = Counter(
            self.OVERFLOW_COUNTER_NAME,
            "Mutations collapsed into a metric's _overflow series "
            "because the per-metric label-set cap was hit",
            ("metric",),
            max_series=4096,
        )
        self._metrics[self.OVERFLOW_COUNTER_NAME] = self._overflow_counter

    def _record_overflow(self, name: str) -> None:
        self._overflow_counter.inc(metric=name)

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered with a different "
                        "type or label set"
                    )
                return existing
            kwargs.setdefault("on_overflow", self._record_overflow)
            metric = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str, labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str, labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    # --- collectors -------------------------------------------------------

    def register_collector(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Register a scrape-time callback that refreshes live-state
        gauges; returns an unregister callable."""
        with self._lock:
            self._collectors.append(fn)

        def unregister() -> None:
            with self._lock:
                if fn in self._collectors:
                    self._collectors.remove(fn)

        return unregister

    # --- exposition -------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition (0.0.4). Collector errors are
        swallowed per collector: one broken data source must not take
        the whole scrape down."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 - scrape survives collectors
                pass
        with self._lock:
            metrics = [self._metrics[name] for name in sorted(self._metrics)]
        lines: list[str] = []
        for metric in metrics:
            lines.extend(metric.header())
            lines.extend(metric.samples())
        return "\n".join(lines) + "\n"


# --- global registry ------------------------------------------------------

_registry: MetricsRegistry | None = None
_registry_lock = threading.Lock()


def get_metrics_registry() -> MetricsRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def reset_metrics_registry() -> None:
    """Drop the global registry (tests)."""
    global _registry
    with _registry_lock:
        _registry = None
