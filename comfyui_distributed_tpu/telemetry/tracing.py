"""Span-based tracing keyed by the existing ``exec_*`` trace ids.

Subsumes the grep-oriented `utils/trace_logger.py`: instead of log
lines, one execution produces a TREE of spans (queue → dispatch → tile
pull → sampler → blend) that `/distributed/trace/{trace_id}` serves as
JSON and `scripts/perf_report.py` turns into a per-stage latency
breakdown.

Design:

- a span is {trace_id, span_id, parent_id, name, start, end, attrs,
  events, status}; times come from an injectable monotonic clock so
  tier-1 tests (and the chaos harness) are deterministic on CPU;
- the CURRENT span lives in a contextvar. Contexts are per-thread, so
  a compute thread joins a trace by calling `tracer.activate(trace_id)`
  (the server's executor thread does this with the PromptJob's trace
  id; chaos worker threads do it explicitly);
- master→worker propagation is one HTTP header, `X-CDT-Trace-Id`,
  carried by /prompt dispatch and by every tile-pull/submit RPC
  (graph/usdu_elastic.HTTPWorkClient); the receiving route re-attaches
  its spans to the propagated id so the whole distributed execution is
  ONE connected tree;
- a span created with no explicit parent and no active span parents to
  the trace's root span (if any) — server-side RPC spans connect to
  the orchestration root without shipping span ids over the wire;
- storage is bounded: at most `max_traces` traces (oldest evicted) of
  at most `max_spans_per_trace` spans each;
- `write_jsonl` exports one span per line for offline analysis.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import threading
import time
import uuid
from typing import Any, Callable, Iterator, Optional

TRACE_HEADER = "X-CDT-Trace-Id"

# (trace_id, span_id) of the active span; span_id None = trace joined
# via activate() but no span open yet.
_current: contextvars.ContextVar[Optional[tuple[str, Optional[str]]]] = (
    contextvars.ContextVar("cdt_current_span", default=None)
)

# Span lifecycle listener: the live event bus (telemetry/events.py)
# installs one callback that forwards span open/close as stream events.
_span_listener: Optional[Callable[[str, "Span"], None]] = None


def set_span_listener(fn: Optional[Callable[[str, "Span"], None]]) -> None:
    """Install the (phase, span) lifecycle callback (phase is "open" or
    "close"); None uninstalls. Errors are swallowed."""
    global _span_listener
    _span_listener = fn


def _notify_span(phase: str, span: "Span") -> None:
    listener = _span_listener
    if listener is not None:
        try:
            listener(phase, span)
        except Exception:  # noqa: BLE001 - telemetry must not break tracing
            pass


class Span:
    __slots__ = (
        "trace_id", "span_id", "parent_id", "name",
        "start", "end", "attrs", "events", "status",
    )

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        name: str,
        start: float,
        attrs: Optional[dict[str, Any]] = None,
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.attrs: dict[str, Any] = dict(attrs or {})
        self.events: list[dict[str, Any]] = []
        self.status = "ok"

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_dict(self) -> dict[str, Any]:
        # attrs/events are COPIED: callers serialize outside the tracer
        # lock while instrumented code may still be annotating the span
        # (e.g. pull_span.attrs["tile_idx"] = ... after the span ended).
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration": self.duration,
            "attrs": dict(self.attrs),
            "events": [dict(e) for e in self.events],
            "status": self.status,
        }


class Tracer:
    """Thread-safe bounded span store + context management."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        max_traces: int = 256,
        max_spans_per_trace: int = 20000,
    ) -> None:
        self._clock = clock
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, list[Span]]" = (
            collections.OrderedDict()
        )
        self._roots: dict[str, str] = {}  # trace_id -> root span_id
        # span_id -> Span per trace: O(1) event attachment (trace_info
        # fires per log line; scanning 20k spans under the lock won't do)
        self._by_id: dict[str, dict[str, Span]] = {}

    # --- bookkeeping ------------------------------------------------------

    def _store(self, span: Span) -> None:
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = []
                self._traces[span.trace_id] = spans
                self._by_id[span.trace_id] = {}
                self._roots.setdefault(span.trace_id, span.span_id)
                while len(self._traces) > self.max_traces:
                    evicted, _ = self._traces.popitem(last=False)
                    self._roots.pop(evicted, None)
                    self._by_id.pop(evicted, None)
            else:
                # LRU, not insertion order: a long execution keeps
                # appending spans, so it stays most-recent and a burst
                # of short traces (or hostile trace-id headers on the
                # open RPC surface) evicts idle history instead of the
                # in-flight tree.
                self._traces.move_to_end(span.trace_id)
            if len(spans) < self.max_spans_per_trace:
                spans.append(span)
                self._by_id[span.trace_id][span.span_id] = span

    def root_span_id(self, trace_id: str) -> Optional[str]:
        with self._lock:
            return self._roots.get(trace_id)

    # --- context ----------------------------------------------------------

    def activate(self, trace_id: str) -> contextvars.Token:
        """Join `trace_id` in the current context (thread); new spans
        with no active parent attach to the trace's root. Returns a
        token for `deactivate`."""
        return _current.set((trace_id, None))

    def deactivate(self, token: contextvars.Token) -> None:
        _current.reset(token)

    def current_trace_id(self) -> Optional[str]:
        state = _current.get()
        return state[0] if state else None

    def current_span_id(self) -> Optional[str]:
        state = _current.get()
        return state[1] if state else None

    # --- span lifecycle ---------------------------------------------------

    def start_span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[dict[str, Any]] = None,
    ) -> Span:
        """Manual span start (no context mutation); pair with
        `end_span`. Parent resolution: explicit parent_id → active span
        (same trace) → the trace's root span."""
        state = _current.get()
        if trace_id is None:
            if state is None:
                trace_id = f"trace_{uuid.uuid4().hex[:12]}"
            else:
                trace_id = state[0]
        if parent_id is None:
            if state is not None and state[0] == trace_id and state[1] is not None:
                parent_id = state[1]
            else:
                root = self.root_span_id(trace_id)
                parent_id = root  # None for the first span of a trace
        span = Span(
            trace_id=trace_id,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent_id,
            name=name,
            start=self._clock(),
            attrs=attrs,
        )
        self._store(span)
        _notify_span("open", span)
        return span

    def end_span(self, span: Span, status: str = "ok") -> None:
        if span.end is None:
            span.end = self._clock()
            # preserve a status the body set explicitly (e.g. a span
            # whose failure is swallowed by a best-effort except arm)
            if span.status == "ok":
                span.status = status
            _notify_span("close", span)

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        **attrs: Any,
    ) -> Iterator[Span]:
        """Context-managed span that becomes the active span for
        nesting; exceptions mark the span status 'error' and re-raise."""
        span = self.start_span(name, trace_id, parent_id, attrs)
        token = _current.set((span.trace_id, span.span_id))
        try:
            yield span
        except BaseException as exc:
            span.attrs.setdefault("error", f"{type(exc).__name__}: {exc}")
            self.end_span(span, status="error")
            raise
        else:
            self.end_span(span)
        finally:
            _current.reset(token)

    def event(self, name: str, **attrs: Any) -> None:
        """Attach a point-in-time event to the active span, falling
        back to the active trace's root span; no-op outside a trace."""
        state = _current.get()
        if state is None:
            return
        trace_id, span_id = state
        target = span_id or self.root_span_id(trace_id)
        if target is None:
            return
        with self._lock:
            span = self._by_id.get(trace_id, {}).get(target)
        if span is not None and len(span.events) < 1000:
            span.events.append({"name": name, "ts": self._clock(), "attrs": attrs})

    # --- export -----------------------------------------------------------

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def spans(self, trace_id: str) -> list[dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._traces.get(trace_id, [])]

    def tree(
        self,
        trace_id: str,
        spans: Optional[list[dict[str, Any]]] = None,
    ) -> list[dict[str, Any]]:
        """Span forest for one trace: each node is the span dict plus
        'children', ordered by start time. Spans whose parent is
        missing (evicted / foreign) surface as extra roots. Pass an
        already-fetched `spans` list to avoid re-copying a large trace
        under the lock (and to keep the tree consistent with it)."""
        if spans is None:
            spans = self.spans(trace_id)
        nodes = {s["span_id"]: {**s, "children": []} for s in spans}
        roots: list[dict[str, Any]] = []
        for node in nodes.values():
            parent = nodes.get(node["parent_id"]) if node["parent_id"] else None
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)
        def sort_rec(items: list[dict[str, Any]]) -> None:
            items.sort(key=lambda n: (n["start"], n["span_id"]))
            for item in items:
                sort_rec(item["children"])
        sort_rec(roots)
        return roots

    def write_jsonl(self, trace_id: str, path: str) -> int:
        """Export one span per line; returns the number written."""
        spans = self.spans(trace_id)
        with open(path, "w", encoding="utf-8") as fh:
            for span in spans:
                fh.write(json.dumps(span, sort_keys=True) + "\n")
        return len(spans)

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._roots.clear()
            self._by_id.clear()


# --- global tracer --------------------------------------------------------

_tracer: Tracer | None = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
        return _tracer


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Install a specific tracer (chaos harness: fake clock)."""
    global _tracer
    with _tracer_lock:
        _tracer = tracer


def reset_tracer() -> None:
    """Drop the global tracer (tests)."""
    set_tracer(None)


def current_trace_id() -> Optional[str]:
    """Module-level convenience for transport code building headers."""
    state = _current.get()
    return state[0] if state else None
