"""Bounded in-process time series: two-tier ring-buffer retention.

The Prometheus surface (`/distributed/metrics`) is a *point-in-time*
scrape — it answers "what is the value now", never "what happened over
the last hour". The fleet observability plane needs history: queue-wait
p95 five minutes ago, a worker's tiles/sec trend, how long an SLO burn
has been running. An external TSDB would give us that, but this stack
is zero-dep by construction, so this module is the in-process
equivalent: a `SeriesStore` of named, labelled series, each retained in
two downsampling tiers —

- **raw tier**: one bucket per ``raw_step`` seconds (default 10 s),
  ``raw_points`` buckets deep (default 360 → one hour);
- **rollup tier**: one bucket per ``rollup_step`` seconds (default
  5 min), ``rollup_points`` buckets deep (default 288 → one day).

Every bucket aggregates the samples that landed in its step:
``{t, last, min, max, sum, count}`` — enough to reconstruct rates from
cumulative counters (``last`` deltas), envelopes from gauges
(min/max), and means. Windows recent enough for the raw tier come from
it; older windows fall back to the rollup tier, so a query never pays
more resolution than retention kept.

Cardinality is capped exactly like the metrics registry: at most
``CDT_METRIC_MAX_SERIES`` distinct label sets per series name; samples
for NEW label sets beyond the cap are dropped and counted in
``overflows`` (one worker-id churn storm must not grow master memory —
the same bound `telemetry/metrics.py` enforces on the scrape).

Thread-safe; the clock is injectable so tier-1 tests drive windows and
retention deterministically. Consumed by `telemetry/fleet.py`
(FleetRegistry) and `telemetry/slo.py` (burn-rate windows).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from .metrics import _env_max_series

# Two-tier retention defaults: 1 h of 10 s raw points, 24 h of 5 min
# rollups. Fixed constants (not knobs): they bound memory at ~a few KB
# per series either way, and the fleet route reports the tier it
# answered from.
RAW_STEP_SECONDS = 10.0
RAW_POINTS = 360
ROLLUP_STEP_SECONDS = 300.0
ROLLUP_POINTS = 288


class _Tier:
    """One downsampling tier: a bounded list of step-aligned buckets."""

    __slots__ = ("step", "maxlen", "buckets")

    def __init__(self, step: float, maxlen: int) -> None:
        self.step = float(step)
        self.maxlen = int(maxlen)
        # list of dict buckets, oldest first; appended in time order
        self.buckets: list[dict[str, float]] = []

    def record(self, ts: float, value: float) -> None:
        t0 = (ts // self.step) * self.step
        if self.buckets and self.buckets[-1]["t"] == t0:
            b = self.buckets[-1]
            b["last"] = value
            b["min"] = min(b["min"], value)
            b["max"] = max(b["max"], value)
            b["sum"] += value
            b["count"] += 1
            return
        if self.buckets and self.buckets[-1]["t"] > t0:
            # clock went backwards across a bucket boundary (test clocks,
            # NTP steps): fold into the newest bucket rather than
            # corrupting time order
            self.record(self.buckets[-1]["t"], value)
            return
        self.buckets.append(
            {"t": t0, "last": value, "min": value, "max": value,
             "sum": value, "count": 1}
        )
        if len(self.buckets) > self.maxlen:
            del self.buckets[: len(self.buckets) - self.maxlen]

    def window(self, since_ts: float) -> list[dict[str, float]]:
        return [dict(b) for b in self.buckets if b["t"] >= since_ts]

    def value_at_or_before(self, ts: float) -> Optional[dict[str, float]]:
        """Newest bucket whose step started at or before `ts`."""
        found = None
        for b in self.buckets:
            if b["t"] <= ts:
                found = b
            else:
                break
        return dict(found) if found is not None else None

    def oldest(self) -> Optional[dict[str, float]]:
        return dict(self.buckets[0]) if self.buckets else None

    def latest(self) -> Optional[dict[str, float]]:
        return dict(self.buckets[-1]) if self.buckets else None


class _Series:
    __slots__ = ("raw", "rollup")

    def __init__(
        self, raw_step: float, raw_points: int,
        rollup_step: float, rollup_points: int,
    ) -> None:
        self.raw = _Tier(raw_step, raw_points)
        self.rollup = _Tier(rollup_step, rollup_points)

    def record(self, ts: float, value: float) -> None:
        self.raw.record(ts, value)
        self.rollup.record(ts, value)


class SeriesStore:
    """Named, labelled, two-tier retained series. All mutation and
    query methods are safe to call from any thread."""

    def __init__(
        self,
        raw_step: float = RAW_STEP_SECONDS,
        raw_points: int = RAW_POINTS,
        rollup_step: float = ROLLUP_STEP_SECONDS,
        rollup_points: int = ROLLUP_POINTS,
        max_series: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.raw_step = float(raw_step)
        self.raw_points = int(raw_points)
        self.rollup_step = float(rollup_step)
        self.rollup_points = int(rollup_points)
        # same cap the metrics registry applies per metric name
        self.max_series = (
            max_series if max_series is not None else _env_max_series()
        )
        self.clock = clock
        self._lock = threading.Lock()
        # name -> {labels_tuple -> _Series}; labels_tuple is sorted
        # (key, value) pairs so label order never splits a series
        self._series: dict[str, dict[tuple, _Series]] = {}
        self.overflows = 0

    @staticmethod
    def _key(labels: dict[str, Any]) -> tuple:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    # --- writes -----------------------------------------------------------

    def record(
        self, name: str, value: float, ts: Optional[float] = None,
        **labels: Any,
    ) -> bool:
        """Record one sample; returns False when the per-name series cap
        rejected a NEW label set (established series always record)."""
        ts = self.clock() if ts is None else float(ts)
        key = self._key(labels)
        with self._lock:
            by_label = self._series.setdefault(name, {})
            series = by_label.get(key)
            if series is None:
                if len(by_label) >= self.max_series:
                    self.overflows += 1
                    return False
                series = _Series(
                    self.raw_step, self.raw_points,
                    self.rollup_step, self.rollup_points,
                )
                by_label[key] = series
            series.record(ts, float(value))
        return True

    # --- queries ----------------------------------------------------------

    def _get(self, name: str, labels: dict[str, Any]) -> Optional[_Series]:
        return self._series.get(name, {}).get(self._key(labels))

    def latest(self, name: str, **labels: Any) -> Optional[float]:
        with self._lock:
            series = self._get(name, labels)
            if series is None:
                return None
            b = series.raw.latest() or series.rollup.latest()
            return b["last"] if b else None

    def window(
        self, name: str, since_s: float, **labels: Any
    ) -> list[dict[str, float]]:
        """Buckets covering the last `since_s` seconds, oldest first.
        Served from the raw tier while it still covers the window,
        otherwise from the rollup tier (each bucket carries its own
        timestamp, so consumers see the resolution change)."""
        now = self.clock()
        since_ts = now - max(0.0, float(since_s))
        with self._lock:
            series = self._get(name, labels)
            if series is None:
                return []
            oldest_raw = series.raw.oldest()
            if oldest_raw is not None and oldest_raw["t"] <= since_ts:
                return series.raw.window(since_ts)
            # raw tier doesn't reach back far enough: rollup tier
            points = series.rollup.window(since_ts)
            return points if points else series.raw.window(since_ts)

    def delta(self, name: str, window_s: float, **labels: Any) -> float:
        """Cumulative-counter delta over the last `window_s` seconds:
        newest ``last`` minus the value at the window start (or the
        oldest retained value when history is shorter than the window).
        0.0 for unknown series."""
        now = self.clock()
        with self._lock:
            series = self._get(name, labels)
            if series is None:
                return 0.0
            newest = series.raw.latest() or series.rollup.latest()
            if newest is None:
                return 0.0
            start_ts = now - float(window_s)
            base = series.raw.value_at_or_before(start_ts)
            if base is None:
                # The raw tier doesn't reach back to the window start.
                # A rollup bucket may only serve as the base when it
                # covers history already EVICTED from raw — a rollup
                # bucket overlapping raw coverage (its 5 min span can
                # contain `now` itself) carries a `last` contaminated
                # by samples newer than the window start, which would
                # zero the delta. Otherwise: delta over the available
                # history (oldest raw bucket).
                oldest_raw = series.raw.oldest()
                roll = series.rollup.value_at_or_before(start_ts)
                if roll is not None and (
                    oldest_raw is None
                    or roll["t"] + self.rollup_step <= oldest_raw["t"]
                ):
                    base = roll
                else:
                    base = oldest_raw or series.rollup.oldest()
            if base is None or base["t"] > newest["t"]:
                return 0.0
            if base is newest or base["t"] == newest["t"]:
                return 0.0
            return newest["last"] - base["last"]

    # --- lifecycle / accounting -------------------------------------------

    def label_values(self, name: str, label: str) -> list[str]:
        with self._lock:
            out = set()
            for key in self._series.get(name, {}):
                for k, v in key:
                    if k == label:
                        out.add(v)
            return sorted(out)

    def evict_label(self, label: str, value: str) -> int:
        """Drop every series (any name) carrying ``label=value`` — the
        departed-worker eviction seam. Returns series dropped."""
        pair = (str(label), str(value))
        dropped = 0
        with self._lock:
            for by_label in self._series.values():
                for key in [k for k in by_label if pair in k]:
                    del by_label[key]
                    dropped += 1
        return dropped

    def series_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._series.values())

    def names(self) -> list[str]:
        with self._lock:
            return sorted(n for n, v in self._series.items() if v)

    def counts_by_name(self) -> dict[str, int]:
        with self._lock:
            return {n: len(v) for n, v in sorted(self._series.items()) if v}
