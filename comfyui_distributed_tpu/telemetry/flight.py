"""Always-on flight recorder: the black box an incident bundle reads.

PR 12 gave the master burn-rate alerts, but by the time `alert_fired`
lands the evidence is gone: bus frames are unretained past connected
subscribers and trace spans age out of bounded retention. This module
keeps the last window of *everything* in memory, all the time, so the
incident manager (telemetry/incidents.py) can snapshot it AFTER a
trigger and still hold the frames from BEFORE it — the aircraft
flight-recorder idiom.

Mechanics:

- a synchronous `EventBus` tap (`EventBus.add_tap`) receives every
  published event inline and appends it to a bounded drop-oldest ring;
  `span_close` frames are routed to their own ring so a metric-delta
  firehose cannot evict the span history an incident analysis needs;
- rings are `collections.deque(maxlen=...)` under a small lock, with
  explicit drop counters (`cdt_flight_dropped_total{stream}` mirrors
  them at scrape time — the tap itself never touches a metric, which
  would recurse through the forwarding hook);
- cost model: with the recorder installed the bus is never in its
  zero-listener fast path, so every metric mutation and span close
  pays one event-dict build + one ring append. That is the designed
  price of postmortem-grade observability (CDT_FLIGHT=0 refuses it);
- `dump()` returns a JSON-able snapshot (events, spans, drop/append
  accounting) — the `flight` section of every incident bundle.

The recorder is process-global (`get_flight_recorder()`), created and
re-installed lazily: after a test resets the event bus, the next
`get_flight_recorder()` call re-taps the current bus.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Optional

from ..utils import constants

# Compact span-record vocabulary kept in the span ring (a full
# span_close payload also carries attrs — kept, they are small and
# carry tile_idx/role/stage the critical-path analyzer needs).
SPAN_STREAM = "spans"
EVENT_STREAM = "events"


class FlightRing:
    """Bounded drop-oldest ring with append/drop accounting, safe to
    append from any thread (the bus tap runs on publishing threads)."""

    def __init__(self, capacity: int) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=self.capacity)
        self.appended = 0
        self.dropped = 0

    def append(self, item: Any) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self.dropped += 1
            self._buf.append(item)
            self.appended += 1

    def snapshot(self) -> list:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


class FlightRecorder:
    """Tails the process event bus (all types) into bounded rings."""

    def __init__(
        self,
        event_capacity: Optional[int] = None,
        span_capacity: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self._clock = clock
        self.events = FlightRing(
            event_capacity
            if event_capacity is not None
            else constants.FLIGHT_EVENT_CAPACITY
        )
        self.spans = FlightRing(
            span_capacity
            if span_capacity is not None
            else constants.FLIGHT_SPAN_CAPACITY
        )
        self.started_at = clock()
        self._remove_tap: Optional[Callable[[], None]] = None
        self._tapped_bus: Any = None
        # per-stream drop totals already mirrored into the scrape
        # counter — lives HERE (not per collector closure) so two
        # co-hosted servers' collectors share one high-water mark and
        # the process-global counter never double-counts a drop
        self.scrape_mirrored: dict[str, int] = {}

    # --- bus wiring -------------------------------------------------------

    def install(self, bus: Any = None) -> None:
        """Tap `bus` (default: the current global bus). Idempotent per
        bus; re-installing after a bus reset moves the tap to the new
        bus (the old one is gone with its subscribers)."""
        from .events import get_event_bus

        bus = bus if bus is not None else get_event_bus()
        if bus is self._tapped_bus:
            return
        self.uninstall()
        self._remove_tap = bus.add_tap(self._tap, name="flight")
        self._tapped_bus = bus

    def uninstall(self) -> None:
        remove, self._remove_tap = self._remove_tap, None
        self._tapped_bus = None
        if remove is not None:
            remove()

    @property
    def installed(self) -> bool:
        return self._remove_tap is not None

    def _tap(self, event: dict[str, Any]) -> None:
        """Runs inline on the PUBLISHING thread: one ring append, no
        metrics, no locks beyond the ring's own."""
        if event.get("type") == "span_close":
            self.spans.append(event)
        else:
            self.events.append(event)

    # --- surfaces ---------------------------------------------------------

    def drop_totals(self) -> dict[str, int]:
        return {
            EVENT_STREAM: self.events.dropped,
            SPAN_STREAM: self.spans.dropped,
        }

    def status(self) -> dict[str, Any]:
        """Cheap accounting summary (system_info / incidents route)."""
        return {
            "installed": self.installed,
            "capacity": {
                EVENT_STREAM: self.events.capacity,
                SPAN_STREAM: self.spans.capacity,
            },
            "retained": {
                EVENT_STREAM: len(self.events),
                SPAN_STREAM: len(self.spans),
            },
            "appended": {
                EVENT_STREAM: self.events.appended,
                SPAN_STREAM: self.spans.appended,
            },
            "dropped": self.drop_totals(),
        }

    def dump(self) -> dict[str, Any]:
        """The incident bundle's `flight` section: both rings plus the
        accounting needed to read them honestly (how much history the
        rings dropped before the capture)."""
        return {
            "captured_at": self._clock(),
            "started_at": self.started_at,
            "events": self.events.snapshot(),
            "spans": self.spans.snapshot(),
            "appended": {
                EVENT_STREAM: self.events.appended,
                SPAN_STREAM: self.spans.appended,
            },
            "dropped": self.drop_totals(),
        }


# --- global recorder --------------------------------------------------------

_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> Optional[FlightRecorder]:
    """The process-global recorder, created on first call and
    (re-)installed on the CURRENT event bus. Returns None when
    CDT_FLIGHT=0 — callers treat a disabled recorder as absent."""
    if not constants.FLIGHT_ENABLED:
        return None
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        _recorder.install()
        return _recorder


def peek_flight_recorder() -> Optional[FlightRecorder]:
    """The recorder if one exists — never creates or re-taps (scrape
    collectors read accounting without changing wiring)."""
    return _recorder


def reset_flight_recorder() -> None:
    """Drop the global recorder (tests); the next get re-creates."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            _recorder.uninstall()
        _recorder = None
