"""Telemetry core: metrics, tracing, live events, watchdog, runtime.

The observability subsystem the ROADMAP's perf work hangs off:

- `metrics`: zero-dependency Counter/Gauge/Histogram registry with
  Prometheus text exposition (cardinality-capped per metric), served
  by `/distributed/metrics`;
- `tracing`: span trees keyed by the existing ``exec_*`` trace ids,
  propagated master→worker via the ``X-CDT-Trace-Id`` header and
  served by `/distributed/trace/{trace_id}`; JSONL export feeds
  `scripts/perf_report.py`;
- `instruments`: every metric name/label vocabulary in one place,
  plus `bind_server_collectors` for live-state gauges;
- `events`: push-based event bus (metric deltas, span open/close,
  health transitions, watchdog verdicts) streamed by the
  `GET /distributed/events` WebSocket;
- `watchdog`: straggler & stall detector feeding breaker suspect
  transitions and speculative tail-tile re-dispatch;
- `runtime`: JAX compile/cache/HBM/host-RSS collectors on the scrape,
  stamped into bench output via `runtime_snapshot`;
- `timeseries`: bounded two-tier ring-buffer retention (10 s raw /
  5 min rollup) for the fleet plane's windowed history;
- `fleet`: worker snapshot production + the master's `FleetRegistry`
  (per-worker merge, rollups, departed-worker eviction), served by
  `GET /distributed/fleet`;
- `slo`: declarative SLOs with multi-window burn-rate alerting —
  `alert_fired`/`alert_resolved` bus events, `GET /distributed/alerts`,
  and the `cdt_alert_active` scrape gauge;
- `usage`: chip-time attribution — both execution tiers emit
  slot-exact timed records per device dispatch (tenant/job/lane
  charges + padding/recompute/speculation/poison waste buckets, exact
  conservation), worker meters merge into the master by riding the
  fleet snapshot, served by `GET /distributed/usage`.

All clocks are injectable so tier-1 tests run deterministically on
CPU. See docs/observability.md for the operator-facing story.
"""

from __future__ import annotations

from .instruments import BREAKER_STATE_CODES, bind_server_collectors
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_metrics_registry,
    reset_metrics_registry,
)
from .tracing import (
    TRACE_HEADER,
    Span,
    Tracer,
    current_trace_id,
    get_tracer,
    reset_tracer,
    set_tracer,
)
from .events import EventBus, get_event_bus, reset_event_bus
from .fleet import FleetMonitor, FleetRegistry, local_snapshot
from .flight import (
    FlightRecorder,
    get_flight_recorder,
    peek_flight_recorder,
    reset_flight_recorder,
)
from .incidents import IncidentManager, validate_bundle
from .slo import BurnRule, SLOEngine, SLOSpec, default_slos
from .timeseries import SeriesStore
from .usage import UsageAggregator, UsageMeter, get_usage_meter
from .watchdog import Watchdog

__all__ = [
    "BREAKER_STATE_CODES",
    "BurnRule",
    "Counter",
    "EventBus",
    "FleetMonitor",
    "FleetRegistry",
    "FlightRecorder",
    "Gauge",
    "IncidentManager",
    "Histogram",
    "MetricsRegistry",
    "SLOEngine",
    "SLOSpec",
    "SeriesStore",
    "Span",
    "TRACE_HEADER",
    "Tracer",
    "UsageAggregator",
    "UsageMeter",
    "Watchdog",
    "default_slos",
    "local_snapshot",
    "bind_server_collectors",
    "current_trace_id",
    "get_event_bus",
    "get_flight_recorder",
    "get_metrics_registry",
    "get_tracer",
    "get_usage_meter",
    "peek_flight_recorder",
    "reset_event_bus",
    "reset_flight_recorder",
    "reset_metrics_registry",
    "reset_tracer",
    "set_tracer",
    "validate_bundle",
]
