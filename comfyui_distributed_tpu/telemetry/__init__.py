"""Telemetry core: metrics registry, span tracing, canonical instruments.

The observability subsystem the ROADMAP's perf work hangs off:

- `metrics`: zero-dependency Counter/Gauge/Histogram registry with
  Prometheus text exposition, served by `/distributed/metrics`;
- `tracing`: span trees keyed by the existing ``exec_*`` trace ids,
  propagated master→worker via the ``X-CDT-Trace-Id`` header and
  served by `/distributed/trace/{trace_id}`; JSONL export feeds
  `scripts/perf_report.py`;
- `instruments`: every metric name/label vocabulary in one place,
  plus `bind_server_collectors` for live-state gauges.

All clocks are injectable so tier-1 tests run deterministically on
CPU. See docs/observability.md for the operator-facing story.
"""

from __future__ import annotations

from .instruments import BREAKER_STATE_CODES, bind_server_collectors
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_metrics_registry,
    reset_metrics_registry,
)
from .tracing import (
    TRACE_HEADER,
    Span,
    Tracer,
    current_trace_id,
    get_tracer,
    reset_tracer,
    set_tracer,
)

__all__ = [
    "BREAKER_STATE_CODES",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "TRACE_HEADER",
    "Tracer",
    "bind_server_collectors",
    "current_trace_id",
    "get_metrics_registry",
    "get_tracer",
    "reset_metrics_registry",
    "reset_tracer",
    "set_tracer",
]
