"""Canonical instrument definitions: every metric name in one place.

Call sites fetch instruments through these accessors instead of naming
strings inline, so the name/label vocabulary stays consistent (and one
test can enforce the ``cdt_`` + snake_case conventions over the whole
set — tests/test_telemetry_metrics.py).

Accessors are get-or-create against the CURRENT global registry, so a
test that resets the registry gets fresh instruments transparently.

Live-state gauges (queue depths, breaker states) are scrape-time
collectors bound per server via `bind_server_collectors`.
"""

from __future__ import annotations

from typing import Callable

from .metrics import Counter, Gauge, Histogram, get_metrics_registry

# Breaker states in gauge encoding (docs/observability.md documents it).
BREAKER_STATE_CODES = {
    "healthy": 0,
    "suspect": 1,
    "quarantined": 2,
    "probing": 3,
    "recovered": 4,
}

# Short buckets for store-level ops (sub-ms .. 1s).
STORE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0)


# --- job store ------------------------------------------------------------

def store_pulls_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_store_pulls_total",
        "Tile/image pull RPCs against the JobStore by outcome (task|empty)",
        ("worker_id", "outcome"),
    )


def store_submits_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_store_submits_total",
        "Result submissions by outcome (accepted|duplicate)",
        ("worker_id", "outcome"),
    )


def store_heartbeats_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_store_heartbeats_total",
        "Heartbeats recorded per worker (explicit + piggybacked)",
        ("worker_id",),
    )


def store_requeued_tasks_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_store_requeued_tasks_total",
        "Tasks returned to the pending queue by reason "
        "(timeout|quarantine|speculative|released)",
        ("worker_id", "reason"),
    )


# --- dispatch / orchestration --------------------------------------------

# --- request lifecycle (deadlines / cancel / poison / brownout) -----------

def jobs_cancelled_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_jobs_cancelled_total",
        "Jobs reaching the terminal cancelled state by reason "
        "(client|deadline|chaos|...)",
        ("reason",),
    )


def cancel_refunded_tiles_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_cancel_refunded_tiles_total",
        "Tiles refunded by job cancellation by kind (pending|in_flight)",
        ("kind",),
    )


def poison_quarantined_tiles_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_poison_quarantined_tiles_total",
        "Tiles quarantined out of the pull set after exhausting their "
        "delivery-attempt budget",
    )


def poison_pardons_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_poison_pardons_total",
        "Breaker pardons issued to workers whose failures traced to a "
        "poison-quarantined tile",
    )


def shed_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_shed_total",
        "Admissions shed by the brownout controller, by lane",
        ("lane",),
    )


def brownout_level() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_brownout_level",
        "Current brownout level (number of lowest-priority lanes shed)",
    )


def dispatch_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_dispatch_seconds",
        "Prompt dispatch latency per worker by outcome "
        "(ok|rejected|unreachable|error)",
        ("worker_id", "outcome"),
    )


def orchestrations_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_orchestrations_total",
        "Distributed queue orchestrations by mode (fan_out|load_balance)",
        ("mode",),
    )


def media_sync_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_media_sync_seconds",
        "Media sync duration per worker",
        ("worker_id",),
    )


def media_sync_uploads_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_media_sync_uploads_total",
        "Media files uploaded to workers by outcome (ok|failed)",
        ("worker_id", "outcome"),
    )


def collector_results_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_collector_results_total",
        "Images accepted into collector queues per worker",
        ("worker_id",),
    )


# --- resilience -----------------------------------------------------------

def retries_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_retries_total",
        "Retry attempts by retry_async, labelled by operation",
        ("op",),
    )


def breaker_transitions_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_worker_breaker_transitions_total",
        "Circuit-breaker state transitions per worker",
        ("worker_id", "from_state", "to_state"),
    )


def breaker_state() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_worker_breaker_state",
        "Circuit-breaker state per worker "
        "(0=healthy 1=suspect 2=quarantined 3=probing 4=recovered)",
        ("worker_id",),
    )


# --- watchdog (telemetry/watchdog.py) -------------------------------------

def worker_tile_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_worker_tile_seconds",
        "Pull-to-submit latency per worker (the straggler-detection "
        "signal; cardinality-capped per the registry's series bound)",
        ("worker_id",),
    )


def watchdog_stragglers_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_watchdog_stragglers_total",
        "Workers flagged as stragglers (rolling-median tile latency "
        "above k x the global rolling median)",
        ("worker_id",),
    )


def watchdog_stalls_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_watchdog_stalls_total",
        "Stalled executions detected (no completion progress for the "
        "stall window while tiles were in flight)",
    )


# --- scheduler control plane (scheduler/) ---------------------------------

# Scheduler states in gauge encoding.
SCHED_STATE_CODES = {"running": 0, "paused": 1, "draining": 2}


def sched_admissions_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_sched_admissions_total",
        "Admission decisions by outcome (admitted|rejected_full|"
        "rejected_draining|cancelled)",
        ("lane", "tenant", "outcome"),
    )


def sched_grants_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_sched_grants_total",
        "Requests granted an orchestration slot per lane/tenant",
        ("lane", "tenant"),
    )


def sched_wait_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_sched_wait_seconds",
        "Queue wait from admission to grant per lane/tenant",
        ("lane", "tenant"),
    )


def sched_lane_depth() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_sched_lane_depth",
        "Requests queued (admitted, not yet granted) per lane per server",
        ("lane", "server"),
    )


def sched_active() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_sched_active",
        "Granted orchestrations currently holding a slot per server",
        ("server",),
    )


def sched_state() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_sched_state",
        "Scheduler state per server (0=running 1=paused 2=draining)",
        ("server",),
    )


def sched_worker_speed_ratio() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_sched_worker_speed_ratio",
        "Placement speed weight per worker (1.0 = fleet mean; pull "
        "batches scale with it)",
        ("worker_id", "server"),
    )


# --- durable control plane (durability/) ----------------------------------

def journal_appends_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_journal_appends_total",
        "Write-ahead-journal records appended by record type",
        ("record",),
    )


def journal_fsync_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_journal_fsync_seconds",
        "fsync latency of journal appends (CDT_JOURNAL_FSYNC policy)",
        buckets=STORE_BUCKETS,
    )


def snapshots_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_snapshots_total",
        "Control-plane snapshots written (periodic + post-recovery)",
    )


def snapshot_age_seconds() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_snapshot_age_seconds",
        "Seconds since the last control-plane snapshot was written "
        "(bounds the WAL tail a restart must replay)",
    )


def recovery_replayed_records() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_recovery_replayed_records",
        "Journal records replayed beyond the snapshot by the last "
        "recovery on this process",
    )


def recovery_requeued_tasks() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_recovery_requeued_tasks",
        "In-flight/volatile tiles the last recovery requeued for "
        "bit-identical recompute",
    )


# --- high availability: replication, failover, push grants ----------------

def replication_lag_records() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_replication_lag_records",
        "Journal records the standby replica is behind the active "
        "master's head (source head lsn - applied lsn)",
    )


def replication_lag_seconds() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_replication_lag_seconds",
        "Staleness of the newest replication frame the standby applied",
    )


def failover_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_failover_total",
        "Master failovers by role: standby = promotions performed, "
        "worker = client re-points to another master address",
        ("role",),
    )


def push_grants_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_push_grants_total",
        "Tasks announced over pushed grant_available events "
        "(CDT_PUSH_GRANTS; workers wake on these instead of "
        "pull-polling)",
    )


def worker_master_errors_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_worker_master_errors_total",
        "Worker->master RPC failures by operation (heartbeat|pull|"
        "submit|transport); consecutive failures back off "
        "exponentially so a master outage never becomes a log/request "
        "flood",
        ("op",),
    )


# --- JAX runtime health (telemetry/runtime.py) ----------------------------

def jax_compiles() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_jax_compiles",
        "Backend compiles observed since process start (jax.monitoring)",
    )


def jax_compile_time_seconds() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_jax_compile_time_seconds",
        "Cumulative backend compile time since process start",
    )


def jax_cache_hits() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_jax_cache_hits",
        "Compilation-cache hits since process start",
    )


def jax_cache_misses() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_jax_cache_misses",
        "Compilation-cache misses since process start",
    )


def device_memory_bytes() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_device_memory_bytes",
        "Accelerator memory stats per device (bytes_in_use, "
        "peak_bytes_in_use, bytes_limit, ... from device.memory_stats)",
        ("device", "stat"),
    )


def host_rss_bytes() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_host_rss_bytes",
        "Resident set size of this process",
    )


# --- fleet observability plane (telemetry/fleet.py, telemetry/slo.py) -----

def fleet_snapshots_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_fleet_snapshots_total",
        "Worker telemetry snapshots received piggybacked on "
        "heartbeat/request_image RPCs, by outcome "
        "(accepted|bad_version|malformed)",
        ("outcome",),
    )


def fleet_evictions_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_fleet_evictions_total",
        "Workers evicted from the fleet registry by reason "
        "(ttl|forgotten|capacity) — every eviction drops the worker's "
        "retained series",
        ("reason",),
    )


def fleet_workers() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_fleet_workers",
        "Workers currently tracked by the fleet registry (snapshotting "
        "within the CDT_FLEET_TTL window)",
    )


def fleet_series() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_fleet_series",
        "Retained time-series count in the fleet store (bounded per "
        "name by CDT_METRIC_MAX_SERIES)",
    )


# --- usage metering / chip-time attribution (telemetry/usage.py) ----------

def usage_chip_seconds_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_usage_chip_seconds_total",
        "Measured chip-seconds attributed to each (tenant, lane) by the "
        "usage meter's dispatch records (mirrored from the aggregator "
        "at scrape time; cardinality bounded by the usage key cap)",
        ("tenant", "lane"),
    )


def usage_tiles_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_usage_tiles_total",
        "Tiles finished per (tenant, lane) as metered by the usage "
        "attribution plane",
        ("tenant", "lane"),
    )


def usage_waste_seconds_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_usage_waste_seconds_total",
        "Measured chip-seconds charged to waste buckets by reason "
        "(padding|preempt_recompute|speculation|poison_retry)",
        ("reason",),
    )


def usage_cached_tiles_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_usage_cached_tiles_total",
        "Tiles settled from the content-addressed tile cache per "
        "(tenant, lane) — the `cached` attribution bucket: they count "
        "toward the tenant's tiles at ~zero chip-time",
        ("tenant", "lane"),
    )


# --- content-addressed tile result cache (cache/) -------------------------

def cache_lookups_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_cache_lookups_total",
        "Tile-cache lookups by outcome (hit_ram|hit_disk|miss) — "
        "mirrored by delta from the store's cumulative stats at scrape "
        "time",
        ("outcome",),
    )


def cache_settled_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_cache_settled_total",
        "Tiles settled into jobs straight from the tile cache at grant "
        "time (they completed without ever entering the pull set)",
    )


def cache_corrupt_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_cache_corrupt_total",
        "Disk-tier cache entries that failed CRC/format validation on "
        "read (deleted and degraded to a miss, never a wrong canvas)",
    )


def cache_bytes() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_cache_bytes",
        "Bytes resident per tile-cache tier (ram|disk) at scrape time",
        ("tier",),
    )


def cache_hit_ratio() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_cache_hit_ratio",
        "Lifetime tile-cache hit rate (hits / lookups) at scrape time",
    )


def cache_unsettled_admission_cost() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_cache_unsettled_admission_cost",
        "Cumulative DRR admission cost charged for tiles that later "
        "settled free from the tile cache at grant time — the PR-17 "
        "full-cost-until-settle gap, surfaced so operators can see how "
        "much fair-share weight cached tenants are over-paying "
        "(docs/operator-runbook.md §cache triage)",
        ("server",),
    )


# --- adapter plane (adapters/) ---------------------------------------------

def adapter_cache_lookups_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_adapter_cache_lookups_total",
        "Adapter operand-cache lookups by outcome (hit|miss) — a miss "
        "means a safetensors decode + operand layout ran on the host "
        "(docs/operator-runbook.md §adapter thrashing)",
        ("outcome",),
    )


def adapter_cache_evictions_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_adapter_cache_evictions_total",
        "Adapter operand entries evicted by the byte-budget LRU "
        "(CDT_ADAPTER_CACHE_MB); sustained growth alongside misses = "
        "the working set exceeds the budget (thrashing)",
    )


def adapter_cache_bytes() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_adapter_cache_bytes",
        "Resident bytes of decoded adapter operands in the host LRU",
    )


def adapter_slots_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_adapter_slots_total",
        "Real device-batch slots that ran wearing an adapter "
        "(segmented application); ratio against cdt_tiles_processed "
        "slots is perf_report's segmented-slot share",
        ("role",),
    )


def adapter_jobs_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_adapter_jobs_total",
        "Jobs admitted carrying a non-empty adapter plan",
        ("tier",),
    )


# --- device-time profiling plane (telemetry/profiling.py) ------------------

def transfer_bytes_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_transfer_bytes_total",
        "Bytes moved across the device↔host boundary by direction "
        "(h2d|d2h), mirrored by delta from the transfer ledger at "
        "scrape time",
        ("direction",),
    )


def device_execute_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_device_execute_seconds",
        "Bracketed wall time of one compiled device dispatch (the "
        "transfer ledger's device side; eager/stub dispatches are "
        "excluded by construction)",
        ("role", "tier"),
    )


def host_tax_ratio() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_host_tax_ratio",
        "host_ns / (host_ns + device_ns) from the transfer ledger at "
        "scrape time — the fraction of attributable wall time spent on "
        "host gather/encode/ship instead of device execution (1.0 when "
        "no device time was observed)",
        ("role",),
    )


def profile_captures_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_profile_captures_total",
        "On-demand jax.profiler captures by outcome "
        "(started|stopped|busy|errors|auto_stopped), mirrored by delta "
        "from the capture manager's counters at scrape time",
        ("outcome",),
    )


# --- incident plane (telemetry/flight.py, telemetry/incidents.py) ---------

def incidents_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_incidents_total",
        "Incident debug bundles captured, by trigger "
        "(alert_fired|tile_quarantined|job_deadline|failover|manual)",
        ("trigger",),
    )


def incident_capture_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_incident_capture_seconds",
        "Wall time of one incident-bundle capture (gather + serialize "
        "+ atomic write + prune) on the single-flight writer thread",
    )


def flight_dropped_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_flight_dropped_total",
        "Flight-recorder ring evictions by stream (events|spans) — "
        "history lost to the bounded window before any capture",
        ("stream",),
    )


def event_subscriber_queue_depth() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_event_subscriber_queue_depth",
        "Events queued per event-bus subscriber at scrape time "
        "(bounded by CDT_EVENT_QUEUE_SIZE)",
        ("subscriber",),
    )


def event_subscriber_dropped() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_event_subscriber_dropped",
        "Cumulative drop-oldest evictions per event-bus subscriber "
        "(a slow consumer loses its oldest events, never the bus)",
        ("subscriber",),
    )


def alert_active() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_alert_active",
        "1 while the named SLO's burn-rate alert is open, 0 otherwise "
        "(transitions also publish alert_fired/alert_resolved events)",
        ("slo",),
    )


def slo_burn_rate() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_slo_burn_rate",
        "Error-budget burn rate per SLO over each rule's LONG window "
        "(1.0 = burning exactly at budget-exhaustion rate)",
        ("slo", "window"),
    )


# --- USDU tile pipeline ---------------------------------------------------

def tile_stage_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_tile_stage_seconds",
        "Per-tile stage latency (pull|sample|readback|encode|submit|"
        "decode|blend) by role (master|worker)",
        ("stage", "role"),
    )


def tiles_processed_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_tiles_processed_total",
        "Tiles fully processed per role",
        ("role",),
    )


# --- local device mesh (parallel/mesh.py + mesh-parallel GrantSampler) -----

def mesh_devices() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_mesh_devices",
        "Local mesh shape per role: devices along each axis "
        "(data = tile fan-out participants, model = tensor-parallel "
        "shards, total = chips in the mesh)",
        ("role", "axis"),
    )


def mesh_batch_share() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_mesh_batch_share",
        "Tiles each mesh participant computed in the most recent "
        "sharded dispatch (bucket size / data-axis width)",
        ("role",),
    )


def mesh_gather_seconds() -> Histogram:
    return get_metrics_registry().histogram(
        "cdt_mesh_gather_seconds",
        "Host-side gather latency of a sharded tile batch "
        "(parallel/collective.host_collect) per role",
        ("role",),
    )


# --- elastic tile pipeline (graph/tile_pipeline.py) ------------------------

def pipeline_batches_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_pipeline_batches_total",
        "Batched device dispatches in the elastic tile pipeline by "
        "role and grant-chunk size",
        ("role", "bucket"),
    )


def batch_fill_ratio() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_batch_fill_ratio",
        "Real tiles / bucket slots in the most recent cross-job device "
        "dispatch (graph/batch_executor.py) per role; 1.0 = no padded "
        "slots",
        ("role",),
    )


def preempt_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_preempt_total",
        "Step-level preemption requests raised by the scheduler "
        "coordinator against running lower-lane jobs, by reason "
        "(premium_arrival|brownout|manual)",
        ("reason",),
    )


def preempt_resume_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_preempt_resume_total",
        "Preempted tiles taken up again by an executor, by mode "
        "(checkpoint = resumed from mid-trajectory latents; recompute "
        "= checkpoint lost, replayed from step 0 — the bit-identity "
        "reference)",
        ("mode",),
    )


def pipeline_inflight() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_pipeline_inflight",
        "Device batches dispatched but not yet read back per role "
        "(bounded by CDT_PIPELINE_DEPTH)",
        ("role",),
    )


def pipeline_padded_tiles_total() -> Counter:
    return get_metrics_registry().counter(
        "cdt_pipeline_padded_tiles_total",
        "Wraparound-duplicate tiles added to pad ragged grants up to a "
        "compiled shape bucket (wasted device work, bounded by bucket "
        "granularity)",
        ("role",),
    )


# --- queue / live state (scrape-time collectors) --------------------------
# The `server` label (e.g. "master:8188", "worker:8189") keeps the
# series of multiple DistributedServers in one process apart — a
# co-hosted master+worker pair (or an integration test) shares the
# process-global registry, and unlabeled gauges would report whichever
# server's collector ran last.

def prompt_queue_depth() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_prompt_queue_depth",
        "Prompts queued (including the one executing) per server",
        ("server",),
    )


def tile_jobs_active() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_tile_jobs_active",
        "Tile/image jobs currently registered per server",
        ("server",),
    )


def tile_queue_depth() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_tile_queue_depth",
        "Pending tasks across all tile/image jobs per server",
        ("server",),
    )


def tiles_in_flight() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_tiles_in_flight",
        "Tasks pulled by a worker but not yet completed, per server",
        ("server",),
    )


def collector_jobs_active() -> Gauge:
    return get_metrics_registry().gauge(
        "cdt_collector_jobs_active",
        "Collector queues currently registered per server",
        ("server",),
    )


_LIVE_GAUGES = (
    prompt_queue_depth,
    tile_jobs_active,
    tile_queue_depth,
    tiles_in_flight,
    collector_jobs_active,
)


def bind_server_collectors(server) -> Callable[[], None]:
    """Register scrape-time collectors mirroring one server's live
    state (prompt queue, JobStore, breaker registry) into gauges.
    Returns an unbind callable (the server calls it on stop) that also
    drops the server's gauge series from the scrape."""
    from ..resilience.health import get_health_registry
    from .runtime import ensure_runtime_collectors

    # JAX runtime gauges (compiles, cache hits, HBM, host RSS) ride the
    # same scrape; process-global, bound once per registry.
    ensure_runtime_collectors()

    # Touch the tile-pipeline instruments so their HELP/TYPE headers are
    # present in the very first scrape (CI smoke asserts on them even
    # before any tile job has run on this server).
    pipeline_batches_total()
    pipeline_inflight()
    pipeline_padded_tiles_total()

    # Same for the durability instruments when this server journals:
    # the web panel's durability card parses them from the first scrape.
    if getattr(server, "durability", None) is not None:
        journal_appends_total()
        journal_fsync_seconds()
        snapshots_total()
        snapshot_age_seconds()
        recovery_replayed_records()
        recovery_requeued_tasks()
        failover_total()
        push_grants_total()
    # Standby masters report replication lag from the first scrape.
    if getattr(server, "standby", None) is not None:
        replication_lag_records()
        replication_lag_seconds()
        failover_total()
    # Fleet plane instruments present from the first scrape on masters
    # running the monitor (the web panel's fleet card and the CI smoke
    # parse them before any worker has snapshotted).
    if getattr(server, "fleet", None) is not None:
        fleet_snapshots_total()
        fleet_evictions_total()
        fleet_workers()
        fleet_series()
        alert_active()
        slo_burn_rate()
        if getattr(server.fleet, "usage", None) is not None:
            usage_chip_seconds_total()
            usage_tiles_total()
            usage_waste_seconds_total()
            usage_cached_tiles_total()
    # Incident-plane instruments present from the first scrape: the
    # flight drop counter whenever a recorder exists, the capture
    # instruments on masters running an incident manager.
    from .flight import peek_flight_recorder

    if peek_flight_recorder() is not None:
        flight_dropped_total()
    # Tile-cache instruments present from the first scrape whenever the
    # cache is live in this process (CDT_CACHE=1 or a harness-installed
    # instance) — the panel's cache card parses them before any lookup.
    from ..cache.store import get_tile_cache as _get_tile_cache

    if _get_tile_cache() is not None:
        cache_lookups_total()
        cache_settled_total()
        cache_corrupt_total()
        cache_bytes()
        cache_hit_ratio()
    if getattr(server, "incidents", None) is not None:
        incidents_total()
        incident_capture_seconds()
    # Profiling-plane instruments present from the first scrape when
    # the transfer ledger is on (CDT_PROFILING, default-enabled) — the
    # panel's profiling card parses host-tax before any dispatch ran.
    from ..utils.constants import PROFILING_ENABLED as _PROFILING_ENABLED

    if _PROFILING_ENABLED:
        transfer_bytes_total()
        device_execute_seconds()
        host_tax_ratio()
    from .profiling import get_profiler_capture as _get_profiler_capture

    if _get_profiler_capture() is not None:
        profile_captures_total()
    # The admission-cost gap gauge rides on masters with both a
    # scheduler (DRR admission) and a live tile cache — the only
    # configuration where settle-after-charge can happen.
    if getattr(server, "scheduler", None) is not None:
        cache_unsettled_admission_cost()

    label = f"{'worker' if server.is_worker else 'master'}:{server.port}"
    # worker ids this server's placement policy last reported: stale
    # series are removed per-server (a global clear would clobber a
    # co-hosted server's series between its scrapes)
    speed_series_seen: set[str] = set()

    def collect() -> None:
        prompt_queue_depth().set(server.queue_remaining, server=label)
        stats = server.job_store.stats_unlocked()
        tile_jobs_active().set(stats["tile_jobs"], server=label)
        tile_queue_depth().set(stats["queue_depth"], server=label)
        tiles_in_flight().set(stats["in_flight"], server=label)
        collector_jobs_active().set(stats["collectors"], server=label)
        scheduler = getattr(server, "scheduler", None)
        if scheduler is not None:
            queue = scheduler.queue
            sched_state().set(
                SCHED_STATE_CODES.get(queue.state, -1), server=label
            )
            sched_active().set(len(queue.active), server=label)
            for lane_name in queue.lane_order:
                sched_lane_depth().set(
                    queue.lanes[lane_name].depth(), lane=lane_name, server=label
                )
            speed_gauge = sched_worker_speed_ratio()
            weights = scheduler.placement.weights()
            # dropped workers must not freeze a series
            for worker_id in speed_series_seen - weights.keys():
                speed_gauge.remove(worker_id=worker_id, server=label)
            speed_series_seen.clear()
            speed_series_seen.update(weights)
            for worker_id, ratio in weights.items():
                speed_gauge.set(ratio, worker_id=worker_id, server=label)
        durability = getattr(server, "durability", None)
        if durability is not None:
            durability.collect_metrics()
        slo = getattr(server, "slo", None)
        if slo is not None:
            # scrape-time refresh: alert gauges reflect the CURRENT
            # engine state even if no transition fired since the last
            # step (and burn rates ride the scrape for dashboards)
            active_gauge = alert_active()
            burn_gauge = slo_burn_rate()
            for spec_name in slo.specs:
                active_gauge.set(
                    1.0 if slo.is_active(spec_name) else 0.0, slo=spec_name
                )
                try:
                    verdict = slo.evaluate(spec_name)
                except Exception:  # noqa: BLE001 - scrape survives eval
                    continue
                for rule in verdict["rules"]:
                    burn_gauge.set(
                        rule["burn_long"],
                        slo=spec_name,
                        window=f"{int(rule['long_s'])}s",
                    )
        standby = getattr(server, "standby", None)
        if standby is not None and not standby.promoted:
            replica = standby.replica
            replication_lag_records().set(replica.lag_records())
            lag_seconds = replica.lag_seconds()
            if lag_seconds is not None:
                replication_lag_seconds().set(lag_seconds)
        # Event-bus consumer accounting (the flight recorder is an
        # always-on tap; a parked WS subscriber is a queue): depth +
        # cumulative drops per subscriber. Clear-then-refill so a
        # departed subscriber's series drops instead of freezing.
        from .events import get_event_bus
        from .flight import peek_flight_recorder as _peek_flight

        bus_stats = get_event_bus().stats()
        depth_gauge = event_subscriber_queue_depth()
        drop_gauge = event_subscriber_dropped()
        depth_gauge.clear()
        drop_gauge.clear()
        for sub_stats in bus_stats["subscribers"]:
            depth_gauge.set(
                sub_stats["queue_depth"], subscriber=sub_stats["name"]
            )
            drop_gauge.set(sub_stats["dropped"], subscriber=sub_stats["name"])
        # flight-ring drops are plain ints on the recorder (the tap
        # must not touch metrics — it runs inside publish); the
        # counter mirrors them by DELTA at scrape time against the
        # recorder's own high-water mark, shared across co-hosted
        # servers' collectors so a drop is counted exactly once
        recorder = _peek_flight()
        if recorder is not None:
            drop_counter = flight_dropped_total()
            for stream, dropped in recorder.drop_totals().items():
                delta = dropped - recorder.scrape_mirrored.get(stream, 0)
                if delta > 0:
                    drop_counter.inc(delta, stream=stream)
                    recorder.scrape_mirrored[stream] = dropped
        # Usage attribution counters mirror the aggregator's cumulative
        # rollup by DELTA against its own high-water marks (the flight-
        # recorder idiom: co-hosted servers' collectors share the marks
        # so a chip-second is counted exactly once).
        fleet = getattr(server, "fleet", None)
        usage = getattr(fleet, "usage", None) if fleet is not None else None
        if usage is not None:
            rollup = usage.rollup()
            chip_counter = usage_chip_seconds_total()
            tiles_counter = usage_tiles_total()
            waste_counter = usage_waste_seconds_total()
            marks = usage.scrape_mirrored
            # exact (tenant, lane) slices from the aggregator's
            # MONOTONIC pair view (live + retired — a TTL-swept job's
            # chip time stays in its pair, so the high-water deltas
            # never undercount after eviction)
            by_pair = usage.pair_totals()
            for (tenant, lane) in sorted(by_pair):
                stats = by_pair[(tenant, lane)]
                chip_key = f"chip:{tenant}:{lane}"
                delta = stats["chip_s"] - marks.get(chip_key, 0.0)
                if delta > 0:
                    chip_counter.inc(delta, tenant=tenant, lane=lane)
                    marks[chip_key] = stats["chip_s"]
                tile_key = f"tiles:{tenant}:{lane}"
                delta = stats["tiles"] - marks.get(tile_key, 0.0)
                if delta > 0:
                    tiles_counter.inc(delta, tenant=tenant, lane=lane)
                    marks[tile_key] = stats["tiles"]
                cached_value = stats.get("cached", 0.0)
                cached_key = f"cached:{tenant}:{lane}"
                delta = cached_value - marks.get(cached_key, 0.0)
                if delta > 0:
                    usage_cached_tiles_total().inc(
                        delta, tenant=tenant, lane=lane
                    )
                    marks[cached_key] = cached_value
            for reason in sorted(rollup["totals"]["waste_s"]):
                value = rollup["totals"]["waste_s"][reason]
                delta = value - marks.get(f"waste:{reason}", 0.0)
                if delta > 0:
                    waste_counter.inc(delta, reason=reason)
                    marks[f"waste:{reason}"] = value
        # Tile-cache stats ride the scrape the same way: gauges set
        # directly, counters mirrored by DELTA against the cache's own
        # high-water marks (shared across co-hosted collectors so a
        # lookup is counted exactly once).
        tile_cache = _get_tile_cache()
        if tile_cache is not None:
            cstats = tile_cache.stats()
            cache_bytes().set(cstats["ram_bytes"], tier="ram")
            cache_bytes().set(cstats["disk_bytes"], tier="disk")
            cache_hit_ratio().set(cstats["hit_rate"])
            cache_marks = tile_cache.scrape_mirrored
            lookup_counter = cache_lookups_total()
            for outcome, value in (
                ("hit_ram", cstats["hits_ram"]),
                ("hit_disk", cstats["hits_disk"]),
                ("miss", cstats["misses"]),
            ):
                delta = value - cache_marks.get(outcome, 0)
                if delta > 0:
                    lookup_counter.inc(delta, outcome=outcome)
                    cache_marks[outcome] = value
            delta = cstats["corrupt"] - cache_marks.get("corrupt", 0)
            if delta > 0:
                cache_corrupt_total().inc(delta)
                cache_marks["corrupt"] = cstats["corrupt"]
        # Transfer-ledger mirroring: the direction byte counters move
        # by DELTA against the ledger's own high-water marks (shared
        # across co-hosted collectors), the host-tax gauge reads the
        # live ratio directly.
        from .profiling import (
            get_profiler_capture as _peek_capture,
            peek_transfer_ledger as _peek_ledger,
        )

        ledger = _peek_ledger()
        if ledger is not None:
            lsnap = ledger.snapshot()
            bytes_counter = transfer_bytes_total()
            for direction in sorted(lsnap["transfer"]):
                value = lsnap["transfer"][direction]["bytes"]
                mark_key = f"bytes:{direction}"
                delta = value - ledger.scrape_mirrored.get(mark_key, 0)
                if delta > 0:
                    bytes_counter.inc(delta, direction=direction)
                    ledger.scrape_mirrored[mark_key] = value
            host_tax_ratio().set(
                lsnap["host_tax"],
                role="worker" if server.is_worker else "master",
            )
        capture = _peek_capture()
        if capture is not None:
            capture_counter = profile_captures_total()
            for outcome in sorted(capture.counters):
                value = capture.counters[outcome]
                delta = value - capture.scrape_mirrored.get(outcome, 0)
                if delta > 0:
                    capture_counter.inc(delta, outcome=outcome)
                    capture.scrape_mirrored[outcome] = value
        # The DRR admission-cost gap: cumulative cost charged at
        # admission for tiles the cache later settled free (the PR-17
        # full-cost-until-settle behavior, made observable).
        if scheduler is not None:
            cache_unsettled_admission_cost().set(
                float(getattr(scheduler, "unsettled_admission_cost", 0.0)),
                server=label,
            )
        gauge = breaker_state()
        # Clear-then-refill: a worker removed from the registry
        # (config delete / reset) must drop its series, not freeze at
        # its last state forever.
        gauge.clear()
        for worker_id, health in get_health_registry().snapshot().items():
            gauge.set(
                BREAKER_STATE_CODES.get(health["state"], -1), worker_id=worker_id
            )

    unregister = get_metrics_registry().register_collector(collect)

    def unbind() -> None:
        unregister()
        for accessor in _LIVE_GAUGES:
            accessor().remove(server=label)
        if getattr(server, "scheduler", None) is not None:
            cache_unsettled_admission_cost().remove(server=label)
        event_subscriber_queue_depth().clear()
        event_subscriber_dropped().clear()
        slo = getattr(server, "slo", None)
        if slo is not None:
            for spec_name in slo.specs:
                alert_active().remove(slo=spec_name)
            slo_burn_rate().clear()
        scheduler = getattr(server, "scheduler", None)
        if scheduler is not None:
            sched_state().remove(server=label)
            sched_active().remove(server=label)
            for lane_name in scheduler.queue.lane_order:
                sched_lane_depth().remove(lane=lane_name, server=label)
            for worker_id in speed_series_seen:
                sched_worker_speed_ratio().remove(
                    worker_id=worker_id, server=label
                )

    return unbind
