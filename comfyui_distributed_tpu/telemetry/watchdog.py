"""Straggler & stall watchdog: tail-latency detection that ACTS.

The Dapper-trace + MapReduce-speculative-execution combination for the
elastic tile queue: PR 1 gave the orchestrator a circuit breaker that
reacts to *transport failures*, and PR 2 made latency *visible* — but a
worker that silently slows to 10x median latency fails neither
transport nor heartbeat, so nothing reacted until the whole upscale
finished late. This monitor closes that loop:

- **stragglers** — per-worker pull→submit tile latencies (fed by
  `JobStore.submit_result` through ``latency_sink``, mirrored into the
  ``cdt_worker_tile_seconds`` histogram) are kept in rolling windows; a
  worker whose rolling MEDIAN exceeds ``straggler_factor`` x the global
  rolling median (with at least ``min_samples`` completions) is flagged
  and pushed into the `HealthRegistry` as SUSPECT (`mark_suspect`), so
  dispatch-side policy and the control panel see it immediately;
- **stalls** — a tile job whose completion count stops moving for
  ``stall_seconds`` while tasks are still in flight is stalled (a
  straggler or silent loss is sitting on the tail); the watchdog
  **speculatively re-enqueues** the in-flight tail tiles through the
  existing requeue path (`JobStore.speculate_in_flight`). First result
  wins: duplicate submissions are already dropped by the store, and
  per-tile noise keys fold the global tile index, so whichever
  participant finishes first produces the bit-identical tile.

Everything is deterministic-testable: the clock is injectable, `step()`
runs one detection pass synchronously (tier-1 tests drive it under a
fake stepping clock), and `start()`/`stop()` wrap the same step in a
daemon thread for production (`DistributedServer.start`). Tuning knobs
are the ``CDT_WATCHDOG_*`` env vars (utils/constants.py); verdicts are
published on the event bus (``straggler_detected`` / ``stall_detected``
/ ``speculative_requeue``) and counted by the ``cdt_watchdog_*``
instruments. docs/observability.md documents the operator story.
"""

from __future__ import annotations

import collections
import statistics
import threading
import time
from typing import Any, Callable, Optional

from ..utils import constants
from ..utils.logging import debug_log, log
from . import instruments
from .events import get_event_bus


class Watchdog:
    """Background straggler/stall monitor over one JobStore.

    `store` and `health` are optional so unit tests can drive the
    latency logic alone; `speculate` overrides how a stalled job's
    in-flight tail is re-enqueued (the default round-trips through the
    server loop, the only place JobStore asyncio state may be touched).
    """

    def __init__(
        self,
        store: Any = None,
        health: Any = None,
        clock: Callable[[], float] = time.monotonic,
        straggler_factor: float | None = None,
        min_samples: int | None = None,
        stall_seconds: float | None = None,
        interval: float | None = None,
        window: int | None = None,
        speculate: Optional[Callable[[str], list]] = None,
    ) -> None:
        self.store = store
        self.health = health
        self.clock = clock
        self.straggler_factor = (
            straggler_factor
            if straggler_factor is not None
            else constants.WATCHDOG_STRAGGLER_FACTOR
        )
        self.min_samples = (
            min_samples if min_samples is not None else constants.WATCHDOG_MIN_SAMPLES
        )
        self.stall_seconds = (
            stall_seconds
            if stall_seconds is not None
            else constants.WATCHDOG_STALL_SECONDS
        )
        self.interval = (
            interval if interval is not None else constants.WATCHDOG_INTERVAL_SECONDS
        )
        self.window = window if window is not None else constants.WATCHDOG_LATENCY_WINDOW
        self._speculate = speculate or self._speculate_via_server_loop

        self._lock = threading.Lock()
        # worker_id → rolling latency window; LRU-bounded so worker-id
        # churn (ephemeral pods, hostile ids on the open RPC surface)
        # can't grow the dict — the same storm the metrics registry
        # caps with CDT_METRIC_MAX_SERIES.
        self.max_workers = 256
        self._latencies: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )
        # job_id → ((completed, pending, in_flight), last-change time)
        self._progress: dict[str, tuple[tuple[int, int, int], float]] = {}
        self._current_stragglers: set[str] = set()
        # Verdict history (tests and the chaos harness read these);
        # bounded — a weeks-long master with a flapping straggler must
        # not grow these (the cdt_watchdog_*_total counters carry the
        # unbounded tallies).
        self.stragglers_flagged: collections.deque = collections.deque(maxlen=256)
        self.stalls_detected: collections.deque = collections.deque(maxlen=256)
        self.speculated: dict[str, list[int]] = {}
        self._max_speculated_jobs = 64

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- inputs -----------------------------------------------------------

    def record_latency(self, worker_id: str, seconds: float) -> None:
        """One completed tile's pull→submit latency (JobStore's
        ``latency_sink``; callable from any thread)."""
        with self._lock:
            window = self._latencies.get(worker_id)
            if window is None:
                window = collections.deque(maxlen=self.window)
                self._latencies[worker_id] = window
                while len(self._latencies) > self.max_workers:
                    evicted, _ = self._latencies.popitem(last=False)
                    self._current_stragglers.discard(evicted)
            else:
                self._latencies.move_to_end(worker_id)
            window.append(float(seconds))

    # --- detection --------------------------------------------------------

    def check_stragglers(self) -> list[str]:
        """Flag workers whose rolling-median tile latency exceeds
        k x the global rolling median; returns the NEWLY flagged ids.
        A worker whose median falls back under the bar is silently
        unflagged here (its breaker state recovers through its own
        successes, not through the watchdog)."""
        with self._lock:
            snapshot = {w: list(d) for w, d in self._latencies.items()}
        all_latencies = [v for window in snapshot.values() for v in window]
        if not all_latencies:
            return []
        global_median = statistics.median(all_latencies)
        if global_median <= 0:
            return []
        newly_flagged: list[str] = []
        for worker_id, window in sorted(snapshot.items()):
            if len(window) < self.min_samples:
                continue
            worker_median = statistics.median(window)
            if worker_median > self.straggler_factor * global_median:
                if worker_id in self._current_stragglers:
                    continue
                self._current_stragglers.add(worker_id)
                self.stragglers_flagged.append(worker_id)
                newly_flagged.append(worker_id)
                instruments.watchdog_stragglers_total().inc(worker_id=worker_id)
                get_event_bus().publish(
                    "straggler_detected",
                    worker_id=worker_id,
                    median_seconds=worker_median,
                    global_median_seconds=global_median,
                    factor=self.straggler_factor,
                )
                log(
                    f"watchdog: worker {worker_id} is a straggler "
                    f"(median {worker_median:.3f}s vs global "
                    f"{global_median:.3f}s, k={self.straggler_factor:g}); "
                    "marking suspect"
                )
                if self.health is not None:
                    try:
                        self.health.mark_suspect(worker_id)
                    except Exception as exc:  # noqa: BLE001 - observability only
                        debug_log(f"watchdog mark_suspect({worker_id}): {exc}")
            else:
                self._current_stragglers.discard(worker_id)
        return newly_flagged

    def check_stalls(self) -> list[str]:
        """Detect jobs with in-flight tasks but no completion progress
        for `stall_seconds`; speculatively re-enqueue their in-flight
        tail. Returns the job ids that stalled THIS pass."""
        if self.store is None:
            return []
        now = self.clock()
        stalled: list[str] = []
        # best-effort unlocked iteration, same contract as
        # JobStore.stats_unlocked: counts may be one mutation stale
        jobs = dict(self.store.tile_jobs)
        for job_id in list(self._progress):
            if job_id not in jobs:
                del self._progress[job_id]
        for job_id, job in jobs.items():
            completed = len(job.completed)
            if completed >= job.total_tasks:
                self._progress.pop(job_id, None)
                continue
            stats = self.store.tile_job_stats(job)
            snap = (completed, stats["pending"], stats["in_flight"])
            prev = self._progress.get(job_id)
            if prev is None or prev[0] != snap:
                self._progress[job_id] = (snap, now)
                continue
            if now - prev[1] < self.stall_seconds:
                continue
            # quiet for the whole window: restart the timer either way
            self._progress[job_id] = (snap, now)
            if stats["in_flight"] <= 0:
                continue  # nothing to speculate; heartbeat timeout owns this
            stalled.append(job_id)
            self.stalls_detected.append(job_id)
            instruments.watchdog_stalls_total().inc()
            get_event_bus().publish(
                "stall_detected",
                job_id=job_id,
                quiet_seconds=now - prev[1],
                in_flight=stats["in_flight"],
            )
            try:
                task_ids = list(self._speculate(job_id))
            except Exception as exc:  # noqa: BLE001 - recovery is best effort
                log(f"watchdog: speculative requeue for {job_id} failed: {exc}")
                continue
            if task_ids:
                self.speculated.setdefault(job_id, []).extend(task_ids)
                while len(self.speculated) > self._max_speculated_jobs:
                    self.speculated.pop(next(iter(self.speculated)))
                log(
                    f"watchdog: job {job_id} stalled "
                    f"{now - prev[1]:.1f}s; speculatively re-enqueued "
                    f"{len(task_ids)} in-flight tile(s)"
                )
        return stalled

    def check_deadlines(self) -> list[str]:
        """Drive the store's deadline sweep: jobs whose end-to-end
        deadline passed are cancelled (reason="deadline") even when no
        pull traffic is left to trigger the lazy path. Returns the job
        ids expired by this pass."""
        store = self.store
        if store is None or not hasattr(store, "sweep_deadlines"):
            return []
        # cheap unlocked guard: don't round-trip the server loop unless
        # some live job actually carries a deadline
        if not any(
            getattr(job, "deadline_at", None) is not None
            for job in dict(store.tile_jobs).values()
        ):
            return []
        try:
            from ..utils.async_helpers import run_async_in_server_loop

            return run_async_in_server_loop(store.sweep_deadlines(), timeout=30)
        except Exception as exc:  # noqa: BLE001 - sweep is best effort
            debug_log(f"watchdog deadline sweep failed: {exc}")
            return []

    def step(self) -> dict[str, list]:
        """One synchronous detection pass (the thread loop body; tests
        call it directly under a fake clock)."""
        return {
            "stragglers": self.check_stragglers(),
            "stalls": self.check_stalls(),
            "deadlines": self.check_deadlines(),
        }

    # --- default speculation path -----------------------------------------

    def _speculate_via_server_loop(self, job_id: str) -> list[int]:
        from ..utils.async_helpers import run_async_in_server_loop

        return run_async_in_server_loop(
            self.store.speculate_in_flight(job_id), timeout=30
        )

    # --- thread lifecycle -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.step()
                except Exception as exc:  # noqa: BLE001 - monitor must survive
                    debug_log(f"watchdog step failed: {exc}")

        self._thread = threading.Thread(target=run, name="cdt-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # final pass so verdicts for work that completed between the
        # last tick and shutdown are still recorded (the chaos harness
        # relies on this for deterministic assertions)
        try:
            self.check_stragglers()
        except Exception as exc:  # noqa: BLE001
            debug_log(f"watchdog final pass failed: {exc}")
