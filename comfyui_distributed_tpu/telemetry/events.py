"""Live observability event bus: push-based deltas for subscribers.

PR 2 made the orchestrator inspectable but pull-based — the control
panel polls `/prompt`, Prometheus polls `/distributed/metrics`, and
nothing watches the span stream. This module is the push side: a
process-global, thread-safe `EventBus` that fans out

- ``metric_delta``       — every Counter/Gauge/Histogram mutation
                           (forwarded from telemetry.metrics),
- ``span_open`` / ``span_close`` — span lifecycle (telemetry.tracing),
- ``health_transition``  — circuit-breaker state changes
                           (resilience.health),
- ``straggler_detected`` / ``stall_detected`` /
  ``speculative_requeue`` — watchdog verdicts (telemetry.watchdog),

to asyncio subscribers, each holding a bounded queue on its own event
loop. `GET /distributed/events` (api/telemetry_routes.py) serves the
stream over WebSocket; docs/observability.md documents the wire schema.

Design constraints:

- **zero cost without listeners**: `publish` is one lock-free
  listener check when nobody is on (no queue subscriber AND no
  synchronous tap), so the metric and span hot paths pay nothing in
  normal operation; with only the flight-recorder tap installed
  (telemetry/flight.py) the cost is one dict build + ring append;
- **publishers never block**: events are handed to subscriber loops
  via `call_soon_threadsafe`; a slow consumer's queue drops its OLDEST
  events (the consumer learns via the subscription's `dropped` count)
  instead of backpressuring the pipeline;
- **no feedback loops**: the forwarding hooks are reentrancy-guarded,
  so an event-bus internal that increments a metric can never recurse
  into another publish.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Iterable, Optional

from ..utils.constants import EVENT_QUEUE_SIZE


class Subscription:
    """One consumer's bounded event queue, bound to the asyncio loop
    that called `EventBus.subscribe`. `get()` awaits the next event;
    `dropped` counts events discarded because the queue was full."""

    __slots__ = ("loop", "queue", "types", "dropped", "closed", "name")

    def __init__(
        self,
        loop: asyncio.AbstractEventLoop,
        maxsize: int,
        types: Optional[frozenset[str]],
        name: str = "subscriber",
    ) -> None:
        self.loop = loop
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.types = types
        self.dropped = 0
        self.closed = False
        self.name = name

    def wants(self, event_type: str) -> bool:
        return self.types is None or event_type in self.types

    def _offer(self, event: dict[str, Any]) -> None:
        """Runs ON the subscriber's loop: drop-oldest on overflow."""
        if self.closed:
            return
        while self.queue.full():
            try:
                self.queue.get_nowait()
                self.dropped += 1
            except asyncio.QueueEmpty:  # pragma: no cover - race guard
                break
        self.queue.put_nowait(event)

    async def get(self) -> dict[str, Any]:
        return await self.queue.get()


class EventBus:
    """Thread-safe pub/sub fan-out with per-subscriber bounded queues."""

    def __init__(self, clock=time.time) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._subs: list[Subscription] = []
        # Synchronous taps: (name, callable) pairs invoked INLINE in
        # publish (no queue, no loop hop). The flight recorder
        # (telemetry/flight.py) and the incident trigger watcher
        # (telemetry/incidents.py) ride here — a tap must be cheap
        # (ring append / debounce check) and never raise.
        self._taps: list[tuple[str, Any]] = []
        self._seq = 0
        self._sub_seq = 0
        self.published = 0  # plain ints: bus internals must not publish

    @property
    def subscriber_count(self) -> int:
        # unlocked read of a list length: the no-subscriber fast path
        # must not contend with the publish path
        return len(self._subs)

    @property
    def has_listeners(self) -> bool:
        """True when ANYTHING (queue subscriber or synchronous tap)
        would see a published event — the forwarding hooks' fast-path
        check, so metric/span hot paths stay free with nobody on."""
        return bool(self._subs) or bool(self._taps)

    def subscribe(
        self,
        types: Optional[Iterable[str]] = None,
        maxsize: Optional[int] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        name: str = "subscriber",
    ) -> Subscription:
        """Register a consumer on the CURRENT running loop (or `loop`).
        `types` filters bus-side so unwanted events never hit the
        queue; None subscribes to everything. `name` labels the
        subscription in `stats()` (and the scrape gauges); a
        bus-unique `#n` suffix is appended so two consumers with the
        same name (two panel tabs from one IP) never alias each
        other's depth/drop series."""
        loop = loop or asyncio.get_running_loop()
        with self._lock:
            self._sub_seq += 1
            sub = Subscription(
                loop,
                maxsize if maxsize is not None else EVENT_QUEUE_SIZE,
                frozenset(types) if types is not None else None,
                name=f"{name}#{self._sub_seq}",
            )
            self._subs.append(sub)
        return sub

    def add_tap(self, fn, name: str = "tap"):
        """Install a synchronous tap called with every published event
        dict, from the PUBLISHING thread. Returns a zero-arg remove
        callable. Tap errors are swallowed (a broken observer must not
        break the pipeline it observes)."""
        entry = (name, fn)
        with self._lock:
            self._taps.append(entry)

        def remove() -> None:
            with self._lock:
                if entry in self._taps:
                    self._taps.remove(entry)

        return remove

    def stats(self) -> dict[str, Any]:
        """Per-consumer accounting for /distributed/system_info and
        the scrape gauges: every queue subscriber's depth + cumulative
        drops, and the installed synchronous taps. Queue depth is a
        best-effort cross-thread read (qsize is a plain len)."""
        with self._lock:
            subs = list(self._subs)
            taps = list(self._taps)
        return {
            "published": self.published,
            "subscribers": [
                {
                    "name": sub.name,
                    "types": sorted(sub.types) if sub.types is not None else "all",
                    "queue_depth": sub.queue.qsize(),
                    "dropped": sub.dropped,
                }
                for sub in subs
            ],
            "taps": [name for name, _fn in taps],
        }

    def unsubscribe(self, sub: Subscription) -> None:
        sub.closed = True
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def publish(self, event_type: str, **data: Any) -> None:
        """Fan one event out to every matching subscriber (queued) and
        tap (inline); callable from any thread; never raises, never
        blocks."""
        if not self._subs and not self._taps:
            return
        with self._lock:
            self._seq += 1
            event = {
                "type": event_type,
                "seq": self._seq,
                "ts": self._clock(),
                "data": data,
            }
            targets = [s for s in self._subs if s.wants(event_type)]
            taps = list(self._taps)
            if targets or taps:
                self.published += 1
        for _name, tap in taps:
            try:
                tap(event)
            except Exception:  # noqa: BLE001 - taps must not break publish
                pass
        dead: list[Subscription] = []
        for sub in targets:
            try:
                sub.loop.call_soon_threadsafe(sub._offer, event)
            except RuntimeError:
                dead.append(sub)  # loop closed under the subscriber
        for sub in dead:
            self.unsubscribe(sub)


# --- forwarding hooks (metrics / spans → bus) ------------------------------

_suppress = threading.local()


def _forward_metric(kind, name, labelnames, labelvalues, value) -> None:
    """telemetry.metrics mutation listener → ``metric_delta`` events.
    `value` is the increment for counters, the new value for gauges,
    and the observation for histograms."""
    bus = get_event_bus()
    if not bus.has_listeners or getattr(_suppress, "active", False):
        return
    _suppress.active = True
    try:
        bus.publish(
            "metric_delta",
            metric=name,
            kind=kind,
            labels=dict(zip(labelnames, labelvalues)),
            value=value,
        )
    finally:
        _suppress.active = False


def _forward_span(phase: str, span) -> None:
    """telemetry.tracing span listener → span_open / span_close."""
    bus = get_event_bus()
    if not bus.has_listeners or getattr(_suppress, "active", False):
        return
    _suppress.active = True
    try:
        payload = {
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "name": span.name,
            "start": span.start,
            "attrs": dict(span.attrs),
        }
        if phase == "close":
            payload["end"] = span.end
            payload["duration"] = span.duration
            payload["status"] = span.status
        bus.publish(f"span_{phase}", **payload)
    finally:
        _suppress.active = False


def install_forwarding() -> None:
    """Idempotently wire the metrics registry and tracer mutation hooks
    into the bus (module import of telemetry.events does this once).
    The hooks survive registry/tracer resets — they always resolve the
    CURRENT global bus."""
    from . import metrics, tracing

    metrics.set_mutation_listener(_forward_metric)
    tracing.set_span_listener(_forward_span)


# --- global bus ------------------------------------------------------------

_bus: EventBus | None = None
_bus_lock = threading.Lock()


def get_event_bus() -> EventBus:
    # Lock-free fast path: this runs on EVERY metric mutation and span
    # open/close via the forwarding hooks, so the instrumented hot
    # paths must not serialize on a global mutex (module-global reads
    # are atomic; the lock only guards one-time creation).
    global _bus
    bus = _bus
    if bus is not None:
        return bus
    with _bus_lock:
        if _bus is None:
            _bus = EventBus()
        return _bus


def reset_event_bus() -> None:
    """Drop the global bus (tests); forwarding hooks re-resolve."""
    global _bus
    with _bus_lock:
        _bus = None


install_forwarding()
