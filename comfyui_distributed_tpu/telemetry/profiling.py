"""Device-time attribution: the transfer ledger + on-demand profiler
capture.

The ROADMAP's "speed-of-light on real chips" item needs one number the
existing spans cannot produce: of each tile's wall time, how much was
the chip computing versus the host gathering/encoding/shipping around
it? The spans time whole stages; this module splits the device/host
seam inside them.

Two pieces:

- :class:`TransferLedger` — cumulative integer-nanosecond accounting of
  the device↔host boundary, fed by the execution seams on both tiers
  (``GrantSampler``/``TilePipeline`` on the scan tier,
  ``CrossJobExecutor`` on the xjob tier, checkpoint encode in
  ``ops/stepwise.py``): device-execute time (dispatch bracketing on an
  injectable clock; only dispatches of COMPILED programs count —
  eager-stub harness dispatches are host work by construction, so a
  zero-device run reports host-tax 1.0, never a fiction), bytes moved
  each direction, and host time split into ``gather`` (device→host
  readback), ``encode`` (PNG/decode work), and ``ship`` (submit RPCs).
  The roll-up is the **host-tax ratio** ``host_ns / (host_ns +
  device_ns)`` — the fraction of attributable time the host ate. The
  ledger's cumulative block rides the fleet snapshot piggyback (wire
  v3, telemetry/fleet.py) and is mirrored into
  ``cdt_transfer_bytes_total`` / ``cdt_device_execute_seconds`` /
  ``cdt_host_tax_ratio`` at scrape time.

- :class:`ProfilerCapture` — ``jax.profiler.start_trace``/``stop_trace``
  behind a single-flight guard with a duration cap
  (``CDT_PROFILE_MAX_SECONDS``) and bounded on-disk retention under
  ``CDT_PROFILE_DIR`` (``CDT_PROFILE_MAX`` dirs / ``CDT_PROFILE_MAX_MB``
  total, prune-oldest but never the newest). Served by
  ``POST /distributed/profile/start|stop`` + the index route
  (api/profile_routes.py); the incident manager auto-captures a short
  trace alongside a debug bundle when ``CDT_PROFILE_AUTO=1``.

Determinism contract (cdt-lint CDT004 covers this file): all clocks are
injectable and used only for durations, capture ids derive from a
scanned sequence counter (never wall time), and directory listings sort
before use.
"""

from __future__ import annotations

import contextlib
import os
import re
import shutil
import threading
import time
from typing import Any, Callable, Optional

from ..utils import constants
from ..utils.logging import debug_log

_NS = 1_000_000_000

# Transfer directions (metric label vocabulary).
H2D = "h2d"
D2H = "d2h"

# Host-time buckets; stage_span feeds these via STAGE_HOST_BUCKETS.
HOST_BUCKETS = ("gather", "encode", "ship")

# tile.<stage> span names -> the host bucket their wall time charges.
# `readback` is the device→host gather, `encode`/`decode` are pixel
# codec work, `submit` is the ship RPC. `pull`/`blend`/`dispatch` are
# deliberately absent: pull is wait, blend is master canvas math, and
# dispatch is attributed through note_dispatch's device/eager split.
STAGE_HOST_BUCKETS = {
    "readback": "gather",
    "encode": "encode",
    "decode": "encode",
    "submit": "ship",
}


def _to_ns(seconds: float) -> int:
    """Non-negative integer nanoseconds (the PR-15 conservation idiom:
    all arithmetic downstream is integral, so sums are exact)."""
    return max(0, int(round(float(seconds) * _NS)))


def transfer_nbytes(array: Any) -> int:
    """Byte size of one transferred array, 0 when it cannot say.

    Typed PRNG key arrays (extended dtypes) raise NotImplementedError
    on ``.nbytes``; their backing uint32 buffer answers instead. The
    ledger must never turn a dispatch into a crash, so anything else
    unanswerable counts 0 bytes (the transfer's TIME still lands)."""
    try:
        return int(array.nbytes)
    except AttributeError:
        return 0
    except Exception:
        try:
            import jax

            return int(jax.random.key_data(array).nbytes)
        except Exception:
            return 0


class TransferLedger:
    """Cumulative device/host attribution for one process.

    Thread-safe; every count is a non-negative integer (ns or bytes).
    ``clock`` is injectable for the few places the ledger measures
    itself (``timed_sync``); seams that already bracket their own work
    pass ``elapsed_s`` in.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self.clock = clock
        self._lock = threading.Lock()
        self.device_ns = 0
        self.device_dispatches = 0
        # eager (non-compiled) dispatch wall: tracked so the split is
        # auditable, but NEVER counted as device time — a stubbed run
        # has no device, and its host-tax must read 1.0
        self.eager_ns = 0
        self.eager_dispatches = 0
        self.host_ns: dict[str, int] = {b: 0 for b in HOST_BUCKETS}
        self.transfer: dict[str, dict[str, int]] = {
            H2D: {"bytes": 0, "ns": 0, "count": 0},
            D2H: {"bytes": 0, "ns": 0, "count": 0},
        }
        self.tiles = 0
        # scrape-time delta marks for the mirrored counters (the
        # flight-recorder idiom — see instruments.bind_server_collectors)
        self.scrape_mirrored: dict[str, int] = {}

    # -- seams -------------------------------------------------------------

    def note_dispatch(
        self,
        elapsed_s: float,
        *,
        tier: str = "scan",
        role: str = "worker",
        device: bool = True,
    ) -> None:
        """One device dispatch's bracketed wall time. ``device=False``
        (an eager/stub processor — nothing ran on a chip) keeps the
        time out of ``device_ns``."""
        ns = _to_ns(elapsed_s)
        with self._lock:
            if device:
                self.device_ns += ns
                self.device_dispatches += 1
            else:
                self.eager_ns += ns
                self.eager_dispatches += 1
        if device:
            try:
                from .instruments import device_execute_seconds

                device_execute_seconds().observe(
                    float(elapsed_s), role=role, tier=tier
                )
            except Exception:  # noqa: BLE001 - accounting is best effort
                pass

    def note_host(self, bucket: str, elapsed_s: float) -> None:
        """Host-side wall time in one of the gather/encode/ship
        buckets; unknown buckets are ignored (the stage vocabulary can
        grow without version-locking the ledger)."""
        if bucket not in self.host_ns:
            return
        ns = _to_ns(elapsed_s)
        with self._lock:
            self.host_ns[bucket] += ns

    def note_transfer(
        self, direction: str, nbytes: int, elapsed_s: float = 0.0
    ) -> None:
        """Bytes crossing the device↔host boundary (``h2d``/``d2h``)
        plus the transfer's wall time when the caller measured it."""
        entry = self.transfer.get(direction)
        if entry is None:
            return
        with self._lock:
            entry["bytes"] += max(0, int(nbytes))
            entry["ns"] += _to_ns(elapsed_s)
            entry["count"] += 1

    def note_tiles(self, n: int = 1) -> None:
        with self._lock:
            self.tiles += int(n)

    @contextlib.contextmanager
    def timed_sync(self, *, bucket: str = "gather"):
        """Bracket a host-side materialisation (a ``device_get`` /
        ``block_until_ready`` sync point) on the ledger's clock; the
        elapsed wall charges ``bucket``."""
        started = self.clock()
        try:
            yield
        finally:
            self.note_host(bucket, self.clock() - started)

    # -- roll-ups ----------------------------------------------------------

    def host_total_ns(self) -> int:
        with self._lock:
            return sum(self.host_ns.values())

    def host_tax(self) -> float:
        """``host_ns / (host_ns + device_ns)``. A run that never
        touched a device (device_ns == 0 — eager stubs, CPU fallbacks
        that recorded nothing) reports 1.0: all attributable time was
        host time. Never NaN."""
        with self._lock:
            host = sum(self.host_ns.values())
            device = self.device_ns
        if device <= 0:
            return 1.0
        return host / float(host + device)

    def snapshot(self, role: str = "worker") -> dict[str, Any]:
        """The cumulative wire block (fleet snapshot v3 piggyback /
        bench datum stamp). All integers except the derived ratio."""
        with self._lock:
            return {
                "role": role,
                "device_ns": self.device_ns,
                "device_dispatches": self.device_dispatches,
                "eager_ns": self.eager_ns,
                "eager_dispatches": self.eager_dispatches,
                "host_ns": dict(self.host_ns),
                "transfer": {
                    d: dict(v) for d, v in self.transfer.items()
                },
                "tiles": self.tiles,
                "host_tax": self._host_tax_locked(),
            }

    def _host_tax_locked(self) -> float:
        host = sum(self.host_ns.values())
        if self.device_ns <= 0:
            return 1.0
        return host / float(host + self.device_ns)

    def totals(self, role: str = "worker") -> dict[str, Any]:
        snap = self.snapshot(role)
        snap["host_total_ns"] = sum(snap["host_ns"].values())
        return snap


def merge_profiling_blocks(blocks: list) -> dict[str, Any]:
    """Sum snapshot() wire blocks into one fleet-level profiling
    roll-up (telemetry/fleet.py rollup). Malformed blocks contribute
    nothing; the derived host-tax follows the same zero-device rule."""
    device_ns = 0
    host_ns = {b: 0 for b in HOST_BUCKETS}
    transfer = {
        H2D: {"bytes": 0, "ns": 0, "count": 0},
        D2H: {"bytes": 0, "ns": 0, "count": 0},
    }
    dispatches = 0
    tiles = 0
    for block in blocks:
        if not isinstance(block, dict):
            continue
        try:
            device_ns += int(block.get("device_ns") or 0)
            dispatches += int(block.get("device_dispatches") or 0)
            tiles += int(block.get("tiles") or 0)
            for bucket in HOST_BUCKETS:
                host_ns[bucket] += int(
                    (block.get("host_ns") or {}).get(bucket) or 0
                )
            for direction in (H2D, D2H):
                src = (block.get("transfer") or {}).get(direction) or {}
                for field in ("bytes", "ns", "count"):
                    transfer[direction][field] += int(src.get(field) or 0)
        except (TypeError, ValueError):
            continue
    host_total = sum(host_ns.values())
    tax = 1.0 if device_ns <= 0 else host_total / float(host_total + device_ns)
    return {
        "device_ns": device_ns,
        "device_dispatches": dispatches,
        "host_ns": host_ns,
        "host_total_ns": host_total,
        "transfer": transfer,
        "tiles": tiles,
        "host_tax": tax,
    }


# --- on-demand jax.profiler capture ----------------------------------------

_CAPTURE_DIR_RE = re.compile(r"trace-(\d{4,})(?:-[a-z0-9_]+)?")
_TAG_SAFE_RE = re.compile(r"[^a-z0-9_]+")


class ProfilerCapture:
    """Single-flight on-demand device trace capture with bounded
    retention. One capture at a time; a start while one is active
    answers ``busy`` (never a second ``start_trace`` — TensorBoard's
    tracer is process-global). Captures auto-stop at their duration cap
    via a daemon timer, so an operator who never POSTs /stop cannot
    leave the profiler running."""

    def __init__(
        self,
        directory: str,
        *,
        clock: Callable[[], float] = time.monotonic,
        max_seconds: Optional[float] = None,
        max_captures: Optional[int] = None,
        max_bytes: Optional[float] = None,
    ) -> None:
        self.directory = directory
        self.clock = clock
        self.max_seconds = (
            float(max_seconds)
            if max_seconds is not None
            else constants.PROFILE_MAX_SECONDS
        )
        self.max_captures = (
            int(max_captures)
            if max_captures is not None
            else constants.PROFILE_MAX_CAPTURES
        )
        self.max_bytes = (
            int(max_bytes)
            if max_bytes is not None
            else int(constants.PROFILE_MAX_MB * 1024 * 1024)
        )
        self._lock = threading.Lock()
        self._active: Optional[dict[str, Any]] = None
        self._timer: Optional[threading.Timer] = None
        self._seq = self._scan_seq()
        self.counters = {
            "started": 0, "stopped": 0, "busy": 0, "errors": 0,
            "auto_stopped": 0,
        }
        # scrape-time delta marks for the mirrored counters (the
        # flight-recorder idiom — see instruments.bind_server_collectors)
        self.scrape_mirrored: dict[str, int] = {}

    # -- capture lifecycle -------------------------------------------------

    def start(
        self, duration_s: Optional[float] = None, tag: str = "manual"
    ) -> dict[str, Any]:
        """Begin a capture; returns the disposition dict the route
        serves verbatim. Duration is clamped to the cap; the auto-stop
        timer fires even if nobody ever calls stop()."""
        duration = self.max_seconds
        if duration_s is not None:
            try:
                duration = float(duration_s)
            except (TypeError, ValueError):
                return {"started": False, "reason": "bad_duration"}
        duration = max(0.1, min(duration, self.max_seconds))
        tag_safe = _TAG_SAFE_RE.sub("_", str(tag).lower())[:32] or "manual"
        with self._lock:
            if self._active is not None:
                self.counters["busy"] += 1
                return {
                    "started": False,
                    "reason": "busy",
                    "active": self._active["id"],
                }
            self._seq += 1
            capture_id = f"trace-{self._seq:04d}-{tag_safe}"
            path = os.path.join(self.directory, capture_id)
            try:
                os.makedirs(path, exist_ok=True)
                import jax

                jax.profiler.start_trace(path)
            except Exception as exc:  # noqa: BLE001 - degrade, never 500
                self.counters["errors"] += 1
                with contextlib.suppress(OSError):
                    os.rmdir(path)
                return {"started": False, "reason": f"{type(exc).__name__}: {exc}"}
            self._active = {
                "id": capture_id,
                "path": path,
                "tag": tag_safe,
                "duration_s": duration,
                "started_at": self.clock(),
            }
            self.counters["started"] += 1
            timer = threading.Timer(duration, self._auto_stop, args=(capture_id,))
            timer.daemon = True
            timer.start()
            self._timer = timer
            return {
                "started": True,
                "id": capture_id,
                "path": path,
                "duration_s": duration,
            }

    def stop(self) -> dict[str, Any]:
        """End the active capture (idempotent: no active capture
        answers ``stopped: False``); prunes retention afterwards."""
        with self._lock:
            active = self._active
            self._active = None
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        if active is None:
            return {"stopped": False, "reason": "not_running"}
        elapsed = self.clock() - active["started_at"]
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 - report, don't raise
            with self._lock:
                self.counters["errors"] += 1
            return {
                "stopped": False,
                "id": active["id"],
                "reason": f"{type(exc).__name__}: {exc}",
            }
        with self._lock:
            self.counters["stopped"] += 1
        self._prune()
        return {
            "stopped": True,
            "id": active["id"],
            "path": active["path"],
            "elapsed_s": round(elapsed, 6),
            "bytes": _dir_bytes(active["path"]),
        }

    def _auto_stop(self, capture_id: str) -> None:
        """Timer callback: stop only if the SAME capture is still
        active (a manual stop + fresh start must not be killed by the
        old capture's timer)."""
        with self._lock:
            active = self._active
            if active is None or active["id"] != capture_id:
                return
            self.counters["auto_stopped"] += 1
        result = self.stop()
        debug_log(f"profiler capture {capture_id} auto-stopped: {result}")

    # -- retention / listing -----------------------------------------------

    def _scan_seq(self) -> int:
        """Resume the capture sequence past existing dirs so ids never
        collide across restarts (deterministic: derived from the sorted
        listing, not a clock)."""
        seq = 0
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return 0
        for name in names:
            match = _CAPTURE_DIR_RE.fullmatch(name)
            if match:
                seq = max(seq, int(match.group(1)))
        return seq

    def _capture_dirs(self) -> list[tuple[str, str]]:
        """(name, path) pairs oldest-first — zero-padded sequence ids
        make lexical order capture order."""
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        return [
            (name, os.path.join(self.directory, name))
            for name in names
            if _CAPTURE_DIR_RE.fullmatch(name)
            and os.path.isdir(os.path.join(self.directory, name))
        ]

    def _prune(self) -> None:
        dirs = self._capture_dirs()
        with self._lock:
            active_path = self._active["path"] if self._active else None
        sizes = {path: _dir_bytes(path) for _name, path in dirs}
        total = sum(sizes.values())
        while len(dirs) > 1 and (
            len(dirs) > self.max_captures
            or (self.max_bytes > 0 and total > self.max_bytes)
        ):
            _name, oldest = dirs.pop(0)
            if oldest == active_path:
                continue
            total -= sizes.get(oldest, 0)
            shutil.rmtree(oldest, ignore_errors=True)

    def captures(self) -> list[dict[str, Any]]:
        """Newest-first index of retained trace dirs."""
        out = []
        for name, path in reversed(self._capture_dirs()):
            out.append({"id": name, "bytes": _dir_bytes(path)})
        return out

    def status(self) -> dict[str, Any]:
        with self._lock:
            active = dict(self._active) if self._active else None
            counters = dict(self.counters)
        if active is not None:
            active["elapsed_s"] = round(
                self.clock() - active.pop("started_at"), 6
            )
        return {
            "directory": self.directory,
            "active": active,
            "max_seconds": self.max_seconds,
            "max_captures": self.max_captures,
            "max_bytes": self.max_bytes,
            "counters": counters,
        }


def _dir_bytes(path: str) -> int:
    total = 0
    try:
        for root, _dirs, files in os.walk(path):
            for name in files:
                with contextlib.suppress(OSError):
                    total += os.path.getsize(os.path.join(root, name))
    except OSError:
        return total
    return total


# --- process-global accessors (telemetry/usage.py's meter idiom) -----------

_ledger: TransferLedger | None = None
_ledger_lock = threading.Lock()


def get_transfer_ledger() -> TransferLedger:
    """The process-global ledger (created on first use). Callers gate
    on ``constants.PROFILING_ENABLED`` — the ledger itself is always
    constructible so tests can meter with the knob off."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = TransferLedger()
        return _ledger


def peek_transfer_ledger() -> TransferLedger | None:
    """The global ledger if one exists — never creates (scrape-time
    mirrors must not allocate state on an idle process)."""
    with _ledger_lock:
        return _ledger


def set_transfer_ledger(
    ledger: TransferLedger | None,
) -> TransferLedger | None:
    """Install a specific ledger (chaos/bench harnesses); returns the
    previous one so callers can restore it."""
    global _ledger
    with _ledger_lock:
        prev = _ledger
        _ledger = ledger
        return prev


def _reset_transfer_ledger_for_tests() -> None:
    set_transfer_ledger(None)


def ledger_if_enabled() -> TransferLedger | None:
    """The global ledger when CDT_PROFILING is on, else None — the one
    call hot seams make (a disabled plane costs one attribute read and
    a None check)."""
    if not constants.PROFILING_ENABLED:
        return None
    return get_transfer_ledger()


_capture: ProfilerCapture | None = None
_capture_lock = threading.Lock()


def get_profiler_capture() -> ProfilerCapture | None:
    """The process-global capture manager, or None when
    CDT_PROFILE_DIR is unset (the incident-dir idiom: no directory, no
    capture plane). Constructed lazily on first enabled call."""
    global _capture
    with _capture_lock:
        if _capture is not None:
            return _capture
        directory = constants.profile_dir_from_env()
        if not directory:
            return None
        os.makedirs(directory, exist_ok=True)
        _capture = ProfilerCapture(directory)
        return _capture


def set_profiler_capture(
    capture: ProfilerCapture | None,
) -> ProfilerCapture | None:
    global _capture
    with _capture_lock:
        prev = _capture
        _capture = capture
        return prev


def _reset_profiler_capture_for_tests() -> None:
    set_profiler_capture(None)
