"""Per-worker health state machine + circuit breaker.

States and transitions::

            consecutive failures            cooldown elapsed
    HEALTHY ---------> SUSPECT ---------> QUARANTINED ---------> PROBING
       ^  ^              |    (threshold)      ^                  |   |
       |  '--success-----'                     '----probe fails---'   |
       |                                                              |
       '-------------------- RECOVERED <--------- probe succeeds -----'
                 (next success)

- HEALTHY / SUSPECT / RECOVERED workers are dispatchable.
- QUARANTINED workers receive NOTHING until the cooldown elapses;
  `try_half_open` then admits exactly one probe (state PROBING). The
  probe is the existing `/prompt` busy probe — a successful probe
  re-admits the worker (RECOVERED), a failed one re-opens the circuit
  with a fresh cooldown.
- Transition listeners fire outside the registry lock; the server
  binds one that requeues a quarantined worker's in-flight tiles
  (see `resilience.bind_quarantine_requeue`).

Thresholds come from `CDT_CIRCUIT_SUSPECT_AFTER`,
`CDT_CIRCUIT_FAILURES`, and `CDT_CIRCUIT_COOLDOWN` (see
utils/constants.py); the clock is injectable for deterministic tests.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from typing import Callable, Optional

from ..utils import constants
from ..utils.logging import debug_log, log


class WorkerState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    QUARANTINED = "quarantined"
    PROBING = "probing"
    RECOVERED = "recovered"


# States from which a worker may receive prompts/tiles.
_DISPATCHABLE = frozenset(
    {WorkerState.HEALTHY, WorkerState.SUSPECT, WorkerState.RECOVERED}
)

TransitionListener = Callable[[str, WorkerState, WorkerState], None]


@dataclasses.dataclass
class WorkerHealth:
    worker_id: str
    state: WorkerState = WorkerState.HEALTHY
    consecutive_failures: int = 0
    total_failures: int = 0
    total_successes: int = 0
    last_failure: Optional[float] = None
    last_success: Optional[float] = None
    quarantined_at: Optional[float] = None
    probing_since: Optional[float] = None


class HealthRegistry:
    """Thread-safe circuit breaker over a set of worker ids.

    Shared between event loops and compute threads (dispatch runs on
    the server loop, elastic masters on executor threads), hence a
    `threading.Lock` rather than an asyncio one.
    """

    def __init__(
        self,
        failure_threshold: int | None = None,
        suspect_threshold: int | None = None,
        cooldown_seconds: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.failure_threshold = (
            failure_threshold
            if failure_threshold is not None
            else constants.CIRCUIT_FAILURE_THRESHOLD
        )
        self.suspect_threshold = (
            suspect_threshold
            if suspect_threshold is not None
            else constants.CIRCUIT_SUSPECT_THRESHOLD
        )
        self.cooldown_seconds = (
            cooldown_seconds
            if cooldown_seconds is not None
            else constants.CIRCUIT_COOLDOWN_SECONDS
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._workers: dict[str, WorkerHealth] = {}
        self._listeners: list[TransitionListener] = []
        # Removal seam (distinct from transition listeners): called
        # (outside the lock) with every worker id `reset` drops, so
        # per-worker state keyed elsewhere — the fleet registry's
        # retained series — departs with the breaker entry.
        self.on_forget: Callable[[str], None] | None = None

    # --- listeners -------------------------------------------------------

    def add_listener(self, listener: TransitionListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener: TransitionListener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def _fire(self, worker_id: str, old: WorkerState, new: WorkerState) -> None:
        """Call listeners OUTSIDE the lock; listener errors are logged,
        never propagated into the transport path."""
        if old is new:
            return
        from ..telemetry import instruments
        from ..telemetry.events import get_event_bus

        instruments.breaker_transitions_total().inc(
            worker_id=worker_id, from_state=old.value, to_state=new.value
        )
        # Live stream: health transitions are the events the control
        # panel (and the watchdog's consumers) care about most.
        get_event_bus().publish(
            "health_transition",
            worker_id=worker_id,
            from_state=old.value,
            to_state=new.value,
        )
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(worker_id, old, new)
            except Exception as exc:  # noqa: BLE001 - observability only
                debug_log(f"health listener failed for {worker_id}: {exc}")

    # --- state queries ---------------------------------------------------

    def _ensure(self, worker_id: str) -> WorkerHealth:
        health = self._workers.get(worker_id)
        if health is None:
            health = WorkerHealth(worker_id=worker_id)
            self._workers[worker_id] = health
        return health

    def state(self, worker_id: str) -> WorkerState:
        with self._lock:
            health = self._workers.get(worker_id)
            return health.state if health else WorkerState.HEALTHY

    def allow(self, worker_id: str) -> bool:
        """May this worker receive prompts/tiles right now? (PROBING is
        reserved for the single half-open probe, so it's not
        dispatchable either.)"""
        return self.state(worker_id) in _DISPATCHABLE

    def quarantined(self) -> list[str]:
        with self._lock:
            return sorted(
                wid
                for wid, h in self._workers.items()
                if h.state in (WorkerState.QUARANTINED, WorkerState.PROBING)
            )

    def snapshot(self) -> dict[str, dict]:
        """Observability view (state endpoints / logs)."""
        with self._lock:
            return {
                wid: {
                    "state": h.state.value,
                    "consecutive_failures": h.consecutive_failures,
                    "total_failures": h.total_failures,
                    "total_successes": h.total_successes,
                    "quarantined_at": h.quarantined_at,
                }
                for wid, h in self._workers.items()
            }

    # --- transitions -----------------------------------------------------

    def record_success(self, worker_id: str) -> WorkerState:
        with self._lock:
            health = self._ensure(worker_id)
            old = health.state
            health.consecutive_failures = 0
            health.total_successes += 1
            health.last_success = self._clock()
            if old in (WorkerState.PROBING, WorkerState.QUARANTINED):
                # half-open probe answered: circuit closes
                health.state = WorkerState.RECOVERED
                health.quarantined_at = None
            else:
                health.state = WorkerState.HEALTHY
            health.probing_since = None
            new = health.state
        if old in (WorkerState.PROBING, WorkerState.QUARANTINED):
            log(f"worker {worker_id} recovered; circuit closed")
        self._fire(worker_id, old, new)
        return new

    def record_failure(self, worker_id: str) -> WorkerState:
        with self._lock:
            health = self._ensure(worker_id)
            old = health.state
            health.consecutive_failures += 1
            health.total_failures += 1
            health.last_failure = self._clock()
            if old is WorkerState.PROBING:
                # failed half-open probe: re-open with a fresh cooldown
                health.state = WorkerState.QUARANTINED
                health.quarantined_at = self._clock()
                health.probing_since = None
            elif health.consecutive_failures >= self.failure_threshold:
                health.state = WorkerState.QUARANTINED
                if health.quarantined_at is None:
                    health.quarantined_at = self._clock()
            elif health.consecutive_failures >= self.suspect_threshold:
                if old is not WorkerState.QUARANTINED:
                    health.state = WorkerState.SUSPECT
            new = health.state
            failures = health.consecutive_failures
        if new is WorkerState.QUARANTINED and old is not WorkerState.QUARANTINED:
            log(
                f"worker {worker_id} quarantined after {failures} consecutive "
                f"failure(s); circuit open for {self.cooldown_seconds:.0f}s"
            )
        self._fire(worker_id, old, new)
        return new

    def mark_suspect(self, worker_id: str) -> WorkerState:
        """Externally-observed degradation (the watchdog's straggler
        verdict): demote a dispatchable worker to SUSPECT without
        touching its failure counters — latency is a symptom, not a
        transport failure, so it must not accumulate toward quarantine.
        QUARANTINED/PROBING workers are left alone (the breaker already
        acted); an already-SUSPECT worker is a no-op."""
        with self._lock:
            health = self._ensure(worker_id)
            old = health.state
            if old in (WorkerState.HEALTHY, WorkerState.RECOVERED):
                health.state = WorkerState.SUSPECT
            new = health.state
        if new is WorkerState.SUSPECT and old is not WorkerState.SUSPECT:
            log(f"worker {worker_id} marked suspect (watchdog straggler)")
        self._fire(worker_id, old, new)
        return new

    def pardon(self, worker_id: str) -> WorkerState:
        """Exonerate a worker whose failures traced to a poison tile
        (the payload was the problem, not the worker): clear the
        consecutive-failure streak and restore a SUSPECT / QUARANTINED
        / PROBING worker to HEALTHY immediately — no cooldown, no
        half-open probe. Totals are kept (history, not guilt). The
        JobStore's poison-quarantine path drives this through the
        server's ``poison_pardon`` hook, so one bad payload cannot
        cascade breaker quarantines across the fleet."""
        with self._lock:
            health = self._workers.get(worker_id)
            if health is None:
                return WorkerState.HEALTHY
            old = health.state
            health.consecutive_failures = 0
            health.quarantined_at = None
            health.probing_since = None
            health.state = WorkerState.HEALTHY
            new = health.state
        if old is not new:
            log(f"worker {worker_id} pardoned (poison tile); circuit closed")
        self._fire(worker_id, old, new)
        return new

    def try_half_open(self, worker_id: str) -> bool:
        """If quarantined and cooled down, move to PROBING and return
        True — the caller owns the single half-open probe. At most one
        caller wins until the probe outcome is recorded, or until the
        probe lease (one cooldown period) expires — a prober cancelled
        between winning the slot and recording the outcome must not
        leave the worker stuck in PROBING forever."""
        now = self._clock()
        with self._lock:
            health = self._workers.get(worker_id)
            if health is None:
                return False
            if health.state is WorkerState.PROBING:
                if (
                    health.probing_since is None
                    or now - health.probing_since < self.cooldown_seconds
                ):
                    return False
                # stale probe lease: reclaim the slot
                health.probing_since = now
                debug_log(f"worker {worker_id}: stale probe lease reclaimed")
                return True
            if health.state is not WorkerState.QUARANTINED:
                return False
            if (
                health.quarantined_at is not None
                and now - health.quarantined_at < self.cooldown_seconds
            ):
                return False
            old = health.state
            health.state = WorkerState.PROBING
            health.probing_since = now
        debug_log(f"worker {worker_id} half-open: probing")
        self._fire(worker_id, old, WorkerState.PROBING)
        return True

    def reset(self, worker_id: str | None = None) -> None:
        with self._lock:
            if worker_id is None:
                forgotten = list(self._workers)
                self._workers.clear()
            else:
                forgotten = (
                    [worker_id] if self._workers.pop(worker_id, None) else []
                )
        hook = self.on_forget
        if hook is None:
            return
        for wid in forgotten:
            try:
                hook(wid)
            except Exception as exc:  # noqa: BLE001 - advisory fan-out
                debug_log(f"health on_forget({wid}) failed: {exc}")


# --- global registry ------------------------------------------------------

_registry: HealthRegistry | None = None
_registry_lock = threading.Lock()


def get_health_registry() -> HealthRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = HealthRegistry()
        return _registry


def reset_health_registry() -> None:
    """Drop the global registry (tests)."""
    global _registry
    with _registry_lock:
        _registry = None
