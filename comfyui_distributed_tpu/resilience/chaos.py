"""In-process chaos harness: the elastic USDU master/worker loop under
a scripted fault plan, CPU-only and hermetic (no sockets, no model).

The harness runs `run_master_elastic` against worker THREADS that pull
from the same JobStore — the production protocol shape (the reference's
fake-comms test pattern) — while a seeded `FaultInjector` kills
workers mid-tile, injects latency, or drops heartbeats on a scripted
schedule. The assertion chaos tests make is strong: the blended output
of a faulted run is BIT-IDENTICAL to the fault-free run.

Two properties make that possible:

1. determinism of the work itself — per-tile noise keys fold the
   global tile index, so a requeued tile reproduces exactly no matter
   which participant re-runs it. The harness stubs the diffusion
   processor with a cheap deterministic op whose outputs are exact
   multiples of 1/255, so the PNG uint8 envelope worker tiles travel
   in is lossless and master-local vs worker-computed tiles are
   bit-equal;
2. determinism of the blend — sequential feathered compositing is
   order-dependent where tiles overlap, and arrival order is a race.
   The harness enables CDT_DETERMINISTIC_BLEND (sorted-order deferred
   compositing, ops/tiles.DeterministicHostCanvas) so the canvas is
   insensitive to who finished first.

Fault-plan op names exposed by the harness (see faults.py grammar):

    chaos:<worker>:pull     before a worker's pull RPC
    chaos:<worker>:pulled   after a successful pull (crash here =
                            crash-after-pull: tile assigned, never
                            submitted — the requeue path must cover it)
    chaos:<worker>:submit   before a worker's submit RPC
    store:heartbeat:<id>    JobStore heartbeat recording (drop = the
                            master never sees the beat)
    store:pull:<id> / store:submit:<id>   JobStore RPC surfaces

`run_chaos_master_crash` extends the harness to the MASTER's own
death: phase 1 runs the elastic loop with the write-ahead journal
attached and a fault plan that kills the master mid-job (after a pull,
or after a partial submit — `crash@store:pull:master#k` /
`crash@store:submit:master#k`); phase 2 simulates the restarted
process — a fresh JobStore recovered from the journal directory — and
drains the job to completion. The acceptance assertion is the same
bit-identical canvas the worker-crash scenarios make.

Used by tests/test_chaos_usdu.py (tier-1, `-m chaos` selectable),
scripts/chaos_smoke.py, and scripts/durability_soak.py.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
import types
from typing import Any, Optional, Sequence
from unittest import mock

import numpy as np

from ..telemetry import Tracer, get_tracer, set_tracer
from ..utils.logging import debug_log
from .faults import FaultAction, FaultInjected, FaultInjector


class FakeClock:
    """Deterministic monotonic clock for trace timestamps: every call
    advances by a fixed step, so span durations in a chaos trace are a
    pure function of the span SEQUENCE, not wall time."""

    def __init__(self, step: float = 0.001):
        self._step = step
        self._now = 0.0
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            self._now += self._step
            return self._now


@dataclasses.dataclass
class ChaosResult:
    """Output image + what the injector actually did (tests assert the
    scripted faults FIRED, so a passing run can't be vacuous)."""

    output: np.ndarray
    fired: list[FaultAction]
    crashed_workers: list[str]
    trace_id: str = ""
    # watchdog verdicts (populated when run_chaos_usdu(watchdog=...)):
    stragglers: list[str] = dataclasses.field(default_factory=list)
    stalls: list[str] = dataclasses.field(default_factory=list)
    speculated: dict[str, list[int]] = dataclasses.field(default_factory=dict)
    health: dict[str, dict] = dataclasses.field(default_factory=dict)
    # accepted (first-wins) submissions per participant, master included
    tiles_by_worker: dict[str, int] = dataclasses.field(default_factory=dict)
    # placement snapshot (populated when run_chaos_usdu(placement=...))
    placement: dict = dataclasses.field(default_factory=dict)
    # SLO alert transitions in order (populated when
    # run_chaos_usdu(slo=...)): each entry is the engine's transition
    # dict ({"type": "alert_fired"|"alert_resolved", "slo", "ts", ...})
    alerts: list[dict] = dataclasses.field(default_factory=list)
    # whether any alert was still open when the harness gave up waiting
    slo_active: bool = False
    # incident bundles captured during the run (populated when
    # run_chaos_usdu(incidents=...)): the manager's newest-first
    # listing, plus the directory for offline analysis
    incidents: list[dict] = dataclasses.field(default_factory=list)
    incident_dir: str = ""
    # debounce proof: the disposition of a simulated second identical
    # alert inside the debounce window ("debounced" when a capture
    # happened; "" when no alert fired)
    incident_retrigger: str = ""
    # chip-time attribution captured on a run-local UsageMeter:
    # {"rollup": per-tenant/lane/job view, "totals": exact ns identity}
    usage: dict = dataclasses.field(default_factory=dict)
    # tile-result-cache counters for the run (populated when
    # run_chaos_usdu(cache=...)): TileResultCache.stats() after the run
    cache: dict = dataclasses.field(default_factory=dict)

    def fired_kinds(self) -> set[str]:
        return {a.kind for a in self.fired}


def _stub_process(params, tile, key, pos, neg, yx):
    """Deterministic stand-in for the jitted VAE→sample→VAE tile
    processor: tile content + keyed noise, snapped to the uint8 grid
    (multiples of 1/255) so the PNG envelope is lossless and
    master-local results are bit-equal to worker results."""
    import jax
    import jax.numpy as jnp

    noisy = jnp.clip(tile + 0.05 * jax.random.normal(key, tile.shape), 0.0, 1.0)
    return jnp.round(noisy * 255.0) / 255.0


@contextlib.contextmanager
def _ensure_server_loop():
    """All JobStore asyncio state must live on ONE loop; start a
    control-plane loop thread if the process doesn't have one."""
    from ..utils.async_helpers import ServerLoopThread, get_server_loop

    existing = get_server_loop()
    if existing is not None and existing.is_running():
        yield
        return
    thread = ServerLoopThread(name="cdt-chaos-loop")
    thread.start()
    try:
        yield
    finally:
        thread.stop()


def run_chaos_usdu(
    seed: int = 0,
    fault_plan: Optional[str] = None,
    *,
    workers: Sequence[str] = ("w1", "w2"),
    image_hw: tuple[int, int] = (64, 64),
    tile: int = 64,
    padding: int = 16,
    upscale_by: float = 2.0,
    worker_timeout: float = 0.6,
    job_id: str = "chaos-job",
    trace_jsonl: Optional[str] = None,
    watchdog: Optional[dict] = None,
    placement: Optional[dict] = None,
    tile_batch: int = 1,
    pipeline: bool = True,
    prefetch: bool = False,
    journal_dir: Optional[str] = None,
    mesh_devices: int = 0,
    slo: Optional[dict] = None,
    incidents: Optional[dict] = None,
    cache=None,
    device_canvas: bool = False,
) -> ChaosResult:
    """One in-process elastic USDU run under `fault_plan`; returns the
    blended [B, H, W, C] image plus the faults that actually fired.
    `fault_plan=None` is the fault-free reference run.

    The whole run executes under a fake-clock tracer (one span tree,
    trace id `exec_chaos_<seed>`): master and worker tile stages are
    recorded deterministically. `trace_jsonl` exports the spans to
    that path for scripts/perf_report.py.

    Worker threads start BEFORE the master and park on the JobStore's
    creation signal (`wait_for_tile_job`), so they contend for tiles
    from the first instant of the job — plans that slow the master's
    pulls (`latency(..)@store:pull:master`) make worker participation
    deterministic instead of a race the master usually wins.

    `watchdog`: pass a dict of Watchdog overrides (may be empty) to run
    a live straggler/stall monitor over the harness store — fed by the
    store's latency sink, pushing stragglers into a PRIVATE
    HealthRegistry and speculating stalled in-flight tiles through the
    real requeue path. Verdicts land in ChaosResult.stragglers /
    .stalls / .speculated / .health. The harness defaults are tight
    (50 ms interval, 300 ms stall window, min_samples=1) so sub-second
    chaos plans trigger real detections.

    `placement`: pass a dict of PlacementPolicy overrides (may be
    empty) to run cost-aware weighted placement over the harness store
    — worker threads then pull speed-sized BATCHES through
    `JobStore.pull_tasks`, the policy's EWMA is fed by the same latency
    sink, and tail pulls from slow/suspect workers are trimmed. The
    harness defaults (min_samples=1, base_batch=2, max_batch=4,
    tail_tiles=1) make a sub-second run develop real weights. Accepted
    submissions per participant land in ChaosResult.tiles_by_worker and
    the policy snapshot in ChaosResult.placement — chaos tests assert a
    straggler receives measurably fewer tiles while the canvas stays
    bit-identical (placement must change WHO, never WHAT).

    `mesh_devices`: N > 1 runs master AND worker grant samplers on an
    N-participant local device mesh (parallel/mesh.build_mesh over the
    first N host devices — the tier-1 suite forces virtual CPU devices,
    conftest.py): batches shard across the data axis with NamedSharding
    and gather through host_collect, exactly the production multi-chip
    path. The mesh-parity acceptance asserts the canvas is
    bit-identical to the 1-device run, square and ragged grids alike.

    `slo`: pass a dict of overrides (may be empty) to run a live
    burn-rate SLO engine (telemetry/slo.py) over the harness store's
    latency stream — one `tile_latency` spec with harness-tight
    windows (threshold 0.15 s, one (1 s, 0.25 s) burn rule, objective
    0.9, resolve hold 50 ms) so a sub-second straggler plan fires a
    real alert. The engine steps on every latency sample; after the
    run the harness keeps stepping (bounded) until the alert resolves
    — no new bad samples arrive once the straggler is quarantined out
    of the tail, so the short window drains and the alert closes.
    Transitions land in ChaosResult.alerts (and the alert events ride
    the process bus like production). Keys: ``threshold_s``,
    ``objective``, ``long_s``, ``short_s``, ``burn_threshold``,
    ``resolve_hold_s``, ``min_events``.

    `incidents`: pass ``{"dir": <path>, ...overrides}`` to run a live
    `IncidentManager` (telemetry/incidents.py) over the run — the
    always-on flight recorder taps the bus, a harness `FleetRegistry`
    retains per-worker tile-rate series from the latency stream, and
    the manager's bus tap turns the SLO engine's `alert_fired` into an
    automatic debug-bundle capture (the production loop, end to end,
    in one process). Overrides beyond ``dir`` are IncidentManager
    kwargs (``debounce_s``, ``min_interval_s``, ``max_bundles``,
    ``max_bytes``); harness defaults: debounce 60 s, no global rate
    limit, 8 retained bundles. Captured bundles land newest-first in
    ChaosResult.incidents (+ .incident_dir) — the chaos acceptance
    asserts the bundle holds the firing evaluation AND the straggler's
    fleet series while the canvas stays bit-identical.

    `cache`: pass a TileResultCache to install it run-locally (the
    process global is swapped in and restored like the usage meter) —
    the master probes it at grant time and settles hits straight into
    the job, so a warm re-run with the same cache serves tiles without
    dispatching them to workers. Counters land in ChaosResult.cache
    (TileResultCache.stats() after the run); the cache acceptance
    asserts warm output is BIT-IDENTICAL to the cold reference, under
    faults included — a cache may only change WHO computes a tile
    (nobody), never WHAT lands on the canvas.

    `tile_batch`/`pipeline`/`prefetch`: the batched-pipelined data path
    (graph/tile_pipeline.py). Worker threads ALWAYS run the production
    TilePipeline (this harness is its chaos coverage); `pipeline=False`
    forces the synchronous staging fallback, `tile_batch>1` runs grants
    through the bucketed vmapped K-tile processor on master and workers
    alike (CDT_TILE_BATCH is patched for the master loop), and
    `prefetch=True` enables the one-grant-ahead pull stage. Defaults
    keep claim timing deterministic (no prefetch) so scripted fault
    schedules fire on the same tiles every run. All combinations must
    produce the bit-identical canvas — that is the point.

    `device_canvas`: route the master's blend through the on-device
    DeviceCanvas (CDT_DEVICE_CANVAS=1, the device-resident hot path's
    one-flush compositing) instead of the deterministic host canvas.
    DeviceCanvas ≡ DeterministicHostCanvas is a BIT-IDENTITY contract,
    so every scenario must match the host baseline exactly — under
    crashes, speculation, and batched grants included.
    """
    import jax
    import jax.numpy as jnp

    from ..graph import ExecutionContext
    from ..graph import usdu_elastic as elastic
    from ..jobs import JobStore
    from ..ops import upscale as upscale_ops
    from ..utils import config as config_mod
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop
    from ..utils.exceptions import JobQueueError

    mesh = None
    if mesh_devices and int(mesh_devices) > 1:
        from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, build_mesh

        local = jax.local_devices()
        if len(local) < int(mesh_devices):
            raise ValueError(
                f"mesh_devices={mesh_devices} but only {len(local)} local "
                "device(s); force more with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N"
            )
        mesh = build_mesh(
            {DATA_AXIS: int(mesh_devices), MODEL_AXIS: 1},
            devices=local[: int(mesh_devices)],
        )

    injector = FaultInjector(fault_plan) if fault_plan else None
    store = JobStore(fault_injector=injector)
    durability = None
    if journal_dir:
        # journaled runs (durability soak / overhead A/B): the standard
        # scenarios with the write-ahead seam attached
        from ..durability import DurabilityManager

        durability = DurabilityManager(journal_dir)
        store.journal_sink = durability.record
    wd = None
    wd_health = None
    latency_sinks = []
    if watchdog is not None:
        from ..telemetry.watchdog import Watchdog
        from .health import HealthRegistry

        wd_health = HealthRegistry()
        wd_kwargs = dict(
            interval=0.05, stall_seconds=0.3, min_samples=1,
            straggler_factor=4.0,
        )
        wd_kwargs.update(watchdog)
        wd = Watchdog(store=store, health=wd_health, **wd_kwargs)
        latency_sinks.append(wd.record_latency)
    slo_engine = None
    if slo is not None:
        from ..telemetry.slo import BurnRule, SLOEngine, SLOSpec
        from ..telemetry.timeseries import SeriesStore

        slo_kwargs = dict(
            threshold_s=0.15, objective=0.9, long_s=1.0, short_s=0.25,
            burn_threshold=1.0, resolve_hold_s=0.05, min_events=2,
        )
        slo_kwargs.update(slo)
        spec = SLOSpec(
            name="tile_latency",
            description="chaos-harness tile pull->submit latency",
            objective=slo_kwargs["objective"],
            kind="latency",
            threshold_s=slo_kwargs["threshold_s"],
            rules=(
                BurnRule(
                    long_s=slo_kwargs["long_s"],
                    short_s=slo_kwargs["short_s"],
                    burn_threshold=slo_kwargs["burn_threshold"],
                ),
            ),
            resolve_hold_s=slo_kwargs["resolve_hold_s"],
            min_events=slo_kwargs["min_events"],
        )
        # fine raw buckets so sub-second windows have real resolution
        slo_engine = SLOEngine(
            specs=(spec,),
            store=SeriesStore(raw_step=0.05, raw_points=4096),
        )

        def _slo_sink(_wid: str, seconds: float) -> None:
            slo_engine.note_latency("tile_latency", seconds)
            slo_engine.step()

        latency_sinks.append(_slo_sink)
    incident_manager = None
    incident_fleet = None
    if incidents is not None:
        from ..telemetry.fleet import S_WORKER_TILES_PER_S, FleetRegistry
        from ..telemetry.flight import get_flight_recorder
        from ..telemetry.incidents import IncidentManager

        if not incidents.get("dir"):
            raise ValueError("incidents requires a 'dir' key")
        get_flight_recorder()  # tap the bus before anything publishes
        incident_fleet = FleetRegistry()
        inc_kwargs = dict(debounce_s=60.0, min_interval_s=0.0, max_bundles=8)
        inc_kwargs.update(
            {k: v for k, v in incidents.items() if k != "dir"}
        )
        incident_manager = IncidentManager(str(incidents["dir"]), **inc_kwargs)
        incident_manager.sources["store"] = store.stats_unlocked
        if wd_health is not None:
            incident_manager.sources["health"] = wd_health.snapshot
        if slo_engine is not None:
            incident_manager.sources["slo"] = slo_engine.status
        incident_manager.sources["fleet"] = (
            lambda: incident_fleet.status(since_s=600.0)
        )

        def _fleet_sink(wid: str, seconds: float) -> None:
            # per-worker tile-rate series on the harness registry: the
            # straggler's slow rate is the evidence the bundle's fleet
            # window must carry
            incident_fleet.store.record(
                S_WORKER_TILES_PER_S,
                (1.0 / seconds) if seconds > 0 else 0.0,
                worker_id=wid,
            )

        # FIRST in the fan-out: the sample that makes the SLO engine
        # fire (and thus capture) must already be in the fleet series
        # when the writer thread reads them — sink order is the only
        # thing keeping that race deterministic
        latency_sinks.insert(0, _fleet_sink)
    policy = None
    if placement is not None:
        from ..scheduler.placement import PlacementPolicy

        pl_kwargs = dict(
            min_samples=1, base_batch=2, max_batch=4, tail_tiles=1,
            health=wd_health,
        )
        pl_kwargs.update(placement)
        policy = PlacementPolicy(**pl_kwargs)
        store.placement = policy
        latency_sinks.append(policy.record_latency)
    if latency_sinks:
        store.latency_sink = lambda wid, sec: [
            sink(wid, sec) for sink in latency_sinks
        ]
    server = types.SimpleNamespace(job_store=store)
    ctx = ExecutionContext(server=server, config={"workers": []})
    bundle = types.SimpleNamespace(params=None)
    crashed: list[str] = []
    trace_id = f"exec_chaos_{seed}"
    chaos_tracer = Tracer(clock=FakeClock())

    h, w = image_hw
    image = jnp.asarray(
        np.random.default_rng(seed).random((1, h, w, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)

    accepted_by_worker: dict[str, int] = {wid: 0 for wid in workers}

    def worker_body(wid: str) -> None:
        # Identical preprocessing to the master: per-tile determinism
        # means the only thing identity changes is WHO computed a tile.
        from ..graph.tile_pipeline import GrantSampler, TilePipeline, stage_span

        _, grid, extracted = upscale_ops.prepare_upscaled_tiles(
            image, upscale_by, tile, padding, "bicubic", None
        )
        key = jax.random.key(seed)
        job = run_async_in_server_loop(
            store.wait_for_tile_job(job_id, grace_seconds=20), timeout=30
        )
        if job is None:
            return
        # Worker threads join the run's trace so their tile stages land
        # in the same span tree the master's stages do.
        tracer = get_tracer()
        token = tracer.activate(trace_id)
        sampler = GrantSampler(
            _stub_process, None, extracted, key, grid.positions_array(),
            None, None, k_max=tile_batch, role="worker", mesh=mesh,
            job_id=job_id,
        )
        flush_pending: dict[int, list] = {}

        def pull():
            if injector is not None:
                injector.check_blocking(f"chaos:{wid}:pull")
            # pull_tasks = the production batch path: singleton batches
            # without a placement policy (byte-identical to the
            # historical pull), speed-sized grants with one.
            return run_async_in_server_loop(
                store.pull_tasks(job_id, wid, timeout=0.2), timeout=10
            ) or None

        def sample(chunk):
            if injector is not None:
                # per-tile crash point AFTER assignment, BEFORE compute
                # (crash here = crash-after-pull: tile assigned, never
                # submitted — the requeue path must cover it)
                for _t in chunk:
                    injector.check_blocking(f"chaos:{wid}:pulled")
            return sampler.sample(chunk)

        def emit(tile_idx, arr):
            flush_pending[int(tile_idx)] = [
                {
                    "batch_idx": i,
                    "image": img_utils.encode_image_data_url(arr[i]),
                }
                for i in range(arr.shape[0])
            ]

        def flush(is_final):
            if not flush_pending:
                return
            grouped = dict(flush_pending)
            flush_pending.clear()
            if injector is not None:
                for _t in sorted(grouped):
                    injector.check_blocking(f"chaos:{wid}:submit")
            with stage_span(
                "submit", "worker", sorted(grouped)[0],
                batch=sorted(grouped), worker_id=wid,
            ):
                accepted = run_async_in_server_loop(
                    store.submit_flush(job_id, wid, grouped), timeout=10
                )
            accepted_by_worker[wid] += accepted

        def heartbeat():
            try:
                run_async_in_server_loop(
                    store.heartbeat(job_id, wid), timeout=10
                )
            except Exception:  # noqa: BLE001 - liveness is best effort
                pass

        def release(idxs):
            run_async_in_server_loop(
                store.release_tasks(job_id, wid, idxs), timeout=10
            )

        try:
            TilePipeline(
                pull=pull,
                sample=sample,
                chunks=sampler.chunks,
                to_host=sampler.collect,
                emit=emit,
                flush=flush,
                heartbeat=heartbeat,
                release=release,
                role="worker",
                span_attrs={"worker_id": wid},
                threaded=pipeline,
                prefetch=prefetch,
            ).run()
        except FaultInjected as exc:
            # Simulated crash: the thread dies with a tile assigned and
            # unsubmitted; the master's requeue path must recover it.
            debug_log(f"chaos worker {wid} died: {exc}")
            crashed.append(wid)
        except JobQueueError:
            pass  # master cleaned the job up while we were pulling
        finally:
            tracer.deactivate(token)

    threads = [
        threading.Thread(target=worker_body, args=(wid,), daemon=True)
        for wid in workers
    ]

    previous_tracer = get_tracer()
    if incident_manager is not None:
        # writer thread + bus trigger tap (alert_fired -> capture) —
        # started HERE, immediately before the guarded try, so any
        # raise in the remaining setup or the run itself reaches the
        # except arm that stops it (no leaked tap/thread)
        incident_manager.start()
    set_tracer(chaos_tracer)
    from ..telemetry.usage import UsageMeter, set_usage_meter

    usage_meter = UsageMeter()
    try:
        with contextlib.ExitStack() as stack:
            stack.enter_context(_ensure_server_loop())
            # run-local chip-time attribution: master loop, worker
            # threads, and store waste notes all meter into this
            # swapped-in meter (restored on stack exit); the result's
            # usage block is exactly this run's burn
            stack.callback(set_usage_meter, set_usage_meter(usage_meter))
            if cache is not None:
                # run-local tile result cache, same swap/restore idiom:
                # explicit set wins over the CDT_CACHE gate, so the
                # master's grant-time probe sees exactly this instance
                from ..cache.store import set_tile_cache

                stack.callback(set_tile_cache, set_tile_cache(cache))
            if wd is not None:
                # start after the loop exists (speculation round-trips
                # through it); stop (LIFO) before the loop shuts down
                wd.start()
                stack.callback(wd.stop)
            stack.enter_context(
                mock.patch.object(
                    elastic, "_jit_tile_processor", lambda *a, **k: _stub_process
                )
            )
            stack.enter_context(
                mock.patch.object(
                    config_mod, "get_worker_timeout_seconds",
                    lambda path=None: worker_timeout,
                )
            )
            stack.enter_context(
                mock.patch.dict(
                    os.environ,
                    {
                        "CDT_DETERMINISTIC_BLEND": "1",
                        # master loop + any nested tile_scan_batch()
                        # read share the harness's batching knob
                        "CDT_TILE_BATCH": str(max(1, int(tile_batch))),
                        "CDT_DEVICE_CANVAS": "1" if device_canvas else "0",
                    },
                )
            )
            token = chaos_tracer.activate(trace_id)
            try:
                with chaos_tracer.span(
                    "chaos_usdu", trace_id=trace_id, seed=seed,
                    fault_plan=fault_plan or "",
                ):
                    for t in threads:
                        t.start()
                    out = elastic.run_master_elastic(
                        bundle, image, pos, neg,
                        job_id=job_id,
                        enabled_worker_ids=list(workers),
                        mesh=mesh,
                        upscale_by=upscale_by, tile=tile, padding=padding,
                        steps=1, sampler="euler", scheduler="karras",
                        cfg=1.0, denoise=0.3, seed=seed, context=ctx,
                    )
                    for t in threads:
                        t.join(timeout=30)
            finally:
                chaos_tracer.deactivate(token)
        if trace_jsonl:
            chaos_tracer.write_jsonl(trace_id, trace_jsonl)
    except BaseException:
        # a raising run must not leak the incident plane: the bus tap
        # would keep capturing for unrelated later activity and the
        # writer thread would park on its queue forever (stop is
        # idempotent — the happy path below stops it again harmlessly)
        if incident_manager is not None:
            incident_manager.stop()
        raise
    finally:
        set_tracer(previous_tracer)
        if durability is not None:
            durability.close()
    if slo_engine is not None and slo_engine.is_active("tile_latency"):
        # the straggler is quarantined and the job is done — no new bad
        # samples can arrive, so continued evaluation MUST resolve the
        # alert once the short window drains past the resolve hold.
        # Bounded wait: a stuck alert here is a real engine bug, and
        # the test asserts on slo_active instead of hanging.
        deadline = time.monotonic() + 5.0
        while (
            slo_engine.is_active("tile_latency")
            and time.monotonic() < deadline
        ):
            slo_engine.step()
            time.sleep(0.02)
    incident_list: list[dict] = []
    incident_retrigger = ""
    if incident_manager is not None:
        # barrier: every queued capture written before the listing (a
        # trigger that fired in the final submit must not race)
        incident_manager.flush(10.0)
        if slo_engine is not None:
            fired = [
                a for a in slo_engine.history if a["type"] == "alert_fired"
            ]
            if fired:
                # debounce proof: a second identical alert inside the
                # window must capture NOTHING
                incident_retrigger = incident_manager.trigger(
                    "alert_fired",
                    key=str(fired[0].get("slo", "")),
                    context={"resimulated": True},
                )
                incident_manager.flush(5.0)
        incident_list = incident_manager.list_bundles()
        incident_manager.stop()
    # every tile is accepted exactly once (first result wins), so the
    # master's share is the remainder (plan_grid: geometry only, no
    # second resize/extract pass)
    _, _, grid = upscale_ops.plan_grid(h, w, upscale_by, tile, padding, None)
    tiles_by_worker = dict(accepted_by_worker)
    tiles_by_worker["master"] = grid.num_tiles - sum(accepted_by_worker.values())
    return ChaosResult(
        output=np.asarray(out),
        fired=list(injector.fired) if injector is not None else [],
        crashed_workers=crashed,
        trace_id=trace_id,
        stragglers=list(wd.stragglers_flagged) if wd is not None else [],
        stalls=list(wd.stalls_detected) if wd is not None else [],
        speculated=dict(wd.speculated) if wd is not None else {},
        health=wd_health.snapshot() if wd_health is not None else {},
        tiles_by_worker=tiles_by_worker,
        placement=policy.snapshot() if policy is not None else {},
        alerts=list(slo_engine.history) if slo_engine is not None else [],
        slo_active=(
            slo_engine.is_active("tile_latency")
            if slo_engine is not None
            else False
        ),
        incidents=incident_list,
        incident_dir=str(incidents["dir"]) if incidents else "",
        incident_retrigger=incident_retrigger,
        usage={
            "rollup": usage_meter.rollup(),
            "totals": usage_meter.totals(),
        },
        cache=cache.stats() if cache is not None else {},
    )


# --------------------------------------------------------------------------
# kill-the-master scenarios (durable control plane acceptance)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class MasterCrashResult:
    """Outcome of a two-phase kill-the-master run: the recovered
    canvas, what recovery found, and proof the crash actually fired."""

    output: np.ndarray
    report: dict
    crash_error: str
    fired: list[FaultAction]

    def fired_kinds(self) -> set[str]:
        return {a.kind for a in self.fired}


def run_chaos_master_crash(
    seed: int = 0,
    crash_plan: str = "crash@store:pull:master#3",
    *,
    journal_dir: str,
    workers: Sequence[str] = ("w1", "w2"),
    image_hw: tuple[int, int] = (64, 64),
    tile: int = 64,
    padding: int = 16,
    upscale_by: float = 2.0,
    worker_timeout: float = 0.6,
    job_id: str = "chaos-crash-job",
    snapshot_every: int = 4,
    fsync_every: int = 0,
) -> MasterCrashResult:
    """SIGKILL-the-master simulation, in process and deterministic.

    Phase 1 ("the process that dies"): the elastic USDU loop runs with
    the write-ahead journal attached (`journal_dir`) under a fault plan
    that raises out of one of the MASTER's own store RPCs
    (`crash@store:pull:master#k` = killed after k-1 successful pulls,
    `crash@store:submit:master#k` = killed after a partial submit). The
    abandoned JobStore — like the dead process's memory — is discarded;
    worker threads are orphaned mid-flight exactly as a real master
    SIGKILL orphans them, then drained out.

    Phase 2 ("the restarted process"): a FRESH JobStore is recovered
    from `journal_dir` (snapshot + WAL tail; in-flight and
    master-volatile tiles requeue, durable worker payloads restore to
    the results queue) and a fresh master loop drains the job to
    completion with no workers.

    Determinism: per-tile noise keys fold the global tile index, so
    whichever tiles phase 2 recomputes reproduce exactly; restored
    worker tiles travel the lossless PNG envelope; the deterministic
    blend makes compositing order irrelevant. The caller asserts the
    returned canvas is bit-identical to an uninterrupted run — journal
    CONTENT races (which worker submits landed before the crash) change
    the requeue/restore split, never the output.
    """
    import jax.numpy as jnp

    from ..durability import DurabilityManager
    from ..graph import ExecutionContext
    from ..graph import usdu_elastic as elastic
    from ..graph.tile_pipeline import GrantSampler, TilePipeline
    from ..jobs import JobStore
    from ..ops import upscale as upscale_ops
    from ..utils import config as config_mod
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop
    from ..utils.exceptions import JobQueueError

    h, w = image_hw
    image = jnp.asarray(
        np.random.default_rng(seed).random((1, h, w, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)
    bundle = types.SimpleNamespace(params=None)

    def worker_body(store: Any, wid: str) -> None:
        _, grid, extracted = upscale_ops.prepare_upscaled_tiles(
            image, upscale_by, tile, padding, "bicubic", None
        )
        import jax as _jax

        key = _jax.random.key(seed)
        job = run_async_in_server_loop(
            store.wait_for_tile_job(job_id, grace_seconds=20), timeout=30
        )
        if job is None:
            return
        sampler = GrantSampler(
            _stub_process, None, extracted, key, grid.positions_array(),
            None, None, k_max=1, role="worker",
        )
        flush_pending: dict[int, list] = {}

        def pull():
            return run_async_in_server_loop(
                store.pull_tasks(job_id, wid, timeout=0.2), timeout=10
            ) or None

        def emit(tile_idx, arr):
            flush_pending[int(tile_idx)] = [
                {
                    "batch_idx": i,
                    "image": img_utils.encode_image_data_url(arr[i]),
                }
                for i in range(arr.shape[0])
            ]

        def flush(is_final):
            if not flush_pending:
                return
            grouped = dict(flush_pending)
            flush_pending.clear()
            run_async_in_server_loop(
                store.submit_flush(job_id, wid, grouped), timeout=10
            )

        def heartbeat():
            try:
                run_async_in_server_loop(store.heartbeat(job_id, wid), timeout=10)
            except Exception:  # noqa: BLE001 - liveness best effort
                pass

        try:
            TilePipeline(
                pull=pull, sample=sampler.sample, chunks=sampler.chunks,
                emit=emit, flush=flush, heartbeat=heartbeat,
                role="worker", span_attrs={"worker_id": wid}, threaded=False,
            ).run()
        except JobQueueError:
            pass  # the dead master's job was torn down under us

    def run_master(store: Any) -> Any:
        ctx = ExecutionContext(
            server=types.SimpleNamespace(job_store=store),
            config={"workers": []},
        )
        return elastic.run_master_elastic(
            bundle, image, pos, neg,
            job_id=job_id,
            enabled_worker_ids=[],
            upscale_by=upscale_by, tile=tile, padding=padding,
            steps=1, sampler="euler", scheduler="karras",
            cfg=1.0, denoise=0.3, seed=seed, context=ctx,
        )

    injector = FaultInjector(f"seed={seed};{crash_plan}")
    crash_error = ""
    with contextlib.ExitStack() as stack:
        stack.enter_context(_ensure_server_loop())
        stack.enter_context(
            mock.patch.object(
                elastic, "_jit_tile_processor", lambda *a, **k: _stub_process
            )
        )
        stack.enter_context(
            mock.patch.object(
                config_mod, "get_worker_timeout_seconds",
                lambda path=None: worker_timeout,
            )
        )
        stack.enter_context(
            mock.patch.dict(
                os.environ,
                {"CDT_DETERMINISTIC_BLEND": "1", "CDT_TILE_BATCH": "1"},
            )
        )

        # --- phase 1: the master that dies -------------------------------
        store1 = JobStore(fault_injector=injector)
        manager1 = DurabilityManager(
            journal_dir, snapshot_every=snapshot_every, fsync_every=fsync_every
        )
        store1.journal_sink = manager1.record
        threads = [
            threading.Thread(
                target=worker_body, args=(store1, wid), daemon=True
            )
            for wid in workers
        ]
        for t in threads:
            t.start()
        try:
            run_master(store1)
            raise RuntimeError(
                f"master crash plan {crash_plan!r} never fired; the "
                "scenario would be vacuous"
            )
        except FaultInjected as exc:
            crash_error = str(exc)
            debug_log(f"chaos master died: {exc}")
        # The dead process takes its journal seam with it; late worker
        # submissions against the abandoned store are lost exactly as
        # they would be against a closed socket (recovery requeues
        # them — bit-identical recompute either way).
        store1.journal_sink = None

        async def _teardown():
            async with store1.lock:
                store1.tile_jobs.pop(job_id, None)

        run_async_in_server_loop(_teardown(), timeout=10)
        for t in threads:
            t.join(timeout=30)
        manager1.close()

        # --- phase 2: the restarted master -------------------------------
        store2 = JobStore()
        manager2 = DurabilityManager(
            journal_dir, snapshot_every=snapshot_every, fsync_every=fsync_every
        )
        report = manager2.recover(store2)
        store2.journal_sink = manager2.record
        try:
            out = run_master(store2)
        finally:
            manager2.close()

    return MasterCrashResult(
        output=np.asarray(out),
        report=report.as_json(),
        crash_error=crash_error,
        fired=list(injector.fired),
    )


# --------------------------------------------------------------------------
# warm-standby failover scenarios (HA layer acceptance)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class FailoverResult:
    """Outcome of a kill-the-active-master + standby-promotes run."""

    output: np.ndarray
    report: dict          # the promotion's recovery report
    crash_error: str
    fired: list[FaultAction]
    epochs: tuple[int, int]        # (active's epoch, promoted epoch)
    replica: dict                  # standby replica status at promotion
    zombie_fenced: bool            # ex-active journal append -> FencedOut
    stale_pull_rejected: bool      # epoch-1 pull on the new store -> StaleEpoch
    stale_submit_rejected: bool    # epoch-1 submit -> StaleEpoch
    zombie_journaled_records: int  # journal growth from fenced attempts (0!)
    repointed_workers: list[str]   # workers that pulled the PROMOTED store
    # tile the harness claimed against the dying master and never
    # submitted: its requeue-at-promotion is the non-vacuous proof the
    # prepare_for_restart path ran (None when the queue was already dry)
    orphan_tile: Optional[int] = None

    def fired_kinds(self) -> set[str]:
        return {a.kind for a in self.fired}


def run_chaos_failover(
    seed: int = 0,
    crash_plan: str = "crash@store:pull:master#2;crash@chaos:w1:pulled#2",
    *,
    journal_dir: str,
    workers: Sequence[str] = ("w1", "w2"),
    image_hw: tuple[int, int] = (64, 64),
    tile: int = 64,
    padding: int = 16,
    upscale_by: float = 2.0,
    worker_timeout: float = 0.6,
    job_id: str = "chaos-failover-job",
    snapshot_every: int = 4,
    lease_ttl: float = 0.3,
    push_grants: bool = False,
    quorum_peers: Optional[Sequence[Any]] = None,
    peer_crash: Optional[str] = None,
) -> FailoverResult:
    """Kill-the-active-master failover, in process and deterministic.

    The full HA protocol with the transports removed (the same halves
    api/replication_routes.py + api/standby.py put on a WebSocket):

    - **phase 1 (the active master that dies)**: the elastic USDU loop
      runs with the write-ahead journal attached, holding the
      epoch-numbered lease on `journal_dir`; a live standby replica
      tails the journal through a ``ReplicationSubscription`` (attach-
      consistent snapshot + record tee — the exact stream the WS route
      serves) on its own thread. `crash_plan` kills the master mid-job
      at a scripted store RPC (`crash@store:pull:master#k` = after a
      pull, `crash@store:submit:master#k` = after a partial submit;
      pass ``snapshot_every=1`` to land the crash inside the snapshot
      cadence). A worker-crash rule (`crash@chaos:<w>:pulled#k`)
      guarantees an in-flight orphan tile exists at takeover, so the
      promotion's requeue path is never vacuous.

    - **takeover**: surviving workers observe the dead master (their
      next pull parks, exactly as re-pointed HTTP clients park in their
      retry/rotation loop); the standby waits out the lease TTL, takes
      the lease (epoch+1), drains the final teed records, and promotes:
      ``DurabilityManager.adopt`` — `prepare_for_restart` semantics
      end to end (in-flight tiles requeued for bit-identical recompute,
      durable worker payloads restored), journal reopened at the
      replicated head, immediate snapshot.

    - **fencing probes** (the regression the acceptance demands): after
      takeover the ex-active's journal seam must raise ``FencedOut``
      and journal NOTHING; the promoted store must reject pre-takeover
      authority (pull and submit carrying the old epoch) with
      ``StaleEpoch`` BEFORE any mutation — both probed directly and
      reported in the result.

    - **phase 2 (the promoted master serves)**: workers re-point to the
      promoted store (carrying the new epoch) and a fresh master loop
      drains the job to completion — no process restart anywhere. The
      caller asserts the canvas is bit-identical to an uninterrupted
      run.

    `push_grants=True` wires the store's grant notifier through a
    PlacementPolicy (the production push publisher) on both stores —
    the pushed-grant path must survive the same failover the pull
    fallback does.

    `quorum_peers` swaps the arbitration medium: both claimants run a
    ``QuorumLease`` over the given shared peer registers instead of a
    flock'd file on `journal_dir` — region mode, where no shared
    filesystem arbitrates. The protocol downstream is identical (epoch
    fencing, ``FencedOut``, ``StaleEpoch``), which is exactly what the
    scenario proves. `peer_crash` ("before" / "after") additionally
    crashes one peer mid-way through the standby's acquire — the
    mid-acquire peer-crash case: a majority of the survivors still
    elects, epochs stay monotonic, and the canvas stays bit-identical.
    """
    import jax.numpy as jnp

    from ..durability import (
        DurabilityManager,
        FencedOut,
        Lease,
        LeaseHeld,
        QuorumLease,
        StandbyReplica,
    )
    from ..graph import ExecutionContext
    from ..graph import usdu_elastic as elastic
    from ..graph.tile_pipeline import GrantSampler, TilePipeline
    from ..jobs import JobStore
    from ..ops import upscale as upscale_ops
    from ..utils import config as config_mod
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop
    from ..utils.exceptions import JobQueueError, StaleEpoch

    h, w = image_hw
    image = jnp.asarray(
        np.random.default_rng(seed).random((1, h, w, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)
    bundle = types.SimpleNamespace(params=None)

    # Shared failover state the worker threads re-point through: the
    # in-process analogue of HTTPWorkClient's address list + epoch.
    crashed = threading.Event()
    promoted = threading.Event()
    holder: dict[str, Any] = {"store": None, "epoch": 0}
    repointed: list[str] = []
    repointed_lock = threading.Lock()

    def worker_body(wid: str) -> None:
        _, grid, extracted = upscale_ops.prepare_upscaled_tiles(
            image, upscale_by, tile, padding, "bicubic", None
        )
        import jax as _jax

        key = _jax.random.key(seed)
        job = run_async_in_server_loop(
            holder["store"].wait_for_tile_job(job_id, grace_seconds=20),
            timeout=30,
        )
        if job is None:
            return
        sampler = GrantSampler(
            _stub_process, None, extracted, key, grid.positions_array(),
            None, None, k_max=1, role="worker",
        )
        flush_pending: dict[int, list] = {}
        seen_promoted = False

        def pull():
            nonlocal seen_promoted
            while True:
                if crashed.is_set() and not promoted.is_set():
                    # the master is dead: the re-pointing client parks
                    # in its rotation/retry loop until a standby
                    # promotes (or the run is abandoned)
                    if not promoted.wait(timeout=15):
                        return None
                store, epoch = holder["store"], holder["epoch"]
                if promoted.is_set() and not seen_promoted:
                    seen_promoted = True
                    with repointed_lock:
                        repointed.append(wid)
                if injector is not None:
                    injector.check_blocking(f"chaos:{wid}:pull")
                try:
                    return run_async_in_server_loop(
                        store.pull_tasks(
                            job_id, wid, timeout=0.2, epoch=epoch
                        ),
                        timeout=10,
                    ) or None
                except StaleEpoch:
                    continue  # takeover mid-RPC: refresh epoch and retry
                except (JobQueueError, FencedOut):
                    if promoted.is_set() and store is holder["store"]:
                        return None  # promoted store tore the job down: done
                    continue  # dead master's store; re-point and retry

        def sample(chunk):
            if injector is not None:
                for _t in chunk:
                    injector.check_blocking(f"chaos:{wid}:pulled")
            return sampler.sample(chunk)

        def emit(tile_idx, arr):
            flush_pending[int(tile_idx)] = [
                {
                    "batch_idx": i,
                    "image": img_utils.encode_image_data_url(arr[i]),
                }
                for i in range(arr.shape[0])
            ]

        def flush(is_final):
            if not flush_pending:
                return
            grouped = dict(flush_pending)
            flush_pending.clear()
            store, epoch = holder["store"], holder["epoch"]
            try:
                run_async_in_server_loop(
                    store.submit_flush(job_id, wid, grouped, epoch=epoch),
                    timeout=10,
                )
            except (StaleEpoch, FencedOut, JobQueueError):
                # pre-takeover authority / dead store: drop the flush —
                # the promotion requeued these tiles and their recompute
                # is bit-identical (the whole point of the invariant)
                pass

        def heartbeat():
            try:
                run_async_in_server_loop(
                    holder["store"].heartbeat(
                        job_id, wid, epoch=holder["epoch"]
                    ),
                    timeout=10,
                )
            except Exception:  # noqa: BLE001 - liveness best effort
                pass

        try:
            TilePipeline(
                pull=pull, sample=sample, chunks=sampler.chunks,
                emit=emit, flush=flush, heartbeat=heartbeat,
                role="worker", span_attrs={"worker_id": wid}, threaded=False,
            ).run()
        except FaultInjected as exc:
            debug_log(f"chaos worker {wid} died: {exc}")
        except JobQueueError:
            pass

    def run_master(store: Any) -> Any:
        ctx = ExecutionContext(
            server=types.SimpleNamespace(job_store=store),
            config={"workers": []},
        )
        return elastic.run_master_elastic(
            bundle, image, pos, neg,
            job_id=job_id,
            enabled_worker_ids=list(workers),
            upscale_by=upscale_by, tile=tile, padding=padding,
            steps=1, sampler="euler", scheduler="karras",
            cfg=1.0, denoise=0.3, seed=seed, context=ctx,
        )

    def wire_push(store: JobStore) -> None:
        if not push_grants:
            return
        from ..scheduler.placement import PlacementPolicy

        policy = PlacementPolicy(min_samples=1)
        store.placement = policy
        store.grant_notifier = policy.notify_grants

    injector = FaultInjector(f"seed={seed};{crash_plan}")
    crash_error = ""
    with contextlib.ExitStack() as stack:
        stack.enter_context(_ensure_server_loop())
        stack.enter_context(
            mock.patch.object(
                elastic, "_jit_tile_processor", lambda *a, **k: _stub_process
            )
        )
        stack.enter_context(
            mock.patch.object(
                config_mod, "get_worker_timeout_seconds",
                lambda path=None: worker_timeout,
            )
        )
        stack.enter_context(
            mock.patch.dict(
                os.environ,
                {"CDT_DETERMINISTIC_BLEND": "1", "CDT_TILE_BATCH": "1"},
            )
        )

        # --- phase 1: the active master, its lease, and a live standby ---
        store1 = JobStore(fault_injector=injector)
        manager1 = DurabilityManager(
            journal_dir, snapshot_every=snapshot_every, fsync_every=0
        )

        def make_lease(owner: str) -> Any:
            if quorum_peers is not None:
                return QuorumLease(
                    list(quorum_peers), owner=owner, ttl=lease_ttl
                )
            return Lease(journal_dir, owner=owner, ttl=lease_ttl)

        lease1 = make_lease("chaos-active")
        epoch1 = lease1.acquire(force=True)
        manager1.lease = lease1
        store1.journal_sink = manager1.record
        store1.set_epoch(epoch1)
        wire_push(store1)
        holder["store"], holder["epoch"] = store1, epoch1

        # the standby: attach-consistent subscription + replica tail
        # thread (the direct wiring of the WS stream's two halves)
        sub = manager1.subscribe_replica()
        replica = StandbyReplica()
        replica.reset(sub.snapshot_state, sub.head_lsn, sub.epoch)
        tail_stop = threading.Event()

        def tail_body() -> None:
            while not tail_stop.is_set():
                sub.wait(0.02)
                for record in sub.pop():
                    replica.apply(record)
                replica.note_head(manager1.head_lsn(), epoch1)

        tail = threading.Thread(target=tail_body, name="chaos-standby", daemon=True)
        tail.start()

        threads = [
            threading.Thread(target=worker_body, args=(wid,), daemon=True)
            for wid in workers
        ]
        for t in threads:
            t.start()
        try:
            run_master(store1)
            raise RuntimeError(
                f"failover crash plan {crash_plan!r} never fired; the "
                "scenario would be vacuous"
            )
        except FaultInjected as exc:
            crash_error = str(exc)
            debug_log(f"chaos active master died: {exc}")
        crashed.set()
        # Deterministic orphan: claim one tile against the dying master
        # and never submit it — the pull journals (and replicates), so
        # the promotion MUST requeue it. Models the grant the dead
        # process served in its last instant.
        orphan_tile = None
        try:
            orphan_tile = run_async_in_server_loop(
                store1.pull_task(job_id, "orphan", timeout=0.2, epoch=epoch1),
                timeout=10,
            )
        except Exception:  # noqa: BLE001 - queue already dry is legal
            orphan_tile = None

        # --- takeover: wait out the TTL, then promote the standby --------
        # NOT forced: the standby promotion gate — the acquire succeeds
        # only once the dead active's lease has expired. `peer_crash`
        # arms a one-shot peer crash on the quorum path so the election
        # itself runs through a mid-acquire failure.
        lease2 = make_lease("chaos-standby")
        if peer_crash is not None and quorum_peers is not None:
            quorum_peers[-1].crash_next_propose = peer_crash
        deadline = time.monotonic() + max(5.0, lease_ttl * 20)
        epoch2: Optional[int] = None
        while time.monotonic() < deadline:
            try:
                epoch2 = lease2.acquire()
                break
            except LeaseHeld:
                time.sleep(lease_ttl / 10)  # the dead active's TTL
            except OSError:
                time.sleep(lease_ttl / 10)  # indeterminate quorum read
        if epoch2 is None:
            raise RuntimeError("standby never won the lease")
        # final drain: post-takeover the ex-active is fenced, so no
        # record can land after this
        for record in sub.pop(max_items=100000):
            replica.apply(record)
        tail_stop.set()
        tail.join(timeout=10)
        replica_status = replica.status()

        store2 = JobStore()
        manager2 = DurabilityManager(
            journal_dir, snapshot_every=snapshot_every, fsync_every=0
        )
        report = manager2.adopt(store2, replica, lease=lease2)
        store2.journal_sink = manager2.record
        store2.set_epoch(epoch2)
        wire_push(store2)

        # --- fencing probes (regression: the zombie mutates nothing) -----
        head_before = manager2.head_lsn()
        zombie_fenced = False
        try:
            manager1.record({"type": "submit", "job": job_id, "worker": "zombie",
                             "task": 0, "payload": None})
        except FencedOut:
            zombie_fenced = True
        stale_pull_rejected = False
        try:
            run_async_in_server_loop(
                store2.pull_task(job_id, "zombie", timeout=0.01, epoch=epoch1),
                timeout=10,
            )
        except StaleEpoch:
            stale_pull_rejected = True
        stale_submit_rejected = False
        try:
            run_async_in_server_loop(
                store2.submit_result(
                    job_id, "zombie", 0, None, epoch=epoch1
                ),
                timeout=10,
            )
        except StaleEpoch:
            stale_submit_rejected = True
        zombie_journaled = manager2.head_lsn() - head_before

        # --- phase 2: the promoted master serves; workers re-point -------
        holder["store"], holder["epoch"] = store2, epoch2
        promoted.set()
        try:
            out = run_master(store2)
        finally:
            for t in threads:
                t.join(timeout=30)
            manager2.close()
            manager1.close()
            lease2.release()

    return FailoverResult(
        output=np.asarray(out),
        report=report.as_json(),
        crash_error=crash_error,
        fired=list(injector.fired),
        epochs=(epoch1, epoch2),
        replica=replica_status,
        zombie_fenced=zombie_fenced,
        stale_pull_rejected=stale_pull_rejected,
        stale_submit_rejected=stale_submit_rejected,
        zombie_journaled_records=zombie_journaled,
        repointed_workers=sorted(repointed),
        orphan_tile=orphan_tile,
    )


def run_chaos_quorum_failover(
    seed: int = 0,
    crash_plan: str = "crash@store:pull:master#2;crash@chaos:w1:pulled#2",
    *,
    journal_dir: str,
    n_peers: int = 3,
    peer_crash: Optional[str] = None,
    **kwargs: Any,
) -> FailoverResult:
    """Region-mode failover: the same kill-the-active scenario as
    ``run_chaos_failover``, arbitrated by a ``QuorumLease`` over
    ``n_peers`` in-memory peer registers instead of a shared-filesystem
    flock. `peer_crash` ("before"/"after") crashes one peer mid-way
    through the standby's acquire. The caller asserts the canvas is
    bit-identical to the fault-free run — the acceptance that quorum
    leasing changes the arbitration medium and nothing else."""
    from ..durability import MemoryLeasePeer

    peers = [MemoryLeasePeer(f"peer{i}") for i in range(n_peers)]
    return run_chaos_failover(
        seed,
        crash_plan,
        journal_dir=journal_dir,
        quorum_peers=peers,
        peer_crash=peer_crash,
        **kwargs,
    )


@dataclasses.dataclass
class RegionResult:
    """Outcome of a two-shard region run with one shard failing over."""

    placements: dict          # job id -> shard name (the ring's map)
    shard0: FailoverResult    # the shard that lost its master mid-job
    shard1_tiles_completed: int  # the untouched shard's job, tile-complete
    shard1_epoch: int          # must still be its own epoch 1
    shard1_journal_appends: int
    placement_drift: int       # ring placements changed by the failover (0!)
    autoscale_decisions: list  # the controller's ledger across the run


def run_chaos_region(
    seed: int = 0,
    *,
    journal_root: str,
    crash_plan: str = "crash@store:pull:master#2;crash@chaos:w1:pulled#2",
    peer_crash: Optional[str] = None,
    probe_jobs: int = 64,
) -> RegionResult:
    """Two master shards, one region: shard0's master is killed mid-job
    and fails over through the quorum lease while shard1's job — opened
    BEFORE the crash and completed after — never loses a tile.

    What it proves, in one deterministic in-process run:

    - **placement is coordination-free**: the consistent-hash ring maps
      every probe job to the same shard before and after the failover
      (membership never changed, so zero keys move);
    - **shard isolation**: shard1's journal, lease epoch, and job state
      are untouched by shard0's crash/promotion — separate WALs,
      separate leases, zero cross-shard job loss;
    - **the failed shard recovers bit-identically** (delegated to
      ``run_chaos_quorum_failover``: zombie fenced, stale submits
      journal nothing, canvas equals the fault-free run);
    - **the autoscaler observes the region**: its step ledger across
      the run records each decision with the chip-second demand /
      capacity window that justified it (a burn alert during the
      outage forces a scale-up whose cost is measured on the next
      evaluation).
    """
    from ..durability import DurabilityManager, Lease
    from ..jobs import JobStore
    from ..scheduler.autoscale import AutoscaleController
    from ..scheduler.router import ShardRouter
    from ..utils.async_helpers import run_async_in_server_loop

    router = ShardRouter(
        {"shard0": ["http://s0:8188"], "shard1": ["http://s1:8188"]},
        vnodes=32,
    )
    placements = {
        f"region-job-{i}": router.shard_for(f"region-job-{i}")
        for i in range(probe_jobs)
    }
    job1 = next(j for j, s in placements.items() if s == "shard1")

    # The autoscaler watching the region: a burn alert flips during the
    # outage window; demand is the chip-seconds the shards burn.
    burn: set = set()
    usage_counter = {"chip_s": 0.0}
    pool = {"workers": 2}
    slo = types.SimpleNamespace(is_active=lambda name: name in burn)
    usage = types.SimpleNamespace(
        rollup=lambda: {"totals": {"chip_s": usage_counter["chip_s"]}}
    )
    controller = AutoscaleController(
        slo=slo,
        usage=usage,
        launcher=lambda: (
            pool.__setitem__("workers", pool["workers"] + 1)
            or f"w{pool['workers']}"
        ),
        drainer=None,
        capacity_fn=lambda: (pool["workers"], float(pool["workers"])),
        interval=1.0,
        min_workers=1,
        max_workers=4,
        target_util=0.7,
        down_hold=3600.0,
    )
    controller.step()  # baseline window

    with contextlib.ExitStack() as stack:
        stack.enter_context(_ensure_server_loop())
        # --- shard1: open its job BEFORE shard0's crash ----------------
        shard1_dir = os.path.join(journal_root, "shard1")
        store_s1 = JobStore()
        manager_s1 = DurabilityManager(
            shard1_dir, snapshot_every=4, fsync_every=0
        )
        lease_s1 = Lease(shard1_dir, owner="shard1-master", ttl=30.0)
        epoch_s1 = lease_s1.acquire(force=True)
        manager_s1.lease = lease_s1
        store_s1.journal_sink = manager_s1.record
        store_s1.set_epoch(epoch_s1)
        tiles_s1 = list(range(4))
        run_async_in_server_loop(
            store_s1.init_tile_job(job1, tiles_s1), timeout=10
        )
        first = run_async_in_server_loop(
            store_s1.pull_task(job1, "s1-w1", timeout=0.2, epoch=epoch_s1),
            timeout=10,
        )
        in_flight = [first] if first is not None else []

        # --- shard0: the full quorum-lease failover mid-job ------------
        usage_counter["chip_s"] += 1.4   # the window's measured demand
        burn.add("availability")          # the outage fires the SLO
        controller.step()                 # decision: scale_up (burn)
        shard0_result = run_chaos_quorum_failover(
            seed,
            crash_plan,
            journal_dir=os.path.join(journal_root, "shard0"),
            peer_crash=peer_crash,
        )
        burn.clear()
        usage_counter["chip_s"] += 0.4
        controller.step()                 # settles the scale_up's cost

        # --- shard1 again: finish the job it held across the outage ----
        for t in in_flight:
            run_async_in_server_loop(
                store_s1.submit_result(
                    job1, "s1-w1", int(t), None, epoch=epoch_s1
                ),
                timeout=10,
            )
        while True:
            t = run_async_in_server_loop(
                store_s1.pull_task(
                    job1, "s1-w1", timeout=0.05, epoch=epoch_s1
                ),
                timeout=10,
            )
            if t is None:
                break
            run_async_in_server_loop(
                store_s1.submit_result(
                    job1, "s1-w1", int(t), None, epoch=epoch_s1
                ),
                timeout=10,
            )
        job_state = store_s1.tile_jobs[job1]
        completed = len(job_state.completed)
        shard1_appends = manager_s1.head_lsn()
        manager_s1.close()
        lease_s1.release()

    drift = sum(
        1
        for j, s in placements.items()
        if router.shard_for(j) != s
    )
    if completed != len(tiles_s1):
        raise RuntimeError(
            f"cross-shard job loss: shard1 completed {completed}/"
            f"{len(tiles_s1)} tiles across shard0's failover"
        )
    return RegionResult(
        placements=placements,
        shard0=shard0_result,
        shard1_tiles_completed=completed,
        shard1_epoch=epoch_s1,
        shard1_journal_appends=shard1_appends,
        placement_drift=drift,
        autoscale_decisions=list(controller.decisions),
    )


# --------------------------------------------------------------------------
# request-lifecycle scenarios (cancel / poison-tile acceptance)
# --------------------------------------------------------------------------


class _TrimMaster:
    """Placement stub that trims the MASTER out of the pull set (and
    keeps worker grants at one tile): lifecycle scenarios need the
    poison/cancel tiles to stay with worker threads instead of being
    instantly drained by the in-process master."""

    def may_pull(self, worker_id: str, pending: int) -> bool:
        return worker_id != "master"

    def batch_size(self, worker_id: str, pending: int) -> int:
        return 1


@dataclasses.dataclass
class CancelResult:
    """Outcome of a cancel-mid-job run: the refund accounting, the
    leak check, and the terminal-state parity evidence."""

    raised: str                    # exception type the master died with
    reason: str                    # cancel reason it carried
    accounting: dict               # cancel_job's refund accounting
    completed_before_cancel: int
    stats_after: dict              # store stats right after cancel
    state_after_cancel: dict       # manager shadow at cancel time
    journal_jobs_after: dict       # jobs left in the journal at the end
    replica_jobs_after: dict       # jobs left in the replica at the end
    replica_saw_cancel: bool       # the cancel record reached the standby
    idempotent_replay: bool
    cancel_latency_ms: float       # cancel call -> all tiles refunded


def run_chaos_cancel(
    seed: int = 0,
    *,
    journal_dir: str,
    workers: Sequence[str] = ("w1", "w2"),
    image_hw: tuple[int, int] = (96, 96),
    tile: int = 48,
    padding: int = 16,
    upscale_by: float = 2.0,
    worker_timeout: float = 5.0,
    job_id: str = "chaos-cancel-job",
    cancel_after: int = 2,
    tile_delay: float = 0.08,
    reason: str = "chaos",
) -> CancelResult:
    """Cancel-mid-job acceptance: the elastic USDU loop runs with the
    write-ahead journal attached and a live standby replica teed in;
    once ``cancel_after`` tiles have completed, a canceller thread
    fires ``JobStore.cancel_job`` — mid-flight, with tiles pending AND
    assigned. The scenario then proves the acceptance bundle:

    - the refund accounting balances (no leaked in-flight assignment
      survives the cancel — ``stats_after``);
    - the master loop settles with a terminal ``JobCancelled`` instead
      of blending a partial canvas; workers' later submissions drop;
    - the cancel round-trips the journal: the shadow state at cancel
      time shows the job terminally drained, the standby replica
      applied the same record, and replay is idempotent.

    Workers are slowed by ``tile_delay`` per tile (and the master is
    trimmed out of the pull set) so the cancel deterministically lands
    while work is still in flight.
    """
    import jax
    import jax.numpy as jnp

    from ..durability import DurabilityManager, StandbyReplica
    from ..durability import state as dstate
    from ..durability.recovery import recover_state, verify_idempotent_replay
    from ..graph import ExecutionContext
    from ..graph import usdu_elastic as elastic
    from ..graph.tile_pipeline import GrantSampler, TilePipeline
    from ..jobs import JobStore
    from ..ops import upscale as upscale_ops
    from ..utils import config as config_mod
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop
    from ..utils.exceptions import JobCancelled, JobQueueError

    h, w = image_hw
    image = jnp.asarray(
        np.random.default_rng(seed).random((1, h, w, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)
    bundle = types.SimpleNamespace(params=None)

    store = JobStore()
    store.placement = _TrimMaster()
    manager = DurabilityManager(journal_dir, snapshot_every=64, fsync_every=0)
    store.journal_sink = manager.record

    # live standby: attach-consistent subscription + replica tail
    sub = manager.subscribe_replica()
    replica = StandbyReplica()
    replica.reset(sub.snapshot_state, sub.head_lsn, sub.epoch)
    tail_stop = threading.Event()

    def tail_body() -> None:
        while not tail_stop.is_set():
            sub.wait(0.02)
            for record in sub.pop():
                replica.apply(record)

    tail = threading.Thread(target=tail_body, name="chaos-cancel-standby", daemon=True)
    tail.start()

    def worker_body(wid: str) -> None:
        _, grid, extracted = upscale_ops.prepare_upscaled_tiles(
            image, upscale_by, tile, padding, "bicubic", None
        )
        key = jax.random.key(seed)
        job = run_async_in_server_loop(
            store.wait_for_tile_job(job_id, grace_seconds=20), timeout=30
        )
        if job is None:
            return
        sampler = GrantSampler(
            _stub_process, None, extracted, key, grid.positions_array(),
            None, None, k_max=1, role="worker",
        )
        flush_pending: dict[int, list] = {}

        def pull():
            try:
                return run_async_in_server_loop(
                    store.pull_tasks(job_id, wid, timeout=0.2), timeout=10
                ) or None
            except JobQueueError:
                return None

        def sample(chunk):
            time.sleep(tile_delay)  # keep work in flight at cancel time
            return sampler.sample(chunk)

        def emit(tile_idx, arr):
            flush_pending[int(tile_idx)] = [
                {
                    "batch_idx": i,
                    "image": img_utils.encode_image_data_url(arr[i]),
                }
                for i in range(arr.shape[0])
            ]

        def flush(is_final):
            if not flush_pending:
                return
            grouped = dict(flush_pending)
            flush_pending.clear()
            try:
                run_async_in_server_loop(
                    store.submit_flush(job_id, wid, grouped), timeout=10
                )
            except JobQueueError:
                pass  # cancelled + cleaned up under us

        try:
            TilePipeline(
                pull=pull, sample=sample, chunks=sampler.chunks,
                emit=emit, flush=flush, role="worker",
                span_attrs={"worker_id": wid}, threaded=False,
            ).run()
        except JobQueueError:
            pass

    cancel_outcome: dict[str, Any] = {}

    def canceller_body() -> None:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            job = run_async_in_server_loop(
                store.get_tile_job(job_id), timeout=10
            )
            if job is not None and len(job.completed) >= cancel_after:
                started = time.monotonic()
                accounting = run_async_in_server_loop(
                    store.cancel_job(job_id, reason=reason), timeout=10
                )
                cancel_outcome["latency_ms"] = (
                    time.monotonic() - started
                ) * 1000.0
                cancel_outcome["accounting"] = accounting
                cancel_outcome["completed"] = len(job.completed)
                with manager._lock:
                    cancel_outcome["state"] = dstate.clone(manager._state)
                cancel_outcome["stats"] = store.stats_unlocked()
                return
            time.sleep(0.005)

    raised = ""
    got_reason = ""
    with contextlib.ExitStack() as stack:
        stack.enter_context(_ensure_server_loop())
        stack.enter_context(
            mock.patch.object(
                elastic, "_jit_tile_processor", lambda *a, **k: _stub_process
            )
        )
        stack.enter_context(
            mock.patch.object(
                config_mod, "get_worker_timeout_seconds",
                lambda path=None: worker_timeout,
            )
        )
        stack.enter_context(
            mock.patch.dict(
                os.environ,
                {"CDT_DETERMINISTIC_BLEND": "1", "CDT_TILE_BATCH": "1"},
            )
        )
        ctx = ExecutionContext(
            server=types.SimpleNamespace(job_store=store),
            config={"workers": []},
        )
        threads = [
            threading.Thread(target=worker_body, args=(wid,), daemon=True)
            for wid in workers
        ]
        canceller = threading.Thread(target=canceller_body, daemon=True)
        for t in threads:
            t.start()
        canceller.start()
        try:
            elastic.run_master_elastic(
                bundle, image, pos, neg,
                job_id=job_id,
                enabled_worker_ids=list(workers),
                upscale_by=upscale_by, tile=tile, padding=padding,
                steps=1, sampler="euler", scheduler="karras",
                cfg=1.0, denoise=0.3, seed=seed, context=ctx,
            )
        except JobCancelled as exc:
            raised = type(exc).__name__
            got_reason = exc.reason
        finally:
            for t in threads:
                t.join(timeout=30)
            canceller.join(timeout=30)
            # final drain of the replication tee, then stop the tail
            for record in sub.pop(max_items=100000):
                replica.apply(record)
            tail_stop.set()
            tail.join(timeout=10)
            manager.close()

    journal_state, _ = recover_state(journal_dir)
    replica_state = dstate.clone(replica._state)
    state_after_cancel = cancel_outcome.get("state", {})
    job_at_cancel = state_after_cancel.get("jobs", {}).get(job_id, {})
    return CancelResult(
        raised=raised,
        reason=got_reason,
        accounting=cancel_outcome.get("accounting") or {},
        completed_before_cancel=int(cancel_outcome.get("completed", 0)),
        stats_after=cancel_outcome.get("stats") or {},
        state_after_cancel=job_at_cancel,
        journal_jobs_after=dict(journal_state.get("jobs", {})),
        replica_jobs_after=dict(replica_state.get("jobs", {})),
        replica_saw_cancel=bool(job_at_cancel.get("cancelled", False)),
        idempotent_replay=verify_idempotent_replay(journal_dir),
        cancel_latency_ms=float(cancel_outcome.get("latency_ms", 0.0)),
    )


class _PoisonCrash(RuntimeError):
    """Simulated worker-process death on a poison payload."""


@dataclasses.dataclass
class PoisonResult:
    """Outcome of a poison-tile run: quarantine evidence, breaker
    states, and the degraded canvas."""

    output: np.ndarray
    poison_tile: int
    poison_rect: tuple[int, int, int, int]   # y, x, h, w in output coords
    crashed_workers: list[str]
    attempts: dict
    quarantined: list[int]
    pardons: list[str]
    health_after: dict
    charged_states: list[str]   # breaker states observed right after each crash
    journal_quarantined: list[int]


def run_chaos_poison(
    seed: int = 0,
    *,
    journal_dir: Optional[str] = None,
    workers: Sequence[str] = ("w1", "w2", "w3"),
    image_hw: tuple[int, int] = (96, 96),
    tile: int = 48,
    padding: int = 16,
    upscale_by: float = 2.0,
    worker_timeout: float = 0.4,
    job_id: str = "chaos-poison-job",
    poison_tile: int = 0,
    max_attempts: int = 3,
    poison_policy: str = "degrade",
) -> PoisonResult:
    """Poison-tile acceptance: tile ``poison_tile``'s payload crashes
    EVERY worker that samples it (each crash also charges the worker's
    circuit breaker with failure_threshold=1 — the harshest cascade
    setting). The store must quarantine the tile after ``max_attempts``
    crash-requeues, the job must complete DEGRADED (the quarantined
    region blended from the base image, every other tile bit-identical
    to a clean run), and the breaker pardon must leave NO worker
    quarantined on account of the poison.

    The master is trimmed out of the pull set (``_TrimMaster``) so the
    poison can only travel through workers; its deadline fallback
    covers whatever healthy tiles the dead fleet left behind —
    explicitly skipping the quarantined one."""
    import jax
    import jax.numpy as jnp

    from ..graph import ExecutionContext
    from ..graph import usdu_elastic as elastic
    from ..graph.tile_pipeline import GrantSampler, TilePipeline
    from ..jobs import JobStore
    from ..ops import upscale as upscale_ops
    from ..utils import config as config_mod
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop
    from ..utils.exceptions import JobQueueError
    from .health import HealthRegistry

    h, w = image_hw
    image = jnp.asarray(
        np.random.default_rng(seed).random((1, h, w, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)
    bundle = types.SimpleNamespace(params=None)

    health = HealthRegistry(failure_threshold=1, suspect_threshold=1)
    pardons: list[str] = []
    charged_states: list[str] = []
    captured: dict[str, Any] = {}

    store = JobStore(max_attempts=max_attempts, poison_policy=poison_policy)
    store.placement = _TrimMaster()

    def pardon(worker_ids: list) -> None:
        # fires at quarantine time ON the server loop: snapshot the
        # job's final attempt/quarantine books here — the job may be
        # cleaned up before any poller can observe them
        job_obj = store.tile_jobs.get(job_id)
        if job_obj is not None:
            captured["attempts"] = {
                int(t): int(n) for t, n in dict(job_obj.attempts).items()
            }
            captured["quarantined"] = sorted(job_obj.quarantined_tiles)
        for wid in worker_ids:
            pardons.append(str(wid))
            health.pardon(str(wid))

    store.poison_pardon = pardon
    manager = None
    if journal_dir:
        from ..durability import DurabilityManager

        manager = DurabilityManager(journal_dir, snapshot_every=64, fsync_every=0)
        store.journal_sink = manager.record

    crashed: list[str] = []
    crashed_lock = threading.Lock()

    def worker_body(wid: str) -> None:
        _, grid, extracted = upscale_ops.prepare_upscaled_tiles(
            image, upscale_by, tile, padding, "bicubic", None
        )
        key = jax.random.key(seed)
        job = run_async_in_server_loop(
            store.wait_for_tile_job(job_id, grace_seconds=20), timeout=30
        )
        if job is None:
            return
        sampler = GrantSampler(
            _stub_process, None, extracted, key, grid.positions_array(),
            None, None, k_max=1, role="worker",
        )
        flush_pending: dict[int, list] = {}

        def pull():
            # persistent pull: park through empty windows so a
            # requeued poison tile finds a live victim (a real worker
            # process keeps polling exactly like this)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                try:
                    job_obj = run_async_in_server_loop(
                        store.get_tile_job(job_id), timeout=10
                    )
                    if job_obj is None or job_obj.cancelled:
                        return None
                    done = (
                        len(job_obj.completed)
                        + len(job_obj.quarantined_tiles)
                    )
                    if done >= job_obj.total_tasks:
                        return None
                    grant = run_async_in_server_loop(
                        store.pull_tasks(job_id, wid, timeout=0.1), timeout=10
                    )
                except JobQueueError:
                    return None
                if grant:
                    return grant
            return None

        def sample(chunk):
            if int(poison_tile) in [int(t) for t in chunk]:
                # the poison payload kills the worker process; the
                # breaker observes the death as a transport failure
                charged_states.append(
                    health.record_failure(wid).value
                )
                raise _PoisonCrash(f"{wid} crashed sampling tile {poison_tile}")
            return sampler.sample(chunk)

        def emit(tile_idx, arr):
            flush_pending[int(tile_idx)] = [
                {
                    "batch_idx": i,
                    "image": img_utils.encode_image_data_url(arr[i]),
                }
                for i in range(arr.shape[0])
            ]

        def flush(is_final):
            if not flush_pending:
                return
            grouped = dict(flush_pending)
            flush_pending.clear()
            try:
                run_async_in_server_loop(
                    store.submit_flush(job_id, wid, grouped), timeout=10
                )
            except JobQueueError:
                pass

        try:
            TilePipeline(
                pull=pull, sample=sample, chunks=sampler.chunks,
                emit=emit, flush=flush, role="worker",
                span_attrs={"worker_id": wid}, threaded=False,
            ).run()
        except _PoisonCrash as exc:
            debug_log(f"chaos poison worker died: {exc}")
            with crashed_lock:
                crashed.append(wid)
        except JobQueueError:
            pass

    with contextlib.ExitStack() as stack:
        stack.enter_context(_ensure_server_loop())
        stack.enter_context(
            mock.patch.object(
                elastic, "_jit_tile_processor", lambda *a, **k: _stub_process
            )
        )
        stack.enter_context(
            mock.patch.object(
                config_mod, "get_worker_timeout_seconds",
                lambda path=None: worker_timeout,
            )
        )
        stack.enter_context(
            mock.patch.dict(
                os.environ,
                {"CDT_DETERMINISTIC_BLEND": "1", "CDT_TILE_BATCH": "1"},
            )
        )
        ctx = ExecutionContext(
            server=types.SimpleNamespace(job_store=store),
            config={"workers": []},
        )
        threads = [
            threading.Thread(target=worker_body, args=(wid,), daemon=True)
            for wid in workers
        ]
        # monitor: fallback snapshots of the live job's books while it
        # exists (the pardon hook takes the authoritative final one)
        monitor_stop = threading.Event()

        def monitor_body() -> None:
            while not monitor_stop.is_set():
                try:
                    job_obj = run_async_in_server_loop(
                        store.get_tile_job(job_id), timeout=10
                    )
                except Exception:  # noqa: BLE001 - loop shutting down
                    return
                if job_obj is not None:
                    if job_obj.attempts:
                        captured["attempts"] = {
                            int(t): int(n)
                            for t, n in dict(job_obj.attempts).items()
                        }
                    if job_obj.quarantined_tiles:
                        captured["quarantined"] = sorted(
                            job_obj.quarantined_tiles
                        )
                time.sleep(0.02)

        monitor = threading.Thread(target=monitor_body, daemon=True)
        monitor.start()
        for t in threads:
            t.start()
        # ghost ids pad the master's collection deadline (timeout x N)
        # so three crash->timeout->requeue cycles fit before its local
        # fallback would race the quarantine
        padded_ids = list(workers) + [f"ghost{i}" for i in range(9)]
        try:
            out = elastic.run_master_elastic(
                bundle, image, pos, neg,
                job_id=job_id,
                enabled_worker_ids=padded_ids,
                upscale_by=upscale_by, tile=tile, padding=padding,
                steps=1, sampler="euler", scheduler="karras",
                cfg=1.0, denoise=0.3, seed=seed, context=ctx,
            )
        finally:
            for t in threads:
                t.join(timeout=30)
            monitor_stop.set()
            monitor.join(timeout=10)
            if manager is not None:
                manager.close()

    journal_quarantined: list[int] = []
    if journal_dir:
        from ..durability.recovery import recover_state

        state, _ = recover_state(journal_dir)
        job_state = state.get("jobs", {}).get(job_id, {})
        journal_quarantined = [int(t) for t in job_state.get("quarantined", [])]

    _, _, grid = upscale_ops.plan_grid(h, w, upscale_by, tile, padding, None)
    y, x = grid.positions[int(poison_tile)]
    rect = (int(y), int(x), int(grid.padded_h), int(grid.padded_w))
    return PoisonResult(
        output=np.asarray(out),
        poison_tile=int(poison_tile),
        poison_rect=rect,
        crashed_workers=sorted(crashed),
        attempts=captured.get("attempts", {}),
        quarantined=captured.get("quarantined", []),
        pardons=list(pardons),
        health_after=health.snapshot(),
        charged_states=charged_states,
        journal_quarantined=journal_quarantined,
    )


# --------------------------------------------------------------------------
# cross-job continuous batching + step-level preemption scenarios
# --------------------------------------------------------------------------


def _stub_stepwise(n_steps: int, signature: tuple = ("chaos-stepwise",)):
    """Step-resumable stand-in for the jitted stepwise tile processor
    (ops/stepwise.py): each step adds keyed noise derived from
    (tile key, step index) — a pure function of per-item inputs, so
    mixed-batch / preempt-resume runs are bit-identical to solo runs —
    and finish snaps to the uint8 grid so the PNG envelope is
    lossless (exactly the `_stub_process` contract)."""
    import jax
    import jax.numpy as jnp

    def init(params, tile, key):
        return tile + 0.0

    def step(params, x, key, pos, neg, yx, i):
        ki = jax.random.fold_in(key, i)
        return jnp.clip(
            x + (0.05 / max(1, n_steps)) * jax.random.normal(ki, x.shape),
            0.0,
            1.0,
        )

    def finish(params, x):
        return jnp.round(jnp.clip(x, 0.0, 1.0) * 255.0) / 255.0

    return types.SimpleNamespace(
        init=init, step=step, finish=finish, n_steps=int(n_steps),
        signature=tuple(signature),
    )


class _WideBatches:
    """Placement stub for xjob scenarios: pulls claim whole grants (the
    executor shapes its own device batches), and the master id is
    unused — the executor is the only compute participant."""

    def __init__(self, size: int = 64):
        self.size = int(size)

    def may_pull(self, worker_id: str, pending: int) -> bool:
        return True

    def batch_size(self, worker_id: str, pending: int) -> int:
        return self.size


@dataclasses.dataclass
class XJobResult:
    """Outcome of one cross-job continuous-batching fleet run."""

    canvases: dict[str, np.ndarray]       # job id -> blended canvas
    stats: dict                           # executor summary stats
    fill_ratio: float
    completion_order: list                # (job_id, tile_idx) in finish order
    preempted_jobs: list                  # jobs flagged during the run
    evictions: int
    resumes_device: int
    resumes_checkpoint: int
    resumes_recompute: int
    leaks: dict                           # job id -> leak accounting
    tiles_by_job: dict                    # job id -> accepted tile count
    # chip-time attribution captured on a run-local UsageMeter:
    # {"rollup": per-tenant/lane/job view, "totals": exact ns identity}
    usage: dict = dataclasses.field(default_factory=dict)


def run_chaos_xjob(
    seed: int = 0,
    *,
    jobs: Optional[Sequence[dict]] = None,
    k_max: int = 8,
    bucket_multiple: int = 1,
    cross_job: bool = True,
    steps: int = 4,
    lanes: Sequence[str] = ("premium", "batch"),
    premium: Optional[dict] = None,
    drop_checkpoints: bool = False,
    tile: int = 64,
    padding: int = 16,
    upscale_by: float = 2.0,
    trace_jsonl: Optional[str] = None,
) -> XJobResult:
    """One in-process cross-job continuous-batching run: N small jobs
    (different tenants/images/seeds, same geometry family) drain
    through ONE CrossJobExecutor against a real JobStore wired to a
    real PreemptionCoordinator — the production protocol shape with
    the transports removed.

    `jobs`: per-job specs ``{"job_id", "seed", "tenant", "lane",
    "image_hw"}``; defaults to four 3-tile jobs across two tenants on
    the "batch" lane. Each job's tiles blend into its own
    DeterministicHostCanvas at final flush; the caller compares each
    canvas against a SOLO run of the same spec (``jobs=[spec]``) —
    bit-identity is the acceptance bar.

    `premium`: ``{"job_id", "seed", "tenant", "image_hw",
    "after_tiles": N}`` — injected ON THE EXECUTOR THREAD after the
    fleet completes N tiles (deterministic, no timing race): the store
    inits it on the top lane, the coordinator flags every running
    batch-lane job, the executor checkpoints + releases their
    in-flight tiles at the next step boundary, the premium job's
    tiles take the freed slots, and on settle the flags lift and the
    evicted work resumes from its checkpoints.

    `drop_checkpoints=True` withholds retained checkpoints at re-grant
    (the master-restart / worker-crash story: checkpoints are volatile
    by design) so resumed tiles recompute from step 0 — the canvas
    must STILL be bit-identical.

    `cross_job=False` restricts every device batch to a single job's
    items: the per-job baseline the fill-ratio A/B (bench
    `mixed_small_jobs`) compares against.
    """
    import jax
    import jax.numpy as jnp

    from ..graph.batch_executor import CrossJobExecutor, XJobHandle
    from ..jobs import JobStore
    from ..ops import tiles as tile_ops
    from ..ops import upscale as upscale_ops
    from ..scheduler.preempt import PreemptionCoordinator
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop

    if jobs is None:
        jobs = [
            {
                "job_id": f"xjob-{i}",
                "seed": seed + i,
                "tenant": "tenant-a" if i % 2 == 0 else "tenant-b",
                "lane": "batch",
                "image_hw": (32, 96),  # 3 tiles: ragged vs pow2 buckets
            }
            for i in range(4)
        ]
    proc = _stub_stepwise(steps)

    store = JobStore()
    store.placement = _WideBatches()
    coordinator = PreemptionCoordinator(list(lanes), store, enabled=True)
    store.preempt_policy = coordinator
    # run-local chip-time attribution: the executor meters into this
    # meter (and it is swapped in as the process global below so the
    # store's attrs/waste notes land in the same place), so the
    # result's usage block is exactly THIS run's burn
    from ..telemetry.usage import UsageMeter, set_usage_meter

    usage_meter = UsageMeter()
    executor = CrossJobExecutor(
        k_max=k_max,
        bucket_multiple=bucket_multiple,
        cross_job=cross_job,
        preempt_enabled=True,
        usage_meter=usage_meter,
    )

    canvases: dict[str, np.ndarray] = {}
    tiles_by_job: dict[str, int] = {}
    preempted_jobs: list[str] = []

    def make_handle(spec: dict, lane: str, worker_id: str) -> XJobHandle:
        job_id = str(spec["job_id"])
        job_seed = int(spec.get("seed", seed))
        h, w = spec.get("image_hw", (32, 96))
        image = jnp.asarray(
            np.random.default_rng(job_seed).random((1, h, w, 3)), jnp.float32
        )
        upscaled, grid, extracted = upscale_ops.prepare_upscaled_tiles(
            image, upscale_by, tile, padding, "bicubic", None
        )
        positions = grid.positions_array()
        from ..parallel.seeds import fold_job_key

        base_key = fold_job_key(jax.random.key(job_seed), job_id)
        canvas = tile_ops.DeterministicHostCanvas(upscaled, grid)
        flush_pending: dict[int, list] = {}

        def pull():
            async def pull_batch():
                tasks = await store.pull_tasks(job_id, worker_id, timeout=0.05)
                if not tasks:
                    return None
                checkpoints = {}
                if not drop_checkpoints:
                    checkpoints = await store.checkpoints_for(job_id, tasks)
                elif tasks:
                    # the crash story: retained checkpoints die with the
                    # volatile store; pop them so recompute is honest
                    await store.checkpoints_for(job_id, tasks)
                return {"tile_idxs": tasks, "checkpoints": checkpoints}

            return run_async_in_server_loop(pull_batch(), timeout=10)

        def emit(tile_idx: int, arr) -> None:
            flush_pending[int(tile_idx)] = [
                {
                    "batch_idx": i,
                    "image": img_utils.encode_image_data_url(arr[i]),
                }
                for i in range(arr.shape[0])
            ]
            maybe_inject_premium()

        def flush(is_final: bool) -> None:
            if flush_pending:
                grouped = dict(flush_pending)
                flush_pending.clear()
                accepted = run_async_in_server_loop(
                    store.submit_flush(job_id, worker_id, grouped), timeout=10
                )
                tiles_by_job[job_id] = tiles_by_job.get(job_id, 0) + accepted
            if is_final:
                finalize()

        def finalize() -> None:
            # drain THIS job's accepted results and blend its canvas
            # (sorted-order deferred compositing — arrival order is
            # irrelevant), then settle the job at the store so the
            # coordinator lifts any flags it raised
            async def drain():
                job = await store.get_tile_job(job_id)
                items = []
                while job is not None and not job.results.empty():
                    items.append(job.results.get_nowait())
                return items

            for tile_idx, payload in run_async_in_server_loop(
                drain(), timeout=10
            ):
                batch = [
                    img_utils.decode_image_data_url(e["image"])
                    for e in sorted(payload, key=lambda e: e["batch_idx"])
                ]
                y, x = grid.positions[tile_idx]
                canvas.blend(jnp.asarray(np.stack(batch, axis=0)), y, x)
            canvases[job_id] = np.asarray(canvas.result())
            run_async_in_server_loop(store.cleanup_tile_job(job_id), timeout=10)

        def release(idxs: list, checkpoints: dict) -> None:
            if job_id not in preempted_jobs:
                preempted_jobs.append(job_id)
            run_async_in_server_loop(
                store.release_tasks(
                    job_id, worker_id, idxs, checkpoints=checkpoints
                ),
                timeout=10,
            )

        def preempt_check() -> bool:
            async def read():
                job = await store.get_tile_job(job_id)
                return bool(job is not None and job.preempt_requested)

            return run_async_in_server_loop(read(), timeout=10)

        run_async_in_server_loop(
            store.init_tile_job(
                job_id, list(range(grid.num_tiles)), lane=lane,
                tenant=str(spec.get("tenant", "default")),
            ),
            timeout=10,
        )
        return XJobHandle(
            job_id=job_id,
            proc=proc,
            params=None,
            extracted=extracted,
            positions=positions,
            pos=jnp.zeros((1,), jnp.float32),
            neg=jnp.zeros((1,), jnp.float32),
            base_key=base_key,
            pull=pull,
            emit=emit,
            flush=flush,
            release=release,
            preempt_check=preempt_check,
            tenant=str(spec.get("tenant", "default")),
            lane=lane,
            priority=list(lanes).index(lane) if lane in lanes else len(lanes),
        )

    injected = {"done": premium is None}

    def inject_premium() -> None:
        injected["done"] = True
        spec = {
            "job_id": premium.get("job_id", "xjob-premium"),
            "seed": premium.get("seed", seed + 1000),
            "tenant": premium.get("tenant", "tenant-premium"),
            "image_hw": premium.get("image_hw", (32, 64)),
        }
        handle = make_handle(spec, lane=str(lanes[0]), worker_id="xworker")
        executor.register(handle)

    def maybe_inject_premium() -> None:
        """Runs on the executor thread (from a batch job's emit): once
        the fleet has finished `after_tiles` tiles, init + register the
        premium job — deterministically mid-flight."""
        if injected["done"] or "after_tiles" not in premium:
            return
        if executor.tiles_finished >= int(premium["after_tiles"]):
            inject_premium()

    if premium is not None and premium.get("after_dispatches"):
        # inject at a STEP boundary (after the Nth device dispatch),
        # while the batch jobs' tiles are mid-trajectory — the scenario
        # that forces checkpointed eviction rather than a clean handoff
        target = int(premium["after_dispatches"])
        orig_step_batch = executor._step_batch

        def hooked_step_batch(batch):
            orig_step_batch(batch)
            if not injected["done"] and executor.dispatches >= target:
                inject_premium()

        executor._step_batch = hooked_step_batch

    chaos_tracer = Tracer(clock=FakeClock())
    previous_tracer = get_tracer()
    trace_id = f"exec_chaos_xjob_{seed}"
    with contextlib.ExitStack() as stack:
        stack.enter_context(_ensure_server_loop())
        stack.enter_context(
            mock.patch.dict(os.environ, {"CDT_DETERMINISTIC_BLEND": "1"})
        )
        stack.callback(set_usage_meter, set_usage_meter(usage_meter))
        set_tracer(chaos_tracer)
        stack.callback(set_tracer, previous_tracer)
        token = chaos_tracer.activate(trace_id)
        stack.callback(chaos_tracer.deactivate, token)
        for spec in jobs:
            executor.register(
                make_handle(
                    spec, lane=str(spec.get("lane", lanes[-1])),
                    worker_id="xworker",
                )
            )
        with chaos_tracer.span(
            "chaos_xjob", trace_id=trace_id, seed=seed,
            cross_job=cross_job,
        ):
            stats = executor.run()
        if trace_jsonl:
            chaos_tracer.write_jsonl(trace_id, trace_jsonl)
        # leak accounting BEFORE teardown: every job must have settled
        # with nothing pending/assigned/checkpointed
        async def leak_check():
            out = {}
            async with store.lock:
                for job_id in sorted(store.tile_jobs):
                    job = store.tile_jobs[job_id]
                    out[job_id] = {
                        "pending": job.pending.qsize(),
                        "assigned": sum(
                            len(v) for v in job.assigned.values()
                        ),
                        "checkpoints": len(job.checkpoints),
                        "completed": len(job.completed),
                    }
            return out

        leaks = run_async_in_server_loop(leak_check(), timeout=10)

    return XJobResult(
        canvases=canvases,
        stats=stats,
        fill_ratio=executor.fill_ratio(),
        completion_order=list(executor.completion_order),
        preempted_jobs=list(preempted_jobs),
        evictions=executor.preempt_evictions,
        resumes_device=executor.resumes_device,
        resumes_checkpoint=executor.resumes_checkpoint,
        resumes_recompute=executor.resumes_recompute,
        leaks=leaks,
        tiles_by_job=dict(tiles_by_job),
        usage={
            "rollup": usage_meter.rollup(),
            "totals": usage_meter.totals(),
        },
    )
