"""Unified retry/backoff policy for every cross-host RPC.

One `RetryPolicy` (exponential backoff + jitter + an overall deadline)
and one `retry_async` helper replace the hand-rolled retry loops that
used to live in `graph/usdu_elastic.py` (job-ready poll, work pull),
`api/orchestration/dispatch.py`, and `api/orchestration/media_sync.py`.

Design points:

- policies are values (frozen dataclasses) so call sites can derive
  variants with `dataclasses.replace` / `with_deadline`;
- the deadline is a wall-clock budget for the WHOLE retry sequence —
  a retry whose backoff would overshoot the budget is not attempted,
  so caller-level timeouts compose instead of stacking;
- jitter is multiplicative (+-`jitter` fraction) and draws from an
  injectable `random.Random`, which keeps fault-injection runs
  deterministic under a fixed seed;
- `retry_async` re-raises the LAST failure on exhaustion, so callers
  keep their existing exception taxonomy (`WorkerError`,
  `aiohttp.ClientError`, ...) instead of learning a new wrapper type.

The default attempt counts / backoff bases come from the same env
knobs the old loops used (`CDT_REQUEST_RETRIES`, `CDT_REQUEST_BACKOFF`,
`CDT_WORK_PULL_RETRIES`, `CDT_WORK_PULL_RETRY_CAP`,
`CDT_JOB_READY_POLLS`, `CDT_JOB_READY_POLL_INTERVAL`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import Any, Awaitable, Callable, Optional, Tuple, Type

from ..utils import constants
from ..utils.logging import debug_log

# Shared jitter source for call sites that don't inject their own.
_default_rng = random.Random()


def transport_errors() -> Tuple[Type[BaseException], ...]:
    """Failures where the request may never have arrived — the only
    class worth retrying for non-idempotent sends and the only class
    the circuit breaker counts. One definition so dispatch and media
    sync can't drift apart."""
    import aiohttp

    return (aiohttp.ClientConnectionError, asyncio.TimeoutError, OSError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff schedule with jitter and an overall deadline."""

    max_attempts: int = 5
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.1          # +- fraction of the computed delay
    deadline: Optional[float] = None  # wall-clock budget for all attempts

    def delay_for(self, attempt: int, rng: random.Random | None = None) -> float:
        """Backoff to sleep after failed attempt `attempt` (0-based)."""
        raw = min(self.base_delay * (self.multiplier ** attempt), self.max_delay)
        if self.jitter > 0:
            raw *= 1.0 + (rng or _default_rng).uniform(-self.jitter, self.jitter)
        return max(0.0, raw)

    def with_deadline(self, deadline: float | None) -> "RetryPolicy":
        return dataclasses.replace(self, deadline=deadline)


async def retry_async(
    fn: Callable[[], Awaitable[Any]],
    policy: RetryPolicy,
    *,
    retryable: Tuple[Type[BaseException], ...] = (Exception,),
    label: str = "",
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    sleep: Callable[[float], Awaitable[None]] = asyncio.sleep,
    rng: random.Random | None = None,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Await `fn()` under `policy`; re-raise the last failure when the
    attempt budget or the deadline is exhausted.

    Exceptions not matching `retryable` propagate immediately — use it
    to separate transport failures (retry) from semantic rejections
    (don't re-send a prompt a worker refused).
    """
    start = clock()
    last: BaseException | None = None
    attempts = max(1, policy.max_attempts)
    for attempt in range(attempts):
        try:
            return await fn()
        except retryable as exc:  # noqa: PERF203 - retry loop by design
            last = exc
            if attempt + 1 >= attempts:
                break
            delay = policy.delay_for(attempt, rng)
            if (
                policy.deadline is not None
                and clock() - start + delay > policy.deadline
            ):
                debug_log(
                    f"retry[{label}]: deadline {policy.deadline}s exhausted "
                    f"after {attempt + 1} attempt(s)"
                )
                break
            # Retry visibility: one counter labelled by the operation
            # part of the label ("dispatch:w1" → op="dispatch"), so
            # dashboards see retry pressure without per-target series.
            from ..telemetry import instruments

            instruments.retries_total().inc(
                op=label.split(":", 1)[0] if label else "unlabeled"
            )
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            debug_log(
                f"retry[{label}]: attempt {attempt + 1}/{attempts} failed "
                f"({type(exc).__name__}: {exc}); backing off {delay:.2f}s"
            )
            await sleep(delay)
    assert last is not None
    raise last


# --- canonical policies ---------------------------------------------------
# Factories (not module constants) so tests can monkeypatch
# utils.constants and get fresh values, matching the old loops which
# read the constants at call time.

def http_policy(deadline: float | None = None) -> RetryPolicy:
    """General request retry: short exponential backoff, 30 s cap."""
    return RetryPolicy(
        max_attempts=constants.REQUEST_RETRY_COUNT,
        base_delay=constants.REQUEST_RETRY_BACKOFF,
        multiplier=2.0,
        max_delay=30.0,
        jitter=0.1,
        deadline=deadline,
    )


def work_pull_policy() -> RetryPolicy:
    """Worker->master tile pull: patient (x10, capped) — losing the
    pull loop strands the whole worker for the job."""
    return RetryPolicy(
        max_attempts=constants.WORK_PULL_RETRY_COUNT,
        base_delay=constants.REQUEST_RETRY_BACKOFF,
        multiplier=2.0,
        max_delay=constants.WORK_PULL_RETRY_CAP_SECONDS,
        jitter=0.1,
    )


def poll_ready_policy() -> RetryPolicy:
    """Job-ready poll: fixed interval (multiplier 1, no jitter), the
    reference's N x 1 s readiness probe."""
    return RetryPolicy(
        max_attempts=constants.JOB_READY_POLL_ATTEMPTS,
        base_delay=constants.JOB_READY_POLL_INTERVAL,
        multiplier=1.0,
        max_delay=constants.JOB_READY_POLL_INTERVAL,
        jitter=0.0,
    )
