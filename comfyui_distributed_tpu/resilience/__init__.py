"""Resilience core: unified retry/backoff, worker health circuit
breaker, deterministic fault injection.

- `policy`: RetryPolicy + retry_async — the single backoff engine for
  every cross-host RPC (dispatch, media sync, USDU work pulls).
- `health`: per-worker state machine (healthy → suspect → quarantined
  → probing → recovered) consulted by worker selection/dispatch.
- `faults`: seeded FaultInjector scripted via CDT_FAULT_PLAN; wraps
  the HTTP transport and the JobStore for deterministic chaos tests.
- `chaos`: in-process master/worker USDU harness that runs under a
  fault plan and checks bit-identical output against a fault-free run.

See docs/resilience.md for the operator-facing story.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..utils.logging import debug_log, log
from .faults import (
    FaultInjected,
    FaultInjector,
    get_fault_injector,
    reset_fault_injector,
    set_fault_injector,
)
from .health import (
    HealthRegistry,
    WorkerState,
    get_health_registry,
    reset_health_registry,
)
from .policy import RetryPolicy, http_policy, retry_async, work_pull_policy

__all__ = [
    "FaultInjected",
    "FaultInjector",
    "HealthRegistry",
    "RetryPolicy",
    "WorkerState",
    "bind_quarantine_requeue",
    "get_fault_injector",
    "get_health_registry",
    "http_policy",
    "reset_fault_injector",
    "reset_health_registry",
    "retry_async",
    "set_fault_injector",
    "work_pull_policy",
]


def bind_quarantine_requeue(registry: HealthRegistry, store) -> Callable[[], None]:
    """Wire the circuit breaker to the JobStore: the moment a worker is
    quarantined, its in-flight tiles across every active job go back
    on the pending queue (no waiting for heartbeat staleness).

    Returns an unbind callable (the server calls it on shutdown so a
    dead server's store isn't kept alive by the global registry).
    """

    # Strong references to in-flight requeue tasks: the loop only keeps
    # a weak ref to a Task, so a fire-and-forget create_task can be
    # garbage-collected before it runs.
    pending_tasks: set = set()

    def on_transition(worker_id: str, old: WorkerState, new: WorkerState) -> None:
        if new is not WorkerState.QUARANTINED:
            return

        async def requeue() -> None:
            moved = await store.requeue_worker_tasks(worker_id)
            if moved:
                log(
                    f"quarantine of {worker_id}: requeued "
                    + ", ".join(f"{len(v)} task(s) of job {k}" for k, v in moved.items())
                )

        def done(task) -> None:
            pending_tasks.discard(task)
            exc = task.exception() if not task.cancelled() else None
            if exc is not None:
                debug_log(f"quarantine requeue for {worker_id} failed: {exc}")

        try:
            task = asyncio.get_running_loop().create_task(requeue())
            pending_tasks.add(task)
            task.add_done_callback(done)
        except RuntimeError:
            # Not on a loop (compute thread): hop to the server loop,
            # falling back to a transient one.
            from ..utils.async_helpers import run_async_in_server_loop

            try:
                run_async_in_server_loop(requeue(), timeout=30)
            except Exception as exc:  # noqa: BLE001 - requeue best effort
                debug_log(f"quarantine requeue for {worker_id} failed: {exc}")

    registry.add_listener(on_transition)
    return lambda: registry.remove_listener(on_transition)
