"""Deterministic, seeded fault injection for chaos testing.

A `FaultInjector` is parsed from a *fault plan* string (env var
`CDT_FAULT_PLAN`) and consulted at instrumented call sites: the HTTP
transport (`utils/network.py` wraps the pooled session) and the
`JobStore` (`jobs/store.py` checks pull/submit/heartbeat ops). The
in-process chaos harness (`resilience/chaos.py`) adds worker-loop
sites (`chaos:<worker>:pull` / `pulled` / `submit`).

Plan grammar (rules joined with ';')::

    plan   := rule (';' rule)*
    rule   := 'seed=' INT
            | FAULT ['(' NUMBER ')'] '@' PATTERN [SCHEDULE]
    SCHEDULE := '#' OCC (',' OCC)*        occurrence schedule (1-based)
              | '%' FLOAT                 per-match probability (seeded)
    OCC    := INT | INT '-' INT | '*'

    FAULT  := 'connect_error'   transport-level connection failure
            | 'http500'         server error response (transport sites)
            | 'latency'         sleep NUMBER seconds, then proceed
            | 'drop'            swallow the operation (fire-and-forget
                                sites: heartbeats). At request/response
                                sites the caller sees an empty OK, so a
                                dropped pull reads as queue-drained —
                                model a lost REQUEST with connect_error
            | 'crash'           kill the participant at this site

`PATTERN` matches operation names (glob if it contains ``*?[``,
substring otherwise). Operation names are hierarchical strings such as
``http:POST:/distributed/request_image``, ``store:pull:w1``,
``store:heartbeat:w1``, ``chaos:w1:pulled``. Without a schedule a rule
fires on its FIRST match only (``#1``); ``#*`` fires on every match.

Examples::

    CDT_FAULT_PLAN='seed=7;crash@chaos:w1:pulled#2'
        worker w1 crashes right after pulling its 2nd tile

    CDT_FAULT_PLAN='connect_error@http:POST:/distributed/submit_tiles#1-3'
        the first three tile submissions fail at the transport

    CDT_FAULT_PLAN='seed=3;latency(0.2)@request_image%0.5'
        every pull has a seeded 50% chance of a 200 ms latency spike

Determinism: occurrence counting is per-rule, and probabilistic rules
draw from a per-rule `random.Random` seeded from (plan seed, rule
index) — two injectors built from the same plan observe identical
fault sequences for identical op sequences.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import re
import threading
import time
from typing import Optional

from ..utils.exceptions import DistributedError
from ..utils.logging import debug_log

FAULT_KINDS = ("connect_error", "http500", "latency", "drop", "crash")

ENV_FAULT_PLAN = "CDT_FAULT_PLAN"

_RULE_RE = re.compile(
    r"^(?P<kind>[a-z_0-9]+)"
    r"(?:\((?P<arg>[^)]*)\))?"
    r"@(?P<pattern>[^#%]+)"
    r"(?:#(?P<occ>[0-9,\-*]+)|%(?P<prob>[0-9.]+))?$"
)


class FaultInjected(DistributedError):
    """Raised at a call site the active fault plan targets."""

    def __init__(self, kind: str, op: str):
        super().__init__(f"injected fault {kind!r} at {op!r}")
        self.kind = kind
        self.op = op


class FaultPlanError(DistributedError):
    """The CDT_FAULT_PLAN string does not parse."""


@dataclasses.dataclass(frozen=True)
class FaultRule:
    kind: str
    pattern: str
    arg: Optional[float] = None
    occurrences: Optional[frozenset[int]] = None  # None + no prob = {1}
    all_matches: bool = False
    probability: Optional[float] = None

    def matches(self, op: str) -> bool:
        if any(c in self.pattern for c in "*?["):
            return fnmatch.fnmatchcase(op, self.pattern)
        return self.pattern in op

    def fires(self, nth_match: int, rng) -> bool:
        if self.probability is not None:
            return rng.random() < self.probability
        if self.all_matches:
            return True
        occ = self.occurrences if self.occurrences is not None else frozenset({1})
        return nth_match in occ


@dataclasses.dataclass(frozen=True)
class FaultAction:
    kind: str
    op: str
    arg: Optional[float] = None


def parse_fault_plan(text: str) -> tuple[int, list[FaultRule]]:
    """Parse a plan string into (seed, rules); raises FaultPlanError."""
    seed = 0
    rules: list[FaultRule] = []
    for raw in text.split(";"):
        part = raw.strip()
        if not part:
            continue
        if part.startswith("seed="):
            try:
                seed = int(part[len("seed="):])
            except ValueError as exc:
                raise FaultPlanError(f"bad seed in fault plan: {part!r}") from exc
            continue
        m = _RULE_RE.match(part)
        if m is None:
            raise FaultPlanError(f"unparseable fault rule: {part!r}")
        kind = m.group("kind")
        if kind not in FAULT_KINDS:
            raise FaultPlanError(
                f"unknown fault kind {kind!r} (known: {', '.join(FAULT_KINDS)})"
            )
        arg = None
        if m.group("arg"):
            try:
                arg = float(m.group("arg"))
            except ValueError as exc:
                raise FaultPlanError(f"bad fault arg in {part!r}") from exc
        occurrences: Optional[frozenset[int]] = None
        all_matches = False
        probability = None
        if m.group("occ") is not None:
            occ_text = m.group("occ")
            if occ_text == "*":
                all_matches = True
            else:
                occ: set[int] = set()
                for piece in occ_text.split(","):
                    piece = piece.strip()
                    if not piece:
                        continue
                    if "-" in piece:
                        lo_s, _, hi_s = piece.partition("-")
                        try:
                            lo, hi = int(lo_s), int(hi_s)
                        except ValueError as exc:
                            raise FaultPlanError(
                                f"bad occurrence range {piece!r} in {part!r}"
                            ) from exc
                        occ.update(range(lo, hi + 1))
                    else:
                        try:
                            occ.add(int(piece))
                        except ValueError as exc:
                            raise FaultPlanError(
                                f"bad occurrence {piece!r} in {part!r}"
                            ) from exc
                occurrences = frozenset(occ)
        elif m.group("prob") is not None:
            try:
                probability = float(m.group("prob"))
            except ValueError as exc:
                raise FaultPlanError(f"bad probability in {part!r}") from exc
        rules.append(
            FaultRule(
                kind=kind,
                pattern=m.group("pattern").strip(),
                arg=arg,
                occurrences=occurrences,
                all_matches=all_matches,
                probability=probability,
            )
        )
    return seed, rules


class FaultInjector:
    """Consults a parsed plan at named call sites; thread-safe."""

    def __init__(self, plan: str):
        import random

        self.plan = plan
        self.seed, self.rules = parse_fault_plan(plan)
        self._lock = threading.Lock()
        self._counters = [0] * len(self.rules)
        # Stable per-rule streams: NOT hash() (randomized per process).
        self._rngs = [
            random.Random(self.seed * 1000003 + idx) for idx in range(len(self.rules))
        ]
        self.fired: list[FaultAction] = []

    def hit(self, op: str) -> Optional[FaultAction]:
        """Pure decision: does any rule fire for this op occurrence?
        Every matching rule's counter advances; the first firing rule
        wins (rule order = plan order)."""
        with self._lock:
            fired: Optional[FaultAction] = None
            for idx, rule in enumerate(self.rules):
                if not rule.matches(op):
                    continue
                self._counters[idx] += 1
                if fired is None and rule.fires(self._counters[idx], self._rngs[idx]):
                    fired = FaultAction(kind=rule.kind, op=op, arg=rule.arg)
            if fired is not None:
                self.fired.append(fired)
        if fired is not None:
            debug_log(f"fault injected: {fired.kind} at {op}")
        return fired

    async def check(self, op: str) -> Optional[FaultAction]:
        """Async call-site helper: applies latency, raises for
        error/crash kinds, returns the action for 'drop' (the site
        decides what swallowing means)."""
        action = self.hit(op)
        if action is None:
            return None
        if action.kind == "latency":
            import asyncio

            await asyncio.sleep(action.arg or 0.0)
            return action
        if action.kind == "drop":
            return action
        raise FaultInjected(action.kind, op)

    def check_blocking(self, op: str) -> Optional[FaultAction]:
        """Sync twin of `check` for worker threads."""
        action = self.hit(op)
        if action is None:
            return None
        if action.kind == "latency":
            time.sleep(action.arg or 0.0)
            return action
        if action.kind == "drop":
            return action
        raise FaultInjected(action.kind, op)


# --- global (env-driven) injector -----------------------------------------

_env_injector: FaultInjector | None = None
_env_plan: str | None = None
_override: FaultInjector | None = None
_has_override = False
_global_lock = threading.Lock()


def get_fault_injector() -> Optional[FaultInjector]:
    """The process-wide injector: an explicit override if set, else one
    built (and cached) from CDT_FAULT_PLAN; None when no plan is
    active, so un-instrumented runs pay a dict lookup at most."""
    global _env_injector, _env_plan
    with _global_lock:
        if _has_override:
            return _override
        plan = os.environ.get(ENV_FAULT_PLAN, "").strip()
        if not plan:
            _env_injector, _env_plan = None, None
            return None
        if _env_injector is None or _env_plan != plan:
            _env_injector = FaultInjector(plan)
            _env_plan = plan
        return _env_injector


def set_fault_injector(injector: Optional[FaultInjector]) -> None:
    """Install an explicit injector (chaos harness / tests); overrides
    the env plan until `reset_fault_injector`."""
    global _override, _has_override
    with _global_lock:
        _override = injector
        _has_override = True


def reset_fault_injector() -> None:
    """Drop any override and the env-plan cache (tests)."""
    global _override, _has_override, _env_injector, _env_plan
    with _global_lock:
        _override = None
        _has_override = False
        _env_injector = None
        _env_plan = None
