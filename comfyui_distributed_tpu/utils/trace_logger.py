"""Per-execution trace ids + log-line bridge into span tracing.

Every distributed queue execution gets a trace id `exec_<ms>_<uuid6>`
threaded from the entry point through orchestration, dispatch, and
collection. Historically the only consumer was grep (one id
reconstructs a job across master and worker logs); the telemetry
subsystem (telemetry/tracing.py) subsumes that: the same id keys a
span TREE served by /distributed/trace/{trace_id}, and `trace_info` /
`trace_debug` ALSO attach their message as a span event on that trace,
so the narrative log lines land inside the structured timeline.

Parity: reference utils/trace_logger.py + api/queue_orchestration.py:38-39.
"""

from __future__ import annotations

import time
import uuid

from .logging import debug_log, log


def generate_trace_id(node_hint: str | None = None) -> str:
    base = f"exec_{int(time.time() * 1000)}_{uuid.uuid4().hex[:6]}"
    return f"{base}_{node_hint}" if node_hint else base


def _span_event(trace_id: str, message: str, level: str) -> None:
    """Mirror the log line as an event on the trace's span tree (the
    active span if this context is inside one, else the root)."""
    from ..telemetry import get_tracer

    tracer = get_tracer()
    if tracer.root_span_id(trace_id) is None:
        return  # no spans for this trace yet; stay log-only
    if tracer.current_trace_id() == trace_id:
        tracer.event("log", message=message, level=level)
    else:
        token = tracer.activate(trace_id)
        try:
            tracer.event("log", message=message, level=level)
        finally:
            tracer.deactivate(token)


def trace_info(trace_id: str, message: str) -> None:
    log(f"[exec:{trace_id}] {message}")
    _span_event(trace_id, message, "info")


def trace_debug(trace_id: str, message: str) -> None:
    debug_log(f"[exec:{trace_id}] {message}")
    _span_event(trace_id, message, "debug")
