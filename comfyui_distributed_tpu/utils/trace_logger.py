"""Per-execution trace logging.

Every distributed queue execution gets a trace id `exec_<ms>_<uuid6>`
threaded from the entry point through orchestration, dispatch, and
collection, so one grep reconstructs the lifecycle of one job across
master and worker logs. Parity: reference utils/trace_logger.py +
api/queue_orchestration.py:38-39.
"""

from __future__ import annotations

import time
import uuid

from .logging import debug_log, log


def generate_trace_id(node_hint: str | None = None) -> str:
    base = f"exec_{int(time.time() * 1000)}_{uuid.uuid4().hex[:6]}"
    return f"{base}_{node_hint}" if node_hint else base


def trace_info(trace_id: str, message: str) -> None:
    log(f"[exec:{trace_id}] {message}")


def trace_debug(trace_id: str, message: str) -> None:
    debug_log(f"[exec:{trace_id}] {message}")
